"""Bandit engine driver (multi-armed bandits).

API parity with the reference's bandit service
(jubatus/server/server/bandit.idl: register_arm / delete_arm / select_arm /
register_reward / get_arm_info / reset / clear). Methods + parameters from
/root/reference/config/bandit/*.json: epsilon_greedy {epsilon}, softmax
{tau}, exp3 {gamma}, ucb1 {}; all take {assume_unrewarded}.

Semantics (reconstructed from jubatus_core's bandit package, SURVEY.md §2.9):

- Arms are registered globally (``register_arm`` is #@broadcast); per-player
  statistics (trial_count, cumulative reward weight) appear lazily.
- ``assume_unrewarded=true``: selecting an arm immediately counts as an
  unrewarded trial (select registers trial, reward adds weight only).
  ``false``: ``register_reward`` increments both trial count and weight.
- ``get_arm_info`` returns {arm: arm_info{trial_count, weight}}.
- ``reset(player)`` drops one player's stats; ``clear()`` drops everything
  including registered arms.

Selection rules:
  epsilon_greedy: with prob ε a uniform arm, else argmax empirical mean.
  softmax:        sample ∝ exp(mean / τ).
  exp3:           p_a = (1-γ) w_a / Σw + γ/K, sample; on reward
                  log w_a += γ · (r / p_a) / K.
  ucb1:           any untried arm first, else argmax mean + √(2 ln N / n_a).

TPU design note: bandit state is a handful of scalars per (player, arm) —
no MXU-shaped work (the reference runs it on C++ maps). Stats are host
numpy; the mix plane uses the standard additive array-diff protocol: per
(player, arm) [P, A] delta matrices of (trials, weight, log_w), schema-synced
so replica psum is exact — matching the reference's additive bandit_storage
mix.
"""

from __future__ import annotations

import json
import math
from typing import Any, Dict, List

import numpy as np

from jubatus_tpu.framework.driver import DriverBase, locked

METHODS = ("epsilon_greedy", "softmax", "exp3", "ucb1")


class BanditConfigError(ValueError):
    pass


class _PlayerStats:
    """Per-player per-arm accumulators, master/diff split like the array
    engines: *_m = state as of last mix, *_d = local since last mix."""

    __slots__ = ("trials_m", "trials_d", "weight_m", "weight_d",
                 "logw_m", "logw_d")

    def __init__(self) -> None:
        self.trials_m: Dict[str, float] = {}
        self.trials_d: Dict[str, float] = {}
        self.weight_m: Dict[str, float] = {}
        self.weight_d: Dict[str, float] = {}
        self.logw_m: Dict[str, float] = {}
        self.logw_d: Dict[str, float] = {}

    def trials(self, arm: str) -> float:
        return self.trials_m.get(arm, 0.0) + self.trials_d.get(arm, 0.0)

    def weight(self, arm: str) -> float:
        return self.weight_m.get(arm, 0.0) + self.weight_d.get(arm, 0.0)

    def logw(self, arm: str) -> float:
        return self.logw_m.get(arm, 0.0) + self.logw_d.get(arm, 0.0)

    def mean(self, arm: str) -> float:
        t = self.trials(arm)
        return self.weight(arm) / t if t > 0 else 0.0


class BanditDriver(DriverBase):
    TYPE = "bandit"

    def __init__(self, config: dict, seed: int = 0):
        super().__init__()
        self.config = config
        self.config_json = json.dumps(config)
        method = config.get("method")
        if method not in METHODS:
            raise BanditConfigError(f"unknown bandit method {method!r}")
        self.method = method
        param = config.get("parameter") or {}
        self.assume_unrewarded = bool(param.get("assume_unrewarded", False))
        self.epsilon = float(param.get("epsilon", 0.1))
        self.tau = float(param.get("tau", 0.05))
        self.gamma = float(param.get("gamma", 0.1))
        if method == "epsilon_greedy" and not (0.0 <= self.epsilon <= 1.0):
            raise BanditConfigError("epsilon must be in [0, 1]")
        if method == "softmax" and self.tau <= 0.0:
            raise BanditConfigError("tau must be positive")
        if method == "exp3" and not (0.0 < self.gamma <= 1.0):
            raise BanditConfigError("gamma must be in (0, 1]")
        self._rng = np.random.default_rng(seed)
        self._init_model()

    def _init_model(self) -> None:
        self.arms: List[str] = []
        self.players: Dict[str, _PlayerStats] = {}

    # -- arm registry --------------------------------------------------------
    @locked
    def register_arm(self, arm_id: str) -> bool:
        if arm_id in self.arms:
            return False
        self.arms.append(arm_id)
        self.event_model_updated()
        return True

    @locked
    def delete_arm(self, arm_id: str) -> bool:
        if arm_id not in self.arms:
            return False
        self.arms.remove(arm_id)
        for st in self.players.values():
            for d in (st.trials_m, st.trials_d, st.weight_m, st.weight_d,
                      st.logw_m, st.logw_d):
                d.pop(arm_id, None)
        self.event_model_updated()
        return True

    def _player(self, player_id: str) -> _PlayerStats:
        st = self.players.get(player_id)
        if st is None:
            st = _PlayerStats()
            self.players[player_id] = st
        return st

    # -- selection -----------------------------------------------------------
    @locked
    def select_arm(self, player_id: str) -> str:
        if not self.arms:
            raise RuntimeError("no arms registered")
        st = self._player(player_id)
        arm = self._select(st)
        if self.assume_unrewarded:
            st.trials_d[arm] = st.trials_d.get(arm, 0.0) + 1.0
            self.event_model_updated()
        return arm

    def _select(self, st: _PlayerStats) -> str:
        method = self.method
        if method == "epsilon_greedy":
            if self._rng.random() < self.epsilon:
                return self.arms[self._rng.integers(len(self.arms))]
            return max(self.arms, key=st.mean)
        if method == "softmax":
            logits = np.asarray([st.mean(a) / self.tau for a in self.arms])
            logits -= logits.max()
            p = np.exp(logits)
            p /= p.sum()
            return self.arms[self._rng.choice(len(self.arms), p=p)]
        if method == "exp3":
            p = self._exp3_probs(st)
            return self.arms[self._rng.choice(len(self.arms), p=p)]
        # ucb1: untried arms first
        for a in self.arms:
            if st.trials(a) == 0:
                return a
        total = sum(st.trials(a) for a in self.arms)
        return max(
            self.arms,
            key=lambda a: st.mean(a) + math.sqrt(2.0 * math.log(total) / st.trials(a)),
        )

    def _exp3_probs(self, st: _PlayerStats) -> np.ndarray:
        k = len(self.arms)
        logw = np.asarray([st.logw(a) for a in self.arms])
        logw -= logw.max()
        w = np.exp(logw)
        return (1.0 - self.gamma) * w / w.sum() + self.gamma / k

    # -- reward --------------------------------------------------------------
    @locked
    def register_reward(self, player_id: str, arm_id: str, reward: float) -> bool:
        if arm_id not in self.arms:
            return False
        st = self._player(player_id)
        if not self.assume_unrewarded:
            st.trials_d[arm_id] = st.trials_d.get(arm_id, 0.0) + 1.0
        st.weight_d[arm_id] = st.weight_d.get(arm_id, 0.0) + float(reward)
        if self.method == "exp3":
            p = self._exp3_probs(st)[self.arms.index(arm_id)]
            st.logw_d[arm_id] = st.logw_d.get(arm_id, 0.0) + \
                self.gamma * (float(reward) / p) / len(self.arms)
        self.event_model_updated()
        return True

    @locked
    def get_arm_info(self, player_id: str) -> Dict[str, Dict[str, float]]:
        st = self.players.get(player_id)
        out: Dict[str, Dict[str, float]] = {}
        for a in self.arms:
            out[a] = {
                "trial_count": int(st.trials(a)) if st else 0,
                "weight": float(st.weight(a)) if st else 0.0,
            }
        return out

    @locked
    def reset(self, player_id: str) -> bool:
        self.players.pop(player_id, None)
        self.event_model_updated()
        return True

    @locked
    def clear(self) -> None:
        self._init_model()
        self.update_count = 0

    # -- mix plane -----------------------------------------------------------
    # No schema sync: the registered-arm set propagates only via the
    # register_arm/delete_arm broadcasts (as in the reference, where the
    # storage merged by mix is separate from the registered-arm registry) —
    # schema-syncing arms would resurrect an arm deleted on one replica
    # while a delete broadcast is still in flight. Player stats travel as
    # sparse dict diffs, so no dense (player × arm) grid is ever built.
    def get_mixables(self):
        return {"bandit": _BanditMixable(self)}

    # -- persistence ---------------------------------------------------------
    @locked
    def pack(self) -> Any:
        return {
            "method": self.method,
            "arms": list(self.arms),
            # iterate each player's actual stat keys, not self.arms: a mix
            # can land stats for an arm whose register_arm broadcast hasn't
            # reached this replica yet — a checkpoint must not drop them
            "players": {
                p: {
                    "trials": {a: st.trials(a) for a in
                               set(st.trials_m) | set(st.trials_d)},
                    "weight": {a: st.weight(a) for a in
                               set(st.weight_m) | set(st.weight_d)},
                    "logw": {a: st.logw(a) for a in
                             set(st.logw_m) | set(st.logw_d)},
                }
                for p, st in self.players.items()
            },
        }

    @locked
    def unpack(self, obj: Any) -> None:
        def _s(x):
            return x.decode() if isinstance(x, bytes) else x

        saved = _s(obj.get("method"))
        if saved != self.method:
            raise ValueError(
                f"checkpoint method {saved!r} != driver method {self.method!r}")
        self._init_model()
        self.arms = [_s(a) for a in obj["arms"]]
        for p, rec in obj["players"].items():
            st = self._player(_s(p))
            st.trials_m = {_s(a): float(v) for a, v in rec["trials"].items()}
            st.weight_m = {_s(a): float(v) for a, v in rec["weight"].items()}
            st.logw_m = {_s(a): float(v) for a, v in rec["logw"].items()}

    @locked
    def get_status(self) -> Dict[str, Any]:
        st = super().get_status()
        st.update(method=self.method, num_arms=len(self.arms),
                  num_players=len(self.players))
        return st


class _BanditMixable:
    """Sparse additive diff: {player: {arm: [d_trials, d_weight, d_logw]}},
    carrying only cells touched since the last mix. ``mix`` is a recursive
    dict-sum (the custom-combiner seam in parallel/mix.py) — the fold across
    replicas reproduces the reference's additive bandit_storage merge without
    ever materializing a dense (players × arms) grid."""

    def __init__(self, driver: BanditDriver):
        self._d = driver

    def get_diff(self) -> Dict[str, Dict[str, List[float]]]:
        out: Dict[str, Dict[str, List[float]]] = {}
        for p, st in self._d.players.items():
            arms = set(st.trials_d) | set(st.weight_d) | set(st.logw_d)
            cells = {
                a: [st.trials_d.get(a, 0.0), st.weight_d.get(a, 0.0),
                    st.logw_d.get(a, 0.0)]
                for a in arms
            }
            if cells:
                out[p] = cells
        return out

    @staticmethod
    def mix(acc, diff):
        # merge in place: the fold's acc is always a transient — either the
        # first replica's freshly-built get_diff dict or a prior mix result —
        # so an O(touched-cells) in-place merge keeps the whole reduce linear
        for p, cells in diff.items():
            mine = acc.setdefault(p, {})
            for a, v in cells.items():
                if a in mine:
                    mine[a] = [x + y for x, y in zip(mine[a], v)]
                else:
                    mine[a] = list(v)
        return acc

    def put_diff(self, diff) -> bool:
        def _s(x):
            return x.decode() if isinstance(x, bytes) else x

        for p, cells in diff.items():
            st = self._d._player(_s(p))
            for a, (dt, dw, dl) in cells.items():
                a = _s(a)
                if dt:
                    st.trials_m[a] = st.trials_m.get(a, 0.0) + dt
                if dw:
                    st.weight_m[a] = st.weight_m.get(a, 0.0) + dw
                if dl:
                    st.logw_m[a] = st.logw_m.get(a, 0.0) + dl
            st.trials_d.clear()
            st.weight_d.clear()
            st.logw_d.clear()
        return True
