"""Burst engine driver (Kleinberg burst detection over keyword streams).

API parity with the reference's burst service
(jubatus/server/server/burst.idl: add_documents / get_result /
get_result_at / get_all_bursted_results(_at) / get_all_keywords /
add_keyword / remove_keyword / remove_all_keywords / clear). Config from
/root/reference/config/burst/burst.json: parameter {window_batch_size,
batch_interval, max_reuse_batch_num, costcut_threshold,
result_window_rotate_size}; keywords carry (scaling_param, gamma).

Semantics (reconstructed from the jubatus_core burst package):

- A document is (pos, text). Batch index = floor(pos / batch_interval).
  Every document increments the batch's all_data_count; it increments a
  keyword's relevant_data_count when the text contains the keyword.
- A window is the ``window_batch_size`` consecutive batches ending at a
  position's batch; ``get_result`` uses the latest seen position.
- Burst weights come from Kleinberg's two-state automaton: base state
  emits at rate p0 = Σr/Σd over the window, burst state at
  p1 = min(1, p0 · scaling_param); per-batch emission cost is the negative
  binomial log-likelihood (constant term dropped); raising to the burst
  state costs ``gamma``. The optimal state sequence is found by Viterbi DP;
  a batch in the burst state reports weight = cost_0 − cost_1 (clipped at
  ``costcut_threshold`` when it is positive), else 0.
- Batches older than (result_window_rotate_size + 1) windows are pruned.

Distribution model (burst_serv.cpp:225-239, 264-290): documents are
BROADCAST to every replica (proxy routing, burst.idl), but each replica
PROCESSES only the keywords its CHT(2) placement assigns to it —
keyword memory and per-document matching cost scale with cluster size.
The server wires the assignment via ``set_assignment`` and re-hashes on
membership change (suicide-watcher-style child watcher); ``reassign``
drops counts for keywords that moved away, and a newly assigned replica
back-fills from its peer at the next mix. Because the two owners of a
keyword count the SAME broadcast documents, the mix is an elementwise
MAX of count totals (a semilattice merge, matching the reference's
keep-the-larger-window mixable), not a sum of deltas — so distributed
ingest must flow through the broadcast route. The DP itself is a few
dozen scalar ops per query (no MXU work), so it runs on host.
"""

from __future__ import annotations

import json
import math
from typing import Any, Dict, List, Optional, Tuple

from jubatus_tpu.framework.driver import DriverBase, locked


class BurstConfigError(ValueError):
    pass


class BurstDriver(DriverBase):
    TYPE = "burst"

    def __init__(self, config: dict):
        super().__init__()
        self.config = config
        self.config_json = json.dumps(config)
        param = dict(config.get("parameter") or {})
        self.window_batch_size = int(param.get("window_batch_size", 5))
        self.batch_interval = float(param.get("batch_interval", 10))
        # accepted for config parity; the reference reuses previous windows'
        # DP results for speed — our DP is recomputed per query (it is a few
        # dozen scalar ops), so there is nothing to reuse
        self.max_reuse_batch_num = int(param.get("max_reuse_batch_num", 5))
        self.costcut_threshold = float(param.get("costcut_threshold", -1))
        self.result_window_rotate_size = int(
            param.get("result_window_rotate_size", 5))
        if self.window_batch_size <= 0 or self.batch_interval <= 0:
            raise BurstConfigError(
                "window_batch_size and batch_interval must be positive")
        self._init_model()

    def _init_model(self) -> None:
        # keyword -> (scaling_param, gamma)
        self.keywords: Dict[str, Tuple[float, float]] = {}
        # master + since-last-mix diff counters
        self._all_m: Dict[int, int] = {}     # batch -> all_data_count
        self._all_d: Dict[int, int] = {}
        self._rel_m: Dict[str, Dict[int, int]] = {}
        self._rel_d: Dict[str, Dict[int, int]] = {}
        self._max_batch: Optional[int] = None
        #: keyword -> am-I-assigned predicate; None = standalone (process
        #: every keyword). Set by the server from CHT placement.
        self._assigned = None
        self._assigned_cache: Dict[str, bool] = {}

    # -- keyword partitioning (burst_serv.cpp:86-102, 225-239) ---------------
    def set_assignment(self, assigned) -> None:
        """Install (or update) the CHT assignment predicate and drop
        counts for keywords that are no longer mine — the re-hash step of
        a membership change. Registry entries stay (the keyword list is
        cluster-global); only count state is partitioned."""
        with self.lock:
            self._assigned = assigned
            self._assigned_cache = {}
            if assigned is None:
                return
            for kw in self.keywords:
                if not self._is_assigned(kw):
                    self._rel_m[kw] = {}
                    self._rel_d[kw] = {}

    def _is_assigned(self, kw: str) -> bool:
        """Memoized per keyword: the predicate is a CHT ring walk (md5 +
        bisect) and add_documents asks it per (document x keyword) — the
        cache is cleared whenever the assignment changes."""
        if self._assigned is None:
            return True
        hit = self._assigned_cache.get(kw)
        if hit is None:
            hit = self._assigned_cache[kw] = bool(self._assigned(kw))
        return hit

    # -- keyword registry -------------------------------------------------------
    @locked
    def add_keyword(self, keyword: str, scaling_param: float,
                    gamma: float) -> bool:
        if keyword in self.keywords:
            return False
        if scaling_param <= 1.0:
            raise BurstConfigError("scaling_param must be > 1")
        if gamma <= 0.0:
            raise BurstConfigError("gamma must be positive")
        self.keywords[keyword] = (float(scaling_param), float(gamma))
        self._rel_m.setdefault(keyword, {})
        self._rel_d.setdefault(keyword, {})
        return True

    @locked
    def remove_keyword(self, keyword: str) -> bool:
        if keyword not in self.keywords:
            return False
        del self.keywords[keyword]
        self._rel_m.pop(keyword, None)
        self._rel_d.pop(keyword, None)
        return True

    @locked
    def remove_all_keywords(self) -> bool:
        self.keywords.clear()
        self._rel_m.clear()
        self._rel_d.clear()
        return True

    @locked
    def get_all_keywords(self) -> List[Dict[str, float]]:
        return [{"keyword": kw, "scaling_param": s, "gamma": g}
                for kw, (s, g) in self.keywords.items()]

    # -- ingest -----------------------------------------------------------------
    @locked
    def add_documents(self, documents: List[Tuple[float, str]]) -> int:
        n = 0
        for pos, text in documents:
            b = int(math.floor(float(pos) / self.batch_interval))
            self._all_d[b] = self._all_d.get(b, 0) + 1
            for kw in self.keywords:
                if self._is_assigned(kw) and kw in text:
                    rel = self._rel_d[kw]
                    rel[b] = rel.get(b, 0) + 1
            if self._max_batch is None or b > self._max_batch:
                self._max_batch = b
            n += 1
        if n:
            self._prune()
            self.event_model_updated(n)
        return n

    def _prune(self) -> None:
        if self._max_batch is None:
            return
        horizon = self._max_batch - self.window_batch_size * (
            self.result_window_rotate_size + 1)
        for d in [self._all_m, self._all_d,
                  *self._rel_m.values(), *self._rel_d.values()]:
            for b in [b for b in d if b < horizon]:
                del d[b]

    # -- burst math -------------------------------------------------------------
    def _counts(self, kw: str, b: int) -> Tuple[int, int]:
        d = self._all_m.get(b, 0) + self._all_d.get(b, 0)
        r = self._rel_m.get(kw, {}).get(b, 0) + self._rel_d.get(kw, {}).get(b, 0)
        return d, r

    @staticmethod
    def _emission_cost(r: int, d: int, p: float) -> float:
        if d == 0:
            return 0.0
        p = min(max(p, 1e-9), 1.0 - 1e-9)
        return -(r * math.log(p) + (d - r) * math.log(1.0 - p))

    def _window(self, kw: str, end_batch: int) -> Dict[str, Any]:
        w = self.window_batch_size
        batches = list(range(end_batch - w + 1, end_batch + 1))
        counts = [self._counts(kw, b) for b in batches]
        total_d = sum(d for d, _ in counts)
        total_r = sum(r for _, r in counts)
        scaling, gamma = self.keywords[kw]
        weights = [0.0] * w
        if total_d > 0 and total_r > 0:
            p0 = total_r / total_d
            p1 = min(1.0 - 1e-9, p0 * scaling)
            # Viterbi over states {0: base, 1: burst}; up-transition costs gamma
            c0, c1 = 0.0, gamma
            back: List[Tuple[int, int]] = []
            for d, r in counts:
                e0 = self._emission_cost(r, d, p0)
                e1 = self._emission_cost(r, d, p1)
                n0, b0 = (c0, 0) if c0 <= c1 else (c1, 1)
                n1, b1 = (c0 + gamma, 0) if c0 + gamma < c1 else (c1, 1)
                back.append((b0, b1))
                c0, c1 = n0 + e0, n1 + e1
            state = 0 if c0 <= c1 else 1
            states = [0] * w
            for i in range(w - 1, -1, -1):
                states[i] = state
                state = back[i][state]
            for i, ((d, r), s) in enumerate(zip(counts, states)):
                if s == 1:
                    save = self._emission_cost(r, d, p0) - \
                        self._emission_cost(r, d, p1)
                    if self.costcut_threshold > 0:
                        save = min(save, self.costcut_threshold)
                    weights[i] = max(save, 0.0)
        return {
            "start_pos": (end_batch - w + 1) * self.batch_interval,
            "batches": [
                {"all_data_count": d, "relevant_data_count": r,
                 "burst_weight": weights[i]}
                for i, (d, r) in enumerate(counts)
            ],
        }

    # -- queries ----------------------------------------------------------------
    def _end_batch(self, pos: Optional[float] = None) -> Optional[int]:
        if pos is not None:
            return int(math.floor(float(pos) / self.batch_interval))
        return self._max_batch

    @locked
    def get_result(self, keyword: str) -> Dict[str, Any]:
        return self.get_result_at(keyword, None)

    @locked
    def get_result_at(self, keyword: str, pos: Optional[float]) -> Dict[str, Any]:
        if keyword not in self.keywords:
            raise KeyError(f"unknown keyword {keyword!r}")
        end = self._end_batch(pos)
        if end is None:
            return {"start_pos": 0.0, "batches": []}
        return self._window(keyword, end)

    def _all_results(self, pos: Optional[float]) -> Dict[str, Dict[str, Any]]:
        end = self._end_batch(pos)
        if end is None:
            return {}
        out = {}
        for kw in self.keywords:
            win = self._window(kw, end)
            if any(b["burst_weight"] > 0 for b in win["batches"]):
                out[kw] = win
        return out

    @locked
    def get_all_bursted_results(self) -> Dict[str, Dict[str, Any]]:
        return self._all_results(None)

    @locked
    def get_all_bursted_results_at(self, pos: float) -> Dict[str, Dict[str, Any]]:
        return self._all_results(pos)

    @locked
    def clear(self) -> None:
        self._init_model()
        self.update_count = 0

    # -- mix plane ---------------------------------------------------------------
    def get_mixables(self):
        return {"burst": _BurstMixable(self)}

    # -- persistence ---------------------------------------------------------------
    @locked
    def pack(self) -> Any:
        return {
            "keywords": {kw: list(sg) for kw, sg in self.keywords.items()},
            "all": {b: self._all_m.get(b, 0) + self._all_d.get(b, 0)
                    for b in set(self._all_m) | set(self._all_d)},
            "rel": {kw: {b: self._rel_m.get(kw, {}).get(b, 0) +
                         self._rel_d.get(kw, {}).get(b, 0)
                         for b in set(self._rel_m.get(kw, {})) |
                         set(self._rel_d.get(kw, {}))}
                    for kw in self.keywords},
            "max_batch": self._max_batch,
        }

    @locked
    def unpack(self, obj: Any) -> None:
        def _s(x):
            return x.decode() if isinstance(x, bytes) else x

        self._init_model()
        for kw, (s, g) in obj["keywords"].items():
            kw = _s(kw)
            self.keywords[kw] = (float(s), float(g))
            self._rel_m[kw] = {}
            self._rel_d[kw] = {}
        self._all_m = {int(b): int(c) for b, c in obj["all"].items()}
        for kw, batches in obj["rel"].items():
            self._rel_m[_s(kw)] = {int(b): int(c) for b, c in batches.items()}
        mb = obj.get("max_batch")
        self._max_batch = int(mb) if mb is not None else None

    @locked
    def get_status(self) -> Dict[str, Any]:
        st = super().get_status()
        st.update(num_keywords=len(self.keywords),
                  window_batch_size=self.window_batch_size)
        return st


class _BurstMixable:
    """(keyword, batch) count TOTALS merged by elementwise max.

    Documents are broadcast, so a keyword's two CHT owners hold duplicate
    counts — max is the correct replica merge (the reference's mixable
    keeps the window with more data, mixable_burst semantics). Max over
    totals is also idempotent and order-insensitive, which makes the fold
    safe under retries and partial rounds. A replica newly assigned a
    keyword (membership change) back-fills here: its zero counts max with
    the surviving owner's totals."""

    def __init__(self, driver: BurstDriver):
        self._d = driver

    def get_diff(self):
        d = self._d
        rel = {}
        for kw in d.keywords:
            if not d._is_assigned(kw):
                continue
            tot = {b: d._rel_m.get(kw, {}).get(b, 0) +
                   d._rel_d.get(kw, {}).get(b, 0)
                   for b in set(d._rel_m.get(kw, {})) |
                   set(d._rel_d.get(kw, {}))}
            if tot:
                rel[kw] = tot
        all_tot = {b: d._all_m.get(b, 0) + d._all_d.get(b, 0)
                   for b in set(d._all_m) | set(d._all_d)}
        return {"all": all_tot, "rel": rel, "max_batch": d._max_batch}

    @staticmethod
    def mix(acc, diff):
        for b, c in diff["all"].items():
            acc["all"][b] = max(acc["all"].get(b, 0), c)
        for kw, bs in diff["rel"].items():
            mine = acc["rel"].setdefault(kw, {})
            for b, c in bs.items():
                mine[b] = max(mine.get(b, 0), c)
        if diff["max_batch"] is not None and (
                acc["max_batch"] is None or diff["max_batch"] > acc["max_batch"]):
            acc["max_batch"] = diff["max_batch"]
        return acc

    def put_diff(self, diff) -> bool:
        def _s(x):
            return x.decode() if isinstance(x, bytes) else x

        d = self._d
        for b, c in diff["all"].items():
            b = int(b)
            local = d._all_m.get(b, 0) + d._all_d.get(b, 0)
            d._all_m[b] = max(local, int(c))
        for kw, bs in diff["rel"].items():
            kw = _s(kw)
            if kw not in d.keywords or not d._is_assigned(kw):
                continue  # removed locally, or not my partition to hold
            mine_m = d._rel_m.setdefault(kw, {})
            mine_d = d._rel_d.get(kw, {})
            for b, c in bs.items():
                b = int(b)
                local = mine_m.get(b, 0) + mine_d.get(b, 0)
                mine_m[b] = max(local, int(c))
        mb = diff.get("max_batch")
        if mb is not None and (d._max_batch is None or mb > d._max_batch):
            d._max_batch = int(mb)
        d._all_d.clear()
        for bs in d._rel_d.values():
            bs.clear()
        d._prune()
        return True
