"""Classifier engine driver.

Business API parity with the reference's classifier service
(jubatus/server/server/classifier.idl: train / classify / get_labels /
set_label / delete_label / clear; server logic classifier_serv.cpp:90-146):

- unseen labels are auto-registered on train
- get_labels returns {label: trained_count}
- classify returns per-datum (label, score) for every live label

TPU design: labels are rows of dense [L, D] arrays (ops/classifier.py);
the vocabulary is host metadata. Before a mix, replicas align vocabularies
via sync_schema (sorted union + row permutation) so array diffs psum exactly
(parallel/mix.py). Label train-counts ride the same diff as a dense [L]
array.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.core.fv import make_fv_converter
from jubatus_tpu.core.sparse import _bucket
from jubatus_tpu.framework.driver import DriverBase, locked
from jubatus_tpu.models.classifier_nn import NN_METHODS as _NN_METHODS
from jubatus_tpu.ops import classifier as ops

_LINEAR_METHODS = set(ops.METHODS)
_INITIAL_CAPACITY = 8


class ClassifierConfigError(ValueError):
    pass


class ClassifierDriver(DriverBase):
    TYPE = "classifier"

    def __init__(self, config: dict, dim_bits: int = 18,
                 train_mode: str = "parallel", mesh=None,
                 mesh_axis: str = "shard", shard_features: int = 0):
        super().__init__()
        self.config = config
        self.config_json = json.dumps(config)
        # "parallel" = vectorized microbatch (TPU hot path); "sequential" =
        # exact per-datum reference semantics (ops/classifier.py).
        self.train_mode = train_mode
        # mesh: shard the feature dimension of every [L, D] table over the
        # mesh axis — ONE server exploits all its local chips. The hot
        # train/classify paths run as shard_map programs
        # (parallel/sharded_model.py): the CSR batch is column-range
        # partitioned to the owning shard, one psum reduces the [B, L]
        # logits, and the weight matrix is never gathered. The schema/
        # combo plans keep GSPMD partitioning of the placed state.
        # Orthogonal to cross-server data parallelism via the mix plane
        # (parallel/spmd.py stacks both for the pod path).
        method = config.get("method")
        if method in _NN_METHODS:
            # instance-based classifier over the NN engine — separate driver
            # path, built with ops/knn (models/classifier_nn.py when present).
            raise NotImplementedError(
                f"NN-based classifier method {method!r} handled by "
                "ClassifierNNDriver"
            )
        if method not in _LINEAR_METHODS:
            raise ClassifierConfigError(f"unknown classifier method {method!r}")
        self.method = method
        param = config.get("parameter") or {}
        self.param = float(param.get("regularization_weight", 1.0))
        self.converter = make_fv_converter(config.get("converter"), dim_bits=dim_bits)
        # --shard-features D_PER_SHARD: derive the shard count from the
        # per-device feature budget (the HBM-capacity lever)
        if shard_features and mesh is None:
            from jubatus_tpu.parallel.sharded_model import mesh_for_features

            mesh = mesh_for_features(self.converter.dim, shard_features,
                                     ClassifierConfigError)
        self._mesh = mesh
        self._mesh_axis = mesh_axis
        # sharding derives from the converter's dim, not the dim_bits
        # argument — a config-side "hash_max_size" overrides the latter
        self._sharding = None
        if mesh is not None:
            from jubatus_tpu.parallel.mesh import make_feature_sharding

            self._sharding = make_feature_sharding(
                mesh, mesh_axis, self.converter.hasher.dim_bits,
                ClassifierConfigError, rank=2)
        self._confidence = method in ops.CONFIDENCE_METHODS
        self._init_model()

    def _place(self, state: ops.ClassifierState) -> ops.ClassifierState:
        """Pin [L, D] leaves to the feature-sharded layout (no-op without
        a mesh; (1,1) placeholders stay replicated)."""
        if self._sharding is None:
            return state
        import jax

        def put(a):
            if a.ndim == 2 and a.shape[1] == self.converter.dim:
                return jax.device_put(a, self._sharding)
            return a

        return ops.ClassifierState(*(put(leaf) for leaf in state))

    def _init_model(self) -> None:
        self.labels: List[str] = []           # slot -> label name
        self.label_slots: Dict[str, int] = {}  # label name -> slot
        self.capacity = _INITIAL_CAPACITY
        self.state = self._place(
            ops.init_state(self.capacity, self.converter.dim, self._confidence))
        self.label_counts = np.zeros(self.capacity, dtype=np.float32)
        self._dcounts = np.zeros(self.capacity, dtype=np.float32)

    # -- label management ----------------------------------------------------
    def _mask(self) -> jnp.ndarray:
        m = np.zeros(self.capacity, dtype=bool)
        for s in self.label_slots.values():
            m[s] = True
        return jnp.asarray(m)

    def _ensure_label(self, label: str) -> int:
        slot = self.label_slots.get(label)
        if slot is not None:
            return slot
        # reuse a freed slot if any, else grow capacity
        used = set(self.label_slots.values())
        free = [s for s in range(self.capacity) if s not in used]
        if free:
            slot = free[0]
        else:
            self.capacity *= 2
            self.state = self._place(
                ops.grow_labels(self.state, self.capacity))
            self.label_counts = np.pad(self.label_counts, (0, self.capacity // 2))
            self._dcounts = np.pad(self._dcounts, (0, self.capacity // 2))
            slot = len(self.labels)
        if slot == len(self.labels):
            self.labels.append(label)
        else:
            self.labels[slot] = label
        self.label_slots[label] = slot
        return slot

    @locked
    def set_label(self, label: str) -> bool:
        if label in self.label_slots:
            return False
        self._ensure_label(label)
        return True

    @locked
    def delete_label(self, label: str) -> bool:
        """Remove a label locally. In a cluster this MUST be applied on every
        replica (the reference routes delete_label as #@broadcast,
        classifier.idl): a one-replica delete would be resurrected with a
        zeroed master by the next mix's schema union, leaving that replica's
        weights permanently offset from its peers."""
        slot = self.label_slots.pop(label, None)
        if slot is None:
            return False
        # zero the slot so a future reuse starts clean
        st = self.state
        self.state = ops.ClassifierState(
            w=st.w.at[slot].set(0.0),
            dw=st.dw.at[slot].set(0.0),
            prec=st.prec if st.prec.shape == (1, 1) else st.prec.at[slot].set(1.0),
            dprec=st.dprec if st.dprec.shape == (1, 1) else st.dprec.at[slot].set(0.0),
        )
        self.label_counts[slot] = 0.0
        self._dcounts[slot] = 0.0
        self.labels[slot] = ""
        return True

    @locked
    def get_labels(self) -> Dict[str, int]:
        return {
            lab: int(self.label_counts[slot] + self._dcounts[slot])
            for lab, slot in self.label_slots.items()
        }

    # -- train / classify ----------------------------------------------------
    def featurize_train(self, data: Sequence[Tuple[str, Datum]]):
        """Stage-1 host featurization for the pipelined microbatch
        (server/microbatch.py PipelinedCoalescer): batch-convert WITHOUT
        the driver lock — the WeightManager has its own lock for the
        batch idf observe — so the next batch featurizes while the
        device consumes the previous one. Returns the (labels, idx, val)
        triple ``train_hashed`` consumes."""
        labels = [label for label, _ in data]
        csr = self.converter.convert_batch(
            [datum for _, datum in data], update_weights=True)
        sb = csr.to_padded()
        return labels, sb.idx, sb.val

    def train(self, data: Sequence[Tuple[str, Datum]]) -> int:
        """Batch-native train: one convert_batch sweep (memoized
        tokenization, single hash pass, batch idf observe) into the
        pre-hashed device path — no per-datum SparseVector objects.
        Featurization runs unlocked; train_hashed takes the driver lock
        for the device step (batch_bucket row padding lives there)."""
        if not data:
            return 0
        labels, idx, val = self.featurize_train(data)
        return self.train_hashed(labels, idx, val)

    def _train_slots(self, slots: np.ndarray, idx: np.ndarray,
                     val: np.ndarray, b: int) -> int:
        """Shared pre-hashed dispatch tail: pow2 row bucketing (same shape
        buckets as the converter path), padding, and the device step. Both
        hashed entry points funnel here so their semantics cannot drift."""
        bsz = _bucket(b, 16)
        if bsz != b:
            idx = np.pad(idx, ((0, bsz - b), (0, 0)))
            val = np.pad(val, ((0, bsz - b), (0, 0)))
        slots_arr = np.zeros(bsz, dtype=np.int32)
        slots_arr[:b] = slots
        if self._mesh is not None and self.train_mode == "parallel":
            # shard_map path: batch routed by column range, one psum for
            # the logits — weight state never moves (ISSUE 13 tentpole)
            from jubatus_tpu.parallel import sharded_model as _sm

            self.state = _sm.train_batch(
                self._mesh, self.state, jnp.asarray(idx), jnp.asarray(val),
                jnp.asarray(slots_arr), self._mask(), self.param,
                method=self.method, axis=self._mesh_axis)
        else:
            # sequential mode keeps GSPMD partitioning of the placed state
            self.state = ops.train_batch(
                self.state,
                jnp.asarray(idx),
                jnp.asarray(val),
                jnp.asarray(slots_arr),
                self._mask(),
                self.param,
                method=self.method,
                mode=self.train_mode,
            )
        self.event_model_updated(b)
        return b

    @locked
    def train_hashed(self, labels: Sequence[str], idx: np.ndarray,
                     val: np.ndarray) -> int:
        """Train on pre-hashed features (the native ingest fast path,
        native/fast_ingest.cpp): ``idx``/``val`` are [B, K] arrays carrying
        exactly what converter.convert would have produced. Bypasses the
        converter entirely — callers must have established eligibility (no
        idf/user global weights; jubatus_tpu/native/ingest.py gates)."""
        if len(labels) == 0:
            return 0
        slots = [self._ensure_label(lb) for lb in labels]
        for s in slots:
            self._dcounts[s] += 1.0
        return self._train_slots(np.asarray(slots, dtype=np.int32),
                                 idx, val, len(labels))

    @locked
    def train_indexed(self, uniq_labels: Sequence[str], label_idx: np.ndarray,
                      idx: np.ndarray, val: np.ndarray) -> int:
        """Train on pre-hashed features with C++-deduplicated labels
        (native/fast_ingest.cpp): ``uniq_labels`` are the distinct label
        strings, ``label_idx`` the int32 [B] row->uniq mapping. The host
        loops only over the distinct set — vocabulary work is O(uniq),
        count bookkeeping is one bincount, so the GIL-bound cost per
        sample is constant regardless of batch size."""
        b = int(label_idx.shape[0])
        if b == 0:
            return 0
        slots_u = np.array([self._ensure_label(lb) for lb in uniq_labels],
                           dtype=np.int32)
        counts = np.bincount(label_idx, minlength=len(uniq_labels))
        # np.add.at, not fancy-index +=: the C++ parser MAY emit duplicate
        # uniq labels (past 256 distinct it appends without scanning), and
        # += keeps only the last write per duplicated slot
        np.add.at(self._dcounts, slots_u, counts[:len(slots_u)])
        return self._train_slots(slots_u[label_idx], idx, val, b)

    @locked
    def train_indexed_schema(self, uniq_labels: Sequence[str],
                             label_idx: np.ndarray, uidx: np.ndarray,
                             val: np.ndarray) -> int:
        """train_indexed for a UNIFORM-SCHEMA batch: every example shares
        the hashed index vector ``uidx`` [K] (a fixed key schema — the
        common production feed; the serving flush detects it). Runs the
        dense [L, K]-submatrix plan (ops.train_batch_schema): K-descriptor
        index ops + matmuls instead of B*K-element gathers/scatters —
        the addressing-floor term (docs/PERF_NOTES.md) drops out
        entirely. Falls back to the sparse plan under sequential train
        mode, where exact per-datum semantics take priority."""
        b = int(label_idx.shape[0])
        if b == 0:
            return 0
        slots_u = np.array([self._ensure_label(lb) for lb in uniq_labels],
                           dtype=np.int32)
        counts = np.bincount(label_idx, minlength=len(uniq_labels))
        np.add.at(self._dcounts, slots_u, counts[:len(slots_u)])
        slots = slots_u[label_idx]
        if self.train_mode != "parallel":
            return self._train_slots(
                slots, np.broadcast_to(uidx, (b, uidx.shape[0])), val, b)
        bsz = _bucket(b, 16)
        if bsz != b:  # zero rows are no-ops (x2 = 0 → alpha 0)
            val = np.pad(val, ((0, bsz - b), (0, 0)))
            slots = np.pad(slots, (0, bsz - b))
        self.state = ops.train_batch_schema(
            self.state,
            jnp.asarray(uidx),
            jnp.asarray(val),
            jnp.asarray(slots),
            self._mask(),
            self.param,
            method=self.method,
        )
        self.event_model_updated(b)
        return b

    @locked
    def train_indexed_combo(self, uniq_labels: Sequence[str],
                            label_idx: np.ndarray, uidx: np.ndarray,
                            base_val: np.ndarray, a_idx: np.ndarray,
                            b_idx: np.ndarray, mul_mask: np.ndarray) -> int:
        """train_indexed_schema with DEVICE-SIDE combination expansion:
        ``uidx`` is the full base+slot index vector ([K0+S], no duplicate
        indices — the plan builder guarantees it), ``base_val`` only the
        [B, K0] base columns. The cross product's slot values are
        computed on device (ops._expand_combo), so neither the host
        parse nor the wire ever carries the (K0+S)-wide row — the combo
        serving cliff was upload-bound, not compute-bound."""
        b = int(label_idx.shape[0])
        if b == 0:
            return 0
        slots_u = np.array([self._ensure_label(lb) for lb in uniq_labels],
                           dtype=np.int32)
        counts = np.bincount(label_idx, minlength=len(uniq_labels))
        np.add.at(self._dcounts, slots_u, counts[:len(slots_u)])
        slots = slots_u[label_idx]
        k0 = base_val.shape[1]
        if self.train_mode != "parallel":
            # sequential mode: exact per-datum semantics take priority —
            # expand on host and ride the sparse scan path
            full = _expand_combo_host(base_val, a_idx, b_idx, mul_mask)
            return self._train_slots(
                slots, np.broadcast_to(uidx, (b, uidx.shape[0])), full, b)
        bsz = _bucket(b, 16)
        if bsz != b:  # zero base rows expand to zero slots — still no-ops
            base_val = np.pad(base_val, ((0, bsz - b), (0, 0)))
            slots = np.pad(slots, (0, bsz - b))
        self.state = ops.train_batch_schema_combo(
            self.state,
            jnp.asarray(uidx),
            jnp.asarray(base_val),
            jnp.asarray(a_idx),
            jnp.asarray(b_idx),
            jnp.asarray(mul_mask),
            jnp.asarray(slots),
            self._mask(),
            self.param,
            method=self.method,
        )
        self.event_model_updated(b)
        return b

    def classify_hashed_combo(self, uidx: np.ndarray, base_val: np.ndarray,
                              a_idx: np.ndarray, b_idx: np.ndarray,
                              mul_mask: np.ndarray
                              ) -> List[List[Tuple[str, float]]]:
        """classify_hashed_schema with device-side combo expansion —
        same lock discipline (enqueue under the lock, wait unlocked)."""
        n = base_val.shape[0]
        if n == 0:
            return []
        b = _bucket(n, 16)
        if b != n:
            base_val = np.pad(base_val, ((0, b - n), (0, 0)))
        duidx, dval = jnp.asarray(uidx), jnp.asarray(base_val)
        da, db = jnp.asarray(a_idx), jnp.asarray(b_idx)
        dm = jnp.asarray(mul_mask)
        with self.lock:
            if not self.label_slots:
                return [[] for _ in range(n)]
            slots = list(self.label_slots.items())
            pending = ops.scores_schema_combo(
                self.state, duidx, dval, da, db, dm, self._mask())
        sc = np.asarray(pending)[:n]
        return [[(lab, float(row[slot]))
                 for lab, slot in slots] for row in sc]

    def classify_hashed_schema(self, uidx: np.ndarray,
                               val: np.ndarray) -> List[List[Tuple[str, float]]]:
        """classify_hashed for a uniform-schema batch (ops.scores_schema:
        K descriptors + one matmul). Same lock discipline as
        classify_hashed: enqueue under the lock, wait unlocked."""
        n = val.shape[0]
        if n == 0:
            return []
        b = _bucket(n, 16)
        if b != n:
            val = np.pad(val, ((0, b - n), (0, 0)))
        duidx, dval = jnp.asarray(uidx), jnp.asarray(val)
        with self.lock:
            if not self.label_slots:
                return [[] for _ in range(n)]
            slots = list(self.label_slots.items())
            pending = ops.scores_schema(self.state, duidx, dval, self._mask())
        sc = np.asarray(pending)[:n]
        return [[(lab, float(row[slot]))
                 for lab, slot in slots] for row in sc]

    def classify(self, data: Sequence[Datum]) -> List[List[Tuple[str, float]]]:
        # deliberately NOT @locked: batch conversion touches no driver
        # state and classify_hashed takes the lock for exactly the
        # dispatch window — concurrent Datum-path queries overlap too
        if not data:
            return []
        sb = self.converter.convert_batch(data).to_padded(batch_bucket=16)
        out = self.classify_hashed(sb.idx, sb.val)
        if not out:
            return [[] for _ in data]
        # to_padded already row-bucketed; slice its pad rows back off
        return out[: len(data)]

    def classify_hashed(self, idx: np.ndarray,
                        val: np.ndarray) -> List[List[Tuple[str, float]]]:
        """Classify pre-hashed features (native ingest fast path); same
        output shape as classify().

        Dispatch-under-lock, wait-unlocked: the scores computation is
        ENQUEUED while the driver lock guarantees no train step can
        donate the state buffers first (train_batch donates for in-place
        scatters — dispatching against an already-donated Array raises
        "Array has been deleted"); once enqueued, the runtime keeps the
        buffers alive for the pending read, so the device round trip and
        result wait run unlocked and concurrent queries overlap instead
        of serializing. ≙ the reference's JRLOCK_ shared reads."""
        n = idx.shape[0]
        if n == 0:
            return []
        b = _bucket(n, 16)
        if b != n:
            idx = np.pad(idx, ((0, b - n), (0, 0)))
            val = np.pad(val, ((0, b - n), (0, 0)))
        # H2D transfers touch no driver state: stage them unlocked so the
        # critical section is just the enqueue
        didx, dval = jnp.asarray(idx), jnp.asarray(val)
        with self.lock:
            if not self.label_slots:
                return [[] for _ in range(n)]
            slots = list(self.label_slots.items())
            if self._mesh is not None:
                from jubatus_tpu.parallel import sharded_model as _sm

                pending = _sm.scores(self._mesh, self.state, didx, dval,
                                     self._mask(), axis=self._mesh_axis)
            else:
                pending = ops.scores(self.state, didx, dval, self._mask())
        sc = np.asarray(pending)[:n]
        return [[(lab, float(row[slot]))
                 for lab, slot in slots] for row in sc]

    def shard_stats(self) -> Dict[str, Any]:
        """Feature-shard layout gauges (shard.* catalog rows,
        OBSERVABILITY.md §7): shard count + per-device weight-state
        bytes. Empty when unsharded."""
        if self._mesh is None:
            return {}
        n = self._mesh.shape[self._mesh_axis]
        total = sum(int(a.nbytes) for a in self.state)
        return {"count": n, "rows": self.capacity,
                "bytes_in_use": total,
                "bytes_per_shard": total // n}

    @locked
    def clear(self) -> None:
        self._init_model()
        self.converter.weights.clear()
        self.update_count = 0

    # -- mix plane -----------------------------------------------------------
    def get_schema(self) -> List[str]:
        return sorted(self.label_slots.keys())

    def sync_schema(self, union_schema: List[str]) -> None:
        """Realign label slots to the canonical (sorted union) vocabulary.

        After this, slot i holds union_schema[i] on every replica, so array
        diffs are row-aligned for the psum.

        Runs on EVERY mix prepare, so the already-aligned case (no new
        labels since the last round — every steady-state round) must be
        free: realigning unconditionally would drag all four
        [capacity, D] tables through host numpy each round (~2 GB of
        device→host→device traffic per member at D=2^24). When the
        slots DO move, rows are permuted on-device with a gather instead
        of round-tripping through the host.
        """
        new_cap = max(_INITIAL_CAPACITY, _next_pow2(len(union_schema)))
        target_slots = {lab: i for i, lab in enumerate(union_schema)}
        if new_cap == self.capacity and target_slots == self.label_slots:
            return  # already canonical — the steady-state mix round
        perm = np.full(new_cap, -1, dtype=np.int64)  # new slot -> old slot
        for new_slot, label in enumerate(union_schema):
            old = self.label_slots.get(label)
            if old is not None:
                perm[new_slot] = old
        live_h = perm >= 0
        gather = jnp.asarray(np.where(live_h, perm, 0).astype(np.int32))
        live_d = jnp.asarray(live_h)[:, None]

        def take_rows(a, fill):
            if a.shape == (1, 1):
                return a
            # device-side row permute: one gather + select, no host copy
            return jnp.where(live_d, a[gather], jnp.asarray(fill, a.dtype))

        st = self.state
        self.state = self._place(ops.ClassifierState(
            w=take_rows(st.w, 0.0),
            dw=take_rows(st.dw, 0.0),
            prec=take_rows(st.prec, 1.0),
            dprec=take_rows(st.dprec, 0.0),
        ))

        def take_vec(v):
            out = np.zeros(new_cap, dtype=v.dtype)
            live = perm >= 0
            out[live] = v[perm[live]]
            return out

        self.label_counts = take_vec(self.label_counts)
        self._dcounts = take_vec(self._dcounts)
        self.capacity = new_cap
        self.labels = list(union_schema) + [""] * (new_cap - len(union_schema))
        self.label_slots = {lab: i for i, lab in enumerate(union_schema)}

    def get_mixables(self):
        return {"classifier": _ClassifierMixable(self), "weights": self.converter.weights}

    # -- persistence ---------------------------------------------------------
    @locked
    def pack(self) -> Any:
        return {
            "method": self.method,
            "dim": self.converter.dim,
            "labels": self.labels,
            "capacity": self.capacity,
            "w": np.asarray(self.state.w + self.state.dw),
            "prec": np.asarray(self.state.prec + self.state.dprec),
            "label_counts": self.label_counts + self._dcounts,
            "weights": self.converter.weights.pack(),
        }

    @locked
    def unpack(self, obj: Any) -> None:
        saved_method = obj.get("method")
        if isinstance(saved_method, bytes):
            saved_method = saved_method.decode()
        if saved_method != self.method:
            raise ValueError(
                f"checkpoint method {saved_method!r} != driver method {self.method!r}"
            )
        if int(obj.get("dim", self.converter.dim)) != self.converter.dim:
            raise ValueError(
                f"checkpoint feature dim {obj['dim']} != driver dim "
                f"{self.converter.dim} (dim_bits mismatch)"
            )
        self.capacity = int(obj["capacity"])
        self.labels = [
            s.decode() if isinstance(s, bytes) else s for s in obj["labels"]
        ]
        self.label_slots = {lab: i for i, lab in enumerate(self.labels) if lab}
        w = jnp.asarray(obj["w"])
        prec = jnp.asarray(obj["prec"])
        self.state = self._place(ops.ClassifierState(
            w=w, dw=jnp.zeros_like(w), prec=prec, dprec=jnp.zeros_like(prec)
        ))
        self.label_counts = np.asarray(obj["label_counts"], dtype=np.float32).copy()
        self._dcounts = np.zeros_like(self.label_counts)
        self.converter.weights.unpack(obj["weights"])

    @locked
    def get_status(self) -> Dict[str, Any]:
        st = super().get_status()
        st.update(
            method=self.method,
            num_labels=len(self.label_slots),
            num_features=self.converter.dim,
        )
        st.update({f"shard.{k}": v for k, v in self.shard_stats().items()})
        return st


class _ClassifierMixable:
    """Wraps the ops-level diff with the label-count vector."""

    def __init__(self, driver: ClassifierDriver):
        self._d = driver

    def get_diff(self):
        d = self._d
        diff = ops.get_diff(d.state)
        # ship only the ACTIVE label rows: the [capacity, D] tables are
        # pow2-padded (and capacities can diverge across replicas after
        # deletes), while the slot assignment is cluster-identical after
        # the round's schema sync — [n, D] is the same shape everywhere
        # and cuts the wire 4x at the bench shape (8-slot capacity, 2
        # labels). n = highest slot in use + 1, NOT len(labels): the
        # labels list is ""-padded to capacity by sync_schema. Slicing
        # clamps the (1, 1) no-confidence placeholders untouched.
        n = max(d.label_slots.values(), default=0) + 1
        if d._mesh is not None:
            # feature-sharded state ships PER-SHARD chunks keyed by start
            # column: each shard's slice copies out independently (no
            # full-matrix buffer) and enters the chunked/tiered/quantized
            # mix pipeline on its own. Peers fold chunk-wise — layouts
            # must match (assemble_chunks validates on apply).
            from jubatus_tpu.parallel import sharded_model as _sm

            chunked = {}
            for key in ("dw", "dprec"):
                a = diff[key]
                if a.ndim == 2 and a.shape[1] == d.converter.dim:
                    chunked[key] = _sm.shard_chunks(a, rows=n)
            diff = dict(diff, **chunked)
        elif n < diff["dw"].shape[0]:
            diff = dict(diff, dw=diff["dw"][:n], dprec=diff["dprec"][:n])
        diff["label_counts"] = d._dcounts[:n].copy()
        return diff

    def put_diff(self, diff) -> bool:
        d = self._d
        # the same reduced diff dict is applied to every replica — no mutation
        array_diff = {k: v for k, v in diff.items() if k != "label_counts"}
        array_diff = _assemble_sharded(d, array_diff, rank=2)
        d.state = ops.put_diff(d.state, array_diff)
        counts = diff.get("label_counts")
        if counts is not None:
            counts = np.asarray(counts)
            d.label_counts[:len(counts)] += counts
            d._dcounts[:] = 0.0
        return True


def _assemble_sharded(driver, array_diff: dict, rank: int) -> dict:
    """Reassemble per-shard wire chunks in a diff dict: back onto the
    receiving driver's shard devices when it is sharded (each chunk
    lands on its owner — no host concat of the full matrix), or into
    one host array when an unsharded replica receives a sharded peer's
    diff (mixed fleets stay correct, just not zero-copy)."""
    from jubatus_tpu.parallel import sharded_model as _sm

    out = dict(array_diff)
    for key, v in array_diff.items():
        if not _sm.is_chunked(v):
            continue
        if driver._mesh is not None:
            out[key] = _sm.assemble_chunks(
                v, _sm.chunk_sharding(driver._mesh, rank=rank,
                                      axis=driver._mesh_axis))
        else:
            items = sorted(
                ((int((k.decode() if isinstance(k, bytes) else k)[1:]), c)
                 for k, c in v.items()), key=lambda kv: kv[0])
            out[key] = np.concatenate([c for _, c in items], axis=-1)
    return out


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _expand_combo_host(base_val: np.ndarray, a_idx: np.ndarray,
                       b_idx: np.ndarray, mul_mask: np.ndarray) -> np.ndarray:
    """Host-side mirror of ops._expand_combo (sequential train mode)."""
    va = base_val[:, a_idx]
    vb = base_val[:, b_idx]
    slots = np.where(mul_mask[None, :], va * vb, va + vb)
    return np.concatenate([base_val, slots], axis=1).astype(np.float32)
