"""Instance-based classifiers: methods NN / cosine / euclidean
(config/classifier/{nn,cosine,euclidean}.json — the reference's
nearest_neighbor_classifier family, jubatus_core).

Instead of a linear weight table, the model is a store of labeled
examples; classify finds the ``nearest_neighbor_num`` closest stored
examples and votes per label with weight exp(-d / local_sensitivity),
where d is the backend's distance (1 - cosine similarity for cosine/NN
hash backends, euclidean distance for the euclid family). Smaller
``local_sensitivity`` → sharper voting. Scores are comparable across
labels (argmax = predicted class); the exact numeric scale is this
framework's definition, not bit-parity with the reference.

- method "NN": approximate search through a nested nearest_neighbor
  backend config {"method": "euclid_lsh"|"lsh"|"minhash", "parameter":
  {...}} — the TPU signature-scan path (ops/knn, pallas kernels).
- "cosine" / "euclidean": exact dense scans over the row table.

The label of each stored example rides in the row store's datum slot, so
row mixing, checkpointing, and LRU unlearning all carry labels for free.
Row ids are uuid4 — ids minted on different cluster nodes never collide
when diffs merge in a mix round.
"""

from __future__ import annotations

import json
import math
import uuid
from typing import Any, Dict, List, Tuple

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.core.fv import make_fv_converter
from jubatus_tpu.framework.driver import DriverBase, locked
from jubatus_tpu.models._nn_backend import NNBackend

NN_METHODS = ("NN", "cosine", "euclidean")


class ClassifierConfigError(ValueError):
    pass


def _as_label(x: Any) -> str:
    """Normalize a stored/wire label (bytes after msgpack round trips)."""
    return x.decode() if isinstance(x, bytes) else str(x)


class _LabelSetMixable:
    """Last-writer-wins label-state mix, so set_label / delete_label
    outcomes propagate between replicas even for labels with no examples
    (examples themselves travel in the row diff).

    The diff is the FULL ``{label: [epoch, alive]}`` state map — shipping
    full state is non-destructive (a failed exchange loses nothing) and
    transitively propagating (a peer re-ships what it learned). Conflicts
    resolve per label by highest epoch; on an epoch tie the tombstone
    (alive=False) wins, so a cluster-wide delete is never resurrected by
    an idle replica's old registration."""

    def __init__(self, driver: "ClassifierNNDriver") -> None:
        self._d = driver

    def get_diff(self):
        return {label: [int(e), bool(a)]
                for label, (e, a) in self._d._label_states.items()}

    @staticmethod
    def mix(acc, diff):
        out = {(_as_label(k)): [int(v[0]), bool(v[1])] for k, v in acc.items()}
        for k, v in diff.items():
            k = _as_label(k)
            e, a = int(v[0]), bool(v[1])
            cur = out.get(k)
            if cur is None or e > cur[0] or (e == cur[0] and not a):
                out[k] = [e, a]
        return out

    def put_diff(self, diff) -> bool:
        states = self._d._label_states
        for k, v in diff.items():
            k = _as_label(k)
            e, a = int(v[0]), bool(v[1])
            cur = states.get(k)
            if cur is None or e > cur[0] or (e == cur[0] and not a):
                states[k] = (e, a)
        self._d.registered = {k for k, (_e, a) in states.items() if a}
        self._d._invalidate_counts()
        return True


class _NNRowsMixable:
    """Row diff that also invalidates the driver's label-count cache when
    mixed-in rows land."""

    def __init__(self, driver: "ClassifierNNDriver") -> None:
        from jubatus_tpu.models.nearest_neighbor import _RowUpdateMixable

        self._inner = _RowUpdateMixable(driver.backend)
        self._d = driver

    def get_diff(self):
        return self._inner.get_diff()

    def mix(self, acc, diff):
        return self._inner.mix(acc, diff)

    def put_diff(self, diff) -> bool:
        ok = self._inner.put_diff(diff)
        self._d._invalidate_counts()
        return ok


class ClassifierNNDriver(DriverBase):
    TYPE = "classifier"

    def __init__(self, config: dict, dim_bits: int = 18):
        super().__init__()
        self.config = config
        self.config_json = json.dumps(config)
        method = config.get("method")
        if method not in NN_METHODS:
            raise ClassifierConfigError(
                f"unknown NN classifier method {method!r}")
        self.method = method
        param = dict(config.get("parameter") or {})
        self.k = int(param.get("nearest_neighbor_num", 16))
        self.local_sensitivity = float(param.get("local_sensitivity", 1.0))
        if self.k < 1:
            raise ClassifierConfigError("nearest_neighbor_num must be >= 1")
        if self.local_sensitivity <= 0:
            raise ClassifierConfigError("local_sensitivity must be positive")
        self.converter = make_fv_converter(config.get("converter"),
                                           dim_bits=dim_bits)
        if method == "NN":
            backend_method = param.get("method", "euclid_lsh")
            nn_param = dict(param.get("parameter") or {})
        else:
            backend_method = "inverted_index" if method == "cosine" else "euclid"
            nn_param = {}
        unl_param = param.get("unlearner_parameter") or {}
        self.backend = NNBackend(
            backend_method,
            dim=self.converter.dim,
            hash_num=int(nn_param.get("hash_num", 64)),
            seed=int(nn_param.get("seed", 0)),
            max_size=(int(unl_param["max_size"])
                      if param.get("unlearner") == "lru" else None),
            keep_datum=True,  # the datum slot holds the example's label
        )
        #: labels registered via set_label before any example arrived
        #: (derived view of _label_states, kept for fast membership tests)
        self.registered: set = set()
        #: label → (epoch, alive): the LWW state _LabelSetMixable mixes
        self._label_states: Dict[str, Tuple[int, bool]] = {}
        #: memoized label→example-count map; every mutation path (driver
        #: methods, mixable put_diff, LRU eviction inside those) invalidates
        self._counts_cache: Dict[str, int] = None  # type: ignore[assignment]

    def _invalidate_counts(self) -> None:
        self._counts_cache = None

    def _mark_label(self, label: str, alive: bool) -> None:
        epoch = max((e for e, _a in self._label_states.values()), default=0) + 1
        self._label_states[label] = (epoch, alive)
        if alive:
            self.registered.add(label)
        else:
            self.registered.discard(label)

    # -- training -------------------------------------------------------------
    @locked
    def train(self, data: List[Tuple[str, Datum]]) -> int:
        # batch featurization (one hash sweep + batch idf observe); the
        # backend row inserts stay per-row — that is the row store's API
        csr = self.converter.convert_batch(
            [datum for _, datum in data], update_weights=True)
        for i, (label, _datum) in enumerate(data):
            self.backend.set_row(uuid.uuid4().hex, csr.row(i),
                                 datum=str(label))
            if str(label) not in self.registered:
                self._mark_label(str(label), True)
        self._invalidate_counts()
        self.event_model_updated(len(data))
        return len(data)

    # -- classification -------------------------------------------------------
    def _label_counts(self) -> Dict[str, int]:
        if self._counts_cache is None:
            counts = {label: 0 for label in self.registered}
            for label in self.backend.store.datums.values():
                label = _as_label(label)
                counts[label] = counts.get(label, 0) + 1
            self._counts_cache = counts
        return self._counts_cache

    @locked
    def classify(self, data: List[Datum]) -> List[List[Tuple[str, float]]]:
        labels = sorted(self._label_counts())
        csr = self.converter.convert_batch(list(data))
        out: List[List[Tuple[str, float]]] = []
        for i, _datum in enumerate(data):
            scores = {label: 0.0 for label in labels}
            vec = csr.row(i)
            for rid, dist in self.backend.neighbors(vec, self.k):
                label = self.backend.store.datums.get(rid)
                if label is None:
                    continue
                w = math.exp(-float(dist) / self.local_sensitivity)
                label = _as_label(label)
                scores[label] = scores.get(label, 0.0) + w
            out.append(sorted(scores.items()))
        return out

    # -- label management (classifier.idl get/set/delete_label) ---------------
    @locked
    def get_labels(self) -> Dict[str, int]:
        return dict(self._label_counts())

    @locked
    def set_label(self, label: str) -> bool:
        if label in self._label_counts():
            return False
        self._mark_label(str(label), True)
        self._invalidate_counts()
        self.event_model_updated()
        return True

    @locked
    def delete_label(self, label: str) -> bool:
        """Deletes are cluster-wide only through proxy broadcast (the
        reference's #@broadcast routing); a single-replica delete is
        resurrected by peers' row diffs, by design."""
        if label not in self._label_counts():
            return False
        doomed = [rid for rid, lab in list(self.backend.store.datums.items())
                  if _as_label(lab) == label]
        for rid in doomed:
            self.backend.remove_row(rid)
        self._mark_label(label, False)  # tombstone: survives future mixes
        self._invalidate_counts()
        self.event_model_updated()
        return True

    @locked
    def clear(self) -> None:
        self.backend.clear()
        self.registered.clear()
        self._label_states.clear()
        self._invalidate_counts()
        self.converter.weights.clear()
        self.update_count = 0

    # -- mix plane ------------------------------------------------------------
    def get_mixables(self):
        return {"rows": _NNRowsMixable(self),
                "labels": _LabelSetMixable(self),
                "weights": self.converter.weights}

    # -- persistence ----------------------------------------------------------
    @locked
    def pack(self) -> Any:
        return {"method": self.method,
                "backend": self.backend.pack(),
                "label_states": {k: [e, a] for k, (e, a)
                                 in self._label_states.items()},
                "weights": self.converter.weights.pack()}

    @locked
    def unpack(self, obj: Any) -> None:
        saved = obj.get("method")
        if isinstance(saved, bytes):
            saved = saved.decode()
        if saved != self.method:
            raise ValueError(
                f"checkpoint method {saved!r} != driver method {self.method!r}")
        self.backend.unpack(obj["backend"], datum_decoder=_as_label)
        self._label_states = {
            _as_label(k): (int(v[0]), bool(v[1]))
            for k, v in (obj.get("label_states") or {}).items()
        }
        # checkpoints from before the LWW state map carried a plain list
        for r in obj.get("registered", []):
            self._label_states.setdefault(_as_label(r), (0, True))
        self.registered = {k for k, (_e, a) in self._label_states.items() if a}
        self._invalidate_counts()
        self.converter.weights.unpack(obj["weights"])

    @locked
    def get_status(self) -> Dict[str, Any]:
        st = super().get_status()
        st.update(method=self.method, num_examples=len(self.backend.store),
                  num_labels=len(self._label_counts()))
        return st
