"""Clustering engine driver.

API parity with the reference's clustering service
(jubatus/server/server/clustering.idl: push(indexed_point list) /
get_revision / get_core_members(_light) / get_k_center /
get_nearest_center / get_nearest_members(_light) / clear). Config from
/root/reference/config/clustering/*.json: method kmeans|gmm|dbscan,
parameter {k, seed} or {eps, min_core_point}, compressor_method
simple|compressive{,_bucket} with compressor_parameter {bucket_size, ...}.

Behavior (reconstructed from the jubatus_core clustering driver):

- push() buffers weighted points; every ``bucket_size`` pushed points the
  model re-clusters and ``revision`` increments. Queries serve the *last
  finished* clustering (snapshot semantics) — before the first full bucket,
  query methods raise ("not clustered yet" in the reference).
- ``simple`` compressor keeps every point; ``compressive`` caps the working
  set at compressed_bucket_size points via weighted reservoir-style
  downsampling (coreset approximation — each survivor carries the weight of
  the points it absorbed).
- get_core_members groups the working set by cluster as (weight, datum)
  pairs; *_light variants return (weight, id).
- get_nearest_center / get_nearest_members key off euclidean distance to
  the fitted centers (for dbscan, cluster centroids).

TPU design: the working set is compacted to a dense [N, d_active] matrix
over the bucket's distinct hashed features, then kmeans/gmm/dbscan run as
jitted dense kernels (ops/clustering.py) — one MXU matmul per Lloyd/EM
iteration instead of per-point scalar loops.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.core.fv import make_fv_converter
from jubatus_tpu.framework.driver import DriverBase, locked
from jubatus_tpu.ops import clustering as ops

METHODS = ("kmeans", "gmm", "dbscan")


class ClusteringConfigError(ValueError):
    pass


class NotClusteredError(RuntimeError):
    """Raised by query methods before the first clustering round."""


class ClusteringDriver(DriverBase):
    TYPE = "clustering"

    def __init__(self, config: dict, dim_bits: int = 18):
        super().__init__()
        self.config = config
        self.config_json = json.dumps(config)
        method = config.get("method")
        if method not in METHODS:
            raise ClusteringConfigError(f"unknown clustering method {method!r}")
        self.method = method
        param = dict(config.get("parameter") or {})
        self.k = int(param.get("k", 2))
        self.seed = int(param.get("seed", 0))
        self.eps = float(param.get("eps", 0.2))
        self.min_core_point = int(param.get("min_core_point", 2))
        self.compressor = config.get("compressor_method", "simple")
        cparam = dict(config.get("compressor_parameter") or {})
        self.bucket_size = int(cparam.get("bucket_size", 100))
        self.compressed_size = int(cparam.get("compressed_bucket_size",
                                              self.bucket_size * 4))
        self.converter = make_fv_converter(config.get("converter"),
                                           dim_bits=dim_bits)
        self._init_model()

    def _init_model(self) -> None:
        # working set: parallel lists (id, datum, sparse vec, weight)
        self._ids: List[str] = []
        self._id_pos: Dict[str, int] = {}  # id -> row in the parallel lists
        self._datums: List[Datum] = []
        self._vecs: List[list] = []
        self._weights: List[float] = []
        self._pending = 0
        self._mix_new_ids: List[str] = []
        self.revision = 0
        # snapshot of the last clustering
        self._centers: Optional[np.ndarray] = None   # [k, d_active]
        self._active_dims: Optional[np.ndarray] = None
        self._assign: Optional[np.ndarray] = None    # [N]
        self._members: List[List[int]] = []          # cluster -> working-set rows

    # -- update ----------------------------------------------------------------
    @locked
    def push(self, points: Sequence[Tuple[str, Datum]]) -> bool:
        # batch featurization: one hash sweep + one batch idf observe for
        # the whole push (core/fv convert_batch)
        csr = self.converter.convert_batch(
            [datum for _, datum in points], update_weights=True)
        for pos, (row_id, datum) in enumerate(points):
            vec = csr.row(pos)
            i = self._id_pos.get(row_id)
            if i is not None:
                self._datums[i], self._vecs[i] = datum, vec
            else:
                self._id_pos[row_id] = len(self._ids)
                self._ids.append(row_id)
                self._datums.append(datum)
                self._vecs.append(vec)
                self._weights.append(1.0)
                self._mix_new_ids.append(row_id)
            self._pending += 1
        self.event_model_updated(len(points))
        if self._pending >= self.bucket_size:
            # one fit serves however many buckets this push completed —
            # refitting per bucket over the same final working set would be
            # identical work repeated
            self._pending %= self.bucket_size
            self._recluster()
        return True

    def _compact(self) -> Tuple[np.ndarray, np.ndarray]:
        """Working set → dense [N, d_active] + the active dim index vector."""
        dims = sorted({i for vec in self._vecs for i, _ in vec})
        pos = {d: j for j, d in enumerate(dims)}
        x = np.zeros((len(self._vecs), max(len(dims), 1)), np.float32)
        for r, vec in enumerate(self._vecs):
            for i, v in vec:
                x[r, pos[i]] = v
        return x, np.asarray(dims or [0], np.int64)

    def _downsample(self) -> None:
        """Compressive compressor: cap the working set; evicted points fold
        their weight into their nearest survivor."""
        n = len(self._ids)
        if n <= self.compressed_size:
            return
        rng = np.random.default_rng(self.seed + self.revision)
        w = np.asarray(self._weights)
        keep = rng.choice(n, size=self.compressed_size, replace=False,
                          p=w / w.sum())
        keep_set = set(int(i) for i in keep)
        x, _ = self._compact()
        survivors = sorted(keep_set)
        sx = x[survivors]
        new_w = {s: self._weights[s] for s in survivors}
        for i in range(n):
            if i in keep_set:
                continue
            d2 = ((sx - x[i]) ** 2).sum(axis=1)
            nearest = survivors[int(np.argmin(d2))]
            new_w[nearest] += self._weights[i]
        self._ids = [self._ids[s] for s in survivors]
        self._id_pos = {rid: i for i, rid in enumerate(self._ids)}
        self._datums = [self._datums[s] for s in survivors]
        self._vecs = [self._vecs[s] for s in survivors]
        self._weights = [new_w[s] for s in survivors]

    def _recluster(self) -> None:
        if self.compressor.startswith("compressive"):
            self._downsample()
        if not self._vecs:
            return
        import jax.numpy as jnp
        x, dims = self._compact()
        w = np.asarray(self._weights, np.float32)
        xj, wj = jnp.asarray(x), jnp.asarray(w)
        if self.method == "kmeans":
            k = min(self.k, len(self._vecs))
            centers, assign = ops.kmeans_fit(xj, wj, k=k, seed=self.seed)
            centers = np.asarray(centers)
            assign = np.asarray(assign)
        elif self.method == "gmm":
            k = min(self.k, len(self._vecs))
            state, assign = ops.gmm_fit(xj, wj, k=k, seed=self.seed)
            centers = np.asarray(state.means)
            assign = np.asarray(assign)
        else:  # dbscan
            labels = np.asarray(ops.dbscan_fit(
                xj, wj, self.eps, min_core_point=self.min_core_point))
            reps = sorted({int(l) for l in labels if l >= 0})
            renum = {rep: c for c, rep in enumerate(reps)}
            assign = np.asarray([renum.get(int(l), -1) for l in labels])
            centers = np.zeros((max(len(reps), 1), x.shape[1]), np.float32)
            for c in range(len(reps)):
                rows = assign == c
                if rows.any():
                    cw = w[rows][:, None]
                    centers[c] = (x[rows] * cw).sum(0) / cw.sum()
        self._centers = centers
        self._active_dims = dims
        self._assign = assign
        self._members = [
            [i for i in range(len(assign)) if assign[i] == c]
            for c in range(len(centers))
        ]
        self.revision += 1

    @locked
    def clear(self) -> None:
        self._init_model()
        self.converter.weights.clear()
        self.update_count = 0

    # -- queries ---------------------------------------------------------------
    def _require_clustered(self) -> None:
        if self._centers is None:
            raise NotClusteredError(
                f"not clustered yet: {self._pending + len(self._ids)} points "
                f"pushed, bucket_size={self.bucket_size}")

    @locked
    def get_revision(self) -> int:
        return self.revision

    def _center_datum(self, c: int) -> Datum:
        num_values = []
        for j, dim in enumerate(self._active_dims):
            v = float(self._centers[c, j])
            if v == 0.0:
                continue
            decoded = self.converter.revert_feature(int(dim))
            if decoded is None:
                continue
            key, sval = decoded
            if not sval:
                num_values.append((key, v))
        return Datum(num_values=num_values)

    @locked
    def get_k_center(self) -> List[Datum]:
        self._require_clustered()
        return [self._center_datum(c) for c in range(len(self._centers))]

    @locked
    def get_core_members(self) -> List[List[Tuple[float, Datum]]]:
        self._require_clustered()
        return [[(self._weights[i], self._datums[i]) for i in mem]
                for mem in self._members]

    @locked
    def get_core_members_light(self) -> List[List[Tuple[float, str]]]:
        self._require_clustered()
        return [[(self._weights[i], self._ids[i]) for i in mem]
                for mem in self._members]

    def _nearest_cluster(self, datum: Datum) -> int:
        vec = dict(self.converter.convert(datum))
        pos = {int(d): j for j, d in enumerate(self._active_dims)}
        q = np.zeros(self._centers.shape[1], np.float32)
        for i, v in vec.items():
            j = pos.get(i)
            if j is not None:
                q[j] = v
        d2 = ((self._centers - q) ** 2).sum(axis=1)
        return int(np.argmin(d2))

    @locked
    def get_nearest_center(self, datum: Datum) -> Datum:
        self._require_clustered()
        return self._center_datum(self._nearest_cluster(datum))

    @locked
    def get_nearest_members(self, datum: Datum) -> List[Tuple[float, Datum]]:
        self._require_clustered()
        c = self._nearest_cluster(datum)
        return [(self._weights[i], self._datums[i]) for i in self._members[c]]

    @locked
    def get_nearest_members_light(self, datum: Datum) -> List[Tuple[float, str]]:
        self._require_clustered()
        c = self._nearest_cluster(datum)
        return [(self._weights[i], self._ids[i]) for i in self._members[c]]

    # -- mix plane --------------------------------------------------------------
    def get_mixables(self):
        return {"points": _PointMixable(self)}

    # -- persistence ------------------------------------------------------------
    @locked
    def pack(self) -> Any:
        return {
            "method": self.method,
            "ids": list(self._ids),
            "datums": [d.to_msgpack() for d in self._datums],
            "weights": list(self._weights),
            "pending": self._pending,
            "revision": self.revision,
            "fv_weights": self.converter.weights.pack(),
        }

    @locked
    def unpack(self, obj: Any) -> None:
        saved = obj.get("method")
        if isinstance(saved, bytes):
            saved = saved.decode()
        if saved != self.method:
            raise ValueError(
                f"checkpoint method {saved!r} != driver method {self.method!r}")
        self._init_model()
        ids = [i.decode() if isinstance(i, bytes) else i for i in obj["ids"]]
        datums = [Datum.from_msgpack(d) for d in obj["datums"]]
        self._ids = ids
        self._id_pos = {rid: i for i, rid in enumerate(ids)}
        self._datums = datums
        # restore converter weight state BEFORE re-converting, so idf/user
        # weights reproduce the original vectors
        if "fv_weights" in obj:
            self.converter.weights.unpack(obj["fv_weights"])
        self._vecs = self.converter.convert_batch(datums).rows()
        self._weights = [float(w) for w in obj["weights"]]
        self._pending = int(obj.get("pending", 0))
        if self._vecs:
            self._recluster()
        self.revision = int(obj["revision"])

    @locked
    def get_status(self) -> Dict[str, Any]:
        st = super().get_status()
        st.update(method=self.method, revision=self.revision,
                  num_points=len(self._ids))
        return st


class _PointMixable:
    """Replicates pushed points across the cluster: diff = points added
    since the last mix as {id: (datum_msgpack, weight)}; dict-merge fold."""

    def __init__(self, driver: ClusteringDriver):
        self._d = driver

    def get_diff(self):
        d = self._d
        out = {}
        for rid in d._mix_new_ids:
            i = d._id_pos.get(rid)
            if i is not None:
                out[rid] = (d._datums[i].to_msgpack(), d._weights[i])
        d._mix_new_ids = []
        return out

    @staticmethod
    def mix(acc, diff):
        acc.update(diff)
        return acc

    def put_diff(self, diff) -> bool:
        d = self._d
        pts = []
        for rid, (dm, w) in diff.items():
            rid = rid.decode() if isinstance(rid, bytes) else rid
            if rid not in d._id_pos:
                pts.append((rid, Datum.from_msgpack(dm)))
        if pts:
            d.push(pts)
        d._mix_new_ids = []
        return True
