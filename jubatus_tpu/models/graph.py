"""Graph engine driver (graph_wo_index: centrality + shortest path).

API parity with the reference's graph service
(jubatus/server/server/graph.idl: create_node / remove_node / update_node /
create_edge / update_edge / remove_edge / get_centrality /
add_{centrality,shortest_path}_query / remove_{centrality,shortest_path}_query /
get_shortest_path / update_index / clear / get_node / get_edge, plus the
internal create_node_here / create_edge_here / remove_global_node used for
CHT replication). Config from
/root/reference/config/graph/graph_wo_index.json: {damping_factor,
landmark_num}.

Semantics:

- Nodes carry a string-map property; edges are directed (source, target,
  property) with uint64 ids. get_node returns (property, in_edges,
  out_edges).
- A preset_query is (edge_query, node_query), each a list of (key, value)
  pairs that ALL must match a property map (empty list matches everything).
  Centrality and shortest-path must be computed against a *registered*
  preset query (add_*_query), mirroring the reference's requirement that
  queries be preset before update_index.
- get_centrality(node, type=0) is PageRank in the mean-one formulation
  pr = (1 − α) + α Σ_{j→i} pr_j / outdeg_j with α = damping_factor,
  computed on the preset-query-filtered subgraph. Scores are cached per
  (query, index version); update_index() refreshes eagerly.
- get_shortest_path runs BFS bounded by max_hop on the filtered subgraph
  and returns the node-id path (empty when unreachable). The reference
  approximates with landmark_num landmark trees; exact bounded BFS
  dominates it on quality and is cheap at these scales.

TPU design: PageRank iterations run as a jitted lax.fori_loop over edge
arrays with segment-sum scatter (one gather + scatter-add per iteration);
graph mutation stays host-side (pointer-shaped, no FLOPs).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from jubatus_tpu.framework.driver import DriverBase, locked

CENTRALITY_PAGERANK = 0

QueryPairs = List[Tuple[str, str]]
PresetQuery = Tuple[QueryPairs, QueryPairs]  # (edge_query, node_query)


def _canon_query(query: Any) -> PresetQuery:
    """Normalize a preset_query wire value to hashable canonical form."""
    if query is None:
        return ((), ())
    eq, nq = query[0] if len(query) > 0 else [], query[1] if len(query) > 1 else []

    def _s(x):
        return x.decode() if isinstance(x, bytes) else x

    def canon(pairs):
        return tuple(sorted((_s(k), _s(v)) for k, v in pairs))

    return (canon(eq), canon(nq))


def _match(props: Dict[str, str], pairs) -> bool:
    return all(props.get(k) == v for k, v in pairs)


class GraphDriver(DriverBase):
    TYPE = "graph"

    def __init__(self, config: dict):
        super().__init__()
        self.config = config
        self.config_json = json.dumps(config)
        param = dict(config.get("parameter") or {})
        self.damping_factor = float(param.get("damping_factor", 0.9))
        self.landmark_num = int(param.get("landmark_num", 5))
        self._init_model()

    def _init_model(self) -> None:
        self.nodes: Dict[str, Dict[str, str]] = {}
        self.in_edges: Dict[str, List[int]] = {}
        self.out_edges: Dict[str, List[int]] = {}
        # edge id -> (source, target, property)
        self.edges: Dict[int, Tuple[str, str, Dict[str, str]]] = {}
        self._next_node_id = 0
        self._next_edge_id = 0
        #: cluster-wide id minting (≙ ZK global_id_generator,
        #: graph_serv.cpp:109-126) — set by the server in distributed mode
        self.idgen = None
        self.centrality_queries: set = set()
        self.shortest_path_queries: set = set()
        self._pagerank_cache: Dict[PresetQuery, Dict[str, float]] = {}
        self._index_version = 0
        self._mix_log: Dict[str, Any] = {"nodes": {}, "edges": {}}

    # -- node / edge CRUD -------------------------------------------------------
    def set_id_generator(self, gen) -> None:
        self.idgen = gen

    def create_node(self) -> str:
        # coordinator id minting happens OUTSIDE the model lock (a slow
        # coordinator must not stall serving threads or mix rounds)
        node_id = str(self.idgen.generate()) if self.idgen is not None else None
        with self.lock:
            if node_id is None:
                node_id = str(self._next_node_id)
                self._next_node_id += 1
            self._create_node(node_id)
        return node_id

    def _create_node(self, node_id: str) -> None:
        if node_id not in self.nodes:
            self.nodes[node_id] = {}
            self.in_edges[node_id] = []
            self.out_edges[node_id] = []
            self._mix_log["nodes"][node_id] = {}
            self.event_model_updated()

    @locked
    def create_node_here(self, node_id: str) -> bool:
        """Internal RPC: materialize a node with a caller-chosen id (the
        CHT-replication path, graph_serv.cpp:181-228)."""
        self._create_node(node_id)
        self._next_node_id = max(self._next_node_id,
                                 _int_or(node_id, -1) + 1)
        return True

    @locked
    def update_node(self, node_id: str, properties: Dict[str, str]) -> bool:
        if node_id not in self.nodes:
            raise KeyError(f"unknown node {node_id!r}")
        self.nodes[node_id] = dict(properties)
        self._mix_log["nodes"][node_id] = dict(properties)
        self.event_model_updated()
        return True

    @locked
    def remove_node(self, node_id: str) -> bool:
        if node_id not in self.nodes:
            return False
        for eid in list(self.in_edges[node_id]) + list(self.out_edges[node_id]):
            self._remove_edge(eid)
        del self.nodes[node_id]
        del self.in_edges[node_id]
        del self.out_edges[node_id]
        self._mix_log["nodes"][node_id] = None
        self.event_model_updated()
        return True

    @locked
    def remove_global_node(self, node_id: str) -> bool:
        """Internal RPC: the broadcast half of remove_node
        (graph_serv.cpp:240-265)."""
        return self.remove_node(node_id)

    def create_edge(self, node_id: str, source: str, target: str,
                    properties: Optional[Dict[str, str]] = None) -> int:
        eid = int(self.idgen.generate()) if self.idgen is not None else None
        with self.lock:
            if eid is None:
                eid = self._next_edge_id
                self._next_edge_id += 1
            self._create_edge(eid, source, target, properties or {})
        return eid

    @locked
    def create_edge_here(self, edge_id: int, source: str, target: str,
                         properties: Optional[Dict[str, str]] = None) -> bool:
        self._create_edge(int(edge_id), source, target, properties or {})
        self._next_edge_id = max(self._next_edge_id, int(edge_id) + 1)
        return True

    def _create_edge(self, eid: int, source: str, target: str,
                     properties: Dict[str, str]) -> None:
        if source not in self.nodes:
            raise KeyError(f"unknown source node {source!r}")
        if target not in self.nodes:
            raise KeyError(f"unknown target node {target!r}")
        if eid in self.edges:
            return
        self.edges[eid] = (source, target, dict(properties))
        self.out_edges[source].append(eid)
        self.in_edges[target].append(eid)
        self._mix_log["edges"][eid] = (source, target, dict(properties))
        self.event_model_updated()

    @locked
    def update_edge(self, node_id: str, edge_id: int,
                    properties: Dict[str, str]) -> bool:
        if edge_id not in self.edges:
            raise KeyError(f"unknown edge {edge_id!r}")
        s, t, _ = self.edges[edge_id]
        self.edges[edge_id] = (s, t, dict(properties))
        self._mix_log["edges"][edge_id] = (s, t, dict(properties))
        self.event_model_updated()
        return True

    @locked
    def remove_edge(self, node_id: str, edge_id: int) -> bool:
        return self._remove_edge(int(edge_id))

    def _remove_edge(self, eid: int) -> bool:
        rec = self.edges.pop(eid, None)
        if rec is None:
            return False
        s, t, _ = rec
        if s in self.out_edges and eid in self.out_edges[s]:
            self.out_edges[s].remove(eid)
        if t in self.in_edges and eid in self.in_edges[t]:
            self.in_edges[t].remove(eid)
        self._mix_log["edges"][eid] = None
        self.event_model_updated()
        return True

    # -- reads ------------------------------------------------------------------
    @locked
    def get_node(self, node_id: str) -> Dict[str, Any]:
        if node_id not in self.nodes:
            raise KeyError(f"unknown node {node_id!r}")
        return {"property": dict(self.nodes[node_id]),
                "in_edges": list(self.in_edges[node_id]),
                "out_edges": list(self.out_edges[node_id])}

    @locked
    def get_edge(self, node_id: str, edge_id: int) -> Dict[str, Any]:
        rec = self.edges.get(int(edge_id))
        if rec is None:
            raise KeyError(f"unknown edge {edge_id!r}")
        s, t, p = rec
        return {"property": dict(p), "source": s, "target": t}

    # -- preset queries -----------------------------------------------------------
    @locked
    def add_centrality_query(self, query: Any) -> bool:
        self.centrality_queries.add(_canon_query(query))
        self._pagerank_cache.clear()
        return True

    @locked
    def remove_centrality_query(self, query: Any) -> bool:
        self.centrality_queries.discard(_canon_query(query))
        return True

    @locked
    def add_shortest_path_query(self, query: Any) -> bool:
        self.shortest_path_queries.add(_canon_query(query))
        return True

    @locked
    def remove_shortest_path_query(self, query: Any) -> bool:
        self.shortest_path_queries.discard(_canon_query(query))
        return True

    def _filtered(self, q: PresetQuery):
        """(node set, edge list[(eid, src, dst)]) matching the preset query."""
        eq, nq = q
        nodes = {n for n, p in self.nodes.items() if _match(p, nq)}
        edges = [(eid, s, t) for eid, (s, t, p) in self.edges.items()
                 if s in nodes and t in nodes and _match(p, eq)]
        return nodes, edges

    # -- centrality ---------------------------------------------------------------
    @locked
    def update_index(self) -> bool:
        """Recompute cached centralities (the reference's explicit index
        refresh; queries between update_index calls serve the cache)."""
        self._index_version += 1
        self._pagerank_cache.clear()
        for q in self.centrality_queries:
            self._pagerank_cache[q] = self._pagerank(q)
        return True

    def _pagerank(self, q: PresetQuery, iters: int = 30) -> Dict[str, float]:
        nodes, edges = self._filtered(q)
        if not nodes:
            return {}
        order = sorted(nodes)
        slot = {n: i for i, n in enumerate(order)}
        n = len(order)
        if edges:
            src = np.asarray([slot[s] for _, s, _t in edges], np.int32)
            dst = np.asarray([slot[t] for _, _s, t in edges], np.int32)
        else:
            src = np.zeros(0, np.int32)
            dst = np.zeros(0, np.int32)
        import jax
        import jax.numpy as jnp

        outdeg = jnp.zeros(n, jnp.float32).at[src].add(1.0)
        alpha = self.damping_factor
        srcj, dstj = jnp.asarray(src), jnp.asarray(dst)

        def body(_, pr):
            contrib = pr[srcj] / jnp.maximum(outdeg[srcj], 1.0)
            return (1.0 - alpha) + alpha * \
                jnp.zeros(n, jnp.float32).at[dstj].add(contrib)

        pr = jax.lax.fori_loop(0, iters, body, jnp.ones(n, jnp.float32))
        pr = np.asarray(pr)
        return {order[i]: float(pr[i]) for i in range(n)}

    @locked
    def get_centrality(self, node_id: str, centrality_type: int,
                       query: Any) -> float:
        if centrality_type != CENTRALITY_PAGERANK:
            raise ValueError(f"unsupported centrality type {centrality_type}")
        q = _canon_query(query)
        if q not in self.centrality_queries:
            raise ValueError("centrality query not preset; call "
                             "add_centrality_query + update_index first")
        cached = self._pagerank_cache.get(q)
        if cached is None:
            cached = self._pagerank(q)
            self._pagerank_cache[q] = cached
        if node_id not in cached:
            raise KeyError(f"node {node_id!r} not in filtered graph")
        return cached[node_id]

    # -- shortest path --------------------------------------------------------------
    @locked
    def get_shortest_path(self, source: str, target: str, max_hop: int,
                          query: Any = None) -> List[str]:
        q = _canon_query(query)
        if q not in self.shortest_path_queries:
            raise ValueError("shortest-path query not preset; call "
                             "add_shortest_path_query first")
        nodes, edges = self._filtered(q)
        if source not in nodes or target not in nodes:
            return []
        adj: Dict[str, List[str]] = {}
        for _eid, s, t in edges:
            adj.setdefault(s, []).append(t)
        # BFS bounded by max_hop
        prev: Dict[str, Optional[str]] = {source: None}
        frontier = [source]
        for _hop in range(int(max_hop)):
            if target in prev:
                break
            nxt = []
            for u in frontier:
                for v in adj.get(u, ()):
                    if v not in prev:
                        prev[v] = u
                        nxt.append(v)
            if not nxt:
                break
            frontier = nxt
        if target not in prev:
            return []
        path = [target]
        while prev[path[-1]] is not None:
            path.append(prev[path[-1]])
        return list(reversed(path))

    @locked
    def clear(self) -> None:
        self._init_model()
        self.update_count = 0

    # -- mix plane -------------------------------------------------------------
    def get_mixables(self):
        return {"graph": _GraphMixable(self)}

    # -- persistence -----------------------------------------------------------
    @locked
    def pack(self) -> Any:
        return {
            "nodes": {n: dict(p) for n, p in self.nodes.items()},
            "edges": {eid: [s, t, dict(p)]
                      for eid, (s, t, p) in self.edges.items()},
            "next_node_id": self._next_node_id,
            "next_edge_id": self._next_edge_id,
            "centrality_queries": sorted(self.centrality_queries),
            "shortest_path_queries": sorted(self.shortest_path_queries),
        }

    @locked
    def unpack(self, obj: Any) -> None:
        def _s(x):
            return x.decode() if isinstance(x, bytes) else x

        self._init_model()
        for n, p in obj["nodes"].items():
            n = _s(n)
            self.nodes[n] = {_s(k): _s(v) for k, v in p.items()}
            self.in_edges[n] = []
            self.out_edges[n] = []
        for eid, (s, t, p) in obj["edges"].items():
            eid, s, t = int(eid), _s(s), _s(t)
            self.edges[eid] = (s, t, {_s(k): _s(v) for k, v in p.items()})
            self.out_edges[s].append(eid)
            self.in_edges[t].append(eid)
        self._next_node_id = int(obj["next_node_id"])
        self._next_edge_id = int(obj["next_edge_id"])
        for q in obj.get("centrality_queries", []):
            self.centrality_queries.add(_canon_query(q))
        for q in obj.get("shortest_path_queries", []):
            self.shortest_path_queries.add(_canon_query(q))
        self._mix_log = {"nodes": {}, "edges": {}}

    @locked
    def get_status(self) -> Dict[str, Any]:
        st = super().get_status()
        st.update(num_nodes=len(self.nodes), num_edges=len(self.edges))
        return st


class _GraphMixable:
    """Ships node/edge mutations since the last mix: {nodes: {id: props|None},
    edges: {eid: (s, t, props)|None}} (None = removed); dict-merge fold."""

    def __init__(self, driver: GraphDriver):
        self._d = driver

    def get_diff(self):
        log = self._d._mix_log
        self._d._mix_log = {"nodes": {}, "edges": {}}
        return log

    @staticmethod
    def mix(acc, diff):
        acc["nodes"].update(diff["nodes"])
        acc["edges"].update(diff["edges"])
        return acc

    def put_diff(self, diff) -> bool:
        def _s(x):
            return x.decode() if isinstance(x, bytes) else x

        d = self._d
        for n, props in diff["nodes"].items():
            n = _s(n)
            if props is None:
                if n in d.nodes:
                    d.remove_node(n)
            else:
                d._create_node(n)
                # apply unconditionally: an empty map means the node's
                # properties were cleared, which must replicate too
                d.nodes[n] = {_s(k): _s(v) for k, v in props.items()}
                d._next_node_id = max(d._next_node_id, _int_or(n, -1) + 1)
        for eid, rec in diff["edges"].items():
            eid = int(eid)
            if rec is None:
                d._remove_edge(eid)
            else:
                s, t, props = rec
                s, t = _s(s), _s(t)
                if s in d.nodes and t in d.nodes:
                    if eid in d.edges:
                        d.edges[eid] = (s, t,
                                        {_s(k): _s(v) for k, v in props.items()})
                    else:
                        d._create_edge(eid, s, t,
                                       {_s(k): _s(v) for k, v in props.items()})
                    d._next_edge_id = max(d._next_edge_id, eid + 1)
        d._mix_log = {"nodes": {}, "edges": {}}
        d._pagerank_cache.clear()
        return True


def _int_or(s: str, default: int) -> int:
    try:
        return int(s)
    except (TypeError, ValueError):
        return default
