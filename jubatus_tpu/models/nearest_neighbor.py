"""Nearest-neighbor engine driver.

API parity with the reference's nearest_neighbor service
(jubatus/server/server/nearest_neighbor.idl: clear / set_row /
neighbor_row_from_{id,datum} / similar_row_from_{id,datum} / get_all_rows).
Methods + parameters from /root/reference/config/nearest_neighbor/*.json:
lsh / minhash / euclid_lsh with {hash_num}.

neighbor_* return (id, distance) ascending; similar_* return
(id, similarity) descending — conventions in models/_nn_backend.py.

Distribution: the reference CHT-shards rows (set_row is #@cht(1)); here each
replica owns its shard and the mix ships row updates as a sparse dict diff
(replicated mode) — static mesh sharding of the row table is the pod-scale
path (SURVEY.md §5 long-context note).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Tuple

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.core.fv import make_fv_converter
from jubatus_tpu.framework.driver import DriverBase, locked
from jubatus_tpu.models._nn_backend import (HASH_METHODS, NNBackend,
                                            NNRowMigration)


class NearestNeighborConfigError(ValueError):
    pass


class NearestNeighborDriver(NNRowMigration, DriverBase):
    TYPE = "nearest_neighbor"

    def __init__(self, config: dict, dim_bits: int = 18):
        super().__init__()
        self.config = config
        self.config_json = json.dumps(config)
        method = config.get("method")
        if method not in HASH_METHODS:
            raise NearestNeighborConfigError(
                f"unknown nearest_neighbor method {method!r}")
        self.method = method
        param = config.get("parameter") or {}
        self.converter = make_fv_converter(config.get("converter"),
                                           dim_bits=dim_bits)
        unl_param = param.get("unlearner_parameter") or {}
        self.backend = NNBackend(
            method,
            dim=self.converter.dim,
            hash_num=int(param.get("hash_num", 64)),
            seed=int(param.get("seed", 0)),
            max_size=(int(unl_param["max_size"])
                      if param.get("unlearner") == "lru" else None),
        )

    # -- updates --------------------------------------------------------------
    @locked
    def set_row(self, row_id: str, datum: Datum) -> bool:
        vec = self.converter.convert(datum, update_weights=True)
        self.backend.set_row(row_id, vec)
        self.event_model_updated()
        return True

    @locked
    def clear(self) -> None:
        self.backend.clear()
        self.converter.weights.clear()
        self.update_count = 0

    # -- queries --------------------------------------------------------------
    def _row_vec(self, row_id: str):
        vec = self.backend.store.get_row(row_id)
        if vec is None:
            raise KeyError(f"unknown row id {row_id!r}")
        return vec

    @locked
    def neighbor_row_from_id(self, row_id: str, size: int) -> List[Tuple[str, float]]:
        return self.backend.neighbors(self._row_vec(row_id), size)

    @locked
    def neighbor_row_from_datum(self, query: Datum, size: int) -> List[Tuple[str, float]]:
        return self.backend.neighbors(self.converter.convert(query), size)

    @locked
    def similar_row_from_id(self, row_id: str, ret_num: int) -> List[Tuple[str, float]]:
        return self.backend.similar(self._row_vec(row_id), ret_num)

    @locked
    def similar_row_from_datum(self, query: Datum, ret_num: int) -> List[Tuple[str, float]]:
        return self.backend.similar(self.converter.convert(query), ret_num)

    @locked
    def get_all_rows(self) -> List[str]:
        return self.backend.store.all_ids()

    # -- mix plane -------------------------------------------------------------
    def get_mixables(self):
        return {"rows": _RowUpdateMixable(self.backend),
                "weights": self.converter.weights}

    # -- persistence -----------------------------------------------------------
    @locked
    def pack(self) -> Any:
        return {"method": self.method, "backend": self.backend.pack(),
                "weights": self.converter.weights.pack()}

    @locked
    def unpack(self, obj: Any) -> None:
        saved = obj.get("method")
        if isinstance(saved, bytes):
            saved = saved.decode()
        if saved != self.method:
            raise ValueError(
                f"checkpoint method {saved!r} != driver method {self.method!r}")
        self.backend.unpack(obj["backend"])
        self.converter.weights.unpack(obj["weights"])

    def shard_stats(self) -> Dict[str, Any]:
        """Row-shard layout gauges (shard.* catalog rows): arena shape +
        last sharded top-k merge wall. Empty when unsharded."""
        if self.backend._mesh is None:
            return {}
        return self.backend.shard_stats()

    def ann_stats(self) -> Dict[str, Any]:
        """IVF ANN-tier gauges (ann.* catalog rows); empty when --ann off."""
        return self.backend.ann_stats()

    @locked
    def get_status(self) -> Dict[str, Any]:
        st = super().get_status()
        st.update(method=self.method, num_rows=len(self.backend.store))
        st.update({f"shard.{k}": v for k, v in self.shard_stats().items()})
        st.update({f"ann.{k}": v for k, v in self.ann_stats().items()})
        return st


class _RowUpdateMixable:
    """Sparse row-update diff: {id: (idx, val, datum)} written since the last
    mix; the custom combiner merges dicts (last writer in fold order wins on
    the rare same-id conflict, matching the reference's row-overwrite
    semantics)."""

    def __init__(self, backend: NNBackend):
        self._b = backend

    def get_diff(self):
        return self._b.pop_update_diff()

    @staticmethod
    def mix(acc, diff):
        out = dict(acc)
        out.update(diff)
        return out

    def put_diff(self, diff) -> bool:
        self._b.apply_update_diff(diff)
        return True
