"""Recommender engine driver.

API parity with the reference's recommender service
(jubatus/server/server/recommender.idl: clear_row / update_row / clear /
complete_row_from_{id,datum} / similar_row_from_{id,datum} / decode_row /
get_all_rows / calc_similarity / calc_l2norm). Methods from
/root/reference/config/recommender/*.json: inverted_index,
inverted_index_euclid, lsh, minhash, euclid_lsh,
nearest_neighbor_recommender (nested NN config), each with optional
{"unlearner": "lru", "unlearner_parameter": {"max_size": N}}.

- similar_row_* return (id, similarity) descending (cosine for the
  inverted-index family, 1 - hamming/jaccard distance for lsh/minhash,
  negated distance for the euclid family — models/_nn_backend.py).
- complete_row_* fills in a datum by similarity-weighted averaging of the
  top similar rows' feature vectors, then reverting hashed features back to
  (key, value) pairs through the fv hasher's inverse table.
- decode_row returns the originally stored datum (the store keeps it).

TPU design: all methods run on the padded row arrays of the shared
NNBackend — exact cosine/euclid as one dense-gather pass, LSH family as
bit-packed signature kernels (ops/knn.py).
"""

from __future__ import annotations

import json
import math
from typing import Any, Dict, List, Tuple

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.core.fv import make_fv_converter
from jubatus_tpu.core.sparse import SparseVector
from jubatus_tpu.framework.driver import DriverBase, locked
from jubatus_tpu.models._nn_backend import NNBackend, NNRowMigration

METHODS = ("inverted_index", "inverted_index_euclid", "lsh", "minhash",
           "euclid_lsh", "nearest_neighbor_recommender")

#: rows aggregated by complete_row (similarity-weighted average)
_COMPLETE_TOP_K = 128


class RecommenderConfigError(ValueError):
    pass


class RecommenderDriver(NNRowMigration, DriverBase):
    TYPE = "recommender"

    def __init__(self, config: dict, dim_bits: int = 18):
        super().__init__()
        self.config = config
        self.config_json = json.dumps(config)
        method = config.get("method")
        if method not in METHODS:
            raise RecommenderConfigError(f"unknown recommender method {method!r}")
        self.method = method
        param = dict(config.get("parameter") or {})
        self.converter = make_fv_converter(config.get("converter"),
                                           dim_bits=dim_bits)
        if method == "nearest_neighbor_recommender":
            backend_method = param.get("method")
            param = dict(param.get("parameter") or {})
        elif method == "inverted_index_euclid":
            backend_method = "euclid"
        else:
            backend_method = method
        unl_param = param.get("unlearner_parameter") or {}
        self.backend = NNBackend(
            backend_method,
            dim=self.converter.dim,
            hash_num=int(param.get("hash_num", 64)),
            seed=int(param.get("seed", 0)),
            max_size=(int(unl_param["max_size"])
                      if param.get("unlearner") == "lru" else None),
            keep_datum=True,
        )

    # -- updates --------------------------------------------------------------
    @locked
    def update_row(self, row_id: str, row: Datum) -> bool:
        """Merge semantics like the reference: updating an existing row
        overlays the new datum's keys onto the stored one, then re-converts."""
        old = self.backend.store.datums.get(row_id)
        if old is not None:
            merged_str = dict(old.string_values)
            merged_num = dict(old.num_values)
            merged_str.update(row.string_values)
            merged_num.update(row.num_values)
            row = Datum(string_values=merged_str.items(),
                        num_values=merged_num.items())
        vec = self.converter.convert(row, update_weights=True)
        self.backend.set_row(row_id, vec, datum=row)
        self.event_model_updated()
        return True

    @locked
    def clear_row(self, row_id: str) -> bool:
        ok = self.backend.remove_row(row_id)
        if ok:
            self.event_model_updated()
        return ok

    @locked
    def clear(self) -> None:
        self.backend.clear()
        self.converter.weights.clear()
        self.update_count = 0

    # -- queries --------------------------------------------------------------
    def _row_vec(self, row_id: str) -> SparseVector:
        vec = self.backend.store.get_row(row_id)
        if vec is None:
            raise KeyError(f"unknown row id {row_id!r}")
        return vec

    @locked
    def similar_row_from_id(self, row_id: str, size: int) -> List[Tuple[str, float]]:
        return self.backend.similar(self._row_vec(row_id), size)

    @locked
    def similar_row_from_datum(self, row: Datum, size: int) -> List[Tuple[str, float]]:
        return self.backend.similar(self.converter.convert(row), size)

    def _complete(self, vec: SparseVector) -> Datum:
        # aggregation weights must be positive: cosine/hash similarities are
        # used as-is (dropping anti-correlated rows), but the euclid family's
        # similarity is a negated distance, so weight by 1/(1+d) instead
        neighbors = self.backend.neighbors(vec, _COMPLETE_TOP_K)
        euclid = self.backend.method in ("euclid_lsh", "euclid")
        acc: Dict[int, float] = {}
        total = 0.0
        for rid, dist in neighbors:
            w = 1.0 / (1.0 + dist) if euclid else 1.0 - dist
            if w <= 0:
                continue
            total += w
            for i, v in self.backend.store.get_row(rid) or []:
                acc[i] = acc.get(i, 0.0) + w * v
        if total <= 0:
            return Datum()
        string_values: List[Tuple[str, str]] = []
        num_values: List[Tuple[str, float]] = []
        for i, v in sorted(acc.items()):
            decoded = self.converter.revert_feature(i)
            if decoded is None:
                continue
            key, sval = decoded
            if sval:
                string_values.append((key, sval))
            else:
                num_values.append((key, v / total))
        return Datum(string_values=string_values, num_values=num_values)

    @locked
    def complete_row_from_id(self, row_id: str) -> Datum:
        return self._complete(self._row_vec(row_id))

    @locked
    def complete_row_from_datum(self, row: Datum) -> Datum:
        return self._complete(self.converter.convert(row))

    @locked
    def decode_row(self, row_id: str) -> Datum:
        return self.backend.store.datums.get(row_id) or Datum()

    @locked
    def get_all_rows(self) -> List[str]:
        return self.backend.store.all_ids()

    @locked
    def calc_similarity(self, lhs: Datum, rhs: Datum) -> float:
        a = dict(self.converter.convert(lhs))
        b = dict(self.converter.convert(rhs))
        dot = sum(v * b.get(i, 0.0) for i, v in a.items())
        na = math.sqrt(sum(v * v for v in a.values()))
        nb = math.sqrt(sum(v * v for v in b.values()))
        return dot / (na * nb) if na > 0 and nb > 0 else 0.0

    @locked
    def calc_l2norm(self, row: Datum) -> float:
        vec = self.converter.convert(row)  # one datum by contract
        return math.sqrt(sum(v * v for _, v in vec))

    # -- mix plane -------------------------------------------------------------
    def get_mixables(self):
        from jubatus_tpu.models.nearest_neighbor import _RowUpdateMixable
        return {"rows": _RowUpdateMixable(self.backend),
                "weights": self.converter.weights}

    # -- persistence -----------------------------------------------------------
    @locked
    def pack(self) -> Any:
        return {"method": self.method, "backend": self.backend.pack(),
                "weights": self.converter.weights.pack()}

    @locked
    def unpack(self, obj: Any) -> None:
        saved = obj.get("method")
        if isinstance(saved, bytes):
            saved = saved.decode()
        if saved != self.method:
            raise ValueError(
                f"checkpoint method {saved!r} != driver method {self.method!r}")
        self.backend.unpack(obj["backend"], datum_decoder=Datum.from_msgpack)
        self.converter.weights.unpack(obj["weights"])

    def shard_stats(self) -> Dict[str, Any]:
        """Row-shard layout gauges; empty when unsharded."""
        if self.backend._mesh is None:
            return {}
        return self.backend.shard_stats()

    def ann_stats(self) -> Dict[str, Any]:
        """IVF ANN-tier gauges (ann.* catalog rows); empty when --ann off."""
        return self.backend.ann_stats()

    @locked
    def get_status(self) -> Dict[str, Any]:
        st = super().get_status()
        st.update(method=self.method, num_rows=len(self.backend.store))
        st.update({f"shard.{k}": v for k, v in self.shard_stats().items()})
        st.update({f"ann.{k}": v for k, v in self.ann_stats().items()})
        return st
