"""Regression engine driver.

API parity with the reference regression service (regression.idl: train /
estimate / clear; regression_serv.cpp). Config schema from
/root/reference/config/regression/default.json: method PA/PA1/PA2 with
parameter {sensitivity, regularization_weight}.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.core.fv import make_fv_converter
from jubatus_tpu.core.sparse import _bucket
from jubatus_tpu.framework.driver import DriverBase, locked
from jubatus_tpu.ops import regression as ops


class RegressionConfigError(ValueError):
    pass


class RegressionDriver(DriverBase):
    TYPE = "regression"

    def __init__(self, config: dict, dim_bits: int = 18, mesh=None,
                 mesh_axis: str = "shard", shard_features: int = 0):
        super().__init__()
        self.config = config
        self.config_json = json.dumps(config)
        method = config.get("method")
        if method not in ops.METHODS:
            raise RegressionConfigError(f"unknown regression method {method!r}")
        self.method = method
        param = config.get("parameter") or {}
        self.sensitivity = float(param.get("sensitivity", 0.1))
        self.c = float(param.get("regularization_weight", 1.0))
        self.converter = make_fv_converter(config.get("converter"), dim_bits=dim_bits)
        # feature sharding over local devices (--shard-devices /
        # --shard-features): train/estimate run as shard_map programs
        # (parallel/sharded_model.py) — batch routed by column range,
        # per-example psum, [D] weights never gathered
        if shard_features and mesh is None:
            from jubatus_tpu.parallel.sharded_model import mesh_for_features

            mesh = mesh_for_features(self.converter.dim, shard_features,
                                     RegressionConfigError)
        self._mesh = mesh
        self._mesh_axis = mesh_axis
        self._sharding = None
        if mesh is not None:
            from jubatus_tpu.parallel.mesh import make_feature_sharding

            # converter's dim, not the dim_bits argument: a config-side
            # "hash_max_size" overrides the latter
            self._sharding = make_feature_sharding(
                mesh, mesh_axis, self.converter.hasher.dim_bits,
                RegressionConfigError, rank=1)
        self.state = self._place(ops.init_state(self.converter.dim))

    def _place(self, state: ops.RegressionState) -> ops.RegressionState:
        if self._sharding is None:
            return state
        import jax

        return ops.RegressionState(
            *(jax.device_put(a, self._sharding) for a in state))

    def featurize_train(self, data: Sequence[Tuple[float, Datum]]):
        """Stage-1 host featurization for the pipelined microbatch:
        batch-convert WITHOUT the driver lock (the WeightManager has its
        own lock for the batch idf observe). Returns the (targets, idx,
        val) triple ``train_hashed`` consumes."""
        targets = np.asarray([float(y) for y, _ in data], dtype=np.float32)
        csr = self.converter.convert_batch(
            [d for _, d in data], update_weights=True)
        sb = csr.to_padded()
        return targets, sb.idx, sb.val

    def train(self, data: Sequence[Tuple[float, Datum]]) -> int:
        """Batch-native train: one convert_batch sweep into the
        pre-hashed device path (train_hashed buckets rows to pow2 —
        padded rows predict 0 for target 0 → loss 0 → no update)."""
        if not data:
            return 0
        targets, idx, val = self.featurize_train(data)
        return self.train_hashed(targets, idx, val)

    @locked
    def train_hashed(self, targets: np.ndarray, idx: np.ndarray,
                     val: np.ndarray) -> int:
        """Train on pre-hashed features (native ingest fast path); same
        contract as ClassifierDriver.train_hashed with float targets."""
        n = len(targets)
        if n == 0:
            return 0
        b = idx.shape[0]
        bsz = _bucket(b, 16)
        if bsz != b:
            idx = np.pad(idx, ((0, bsz - b), (0, 0)))
            val = np.pad(val, ((0, bsz - b), (0, 0)))
        tgt = np.zeros(bsz, dtype=np.float32)
        tgt[:n] = targets
        if self._mesh is not None:
            from jubatus_tpu.parallel import sharded_model as _sm

            self.state = _sm.regression_train_batch(
                self._mesh, self.state, jnp.asarray(idx), jnp.asarray(val),
                jnp.asarray(tgt), self.sensitivity, self.c,
                method=self.method, axis=self._mesh_axis)
        else:
            self.state = ops.train_batch(
                self.state,
                jnp.asarray(idx),
                jnp.asarray(val),
                jnp.asarray(tgt),
                self.sensitivity,
                self.c,
                method=self.method,
            )
        self.event_model_updated(n)
        return n

    def estimate(self, data: Sequence[Datum]) -> List[float]:
        # NOT @locked: estimate_hashed locks only its dispatch window
        if not data:
            return []
        sb = self.converter.convert_batch(data).to_padded(batch_bucket=16)
        return self.estimate_hashed(sb.idx, sb.val)[: len(data)]

    def estimate_hashed(self, idx: np.ndarray,
                        val: np.ndarray) -> List[float]:
        """Estimate on pre-hashed features (native ingest fast path).
        Dispatch-under-lock, wait-unlocked (see classify_hashed): enqueue
        while no train can donate the state, then overlap the device
        round trip (≙ the reference's JRLOCK_ reads)."""
        n = idx.shape[0]
        if n == 0:
            return []
        b = _bucket(n, 16)
        if b != n:
            idx = np.pad(idx, ((0, b - n), (0, 0)))
            val = np.pad(val, ((0, b - n), (0, 0)))
        didx, dval = jnp.asarray(idx), jnp.asarray(val)  # staged unlocked
        with self.lock:
            if self._mesh is not None:
                from jubatus_tpu.parallel import sharded_model as _sm

                pending = _sm.regression_estimate(
                    self._mesh, self.state, didx, dval,
                    axis=self._mesh_axis)
            else:
                pending = ops.estimate(self.state, didx, dval)
        return [float(x) for x in np.asarray(pending)[:n]]

    @locked
    def clear(self) -> None:
        self.state = self._place(ops.init_state(self.converter.dim))
        self.converter.weights.clear()
        self.update_count = 0

    def get_mixables(self):
        return {"regression": _RegressionMixable(self), "weights": self.converter.weights}

    @locked
    def pack(self) -> Any:
        return {
            "method": self.method,
            "dim": self.converter.dim,
            "w": np.asarray(self.state.w + self.state.dw),
            "weights": self.converter.weights.pack(),
        }

    @locked
    def unpack(self, obj: Any) -> None:
        saved_method = obj.get("method")
        if isinstance(saved_method, bytes):
            saved_method = saved_method.decode()
        if saved_method != self.method:
            raise ValueError(
                f"checkpoint method {saved_method!r} != driver method {self.method!r}"
            )
        if int(obj.get("dim", self.converter.dim)) != self.converter.dim:
            raise ValueError(
                f"checkpoint feature dim {obj['dim']} != driver dim "
                f"{self.converter.dim} (dim_bits mismatch)"
            )
        w = jnp.asarray(obj["w"])
        self.state = self._place(
            ops.RegressionState(w=w, dw=jnp.zeros_like(w)))
        self.converter.weights.unpack(obj["weights"])

    def shard_stats(self) -> Dict[str, Any]:
        """Feature-shard layout gauges (shard.* catalog rows); empty
        when unsharded."""
        if self._mesh is None:
            return {}
        n = self._mesh.shape[self._mesh_axis]
        total = sum(int(a.nbytes) for a in self.state)
        return {"count": n, "rows": 1, "bytes_in_use": total,
                "bytes_per_shard": total // n}

    def get_status(self) -> Dict[str, Any]:
        st = super().get_status()
        st.update(method=self.method, num_features=self.converter.dim)
        st.update({f"shard.{k}": v for k, v in self.shard_stats().items()})
        return st


class _RegressionMixable:
    def __init__(self, driver: RegressionDriver):
        self._d = driver

    def get_diff(self):
        diff = ops.get_diff(self._d.state)
        if self._d._mesh is not None:
            # per-shard wire chunks, same scheme as the classifier
            # mixable (models/classifier.py _ClassifierMixable)
            from jubatus_tpu.parallel import sharded_model as _sm

            diff = dict(diff, dw=_sm.shard_chunks(diff["dw"]))
        return diff

    def put_diff(self, diff) -> bool:
        from jubatus_tpu.models.classifier import _assemble_sharded

        self._d.state = ops.put_diff(
            self._d.state, _assemble_sharded(self._d, dict(diff), rank=1))
        return True
