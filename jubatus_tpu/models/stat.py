"""Stat engine driver.

API parity with the reference's stat service
(jubatus/server/server/stat.idl: push / sum / stddev / max / min / entropy /
moment / clear; config = {"window_size": N}, /root/reference/config/stat/stat.json).

Semantics (reconstructed from the jubatus_core stat driver the reference
consumes, SURVEY.md §2.9):

- ``push(key, value)`` appends to a per-key sliding window capped at
  ``window_size`` (oldest entry evicted).
- ``sum/max/min/stddev/moment`` reduce over the *current window* of one key.
  ``stddev`` is the population standard deviation; ``moment(key, n, c)`` is
  the mean of ``(x - c)**n``.
- ``entropy()`` is computed over the distribution of window sizes *across
  keys*: with n_k = window count of key k and N = sum n_k,
  ``H = log N - (sum_k n_k log n_k) / N`` (natural log). After a mix it uses
  the cluster-wide counts, matching the reference's mixed-entropy behavior.

TPU design note: stat is scalar bookkeeping with O(window) FLOPs per query —
there is no MXU-shaped work here (the reference likewise runs it on plain
C++ maps). Windows therefore live in host numpy ring buffers; the engines
with real FLOPs (classifier, NN, clustering, …) own the jitted kernels.
The mix plane still speaks the standard array-diff protocol: the per-key
count vector rides the same schema-synced psum as every other engine.
"""

from __future__ import annotations

import json
import math
from typing import Any, Dict, List, Optional

import numpy as np

from jubatus_tpu.framework.driver import DriverBase, locked


class StatDriver(DriverBase):
    TYPE = "stat"

    def __init__(self, config: dict):
        super().__init__()
        self.config = config
        self.config_json = json.dumps(config)
        self.window_size = int(config.get("window_size", 128))
        if self.window_size <= 0:
            raise ValueError("window_size must be positive")
        self._init_model()

    def _init_model(self) -> None:
        # key -> ring buffer of the last window_size values (numpy-backed)
        self._windows: Dict[str, np.ndarray] = {}
        self._counts: Dict[str, int] = {}   # valid entries in the ring
        self._heads: Dict[str, int] = {}    # next write position
        # cluster-wide per-key window counts as of the last mix (None before)
        self._mixed_counts: Optional[Dict[str, float]] = None

    # -- update --------------------------------------------------------------
    @locked
    def push(self, key: str, value: float) -> bool:
        win = self._windows.get(key)
        if win is None:
            win = np.zeros(self.window_size, dtype=np.float64)
            self._windows[key] = win
            self._counts[key] = 0
            self._heads[key] = 0
        head = self._heads[key]
        win[head] = float(value)
        self._heads[key] = (head + 1) % self.window_size
        self._counts[key] = min(self._counts[key] + 1, self.window_size)
        self.event_model_updated()
        return True

    def _window(self, key: str) -> np.ndarray:
        count = self._counts.get(key, 0)
        if count == 0:
            raise KeyError(f"stat key {key!r} has no data")
        return self._windows[key][:count] if count < self.window_size \
            else self._windows[key]

    # -- analysis ------------------------------------------------------------
    @locked
    def sum(self, key: str) -> float:
        return float(self._window(key).sum())

    @locked
    def stddev(self, key: str) -> float:
        return float(self._window(key).std())

    @locked
    def max(self, key: str) -> float:
        return float(self._window(key).max())

    @locked
    def min(self, key: str) -> float:
        return float(self._window(key).min())

    @locked
    def moment(self, key: str, degree: int, center: float) -> float:
        w = self._window(key)
        return float(((w - center) ** int(degree)).mean())

    @locked
    def entropy(self, key: str = "") -> float:
        """Entropy of the across-key count distribution. The RPC carries a
        key argument only for CHT routing (stat.idl); the value is global.
        Uses cluster-wide counts when a mix has run."""
        if self._mixed_counts is not None:
            counts = [c for c in self._mixed_counts.values() if c > 0]
        else:
            counts = [c for c in self._counts.values() if c > 0]
        total = float(np.sum(counts)) if counts else 0.0
        if total <= 0:
            return 0.0
        e = sum(c * math.log(c) for c in counts)
        return math.log(total) - e / total

    @locked
    def clear(self) -> None:
        self._init_model()
        self.update_count = 0

    # -- mix plane -----------------------------------------------------------
    def get_schema(self) -> List[str]:
        return sorted(self._counts.keys())

    def sync_schema(self, union_schema: List[str]) -> None:
        self._schema = list(union_schema)

    def get_mixables(self):
        return {"stat": _StatMixable(self)}

    # -- persistence ---------------------------------------------------------
    @locked
    def pack(self) -> Any:
        return {
            "window_size": self.window_size,
            "windows": {
                k: np.concatenate(
                    [self._windows[k][self._heads[k]:self._counts[k]],
                     self._windows[k][:self._heads[k]]]
                ) if self._counts[k] == self.window_size
                else self._windows[k][:self._counts[k]].copy()
                for k in self._counts
            },
        }

    @locked
    def unpack(self, obj: Any) -> None:
        if int(obj["window_size"]) != self.window_size:
            raise ValueError(
                f"checkpoint window_size {obj['window_size']} != "
                f"config window_size {self.window_size}"
            )
        self._init_model()
        # restore rings directly (oldest-first order from pack); does NOT
        # touch update_count — a freshly loaded model has no pending updates
        for key, values in obj["windows"].items():
            if isinstance(key, bytes):
                key = key.decode()
            vals = np.asarray(values, dtype=np.float64)
            win = np.zeros(self.window_size, dtype=np.float64)
            n = min(len(vals), self.window_size)
            win[:n] = vals[-n:]
            self._windows[key] = win
            self._counts[key] = n
            self._heads[key] = n % self.window_size

    @locked
    def get_status(self) -> Dict[str, Any]:
        st = super().get_status()
        st.update(num_keys=len(self._counts), window_size=self.window_size)
        return st


class _StatMixable:
    """Diff = per-key current window counts, aligned to the synced schema.

    Summing across replicas yields cluster-wide counts; put_diff *snapshots*
    them (they are state, not increments — each round replaces the last)."""

    def __init__(self, driver: StatDriver):
        self._d = driver

    def get_diff(self):
        schema = getattr(self._d, "_schema", None) or self._d.get_schema()
        return {
            "counts": np.asarray(
                [float(self._d._counts.get(k, 0)) for k in schema],
                dtype=np.float32,
            )
        }

    def put_diff(self, diff) -> bool:
        schema = getattr(self._d, "_schema", None) or self._d.get_schema()
        counts = np.asarray(diff["counts"], dtype=np.float64)
        self._d._mixed_counts = {
            k: float(c) for k, c in zip(schema, counts)
        }
        return True
