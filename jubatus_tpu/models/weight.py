"""Weight engine driver.

API parity with the reference weight service (weight.idl: update /
calc_weight / clear; weight_serv.cpp — exposes fv_converter weights for
debugging, SURVEY.md §2.4). `update` runs the train-path conversion
(recording document frequencies); `calc_weight` runs the analyze path. Both
return the named feature list with final weights.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Tuple

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.core.fv import make_fv_converter
from jubatus_tpu.framework.driver import DriverBase, locked


class WeightDriver(DriverBase):
    TYPE = "weight"

    def __init__(self, config: dict, dim_bits: int = 18):
        super().__init__()
        self.config = config
        self.config_json = json.dumps(config)
        self.converter = make_fv_converter(config.get("converter"), dim_bits=dim_bits)

    @locked
    def update(self, d: Datum) -> List[Tuple[str, float]]:
        result = self.converter.convert_named(d, update_weights=True)
        self.event_model_updated()
        return sorted(result.items())

    @locked
    def calc_weight(self, d: Datum) -> List[Tuple[str, float]]:
        return sorted(self.converter.convert_named(d).items())

    @locked
    def clear(self) -> None:
        self.converter.weights.clear()
        self.update_count = 0

    def get_mixables(self):
        return {"weights": self.converter.weights}

    @locked
    def pack(self) -> Any:
        return {"weights": self.converter.weights.pack()}

    @locked
    def unpack(self, obj: Any) -> None:
        self.converter.weights.unpack(obj["weights"])

    def get_status(self) -> Dict[str, Any]:
        st = super().get_status()
        st.update(num_features=self.converter.dim)
        return st
