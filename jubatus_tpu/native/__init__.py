"""ctypes bindings to the native runtime library (native/jt_native.cpp).

The image has no pybind11, so Python↔C++ crosses via ctypes on plain C
ABIs. The library is compiled on first use with g++ (toolchain is baked
into the image) and cached in native/build/; everything degrades to the
pure-Python paths when a compiler is unavailable.

Surface:
- ``load_native_splitter(path, params)`` — dlopen a splitter plugin .so
  implementing the jt_splitter_* ABI (the dlopen/create seam of the
  reference's fv_converter plugins, SURVEY.md §2.8) — the load-bearing
  native feature: tokenizer plugins run at C speed in the ingest path.
- ``hash_names(names, mask)`` — batch feature-name hashing, bit-identical
  to the zlib.crc32 FeatureHasher. Measured: NOT faster than the Python
  loop at realistic sizes (zlib is already C; ctypes marshalling eats the
  win), so FeatureHasher uses it only when JUBATUS_TPU_NATIVE=1.
- ``crc32(data)``             — zlib-compatible checksum (API parity).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Dict, List, Optional

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
BUILD_DIR = os.path.join(NATIVE_DIR, "build")
LIB_PATH = os.path.join(BUILD_DIR, "libjt_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _compile(src: str, out: str) -> bool:
    try:
        os.makedirs(os.path.dirname(out), exist_ok=True)
        res = subprocess.run(
            ["g++", "-O3", "-Wall", "-fPIC", "-std=c++17", "-pthread",
             "-shared", "-o", out, src],
            capture_output=True, timeout=120,
        )
        return res.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


def _stale(src: str, out: str) -> bool:
    return (not os.path.exists(out)
            or os.path.getmtime(out) < os.path.getmtime(src))


def ensure_built() -> Optional[str]:
    """Compile-on-demand; None when the toolchain/source is unavailable."""
    src = os.path.join(NATIVE_DIR, "jt_native.cpp")
    if not os.path.exists(src):
        return None
    if _stale(src, LIB_PATH) and not _compile(src, LIB_PATH):
        return None
    return LIB_PATH


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        path = os.environ.get("JUBATUS_TPU_NATIVE_LIB") or ensure_built()
        if not path or not os.path.exists(path):
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.jt_crc32.restype = ctypes.c_uint32
        lib.jt_crc32.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.jt_hash_names.restype = None
        lib.jt_hash_names.argtypes = [
            ctypes.c_char_p,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            ctypes.c_int64,
            ctypes.c_uint32,
            np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS"),
        ]
        _lib = lib
        return _lib


def available() -> bool:
    if os.environ.get("JUBATUS_TPU_NATIVE", "") in ("0", "false", "no"):
        return False
    return _load() is not None


def crc32(data: bytes) -> int:
    lib = _load()
    if lib is None:
        import zlib

        return zlib.crc32(data) & 0xFFFFFFFF
    return int(lib.jt_crc32(data, len(data)))


def hash_names(names: List[str], mask: int) -> np.ndarray:
    """Batch of utf-8 names → uint32 indices in [1, mask] (0 remapped to 1,
    matching FeatureHasher.index). Falls back to zlib per-name."""
    lib = _load() if available() else None
    if lib is None:
        import zlib

        out = np.empty(len(names), dtype=np.uint32)
        for i, name in enumerate(names):
            h = zlib.crc32(name.encode("utf-8")) & mask
            out[i] = h if h else 1
        return out
    encoded = [n.encode("utf-8") for n in names]
    offsets = np.zeros(len(encoded) + 1, dtype=np.int64)
    np.cumsum([len(e) for e in encoded], out=offsets[1:])
    buf = b"".join(encoded)
    out = np.empty(len(encoded), dtype=np.uint32)
    lib.jt_hash_names(buf, offsets, len(encoded), ctypes.c_uint32(mask), out)
    return out


# -- native splitter plugins (jt_splitter_* ABI) -----------------------------

_splitter_libs: Dict[str, ctypes.CDLL] = {}


def load_native_splitter(path: str, params: Dict[str, str]) -> Callable[[str], List[str]]:
    """dlopen a .so implementing the jt_splitter ABI and wrap it as a
    ``text -> [tokens]`` callable (see native/sample_ngram_splitter.cpp)."""
    from jubatus_tpu.core.fv.converter import ConverterError

    resolved = os.path.abspath(path)
    with _lock:
        lib = _splitter_libs.get(resolved)
        if lib is None:
            if not os.path.exists(resolved):
                raise ConverterError(f"native splitter not found: {path!r}")
            try:
                lib = ctypes.CDLL(resolved)
            except OSError as e:
                raise ConverterError(f"cannot dlopen {path!r}: {e}")
            lib.jt_splitter_create.restype = ctypes.c_void_p
            lib.jt_splitter_create.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_int]
            lib.jt_splitter_split.restype = ctypes.c_int64
            lib.jt_splitter_split.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                ctypes.c_int64]
            lib.jt_splitter_destroy.restype = None
            lib.jt_splitter_destroy.argtypes = [ctypes.c_void_p]
            _splitter_libs[resolved] = lib

    items = [(k, v) for k, v in params.items()
             if k not in ("method", "path", "function")]
    keys = (ctypes.c_char_p * len(items))(*[k.encode() for k, _ in items])
    vals = (ctypes.c_char_p * len(items))(*[str(v).encode() for _, v in items])
    handle = lib.jt_splitter_create(keys, vals, len(items))
    if not handle:
        raise ConverterError(f"native splitter {path!r} rejected params")

    def split(text: str, _lib=lib, _h=handle) -> List[str]:
        data = text.encode("utf-8")
        cap = max(64, len(data) * 2)
        while True:
            begins = np.empty(cap, dtype=np.int64)
            ends = np.empty(cap, dtype=np.int64)
            n = _lib.jt_splitter_split(_h, data, len(data), begins, ends, cap)
            if n <= cap:
                break
            cap = int(n)
        return [data[begins[i]:ends[i]].decode("utf-8", "replace")
                for i in range(max(0, int(n)))]

    return split
