"""ctypes bindings for the native train-request parser (native/fast_ingest.cpp).

``IngestParser`` turns the raw msgpack bytes of one train RPC
([name, [[label, datum], ...]]) into the device kernel's input — padded
int32/float32 [B, K] arrays + label strings — entirely in C++: no Datum
objects, no per-feature Python strings, no GIL-held convert loop. The
supported converter subset and the exact name/hash semantics are
documented in the C++ file; ``from_converter_config`` decides eligibility
and returns None when the config needs the Python converter.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from jubatus_tpu import native as nb

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

_SRC = os.path.join(nb.NATIVE_DIR, "fast_ingest.cpp")
_OUT = os.path.join(nb.BUILD_DIR, "libfast_ingest.so")


class _Out(ctypes.Structure):
    _fields_ = [
        ("batch", ctypes.c_int32),
        ("width", ctypes.c_int32),
        ("labels_numeric", ctypes.c_int32),
        ("idx", ctypes.POINTER(ctypes.c_int32)),
        ("val", ctypes.POINTER(ctypes.c_float)),
        ("labels", ctypes.POINTER(ctypes.c_uint8)),
        ("label_off", ctypes.POINTER(ctypes.c_int32)),
        ("targets", ctypes.POINTER(ctypes.c_float)),
        ("uniq", ctypes.c_int32),
        ("label_idx", ctypes.POINTER(ctypes.c_int32)),
    ]


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SRC):
            return None
        if nb._stale(_SRC, _OUT) and not nb._compile(_SRC, _OUT):
            return None
        try:
            lib = ctypes.CDLL(_OUT)
        except OSError:
            return None
        lib.jt_ingest_create.restype = ctypes.c_void_p
        lib.jt_ingest_create.argtypes = [ctypes.c_char_p]
        lib.jt_ingest_destroy.restype = None
        lib.jt_ingest_destroy.argtypes = [ctypes.c_void_p]
        lib.jt_ingest_parse.restype = ctypes.c_int
        lib.jt_ingest_parse.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_uint32, ctypes.POINTER(_Out)]
        lib.jt_ingest_parse_datums.restype = ctypes.c_int
        lib.jt_ingest_parse_datums.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_uint32, ctypes.POINTER(_Out)]
        _fp = ctypes.POINTER(ctypes.c_float)
        _dp = ctypes.POINTER(ctypes.c_double)
        lib.jt_ingest_parse_w.restype = ctypes.c_int
        lib.jt_ingest_parse_w.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_uint32, _fp, _fp, ctypes.c_double, _dp, ctypes.c_int,
            ctypes.POINTER(_Out)]
        lib.jt_ingest_parse_datums_w.restype = ctypes.c_int
        lib.jt_ingest_parse_datums_w.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_uint32, _fp, _fp, ctypes.c_double, _dp,
            ctypes.POINTER(_Out)]
        lib.jt_ingest_free_out.restype = None
        lib.jt_ingest_free_out.argtypes = [ctypes.POINTER(_Out)]
        _lib = lib
        return _lib


def available() -> bool:
    return _load() is not None


def spec_from_converter_config(conv: dict) -> Optional[str]:
    """Compile a converter config into the C++ rule spec, or None when the
    config needs features the native parser does not implement (STRING
    filters, user "weight" global weights, plugins, regexp splitters,
    binary rules) — the caller then stays on the Python converter. num
    filters, ngram splitters, and idf global weights compile to the
    native spec since round 3; combination rules (mul/add over the named
    cross product) since round 4, except combined with idf."""
    if not isinstance(conv, dict):
        return None
    for k in ("string_filter_rules", "binary_rules", "binary_types"):
        if conv.get(k):
            return None
    combo_lines: List[str] = []
    if conv.get("combination_rules"):
        kinds = {"mul": "mul", "add": "add"}
        for tname, params in (conv.get("combination_types") or {}).items():
            m = (params or {}).get("method")
            kinds[tname] = m if m in ("mul", "add") else None
        for r in conv.get("combination_rules"):
            kind = kinds.get(r.get("type"))
            if kind is None:
                return None
            kl, kr = r.get("key_left", "*"), r.get("key_right", "*")
            if any("\t" in k or "\n" in k for k in (kl, kr)):
                return None
            combo_lines.append(f"combo\t{kind}\t{kl}\t{kr}")
        # combos run over pre-hash NAMES; idf weights hashed indices —
        # composing both stays on the Python converter (C++ create also
        # refuses, belt and suspenders)
        for r in conv.get("string_rules") or []:
            if r.get("global_weight") == "idf":
                return None
    # num filters: pure-math transforms appending (key+suffix, f(value)) —
    # expressible in C++ since round 3. Param validity (max > min, std > 0)
    # is the converter's job at server start; unknown methods decline.
    nf_lines: List[str] = []
    if conv.get("num_filter_rules"):
        kinds = {}
        for tname, params in (conv.get("num_filter_types") or {}).items():
            p = params or {}
            try:
                if p.get("method") == "add":
                    kinds[tname] = ("add", float(p["value"]), 0.0)
                elif p.get("method") == "linear_normalization":
                    kinds[tname] = ("linear", float(p["min"]),
                                    float(p["max"]))
                elif p.get("method") == "gaussian_normalization":
                    kinds[tname] = ("gauss", float(p["average"]),
                                    float(p["standard_deviation"]))
                elif p.get("method") == "sigmoid_normalization":
                    kinds[tname] = ("sigmoid", float(p["gain"]),
                                    float(p["bias"]))
            except (KeyError, TypeError, ValueError):
                pass  # missing/odd params: rules using it decline below
        for r in conv.get("num_filter_rules"):
            k = kinds.get(r.get("type"))
            if k is None:
                return None
            suffix = r.get("suffix", "")
            if "\t" in suffix or "\n" in suffix:
                return None
            nf_lines.append(f"nf\t{k[0]}\t{k[1]!r}\t{k[2]!r}\t"
                            f"{r.get('key', '*')}\t{suffix}")
    # type tables: builtin names plus parameterized ngram
    str_types = {"str": "str", "space": "space"}
    for tname, params in (conv.get("string_types") or {}).items():
        method = (params or {}).get("method")
        if method in ("str", "space"):
            str_types[tname] = method
        elif method == "ngram":
            try:
                n = int((params or {}).get("char_num", ""))
            except (TypeError, ValueError):
                n = 0
            # upper bound: C++ parses with atoi (int); a window wider than
            # any realistic text must decline rather than risk divergence
            str_types[tname] = f"ngram:{n}" if 1 <= n <= 65535 else None
        else:
            str_types[tname] = None  # unsupported; rules using it bail
    num_types = {"num": "num", "log": "log", "str": "str"}
    for tname, params in (conv.get("num_types") or {}).items():
        method = (params or {}).get("method")
        if method in ("num", "log", "str"):
            num_types[tname] = method
        else:
            num_types[tname] = None
    lines: List[str] = []
    for r in conv.get("num_rules") or []:
        kind = num_types.get(r.get("type"))
        if kind is None:
            return None
        lines.append(f"num\t{kind}\t{r.get('key', '*')}")
    for r in conv.get("string_rules") or []:
        split = str_types.get(r.get("type"))
        if split is None:
            return None
        sw = r.get("sample_weight", "bin")
        gw = r.get("global_weight", "bin")
        # idf rides the fast path too (the parser takes the WeightManager's
        # dense df tables); user "weight" needs the user-weight map -> no
        if sw not in ("bin", "tf", "log_tf") or gw not in ("bin", "idf"):
            return None
        lines.append(f"str\t{split}\t{sw}\t{gw}\t{r.get('type')}\t"
                     f"{r.get('key', '*')}")
    if not lines:
        return None
    lines = nf_lines + lines + combo_lines  # filters first, combos last
    for ln in lines:  # keys with separators would corrupt the spec
        if "\n" in ln.replace("\t", " ") or ln.count("\t") > 5:
            return None
    return "\n".join(lines)


#: cross-request memo cap for the hybrid filter path (entries are
#: (rule_idx, input) -> output; a repeated key schema makes the inputs
#: highly repetitive in real feeds)
_FILTER_MEMO_MAX = 1 << 16


def deferred_idf_scale(idx: np.ndarray, val: np.ndarray, weights,
                       observe: bool) -> np.ndarray:
    """Flush-time batch idf for a deferred-idf parser's output: observe
    every document of the coalesced flush ONCE (train path), then scale
    the raw sample-weighted values by log(ndocs/df) gathered over the
    index matrix. ONE weights-lock acquisition per flush instead of one
    serialized parse per request — the idf batch-collapse fix. Padding
    entries (index 0, value 0) stay 0 (df 0 → factor 1.0 → 0.0)."""
    if observe:
        weights.observe_rows(idx)
    w = weights.idf_many(idx.reshape(-1)).reshape(idx.shape)
    return (val.astype(np.float64) * w).astype(np.float32)


def _build_prefilters(conv: dict):
    """[(matcher, suffix, fn)] mirroring converter.Config's
    string_filter_rules, built from the same factories so behavior
    cannot drift. Raises on unknown methods (caller declines)."""
    from jubatus_tpu.core.fv.converter import (_build_string_filter,
                                               make_key_matcher)

    types = {name: _build_string_filter(params or {})
             for name, params in
             (conv.get("string_filter_types") or {}).items()}
    out = []
    for r in conv.get("string_filter_rules") or []:
        out.append((make_key_matcher(r["key"]), r["suffix"],
                    types[r["type"]]))
    return out


class IngestParser:
    """One immutable parser handle per (converter config, dim).

    ``needs_weights``: the spec carries idf rules — every parse must be
    given the converter's WeightManager (and run under its lock: the C++
    mutates the df tables in place on the train path).

    ``_prefilters``: hybrid string-filter mode — Python rewrites the
    request with filter-appended string values (regex memoized per
    distinct input) before the C++ parse; see from_converter_config."""

    def __init__(self, spec: str, dim_bits: int) -> None:
        self._prefilters = None
        self._filter_memo: Dict[tuple, str] = {}
        lib = _load()
        if lib is None:
            raise RuntimeError("native ingest unavailable")
        self._lib = lib
        self._mask = (1 << dim_bits) - 1
        # field-positional, not a substring grep: a string TYPE named
        # "idf" must not make a bin-weighted spec demand weight state
        self.needs_weights = any(
            ln.split("\t")[3] == "idf"
            for ln in spec.split("\n") if ln.startswith("str\t"))
        #: deferred-idf mode (from_converter_config sets it for pure-idf
        #: configs): the parse emits RAW sample-weighted values — names
        #: and hashes unchanged — against zeroed df tables (idf factor
        #: 1.0, nothing observed, NO WeightManager lock), and the caller
        #: applies observe + scaling once per coalesced FLUSH
        #: (deferred_idf_scale). Fixes the idf batch-collapse: per-request
        #: parses no longer serialize on the weights lock.
        self.deferred_idf = False
        self._zero_df: Optional[np.ndarray] = None
        self._zero_nd: Optional[np.ndarray] = None
        self._handle = lib.jt_ingest_create(spec.encode())
        if not self._handle:
            raise ValueError(f"native ingest rejected spec: {spec!r}")

    @classmethod
    def from_converter_config(cls, conv: dict,
                              dim_bits: int) -> Optional["IngestParser"]:
        # A/B switch: "0" declines every config, so the server serves the
        # Python-converter path — how the bench prices the fast path's
        # actual win (e2e_rpc_train_samples_per_sec_combo_python etc.)
        if os.environ.get("JUBATUS_TPU_NATIVE_INGEST", "") in \
                ("0", "false", "no"):
            return None
        prefilters = None
        if conv.get("string_filter_rules"):
            # HYBRID path (VERDICT r4 #4): the regex itself stays in
            # Python (std::regex diverges from `re` on real patterns —
            # the round-3 finding), memoized per distinct input string;
            # everything else (datum walk, tokenize, tf, hash, emit)
            # stays in C++. The C++ spec is built from the config SANS
            # filters; parse() first rewrites the request with the
            # filter-appended values, exactly like converter
            # _apply_filters (converter.py:333-344).
            try:
                prefilters = _build_prefilters(conv)
            except Exception:  # noqa: BLE001 — unknown method: python path
                return None
            conv = {k: v for k, v in conv.items()
                    if k not in ("string_filter_rules",
                                 "string_filter_types")}
        spec = spec_from_converter_config(conv)
        if spec is None or not available():
            return None
        try:
            p = cls(spec, dim_bits)
        except (ValueError, RuntimeError):
            return None
        if prefilters is not None:
            p._prefilters = prefilters
        # pure-idf configs defer weighting to the flush: every feature
        # the spec can emit is idf-weighted (all string rules idf, no
        # num/combination rules), so post-merge scaling at flush time is
        # exact — see deferred_idf_scale. Mixed specs keep the in-parse
        # protocol (a post-merge scale would mis-weight hash collisions
        # between idf and non-idf features).
        if p.needs_weights and not conv.get("num_rules") \
                and not conv.get("combination_rules") \
                and all(r.get("global_weight") == "idf"
                        for r in (conv.get("string_rules") or [])):
            p.deferred_idf = True
        return p

    @staticmethod
    def _idx_val(out: "_Out"):
        """Copy the [B, K] arrays out of a parse result (one place owns
        the ctypes-extraction dance: shapes, .copy() before free, and the
        empty-batch dtype fallback). Also the native path's half of the
        ingest hardening (ISSUE 15): the C++ parser never sees the
        Python converter's finite screen, so a client's inf/NaN num
        value would flow straight into the weights here — non-finite
        entries are zeroed into the padding slot (index 0 — features
        never hash there) and counted, exactly like the converter-path
        rejection."""
        b, w = out.batch, out.width
        idx = np.ctypeslib.as_array(out.idx, shape=(b, w)).copy() \
            if b else np.zeros((0, 8), np.int32)
        val = np.ctypeslib.as_array(out.val, shape=(b, w)).copy() \
            if b else np.zeros((0, 8), np.float32)
        bad = ~np.isfinite(val)
        if bad.any():
            n = int(bad.sum())
            val[bad] = 0.0
            idx[bad] = 0
            from jubatus_tpu.utils import tracing

            _registry = tracing.default_registry()
            _registry.count("fv.nonfinite_rejected", n)
        return idx, val

    def _weight_args(self, weights):
        import ctypes as ct

        fp = ct.POINTER(ct.c_float)
        dp = ct.POINTER(ct.c_double)
        return (weights._df_master.ctypes.data_as(fp),
                weights._df_diff.ctypes.data_as(fp),
                float(weights._ndocs_master),
                weights._ndocs_diff.ctypes.data_as(dp))

    def _zero_weight_args(self):
        """Zeroed df tables for deferred-idf parses: df 0 → idf factor
        1.0 (raw values out), observe 0 → nothing written — the parse
        touches no shared state and needs no lock."""
        import ctypes as ct

        if self._zero_df is None:
            self._zero_df = np.zeros(self._mask + 1, np.float32)
            self._zero_nd = np.zeros(1, np.float64)
        fp = ct.POINTER(ct.c_float)
        dp = ct.POINTER(ct.c_double)
        return (self._zero_df.ctypes.data_as(fp),
                self._zero_df.ctypes.data_as(fp),
                0.0,
                self._zero_nd.ctypes.data_as(dp))

    def _apply_prefilters(self, sv: list) -> None:
        """Append filter outputs to one datum's string_values IN PLACE,
        mirroring converter._apply_filters: each rule snapshots the
        current list, so later rules see earlier rules' appends."""
        memo = self._filter_memo
        for ri, (match, suffix, fn) in enumerate(self._prefilters):
            for kv in list(sv):
                k, v = kv[0], kv[1]
                if not match(k):
                    continue
                key = (ri, v)
                fv = memo.get(key)
                if fv is None:
                    fv = fn(v)
                    if len(memo) >= _FILTER_MEMO_MAX:
                        memo.clear()
                    memo[key] = fv
                sv.append([k + suffix, fv])

    def _prefilter_rewrite(self, raw: bytes, with_labels: bool):
        """The hybrid filter pre-pass: decode the request, apply string
        filters (Python regex, memoized), re-encode for the C++ parse.
        Returns None when the wire shape is not the expected format —
        the caller then falls back to the generic path, which fails or
        serves it with identical semantics."""
        import msgpack

        try:
            req = msgpack.unpackb(raw, raw=False, strict_map_key=False,
                                  use_list=True,
                                  unicode_errors="surrogateescape")
            if not isinstance(req, list) or len(req) != 2 \
                    or not isinstance(req[1], list):
                return None
            for item in req[1]:
                d = item[1] if with_labels else item
                # datums are inline arrays on this wire (Datum.to_msgpack
                # emits the [sv, nv, bv] structure; the C++ parser reads
                # it with array_len directly) — anything else cannot
                # parse natively regardless, so fall back
                if not isinstance(d, list) or not d \
                        or not isinstance(d[0], list):
                    return None
                self._apply_prefilters(d[0])
            return msgpack.packb(req, use_bin_type=True,
                                 unicode_errors="surrogateescape")
        except Exception:  # noqa: BLE001 — any wire oddity: generic path
            return None

    def parse_indexed(self, raw: bytes, weights=None):
        """Raw train params msgpack -> (labels, idx [B,K] i32, val [B,K] f32).

        ``labels`` is a float32 array for regression targets, or — for
        string labels — a ``(uniq_labels, label_idx)`` pair: the DISTINCT
        label strings plus an int32 [B] row->uniq index (the C++ parser
        dedups, so the host never loops over B Python strings). None when
        the wire shape is not the expected train format (caller falls back
        to the generic decode path).

        ``weights``: the converter's WeightManager, REQUIRED for idf specs
        (train path: documents are observed and values idf-scaled exactly
        like converter.convert(update_weights=True)); caller must hold
        ``weights.lock``."""
        if self._prefilters is not None:
            raw = self._prefilter_rewrite(raw, with_labels=True)
            if raw is None:
                return None
        out = _Out()
        if self.needs_weights:
            if self.deferred_idf:
                dfm, dfd, nm, nd = self._zero_weight_args()
                rc = self._lib.jt_ingest_parse_w(
                    self._handle, raw, len(raw), self._mask, dfm, dfd, nm,
                    nd, 0, ctypes.byref(out))
            else:
                if weights is None:
                    return None
                dfm, dfd, nm, nd = self._weight_args(weights)
                rc = self._lib.jt_ingest_parse_w(
                    self._handle, raw, len(raw), self._mask, dfm, dfd, nm,
                    nd, 1, ctypes.byref(out))
        else:
            rc = self._lib.jt_ingest_parse(self._handle, raw, len(raw),
                                           self._mask, ctypes.byref(out))
        if rc != 0:
            return None
        try:
            b = out.batch
            idx, val = self._idx_val(out)
            if out.labels_numeric:
                labels = np.ctypeslib.as_array(
                    out.targets, shape=(b,)).copy() if b else \
                    np.zeros(0, np.float32)
            else:
                u = out.uniq
                offs = np.ctypeslib.as_array(out.label_off, shape=(u + 1,))
                blob = bytes(np.ctypeslib.as_array(
                    out.labels, shape=(max(int(offs[-1]), 1),)))
                uniq = [
                    blob[offs[i]:offs[i + 1]].decode("utf-8",
                                                     "surrogateescape")
                    for i in range(u)
                ]
                lidx = np.ctypeslib.as_array(
                    out.label_idx, shape=(b,)).copy() if b else \
                    np.zeros(0, np.int32)
                labels = (uniq, lidx)
        finally:
            self._lib.jt_ingest_free_out(ctypes.byref(out))
        return labels, idx, val

    def parse(self, raw: bytes, weights=None):
        """Like parse_indexed but with per-row label strings (compat shape:
        a list of B strings for classifiers, float32 array for targets)."""
        parsed = self.parse_indexed(raw, weights=weights)
        if parsed is None:
            return None
        labels, idx, val = parsed
        if isinstance(labels, tuple):
            uniq, lidx = labels
            labels = [uniq[i] for i in lidx]
        return labels, idx, val

    def parse_datums(self, raw: bytes, weights=None):
        """Raw classify/estimate params msgpack ([name, [datum, ...]]) ->
        (idx [B,K] i32, val [B,K] f32), or None when the wire shape is
        not a datum list. For idf specs, ``weights`` is read (NOT
        observed — queries never record documents; caller holds the
        lock)."""
        if self._prefilters is not None:
            raw = self._prefilter_rewrite(raw, with_labels=False)
            if raw is None:
                return None
        out = _Out()
        if self.needs_weights:
            if self.deferred_idf:
                dfm, dfd, nm, nd = self._zero_weight_args()
            elif weights is None:
                return None
            else:
                dfm, dfd, nm, nd = self._weight_args(weights)
            rc = self._lib.jt_ingest_parse_datums_w(
                self._handle, raw, len(raw), self._mask, dfm, dfd, nm, nd,
                ctypes.byref(out))
        else:
            rc = self._lib.jt_ingest_parse_datums(
                self._handle, raw, len(raw), self._mask, ctypes.byref(out))
        if rc != 0:
            return None
        try:
            return self._idx_val(out)
        finally:
            self._lib.jt_ingest_free_out(ctypes.byref(out))

    def __del__(self):  # noqa: D105
        try:
            if getattr(self, "_handle", None):
                self._lib.jt_ingest_destroy(self._handle)
                self._handle = None
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass
