"""XLA/Pallas learning kernels — the compute plane.

Each module provides pure, jittable functions over fixed-shape arrays.
State lives in pytrees of (master, diff) pairs: training writes the diff,
the mix collective psums diffs across replicas (parallel/mix.py), and
masters absorb the mixed diff. All updates are formulated to be *additive*
in the diff so the psum is the exact reduction, not an approximation of the
reference's sequential pairwise fold (linear_mixer.cpp:481-499).
"""
