"""Multiclass linear classifier kernels: perceptron / PA / PA1 / PA2 / CW /
AROW / NHERD.

Rebuild of jubatus_core's classifier algorithms (method names from
/root/reference/config/classifier/*.json; consumed via
classifier_factory::create_classifier, reference
jubatus/server/server/classifier_serv.cpp:108-109) as jitted XLA programs.

Design (TPU-first, not a port):

- Weights are dense [L, D] arrays over the hashed feature space (D = 2^k),
  split as ``w`` (master, state as of last mix) + ``dw`` (local diff).
  Effective weights are w + dw; training scatters into dw only.
- Confidence-weighted methods (CW/AROW/NHERD) keep the diagonal covariance as
  *precision* (1/sigma), also split master+diff, because every update rule's
  precision increment is additive (e.g. AROW: Sigma^-1 += x x^T / r). Additive
  diffs make the distributed mix an exact psum over ICI — the reference's
  sequential get_diff/put_diff fold (linear_mixer.cpp:437-509) becomes one
  XLA collective with identical semantics regardless of node count or order.
- A training microbatch is processed with lax.scan over examples, preserving
  the reference's per-example online semantics (classifier_serv.cpp:137-143)
  while amortizing dispatch; gathers/scatters are XLA dynamic-slice ops on
  TPU. Padding entries (idx 0, val 0) are no-ops by construction.

Update rules (margin m = s_correct - s_best_wrong, loss l = max(0, 1-m),
x2 = ||x||^2, v = x'(Sigma_c + Sigma_w)x, parameter r/C/phi =
"regularization_weight"):

  perceptron: on mistake (m <= 0): w_c += x, w_w -= x
  PA:   alpha = l / (2 x2)
  PA1:  alpha = min(C, l / (2 x2))
  PA2:  alpha = l / (2 x2 + 1/(2C))
  AROW: beta = 1/(v + r); alpha = l * beta; w += alpha Sigma x;
        precision += x^2 / r
  NHERD: alpha = l / (v + r); w += alpha Sigma x;
        precision += x^2 (v + 2r) / r^2
  CW:   alpha from the Dredze/Crammer closed form with phi;
        precision += 2 alpha phi x^2
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

METHODS = ("perceptron", "PA", "PA1", "PA2", "CW", "AROW", "NHERD")
CONFIDENCE_METHODS = ("CW", "AROW", "NHERD")

_NEG = -1e30


class ClassifierState(NamedTuple):
    """Pytree of classifier model arrays.

    w, dw:       [L, D] float32 — master weights / local diff since last mix
    prec, dprec: [L, D] float32 — diagonal precision (1/sigma) master / diff.
                 For non-confidence methods these stay at their init and are
                 ignored (kept so the state pytree shape is method-independent
                 only for confidence methods; PA-family states carry (1,1)
                 placeholders to avoid wasting HBM).
    """

    w: jax.Array
    dw: jax.Array
    prec: jax.Array
    dprec: jax.Array


def init_state(num_labels: int, dim: int, confidence: bool) -> ClassifierState:
    shape = (num_labels, dim)
    cshape = shape if confidence else (1, 1)
    return ClassifierState(
        w=jnp.zeros(shape, jnp.float32),
        dw=jnp.zeros(shape, jnp.float32),
        prec=jnp.ones(cshape, jnp.float32),
        dprec=jnp.zeros(cshape, jnp.float32),
    )


def grow_labels(state: ClassifierState, new_num_labels: int) -> ClassifierState:
    """Host-side label-capacity growth (repack + recompile on next call)."""
    L = state.w.shape[0]
    if new_num_labels <= L:
        return state
    pad = new_num_labels - L

    def _pad(a, fill):
        if a.shape == (1, 1):
            return a
        return jnp.concatenate([a, jnp.full((pad, a.shape[1]), fill, a.dtype)], axis=0)

    return ClassifierState(
        w=_pad(state.w, 0.0),
        dw=_pad(state.dw, 0.0),
        prec=_pad(state.prec, 1.0),
        dprec=_pad(state.dprec, 0.0),
    )


def decide_updates(s, labels, label_mask, x2, v, x2_vec, param, *, method):
    """The shared per-batch update decision — one implementation for the
    single-chip path (train_batch_parallel) and the pod path
    (parallel/spmd.py), so the two can never drift numerically.

    Inputs are already globally reduced where sharded: s [B, L] raw scores,
    x2/v [B] (= ||x||^2 and x'(Sig_c+Sig_w)x), x2_vec [B, K] *local* squared
    feature values (may be a shard's slice — dp is per-feature and local).
    Returns (wrong [B], alpha [B], alpha_w [B], dp [B, K] or None): alpha
    scales the correct row's update, alpha_w the rival row's. When no rival
    label exists (single-label model) the rival score is taken as 0 — the
    reference still learns from the first label's examples — and alpha_w is
    zeroed so nothing lands on the dead slot `wrong` points at.
    """
    B = s.shape[0]
    rows = jnp.arange(B)
    s = jnp.where(label_mask[None, :], s, _NEG)
    s_correct = s[rows, labels]
    s_masked = s.at[rows, labels].set(_NEG)
    s_wrong = jnp.max(s_masked, axis=1)
    wrong = jnp.argmax(s_masked, axis=1)
    no_rival = s_wrong <= _NEG / 2
    margin = s_correct - jnp.where(no_rival, 0.0, s_wrong)
    loss = jnp.maximum(0.0, 1.0 - margin)
    live = x2 > 0.0
    alpha, dp = _alpha_and_prec(method, param, margin, loss, x2, v, x2_vec)
    alpha = jnp.where(live, alpha, 0.0)
    alpha_w = jnp.where(no_rival, 0.0, alpha)
    if dp is not None:
        dp = jnp.where((live & (alpha > 0.0))[:, None], dp, 0.0)
    return wrong, alpha, alpha_w, dp


@functools.partial(jax.jit, donate_argnums=())
def scores(state: ClassifierState, idx: jax.Array, val: jax.Array,
           label_mask: jax.Array) -> jax.Array:
    """Batch classify scores.

    idx/val: [B, K] hashed sparse batch; label_mask: [L] bool (live labels).
    Returns [B, L] margins with dead labels at -inf.

    Layout: the gather runs over a transposed [D, L] table so one gather
    descriptor fetches every label's weight for a feature — TPU gather
    cost is per DESCRIPTOR, not per element (measured on v5e: a [D, 4]
    row gather costs the same ~75 ms/2M as a [D] element gather, while L
    separate gathers scale linearly).
    """
    eff = (state.w + state.dw).T  # [D, L]
    g = jnp.take(eff, idx.reshape(-1), axis=0)       # [B*K, L]
    g = g.reshape(idx.shape + (eff.shape[1],))       # [B, K, L]
    s = jnp.einsum("bkl,bk->bl", g, val)
    return jnp.where(label_mask[None, :], s, _NEG)


def _alpha_and_prec(method: str, param: float, margin, loss, x2, v, x2_vec):
    """Per-method update magnitude and precision increment (per-feature vec).

    Shape-polymorphic: margin/loss/x2/v are scalars (sequential path) or [B]
    (parallel path); x2_vec has one extra trailing [K] axis. Returns
    (alpha, dprec_vec) where the weight update is w_c += alpha * sigma_c * x,
    w_w -= alpha * sigma_w * x (sigma == 1 for PA-family) and dprec_vec is
    added to both rows' precision diff.
    """

    def vec(a):
        """Broadcast a per-example quantity against the per-feature axis."""
        a = jnp.asarray(a)
        return a[..., None] if jnp.ndim(x2_vec) > jnp.ndim(a) else a

    x2s = jnp.maximum(x2, 1e-12)
    if method == "perceptron":
        alpha = jnp.where(margin <= 0.0, 1.0, 0.0)
        return alpha, None
    if method == "PA":
        alpha = jnp.where(loss > 0.0, loss / (2.0 * x2s), 0.0)
        return alpha, None
    if method == "PA1":
        alpha = jnp.where(loss > 0.0, jnp.minimum(param, loss / (2.0 * x2s)), 0.0)
        return alpha, None
    if method == "PA2":
        alpha = jnp.where(loss > 0.0, loss / (2.0 * x2s + 1.0 / (2.0 * param)), 0.0)
        return alpha, None
    if method == "AROW":
        r = param
        beta = 1.0 / (v + r)
        alpha = jnp.where(loss > 0.0, loss * beta, 0.0)
        dp = jnp.where(vec(loss) > 0.0, x2_vec / r, 0.0)
        return alpha, dp
    if method == "NHERD":
        r = param
        alpha = jnp.where(loss > 0.0, loss / (v + r), 0.0)
        dp = jnp.where(vec(loss) > 0.0, x2_vec * vec(v + 2.0 * r) / (r * r), 0.0)
        return alpha, dp
    if method == "CW":
        phi = param
        m = margin
        a = 1.0 + 2.0 * phi * m
        vs = jnp.maximum(v, 1e-12)
        disc = jnp.maximum(a * a - 8.0 * phi * (m - phi * vs), 0.0)
        alpha = jnp.maximum(0.0, (-a + jnp.sqrt(disc)) / (4.0 * phi * vs))
        dp = 2.0 * vec(alpha) * phi * x2_vec
        return alpha, dp
    raise ValueError(f"unknown classifier method {method!r}")


@functools.partial(jax.jit, static_argnames=("method",), donate_argnums=(0,))
def train_batch_parallel(
    state: ClassifierState,
    idx: jax.Array,        # [B, K] int32
    val: jax.Array,        # [B, K] float32
    labels: jax.Array,     # [B] int32 — correct label row per example
    label_mask: jax.Array, # [L] bool — live labels
    param: float,
    *,
    method: str,
) -> ClassifierState:
    """Vectorized microbatch update — the TPU hot path.

    Every example computes its margin/alpha against the batch-start snapshot
    and all updates land in one scatter-add (bounded staleness *within* a
    microbatch; batches remain sequential). This is the batching compromise
    SURVEY.md §7 hard-part (b) calls for: the per-example lax.scan path
    (train_batch_sequential) is ~40 ms/1024 examples on a v5e chip because a
    sequential scan of tiny gathers/scatters is latency-bound, while this
    path is one gather + one einsum + one scatter over the whole batch.
    """
    confidence = method in CONFIDENCE_METHODS
    w, dw, prec, dprec = state
    num_labels = w.shape[0]

    # Packed-layout gather: pre-sum the master+diff planes (dense adds are
    # bandwidth-trivial), interleave them as one [D, 2L] (or [D, L]) table,
    # and fetch EVERYTHING each feature needs with a single descriptor.
    # Measured on v5e (B=32k, K=64, D=2^20, AROW): the four element
    # gathers cost ~101 ms; the packed single gather ~75 ms for the same
    # data — gather cost is per descriptor, not per element — for a
    # bit-exact 1.20x on the whole step (docs/PERF_NOTES.md).
    eff = w + dw                                                   # [L, D]
    if confidence:
        packed = jnp.concatenate([eff, prec + dprec], axis=0).T    # [D, 2L]
    else:
        packed = eff.T                                             # [D, L]
    g = jnp.take(packed, idx.reshape(-1), axis=0)
    g = g.reshape(idx.shape + (packed.shape[1],))                  # [B, K, *]
    eff_g = jnp.moveaxis(g[..., :num_labels], -1, 0)               # [L, B, K]
    s = jnp.einsum("lbk,bk->bl", eff_g, val)
    x2_vec = val * val                                             # [B, K]
    x2 = jnp.sum(x2_vec, axis=1)                                   # [B]

    if confidence:
        p_g = jnp.moveaxis(g[..., num_labels:], -1, 0)             # [L, B, K]
        p_c = jnp.take_along_axis(p_g, labels[None, :, None], axis=0)[0]  # [B,K]
        sig_c = 1.0 / p_c
    else:
        sig_c = jnp.ones_like(val)

    # v needs sigma of the *wrong* row, which needs the scores first; compute
    # the margin decision with a provisional v=0 only for non-confidence
    # methods (their alpha ignores v).
    if confidence:
        # first pass for `wrong` (alpha ignored), then exact v
        wrong0, _, _, _ = decide_updates(
            s, labels, label_mask, x2, jnp.zeros_like(x2), x2_vec, param,
            method=method,
        )
        p_w = jnp.take_along_axis(p_g, wrong0[None, :, None], axis=0)[0]
        # no rival label → `wrong0` points at a dead/arbitrary row; the
        # nonexistent rival carries the unit precision prior, not that
        # row's (possibly trained) precision
        no_rival = jnp.sum(label_mask) < 2
        sig_w = jnp.where(no_rival, 1.0, 1.0 / p_w)
        v = jnp.sum((sig_c + sig_w) * x2_vec, axis=1)              # [B]
    else:
        sig_w = jnp.ones_like(val)
        v = jnp.zeros_like(x2)

    wrong, alpha, alpha_w, dp = decide_updates(
        s, labels, label_mask, x2, v, x2_vec, param, method=method
    )

    # NB: a single fused [2B, K] scatter (concat correct+wrong updates) was
    # measured numerically equivalent but throughput-neutral on v5e; two
    # plain scatters stay for simplicity
    up_c = alpha[:, None] * sig_c * val                            # [B, K]
    up_w = alpha_w[:, None] * sig_w * val
    dw = dw.at[labels[:, None], idx].add(up_c)
    dw = dw.at[wrong[:, None], idx].add(-up_w)
    if confidence:
        dprec = dprec.at[labels[:, None], idx].add(dp)
        dprec = dprec.at[wrong[:, None], idx].add(
            jnp.where((alpha_w > 0.0)[:, None], dp, 0.0)
        )
    return ClassifierState(w, dw, prec, dprec)


@functools.partial(jax.jit, donate_argnums=())
def scores_schema(state: ClassifierState, uidx: jax.Array, val: jax.Array,
                  label_mask: jax.Array) -> jax.Array:
    """Batch classify scores for a UNIFORM-SCHEMA batch: every example
    carries the same hashed index vector ``uidx`` [K] (a fixed key
    schema — the common production feed shape). The [B*K]-element gather
    of scores() collapses to K descriptors and the score math becomes a
    dense [B,K]x[K,L] matmul — MXU work instead of element-granular
    addressing (per-descriptor cost, docs/PERF_NOTES.md)."""
    eff_sub = jnp.take(state.w + state.dw, uidx, axis=1)  # [L, K]
    s = val @ eff_sub.T                                   # [B, L]
    return jnp.where(label_mask[None, :], s, _NEG)


def _expand_combo(base_val: jax.Array, a_idx: jax.Array, b_idx: jax.Array,
                  mul_mask: jax.Array) -> jax.Array:
    """Device-side combination-feature expansion for a uniform-schema
    batch: the cross product's pair values are a bilinear function of the
    [B, K0] BASE feature matrix, so the host ships K0-wide rows and the
    device materializes the S combo slots itself — slot s =
    base[:, a]*base[:, b] (mul) or base[:, a]+base[:, b] (add). The wire
    and host-emit cost of the (K0 + S)-wide row (528 slots at the bench
    shape) drops to K0. Padding rows are all-zero base rows, so every
    slot value is 0 there (0*0 = 0+0 = 0) and the no-op guarantee holds.
    Returns the full [B, K0 + S] value matrix aligned with the caller's
    uidx = concat(base_idx_row, slot_idx)."""
    va = jnp.take(base_val, a_idx, axis=1)
    vb = jnp.take(base_val, b_idx, axis=1)
    slots = jnp.where(mul_mask[None, :], va * vb, va + vb)
    return jnp.concatenate([base_val, slots], axis=1)


@functools.partial(jax.jit, donate_argnums=())
def scores_schema_combo(state: ClassifierState, uidx: jax.Array,
                        base_val: jax.Array, a_idx: jax.Array,
                        b_idx: jax.Array, mul_mask: jax.Array,
                        label_mask: jax.Array) -> jax.Array:
    """scores_schema with on-device combination expansion (see
    _expand_combo): ``uidx`` is the full base+slot index vector, the host
    ships only the base columns."""
    val = _expand_combo(base_val, a_idx, b_idx, mul_mask)
    eff_sub = jnp.take(state.w + state.dw, uidx, axis=1)
    s = val @ eff_sub.T
    return jnp.where(label_mask[None, :], s, _NEG)


@functools.partial(jax.jit, static_argnames=("method",), donate_argnums=(0,))
def train_batch_schema(
    state: ClassifierState,
    uidx: jax.Array,       # [K] int32 — the shared hashed index vector
    val: jax.Array,        # [B, K] float32
    labels: jax.Array,     # [B] int32 — correct label row per example
    label_mask: jax.Array, # [L] bool — live labels
    param: float,
    *,
    method: str,
) -> ClassifierState:
    """Vectorized microbatch update for a UNIFORM-SCHEMA batch.

    Semantics are identical to train_batch_parallel (every example
    decides against the batch-start snapshot, updates land together) —
    only the execution plan differs: with one shared index vector the
    B*K-element packed gather collapses to K descriptors
    (``take(.., uidx)``), scoring becomes a [B,K]x[K,L] matmul, and the
    two B*K-element scatter-adds become label-grouped dense reductions
    (one-hot matmuls, [L,B]x[B,K]) followed by ONE K-column scatter.
    On v5e the sparse step is addressing-bound at ~37 ns/element
    (docs/PERF_NOTES.md); this path removes that term entirely for
    schema-uniform traffic and feeds the MXU instead. Float summation
    order differs from the sparse plan (dense reductions vs scatter
    order), so results agree to tolerance, not bitwise.

    Duplicate entries in ``uidx`` (e.g. width-padding zeros) are safe:
    the final ``.at[:, uidx].add`` accumulates per occurrence, exactly
    like the sparse scatter over repeated (b, k) slots, and padded
    columns carry val 0 so they contribute nothing.
    """
    return _train_schema_impl(state, uidx, val, labels, label_mask, param,
                              method)


@functools.partial(jax.jit, static_argnames=("method",), donate_argnums=(0,))
def train_batch_schema_combo(
    state: ClassifierState,
    uidx: jax.Array,       # [K0+S] int32 — base row + combo slot indices
    base_val: jax.Array,   # [B, K0] float32 — base feature values only
    a_idx: jax.Array,      # [S] int32 — left base column per slot
    b_idx: jax.Array,      # [S] int32 — right base column per slot
    mul_mask: jax.Array,   # [S] bool — mul (True) vs add per slot
    labels: jax.Array,
    label_mask: jax.Array,
    param: float,
    *,
    method: str,
) -> ClassifierState:
    """train_batch_schema with on-device combination expansion: the host
    ships the K0 base columns, the device materializes the S combo slots
    (_expand_combo) and runs the identical dense schema update. The
    caller guarantees ``uidx`` has no duplicate indices across base and
    slots (the plan builder declines colliding schemas), so expansion +
    schema update is exactly the merged per-datum feature vector."""
    val = _expand_combo(base_val, a_idx, b_idx, mul_mask)
    return _train_schema_impl(state, uidx, val, labels, label_mask, param,
                              method)


def _train_schema_impl(state, uidx, val, labels, label_mask, param, method):
    confidence = method in CONFIDENCE_METHODS
    w, dw, prec, dprec = state
    num_labels = w.shape[0]

    eff_sub = jnp.take(w + dw, uidx, axis=1)                       # [L, K]
    s = val @ eff_sub.T                                            # [B, L]
    x2_vec = val * val                                             # [B, K]
    x2 = jnp.sum(x2_vec, axis=1)                                   # [B]

    if confidence:
        sig_sub = 1.0 / jnp.take(prec + dprec, uidx, axis=1)       # [L, K]
        sig_c = jnp.take(sig_sub, labels, axis=0)                  # [B, K]
        # `wrong` needs the scores only, so the provisional pass mirrors
        # train_batch_parallel exactly (alpha from it is ignored)
        wrong0, _, _, _ = decide_updates(
            s, labels, label_mask, x2, jnp.zeros_like(x2), x2_vec, param,
            method=method,
        )
        no_rival = jnp.sum(label_mask) < 2
        sig_w = jnp.where(no_rival, 1.0,
                          jnp.take(sig_sub, wrong0, axis=0))       # [B, K]
        v = jnp.sum((sig_c + sig_w) * x2_vec, axis=1)              # [B]
    else:
        sig_c = jnp.ones_like(val)
        sig_w = jnp.ones_like(val)
        v = jnp.zeros_like(x2)

    wrong, alpha, alpha_w, dp = decide_updates(
        s, labels, label_mask, x2, v, x2_vec, param, method=method
    )

    up_c = alpha[:, None] * sig_c * val                            # [B, K]
    up_w = alpha_w[:, None] * sig_w * val
    onehot_c = jax.nn.one_hot(labels, num_labels, dtype=val.dtype)  # [B, L]
    onehot_w = jax.nn.one_hot(wrong, num_labels, dtype=val.dtype)
    delta_w = onehot_c.T @ up_c - onehot_w.T @ up_w                # [L, K]
    dw = dw.at[:, uidx].add(delta_w)
    if confidence:
        dp_w = jnp.where((alpha_w > 0.0)[:, None], dp, 0.0)
        delta_p = onehot_c.T @ dp + onehot_w.T @ dp_w              # [L, K]
        dprec = dprec.at[:, uidx].add(delta_p)
    return ClassifierState(w, dw, prec, dprec)


@functools.partial(jax.jit, static_argnames=("method",), donate_argnums=(0,))
def train_batch_sequential(
    state: ClassifierState,
    idx: jax.Array,        # [B, K] int32
    val: jax.Array,        # [B, K] float32
    labels: jax.Array,     # [B] int32 — correct label row per example
    label_mask: jax.Array, # [L] bool — live labels
    param: float,
    *,
    method: str,
) -> ClassifierState:
    """Online train with exact per-example sequential semantics (lax.scan).

    Matches the reference's per-datum update loop exactly
    (classifier_serv.cpp:137-143); use train_batch_parallel for throughput.
    """
    confidence = method in CONFIDENCE_METHODS
    mask_scores = jnp.where(label_mask, 0.0, _NEG)  # [L]

    def step(carry, ex):
        w, dw, prec, dprec = carry
        e_idx, e_val, e_label = ex
        # effective weights for this example's features: [L, K]
        w_g = jnp.take(w, e_idx, axis=1) + jnp.take(dw, e_idx, axis=1)
        s = w_g @ e_val + mask_scores  # [L]
        s_correct = s[e_label]
        s_wrong = jnp.max(s.at[e_label].set(_NEG))
        wrong = jnp.argmax(s.at[e_label].set(_NEG))
        # no competitor label → rival score 0 (still learn; nothing lands on
        # the dead slot `wrong` points at)
        no_rival = s_wrong <= _NEG / 2
        margin = s_correct - jnp.where(no_rival, 0.0, s_wrong)
        loss = jnp.maximum(0.0, 1.0 - margin)
        x2_vec = e_val * e_val
        x2 = jnp.sum(x2_vec)
        live = x2 > 0.0

        if confidence:
            p_g = jnp.take(prec, e_idx, axis=1) + jnp.take(dprec, e_idx, axis=1)
            sig_c = 1.0 / p_g[e_label]  # [K]
            # nonexistent rival carries the unit precision prior
            sig_w = jnp.where(no_rival, 1.0, 1.0 / p_g[wrong])
            v = jnp.sum((sig_c + sig_w) * x2_vec)
        else:
            sig_c = sig_w = 1.0
            v = 0.0

        alpha, dp = _alpha_and_prec(method, param, margin, loss, x2, v, x2_vec)
        alpha = jnp.where(live, alpha, 0.0)
        alpha_w = jnp.where(no_rival, 0.0, alpha)

        dw = dw.at[e_label, e_idx].add(alpha * sig_c * e_val)
        dw = dw.at[wrong, e_idx].add(-alpha_w * sig_w * e_val)
        if confidence:
            dp = jnp.where(live & (alpha > 0.0), dp, 0.0)
            dprec = dprec.at[e_label, e_idx].add(dp)
            dprec = dprec.at[wrong, e_idx].add(
                jnp.where(alpha_w > 0.0, dp, 0.0)
            )
        return (w, dw, prec, dprec), alpha > 0.0

    (w, dw, prec, dprec), updated = jax.lax.scan(
        step, tuple(state), (idx, val, labels)
    )
    return ClassifierState(w, dw, prec, dprec)


def train_batch(
    state: ClassifierState,
    idx: jax.Array,
    val: jax.Array,
    labels: jax.Array,
    label_mask: jax.Array,
    param: float,
    *,
    method: str,
    mode: str = "parallel",
) -> ClassifierState:
    """Train dispatcher: mode="parallel" (TPU hot path, intra-batch snapshot
    semantics) or "sequential" (exact reference per-datum semantics)."""
    if mode == "parallel":
        fn = train_batch_parallel
    elif mode == "sequential":
        fn = train_batch_sequential
    else:
        raise ValueError(f"unknown train mode {mode!r}")
    return fn(state, idx, val, labels, label_mask, param, method=method)


# -- mixable protocol -------------------------------------------------------
def get_diff(state: ClassifierState):
    """Local diff pytree; mix = elementwise sum (associative → psum-exact)."""
    return {"dw": state.dw, "dprec": state.dprec, "count": jnp.float32(1.0)}


def mix_diffs(lhs, rhs):
    return jax.tree_util.tree_map(lambda a, b: a + b, lhs, rhs)


@jax.jit
def put_diff(state: ClassifierState, diff) -> ClassifierState:
    """Absorb the summed cross-replica diff into the master (average weights,
    sum precision — precision is additive information like the reference's
    confidence merge) and reset local diffs.

    Accepts ROW-TRIMMED diffs: the mix plane ships only the active label
    rows ([n_labels, D], not the pow2-padded [capacity, D] tables — a 4x
    wire cut at the bench shape), applied here to the leading rows; a
    full-shape diff is the n == capacity case of the same update."""
    n = jnp.maximum(diff["count"], 1.0)
    rows = diff["dw"].shape[0]
    return ClassifierState(
        w=state.w.at[:rows].add(diff["dw"] / n),
        dw=jnp.zeros_like(state.dw),
        prec=state.prec.at[:diff["dprec"].shape[0]].add(diff["dprec"]),
        dprec=jnp.zeros_like(state.dprec),
    )
