"""Clustering kernels: k-means (Lloyd), diagonal-covariance GMM (EM), DBSCAN.

Rebuild of the jubatus_core clustering methods the reference consumes
(method names kmeans/gmm/dbscan from /root/reference/config/clustering/*.json,
SURVEY.md §2.9) as jitted XLA programs.

TPU design: cluster batches are *compacted* host-side from the hashed sparse
feature space to a dense [N, d] matrix over the batch's active dimensions
(d = #distinct features in the batch — clustering workloads are low-dim, so
this is small), then every iteration is dense linear algebra:

- kmeans: assignment via the ||x||² - 2xCᵀ + ||c||² expansion — the x@Cᵀ
  cross term is one MXU matmul per iteration; center update is a one-hot
  matmul (Aᵀx / counts), all inside lax.fori_loop. kmeans++-style seeding
  (distance-weighted sampling) included.
- gmm: EM with diagonal covariance, responsibilities [N, K] computed from
  the same matmul expansion, fixed iteration count under fori_loop.
- dbscan: the [N, N] pairwise-distance matrix is one matmul; neighbor
  counting and core-point detection are vectorized; the label propagation
  (connected components over core points) runs as an iterated boolean
  matmul reachability expansion — no host BFS.

All functions take weights w [N] (coreset/compressor point weights) and
respect them in center/covariance updates.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# shared
# ---------------------------------------------------------------------------
@jax.jit
def pairwise_sq_dists(x, y):
    """[N, d], [M, d] → [N, M] squared euclidean distances (MXU cross term)."""
    xn = jnp.sum(x * x, axis=1)[:, None]
    yn = jnp.sum(y * y, axis=1)[None, :]
    return jnp.maximum(xn - 2.0 * (x @ y.T) + yn, 0.0)


# ---------------------------------------------------------------------------
# k-means
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("k", "iters"))
def kmeans_fit(x, w, *, k: int, iters: int = 25, seed: int = 0):
    """Weighted Lloyd k-means.

    x [N, d] points, w [N] weights → (centers [k, d], assign [N]).
    Seeding: first center = max-weight point, then distance-weighted
    (kmeans++-style) picks with a deterministic PRNG.
    """
    n = x.shape[0]
    key = jax.random.PRNGKey(seed)

    def seed_body(i, carry):
        centers, key = carry
        d2 = jnp.min(pairwise_sq_dists(x, centers), axis=1)
        probs = d2 * w
        key, sub = jax.random.split(key)
        # distance-weighted categorical pick; falls back to uniform when all
        # points coincide with existing centers
        total = jnp.sum(probs)
        logits = jnp.where(total > 0, jnp.log(jnp.maximum(probs, 1e-30)),
                           jnp.zeros_like(probs))
        pick = jax.random.categorical(sub, logits)
        return centers.at[i].set(x[pick]), key

    first = x[jnp.argmax(w)]
    centers0 = jnp.zeros((k, x.shape[1]), x.dtype).at[0].set(first)
    centers0, _ = jax.lax.fori_loop(1, k, seed_body, (centers0, key))

    def lloyd(_, centers):
        d2 = pairwise_sq_dists(x, centers)            # [N, k]
        assign = jnp.argmin(d2, axis=1)
        onehot = jax.nn.one_hot(assign, k, dtype=x.dtype) * w[:, None]  # [N, k]
        sums = onehot.T @ x                           # [k, d] MXU
        counts = jnp.sum(onehot, axis=0)[:, None]
        return jnp.where(counts > 0, sums / jnp.maximum(counts, 1e-30), centers)

    centers = jax.lax.fori_loop(0, iters, lloyd, centers0)
    assign = jnp.argmin(pairwise_sq_dists(x, centers), axis=1)
    return centers, assign


# ---------------------------------------------------------------------------
# gmm (diagonal covariance EM)
# ---------------------------------------------------------------------------
class GMMState(NamedTuple):
    means: jnp.ndarray    # [k, d]
    var: jnp.ndarray      # [k, d] diagonal covariance
    pi: jnp.ndarray       # [k] mixing weights


@functools.partial(jax.jit, static_argnames=("k", "iters"))
def gmm_fit(x, w, *, k: int, iters: int = 25, seed: int = 0):
    """Weighted diagonal-covariance EM → (GMMState, assign [N])."""
    centers, _ = kmeans_fit(x, w, k=k, iters=5, seed=seed)
    d = x.shape[1]
    var0 = jnp.maximum(jnp.var(x, axis=0), 1e-4)
    state0 = GMMState(means=centers,
                      var=jnp.broadcast_to(var0, (k, d)).astype(x.dtype),
                      pi=jnp.full((k,), 1.0 / k, x.dtype))

    def log_resp(state):
        # log N(x | mu_c, diag var_c) for all (n, c)
        inv = 1.0 / state.var                                     # [k, d]
        x2 = (x * x) @ inv.T                                      # [N, k] MXU
        xm = x @ (state.means * inv).T                            # [N, k] MXU
        m2 = jnp.sum(state.means * state.means * inv, axis=1)     # [k]
        quad = x2 - 2.0 * xm + m2[None, :]
        logdet = jnp.sum(jnp.log(state.var), axis=1)              # [k]
        ll = -0.5 * (quad + logdet[None, :]) + jnp.log(state.pi)[None, :]
        return ll - jax.scipy.special.logsumexp(ll, axis=1, keepdims=True)

    def em(_, state):
        r = jnp.exp(log_resp(state)) * w[:, None]                 # [N, k]
        nk = jnp.maximum(jnp.sum(r, axis=0), 1e-10)               # [k]
        means = (r.T @ x) / nk[:, None]
        ex2 = (r.T @ (x * x)) / nk[:, None]
        var = jnp.maximum(ex2 - means * means, 1e-6)
        pi = nk / jnp.sum(nk)
        return GMMState(means=means, var=var, pi=pi)

    state = jax.lax.fori_loop(0, iters, em, state0)
    assign = jnp.argmax(log_resp(state), axis=1)
    return state, assign


# ---------------------------------------------------------------------------
# dbscan
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("min_core_point",))
def dbscan_fit(x, w, eps, *, min_core_point: int = 2):
    """DBSCAN → labels [N]: −1 = noise, else the cluster's representative
    point index (the caller renumbers to 0..C−1).

    Reachability closure runs as ~log2(N) squarings of the core-core
    adjacency matrix (f32 matmuls on the MXU) instead of a host BFS.
    """
    n = x.shape[0]
    d2 = pairwise_sq_dists(x, x)
    adj = d2 <= eps * eps                                       # [N, N] incl self
    ncount = jnp.sum(jnp.where(adj, w[None, :], 0.0), axis=1)
    core = ncount >= min_core_point                              # [N]
    core_adj = adj & core[None, :] & core[:, None]

    def expand(_, reach):
        # reach[i, j]: j reachable from i through core points
        f = reach.astype(jnp.float32)
        return reach | ((f @ f) > 0)

    steps = max(1, math.ceil(math.log2(max(n, 2))))
    reach = jax.lax.fori_loop(0, steps, expand,
                              core_adj | jnp.eye(n, dtype=bool))
    # cluster id of a core point = min index of core points it reaches
    idx = jnp.arange(n)
    member = reach & core[None, :] & core[:, None]
    cluster_of_core = jnp.min(jnp.where(member, idx[None, :], n), axis=1)
    # border points adopt the cluster of any adjacent core point
    border_c = jnp.min(jnp.where(adj & core[None, :],
                                 cluster_of_core[None, :], n), axis=1)
    raw = jnp.where(core, cluster_of_core, border_c)
    return jnp.where(raw >= n, -1, raw)
