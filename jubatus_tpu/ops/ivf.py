"""IVF (inverted-file) approximate-NN kernels — the coarse-quantized
query tier that kills the 10⁸-row exact-scan cliff (ISSUE 16).

The exact scan (ops/knn.py) prices every query at O(rows): ~3.1 s p99 at
10⁸ rows even row-sharded over 8 devices (BENCH_SHARD_r01_knn.json).
IVF replaces the full sweep with two phases, both batched matmuls:

1. **Probe**: embed the query into the method's float space and rank the
   k-means cell centroids against it — one [B, K]×[K, E] matmul
   (pairwise_sq_dists' cross term) + a top-``nprobe`` selection. The
   centroid table is tiny (cells × E floats) and replicated.
2. **Rescore**: gather ONLY the probed cells' member rows from the
   fixed-shape cell-slot table ([n_cells, cell_cap] int32, −1-padded)
   and score them with the method's EXACT distance — the same
   XOR+popcount / lane-match / JL math the full scan uses (and the
   cosine/euclid kernels' expansion for the exact methods), evaluated
   candidate-shaped instead of arena-shaped. Results are therefore
   drawn from the true metric; the only approximation is which rows get
   scored.

Embedding spaces are chosen so k-means cells align with each method's
metric (a cell partition in the wrong geometry probes garbage):

  lsh         unpacked ±1 sign bits — ||a−b||² = 4·hamming exactly, so
              euclidean k-means IS hamming k-means.
  minhash     per-lane derived uniform of the lane's winning feature id
              (counter-based threefry, no HBM table): two rows differ
              in a lane ⇒ expected squared lane gap is a constant, so
              squared euclidean distance ∝ expected mismatch count.
  euclid_lsh  the JL projection itself (already the metric space).
  inverted_index / euclid
              the same JL projection of the raw row (ops/knn.py
              euclid_projection) — a distance-faithful sketch for
              PROBING; the rescore stays the exact cosine/euclid math.

Coarse partitioning: ``ops/clustering.py kmeans_fit`` for small cell
counts; its kmeans++ seeding loop is O(K²·N) so large cell counts use
sample-seeded Lloyd iterations (same update rule, same MXU matmuls).
``build_super``/``assign_cells_hier`` give the two-level assignment used
when labeling 10⁸ rows: route each row through ``n_super`` group
centroids first — per-row cost drops from K·E to (G + M·top)·E flops.

Everything here is single-device; parallel/sharded_ivf.py wraps the same
phases in a shard_map with the log-depth cross-shard merge.
"""

from __future__ import annotations

import functools
import math
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from jubatus_tpu.ops.clustering import kmeans_fit, pairwise_sq_dists

#: kmeans_fit's kmeans++ seeding loop is O(K²·N); above this cell count
#: train_centroids switches to sample-seeded Lloyd (same refinement)
_PLUS_PLUS_MAX_CELLS = 256

#: lane-hash constants for the minhash embedding (splitmix-style mixer)
_MIX1 = np.uint32(0x9E3779B9)
_MIX2 = np.uint32(0x85EBCA6B)
_MIX3 = np.uint32(0xC2B2AE35)


def auto_cells(n_rows: int) -> int:
    """Default cell count: power of two nearest √rows, floored at 8 —
    the classical IVF balance point (probe cost ≈ rescore cost)."""
    if n_rows <= 64:
        return 8
    return max(8, 2 ** int(round(math.log2(max(math.sqrt(n_rows), 8.0)))))


# ---------------------------------------------------------------------------
# embeddings (signature → metric-aligned float space)
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("method", "hash_num"))
def embed_signatures(sigs, *, method: str, hash_num: int):
    """[N, W/H] signature rows → [N, E] float32 embedding whose squared
    euclidean distance tracks the method's distance (module docstring).
    ``euclid_lsh`` and the exact methods' stored JL projections pass
    through unchanged."""
    if method == "lsh":
        shifts = jnp.arange(32, dtype=jnp.uint32)
        bits = (sigs[:, :, None] >> shifts[None, None, :]) & jnp.uint32(1)
        bits = bits.reshape(sigs.shape[0], -1)[:, :hash_num]
        return bits.astype(jnp.float32) * 2.0 - 1.0
    if method == "minhash":
        lane = jnp.arange(sigs.shape[1], dtype=jnp.uint32)[None, :]
        h = (sigs + lane * _MIX1).astype(jnp.uint32)
        h = (h ^ (h >> 16)) * _MIX2
        h = (h ^ (h >> 13)) * _MIX3
        h = h ^ (h >> 16)
        return h.astype(jnp.float32) * (2.0 / 4294967295.0) - 1.0
    # euclid_lsh + exact methods: the JL projection is the metric space
    return sigs.astype(jnp.float32)


# ---------------------------------------------------------------------------
# coarse partitioner (kmeans_fit small-K; sample-seeded Lloyd at scale)
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("iters",))
def _lloyd_refine(x, centers0, *, iters: int):
    """Unweighted Lloyd iterations from given seeds (kmeans_fit's update
    rule, minus its O(K²·N) kmeans++ seeding loop)."""
    k = centers0.shape[0]

    def lloyd(_, centers):
        d2 = pairwise_sq_dists(x, centers)                    # [N, k]
        assign = jnp.argmin(d2, axis=1)
        onehot = jax.nn.one_hot(assign, k, dtype=x.dtype)     # [N, k]
        sums = onehot.T @ x                                   # [k, E] MXU
        counts = jnp.sum(onehot, axis=0)[:, None]
        return jnp.where(counts > 0,
                         sums / jnp.maximum(counts, 1e-30), centers)

    return jax.lax.fori_loop(0, iters, lloyd, centers0)


def train_centroids(emb, n_cells: int, *, iters: int = 8,
                    seed: int = 0) -> np.ndarray:
    """Centroids [n_cells, E] float32 from (a sample of) the embedded
    rows. Small cell counts ride ``clustering.kmeans_fit`` verbatim
    (the ISSUE's coarse partitioner); larger ones seed Lloyd from a
    deterministic row sample instead of the quadratic kmeans++ loop."""
    emb = jnp.asarray(emb, jnp.float32)
    n = emb.shape[0]
    if n == 0:
        raise ValueError("train_centroids needs at least one row")
    if n_cells <= _PLUS_PLUS_MAX_CELLS:
        centers, _ = kmeans_fit(emb, jnp.ones((n,), jnp.float32),
                                k=n_cells, iters=max(iters, 1), seed=seed)
        return np.asarray(centers, np.float32)
    rng = np.random.default_rng(seed)
    if n >= n_cells:
        pick = rng.choice(n, size=n_cells, replace=False)
    else:  # degenerate: fewer rows than cells — repeat rows as seeds
        pick = rng.integers(0, n, size=n_cells)
    seeds = jnp.asarray(np.asarray(emb)[np.sort(pick)])
    return np.asarray(_lloyd_refine(emb, seeds, iters=max(iters, 1)),
                      np.float32)


@jax.jit
def assign_cells(emb, centroids):
    """Nearest-centroid cell per row: one [N, K]×[K, E] matmul expansion
    + argmin. [N] int32."""
    return jnp.argmin(pairwise_sq_dists(emb, centroids),
                      axis=1).astype(jnp.int32)


def build_super(centroids: np.ndarray, *, n_super: int,
                seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Two-level routing tables for bulk assignment: cluster the cell
    centroids into ``n_super`` groups → (supers [G, E] float32,
    members [G, M] int32, −1-padded; M = max group size)."""
    n_super = max(1, min(n_super, centroids.shape[0]))
    supers = train_centroids(centroids, n_super, seed=seed)
    owner = np.asarray(assign_cells(jnp.asarray(centroids),
                                    jnp.asarray(supers)))
    m = max(1, int(np.bincount(owner, minlength=n_super).max()))
    members = np.full((n_super, m), -1, np.int32)
    fill = np.zeros(n_super, np.int64)
    for cell, g in enumerate(owner):
        members[g, fill[g]] = cell
        fill[g] += 1
    return supers, members


@functools.partial(jax.jit, static_argnames=("top_supers",))
def assign_cells_hier(emb, centroids, supers, members, *,
                      top_supers: int = 2):
    """Two-level cell assignment: rank super-groups, then argmin over
    the union of the top groups' member cells — (G + top·M)·E flops per
    row instead of K·E. Exact when the nearest cell's group is among
    the probed groups (overwhelmingly so for top_supers ≥ 2)."""
    ds = pairwise_sq_dists(emb, supers)                       # [N, G]
    top = min(top_supers, supers.shape[0])
    _, gsel = jax.lax.top_k(-ds, top)                         # [N, top]
    cand = members[gsel].reshape(emb.shape[0], -1)            # [N, top·M]
    valid = cand >= 0
    safe = jnp.maximum(cand, 0)
    # expansion form ‖c‖² − 2⟨e, c⟩ (row's own ‖e‖² is argmin-invariant):
    # one batched dot over the gathered centroids instead of the
    # [N, C', E] difference tensor the naive sq-dist materializes twice
    cn2 = jnp.sum(jnp.square(centroids), axis=-1)             # [K]
    dots = jnp.einsum("nce,ne->nc", centroids[safe], emb)
    d2 = jnp.where(valid, cn2[safe] - 2.0 * dots, jnp.inf)
    best = jnp.argmin(d2, axis=1)
    return jnp.take_along_axis(safe, best[:, None],
                               axis=1)[:, 0].astype(jnp.int32)


def assign_cells_grouped(emb: np.ndarray, centroids: np.ndarray,
                         supers: np.ndarray, members: np.ndarray,
                         top_supers: int = 2) -> np.ndarray:
    """Bulk two-level assignment, host-side: rows GROUP by their
    ranked super so each group is one dense [n_g, E]×[E, M] BLAS gemm
    against a centroid block that stays cache-resident — no per-row
    gather tensor at all. Same answer as ``assign_cells_hier``; this
    is the 10⁸-row index-build path (ops are memory-bound there, and
    the gather formulation moves ~100 KB per row where this moves
    ~E·4·top bytes)."""
    emb = np.asarray(emb, np.float32)
    n = emb.shape[0]
    n_super = supers.shape[0]
    top = max(1, min(top_supers, n_super))
    cn2 = np.sum(np.square(centroids), axis=-1)
    sn2 = np.sum(np.square(supers), axis=-1)
    sd = sn2[None, :] - 2.0 * (emb @ supers.T)                # [N, G]
    if top < n_super:
        gtop = np.argpartition(sd, top, axis=1)[:, :top]
    else:
        gtop = np.tile(np.arange(n_super), (n, 1))
    out = np.zeros(n, np.int32)
    best = np.full(n, np.inf, np.float32)
    for t in range(top):
        gs = gtop[:, t]
        order = np.argsort(gs, kind="stable")
        bounds = np.searchsorted(gs[order], np.arange(n_super + 1))
        for g in range(n_super):
            lo, hi = bounds[g], bounds[g + 1]
            if lo == hi:
                continue
            idx = order[lo:hi]
            cells = members[g]
            cells = cells[cells >= 0]
            if cells.size == 0:
                continue
            d = cn2[cells][None, :] - 2.0 * (emb[idx] @ centroids[cells].T)
            am = np.argmin(d, axis=1)
            dm = d[np.arange(len(idx)), am]
            upd = dm < best[idx]
            out[idx[upd]] = cells[am[upd]]
            best[idx[upd]] = dm[upd]
    return out


# ---------------------------------------------------------------------------
# probe + candidate-shaped exact rescore
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("nprobe",))
def probe_cells(q_emb, centroids, *, nprobe: int):
    """Top-``nprobe`` nearest cells per query: [B, P] int32 cell ids."""
    d2 = pairwise_sq_dists(q_emb, centroids)
    _, cells = jax.lax.top_k(-d2, min(nprobe, centroids.shape[0]))
    return cells.astype(jnp.int32)


def candidate_sig_distances(q_sigs, cand_sigs, *, method: str,
                            hash_num: int):
    """The method's EXACT signature distance over gathered candidates —
    the same math as the arena-wide kernels (ops/knn.py), evaluated
    [B, C'] candidate-shaped. q_sigs [B, W/H], cand_sigs [B, C', W/H]."""
    if method == "lsh":
        x = jnp.bitwise_xor(q_sigs[:, None, :], cand_sigs)
        return jnp.sum(jax.lax.population_count(x),
                       axis=-1).astype(jnp.float32) / float(hash_num)
    if method == "minhash":
        match = (q_sigs[:, None, :] == cand_sigs).astype(jnp.float32)
        return 1.0 - jnp.mean(match, axis=-1)
    # euclid_lsh: same ||q||²−2q·r+||r||² expansion as the batch kernel
    dots = jnp.sum(q_sigs[:, None, :] * cand_sigs, axis=-1)
    rn = jnp.sum(cand_sigs * cand_sigs, axis=-1)
    qn = jnp.sum(q_sigs * q_sigs, axis=-1)[:, None]
    return jnp.sqrt(jnp.maximum(qn - 2.0 * dots + rn, 0.0)) \
        / jnp.sqrt(float(hash_num))


def candidate_exact_distances(q_dense, cand_idx, cand_val, *, method: str):
    """Exact cosine/euclid distance over gathered sparse candidate rows
    (the ops/knn.py cosine_scores / euclid_distances expansion,
    candidate-shaped). q_dense [B, D]; cand_idx/val [B, C', K]."""
    gathered = jax.vmap(lambda q, i: q[i])(q_dense, cand_idx)  # [B,C',K]
    dots = jnp.sum(cand_val * gathered, axis=-1)               # [B, C']
    rn2 = jnp.sum(cand_val * cand_val, axis=-1)
    qn2 = jnp.sum(q_dense * q_dense, axis=-1)[:, None]
    if method == "inverted_index":
        denom = jnp.sqrt(rn2) * jnp.sqrt(qn2)
        sim = jnp.where(denom > 0, dots / jnp.maximum(denom, 1e-30), 0.0)
        return 1.0 - sim
    return jnp.sqrt(jnp.maximum(rn2 - 2.0 * dots + qn2, 0.0))


def _tie_ordered(scores, ids):
    """Pin equal-score ordering: score descending, id ascending —
    deterministic results independent of gather/merge order."""
    order = jnp.lexsort((ids, -scores), axis=-1)
    return (jnp.take_along_axis(scores, order, axis=-1),
            jnp.take_along_axis(ids, order, axis=-1))


def _probe_gather(q_emb, centroids, cell_slots, nprobe: int):
    """Shared probe phase: [B, P·cap] candidate slot ids (−1 = padding)
    from the top-``nprobe`` cells."""
    cells = probe_cells(q_emb, centroids, nprobe=nprobe)      # [B, P]
    cand = cell_slots[cells]                                  # [B, P, cap]
    return cand.reshape(q_emb.shape[0], -1)


@functools.partial(jax.jit,
                   static_argnames=("method", "hash_num", "k", "nprobe"))
def ivf_topk(q_sigs, q_emb, sig_table, centroids, cell_slots, *,
             method: str, hash_num: int, k: int, nprobe: int):
    """Single-device two-phase IVF query for the signature methods.

    q_sigs [B, W/H] + q_emb [B, E] (embed_signatures of q_sigs);
    sig_table [C, W/H] full arena; centroids [n_cells, E];
    cell_slots [n_cells, cap] int32 slot ids, −1-padded.
    Returns (distances [B, k'], slots [B, k']) — k' = min(k, P·cap),
    non-finite distance = no candidate (slot is then meaningless)."""
    cand = _probe_gather(q_emb, centroids, cell_slots, nprobe)
    valid = cand >= 0
    safe = jnp.maximum(cand, 0)
    cand_sigs = sig_table[safe]                               # [B, C', W]
    d = candidate_sig_distances(q_sigs, cand_sigs, method=method,
                                hash_num=hash_num)
    sc = jnp.where(valid, -d, -jnp.inf)
    kk = min(k, sc.shape[-1])
    neg, pos = jax.lax.top_k(sc, kk)
    slots = jnp.take_along_axis(safe, pos, axis=-1)
    neg, slots = _tie_ordered(neg, slots)
    return -neg, slots


@functools.partial(jax.jit, static_argnames=("method", "k", "nprobe"))
def ivf_topk_exact(q_dense, q_emb, row_idx, row_val, centroids,
                   cell_slots, *, method: str, k: int, nprobe: int):
    """Single-device two-phase IVF query for the EXACT methods
    (inverted_index/euclid): probe by the stored JL projections, rescore
    the gathered sparse rows with the exact cosine/euclid expansion.
    q_dense [B, D]; row_idx/val [C, K] padded sparse arena."""
    cand = _probe_gather(q_emb, centroids, cell_slots, nprobe)
    valid = cand >= 0
    safe = jnp.maximum(cand, 0)
    d = candidate_exact_distances(q_dense, row_idx[safe], row_val[safe],
                                  method=method)
    sc = jnp.where(valid, -d, -jnp.inf)
    kk = min(k, sc.shape[-1])
    neg, pos = jax.lax.top_k(sc, kk)
    slots = jnp.take_along_axis(safe, pos, axis=-1)
    neg, slots = _tie_ordered(neg, slots)
    return -neg, slots
