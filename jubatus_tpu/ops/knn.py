"""Nearest-neighbor kernels: exact cosine/euclid scoring + LSH family.

Rebuild of the jubatus_core similarity backends the reference consumes
(method names from /root/reference/config/nearest_neighbor/*.json and
config/recommender/*.json: lsh, minhash, euclid_lsh, inverted_index,
euclid — SURVEY.md §2.9) as jitted XLA programs.

TPU design (not a port):

- Rows live as padded sparse arrays [C, K] (idx, val) — C = store capacity,
  K = max nnz bucket (core/row_store.py). Padding entries are (0, 0.0) and
  contribute nothing to any kernel by construction.
- Exact scoring scatters the query into a dense [D] vector once, then every
  row score is a gather + multiply + row-sum over [C, K] — one vectorized
  pass, XLA fuses the gather with the reduction. No inverted index data
  structure is needed: the "index" IS the dense gather (HBM-bandwidth bound,
  which on TPU beats pointer-chasing an inverted list).
- lsh: random-projection signatures. Per-feature projection rows are
  *derived deterministically from the feature index* with the counter-based
  threefry PRNG (no [D, hash_num] matrix in HBM — generated in registers,
  identical on every replica/shard by construction). Signatures are
  bit-packed into uint32 lanes; distance = normalized Hamming via
  XOR + population_count — integer ALU ops, no MXU needed.
- minhash: weighted minhash (Gollapudi/Panigrahy style, as in the
  reference's core): per (feature, lane) exponential draw -log(u)/w, lane
  signature = argmin feature id; similarity = fraction of matching lanes.
- euclid_lsh: Johnson-Lindenstrauss projection to hash_num floats with the
  same derived-gaussian trick; distance estimate = ||p_q - p_r|| / sqrt(H).

All kernels return full [C]-sized score vectors; the drivers extract top-k
host-side after masking dead slots.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# fold_in domain tags so lsh / minhash / euclid_lsh draw independent streams
_TAG_LSH = 0x1A5B
_TAG_MINHASH = 0x3C7D
_TAG_EUCLID = 0x5E9F


# ---------------------------------------------------------------------------
# dense/exact scoring
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("dim",))
def densify(idx, val, *, dim: int):
    """Scatter one sparse vector [K] into dense [dim]. Duplicate indices sum
    (matches hashed-feature-space semantics)."""
    return jnp.zeros(dim, jnp.float32).at[idx].add(val)


@jax.jit
def dot_scores(row_idx, row_val, q_dense):
    """row_i · q for all rows: gather q at each row's indices. [C]."""
    return jnp.sum(row_val * q_dense[row_idx], axis=1)


@jax.jit
def row_norms(row_idx, row_val):
    """L2 norm per row. Padding zeros contribute nothing. [C]."""
    return jnp.sqrt(jnp.sum(row_val * row_val, axis=1))


@jax.jit
def cosine_scores(row_idx, row_val, q_dense):
    """Cosine similarity of every row against the query. [C]; dead/zero rows
    score 0."""
    dots = dot_scores(row_idx, row_val, q_dense)
    rn = row_norms(row_idx, row_val)
    qn = jnp.sqrt(jnp.sum(q_dense * q_dense))
    denom = rn * qn
    return jnp.where(denom > 0, dots / jnp.maximum(denom, 1e-30), 0.0)


@jax.jit
def euclid_distances(row_idx, row_val, q_dense):
    """Exact euclidean distance of every row to the query. [C]."""
    dots = dot_scores(row_idx, row_val, q_dense)
    rn2 = jnp.sum(row_val * row_val, axis=1)
    qn2 = jnp.sum(q_dense * q_dense)
    return jnp.sqrt(jnp.maximum(rn2 - 2.0 * dots + qn2, 0.0))


# ---------------------------------------------------------------------------
# derived pseudo-random streams (feature-index → values, no HBM tables)
# ---------------------------------------------------------------------------
def _feature_gaussians(idx, hash_num: int, seed: int, tag: int):
    """[..., K] int32 feature indices → [..., K, hash_num] N(0,1) draws,
    deterministic in (feature, lane, seed). threefry is counter-based, so
    this is pure compute — the virtual [D, hash_num] projection matrix never
    materializes."""
    base = jax.random.fold_in(jax.random.PRNGKey(seed), tag)
    flat = idx.reshape(-1)
    keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(flat)
    draws = jax.vmap(lambda k: jax.random.normal(k, (hash_num,)))(keys)
    return draws.reshape(idx.shape + (hash_num,))


def _feature_uniforms(idx, hash_num: int, seed: int, tag: int):
    """Like _feature_gaussians but U(0,1) draws, open at 0."""
    base = jax.random.fold_in(jax.random.PRNGKey(seed), tag)
    flat = idx.reshape(-1)
    keys = jax.vmap(lambda i: jax.random.fold_in(base, i))(flat)
    draws = jax.vmap(lambda k: jax.random.uniform(k, (hash_num,),
                                                  minval=1e-12, maxval=1.0))(keys)
    return draws.reshape(idx.shape + (hash_num,))


# ---------------------------------------------------------------------------
# lsh (sign random projection, bit-packed)
# ---------------------------------------------------------------------------
def packed_words(hash_num: int) -> int:
    return (hash_num + 31) // 32


@functools.partial(jax.jit, static_argnames=("hash_num", "seed"))
def lsh_signature(idx, val, *, hash_num: int, seed: int = 0):
    """[B, K] sparse batch → [B, W] uint32 bit-packed sign signatures."""
    g = _feature_gaussians(idx, hash_num, seed, _TAG_LSH)      # [B, K, H]
    proj = jnp.einsum("bk,bkh->bh", val, g)                    # [B, H]
    bits = (proj >= 0).astype(jnp.uint32)                      # [B, H]
    w = packed_words(hash_num)
    pad = w * 32 - hash_num
    if pad:
        bits = jnp.pad(bits, ((0, 0), (0, pad)))
    bits = bits.reshape(bits.shape[0], w, 32)
    shifts = jnp.arange(32, dtype=jnp.uint32)
    return jnp.sum(bits << shifts, axis=-1, dtype=jnp.uint32)  # [B, W]


@functools.partial(jax.jit, static_argnames=("hash_num",))
def _hamming_distances_xla(q_sig, row_sigs, *, hash_num: int):
    x = jnp.bitwise_xor(row_sigs, q_sig[None, :])
    pops = jax.lax.population_count(x)
    return jnp.sum(pops, axis=1).astype(jnp.float32) / float(hash_num)


def hamming_distances(q_sig, row_sigs, *, hash_num: int):
    """Normalized Hamming distance in [0,1]: XOR + popcount over uint32
    lanes. q_sig [W], row_sigs [C, W] → [C]. On TPU the scan runs as a
    pallas kernel (ops/pallas_kernels.py); XLA path elsewhere."""
    from jubatus_tpu.ops import pallas_kernels

    if pallas_kernels.enabled():
        return pallas_kernels.hamming_distances(q_sig, row_sigs,
                                                hash_num=hash_num)
    return _hamming_distances_xla(q_sig, row_sigs, hash_num=hash_num)


# ---------------------------------------------------------------------------
# weighted minhash
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("hash_num", "seed"))
def minhash_signature(idx, val, *, hash_num: int, seed: int = 0):
    """[B, K] weighted sparse batch → [B, H] uint32 signatures.

    Lane h's value is the feature index minimizing -log(u_{f,h}) / w_f
    (exponential race); two sets collide on a lane with probability equal to
    their weighted Jaccard similarity. Padding (val<=0) never wins."""
    u = _feature_uniforms(idx, hash_num, seed, _TAG_MINHASH)   # [B, K, H]
    w = jnp.where(val > 0, val, 0.0)[..., None]                # [B, K, 1]
    keyvals = jnp.where(w > 0, -jnp.log(u) / jnp.maximum(w, 1e-30), jnp.inf)
    winner = jnp.argmin(keyvals, axis=1)                       # [B, H]
    sig = jnp.take_along_axis(idx, winner.astype(idx.dtype), axis=1)
    empty = jnp.all(val <= 0, axis=1, keepdims=True)           # all-padding row
    return jnp.where(empty, jnp.uint32(0xFFFFFFFF), sig.astype(jnp.uint32))


@jax.jit
def _minhash_distances_xla(q_sig, row_sigs):
    match = (row_sigs == q_sig[None, :]).astype(jnp.float32)
    return 1.0 - jnp.mean(match, axis=1)


def minhash_distances(q_sig, row_sigs):
    """1 - (matching lane fraction). q_sig [H], row_sigs [C, H] → [C]."""
    from jubatus_tpu.ops import pallas_kernels

    if pallas_kernels.enabled():
        return pallas_kernels.minhash_distances(q_sig, row_sigs)
    return _minhash_distances_xla(q_sig, row_sigs)


# ---------------------------------------------------------------------------
# euclid_lsh (JL projection)
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("hash_num", "seed"))
def euclid_projection(idx, val, *, hash_num: int, seed: int = 0):
    """[B, K] sparse batch → [B, H] float32 JL projections."""
    g = _feature_gaussians(idx, hash_num, seed, _TAG_EUCLID)
    return jnp.einsum("bk,bkh->bh", val, g)


@functools.partial(jax.jit, static_argnames=("hash_num",))
def euclid_lsh_distances(q_proj, row_projs, *, hash_num: int):
    """Estimated euclidean distance: ||p_q - p_r|| / sqrt(H).
    q_proj [H], row_projs [C, H] → [C]."""
    d = row_projs - q_proj[None, :]
    return jnp.sqrt(jnp.sum(d * d, axis=1)) / jnp.sqrt(float(hash_num))


# ---------------------------------------------------------------------------
# batched (query-batch × row-store) distances — used by LOF's lrd cache
# ---------------------------------------------------------------------------
@functools.partial(jax.jit, static_argnames=("hash_num",))
def _hamming_distances_batch_xla(q_sigs, row_sigs, *, hash_num: int):
    x = jnp.bitwise_xor(q_sigs[:, None, :], row_sigs[None, :, :])
    return jnp.sum(jax.lax.population_count(x), axis=-1).astype(jnp.float32) \
        / float(hash_num)


def hamming_distances_batch(q_sigs, row_sigs, *, hash_num: int):
    """q_sigs [B, W], row_sigs [C, W] → [B, C] normalized Hamming."""
    from jubatus_tpu.ops import pallas_kernels

    if pallas_kernels.enabled():
        return pallas_kernels.hamming_distances_batch(q_sigs, row_sigs,
                                                      hash_num=hash_num)
    return _hamming_distances_batch_xla(q_sigs, row_sigs, hash_num=hash_num)


@jax.jit
def _minhash_distances_batch_xla(q_sigs, row_sigs):
    match = (q_sigs[:, None, :] == row_sigs[None, :, :]).astype(jnp.float32)
    return 1.0 - jnp.mean(match, axis=-1)


def minhash_distances_batch(q_sigs, row_sigs):
    """q_sigs [B, H], row_sigs [C, H] → [B, C]."""
    from jubatus_tpu.ops import pallas_kernels

    if pallas_kernels.enabled():
        return pallas_kernels.minhash_distances_batch(q_sigs, row_sigs)
    return _minhash_distances_batch_xla(q_sigs, row_sigs)


@functools.partial(jax.jit, static_argnames=("hash_num",))
def euclid_lsh_distances_batch(q_projs, row_projs, *, hash_num: int):
    """q_projs [B, H], row_projs [C, H] → [B, C] JL distance estimates.
    Expanded as ||q||²-2q·r+||r||² so the cross term is one MXU matmul."""
    qn = jnp.sum(q_projs * q_projs, axis=1)[:, None]
    rn = jnp.sum(row_projs * row_projs, axis=1)[None, :]
    cross = q_projs @ row_projs.T
    return jnp.sqrt(jnp.maximum(qn - 2.0 * cross + rn, 0.0)) \
        / jnp.sqrt(float(hash_num))
