"""Pallas TPU kernels for the similarity-search hot loops.

The LSH backends (nearest_neighbor / recommender / anomaly, reference
jubatus_core lsh/minhash indexes) reduce every query to a dense
signature-table scan: XOR+popcount over packed uint32 bit signatures
(hamming) or lane-match counting (minhash). The XLA formulation
(ops/knn.py) broadcasts a [B, C, W] intermediate and relies on fusion;
these kernels tile the candidate table into VMEM blocks and unroll the
small signature-word axis into 2D VPU ops, so HBM traffic is exactly
one pass over the table regardless of batch size.

Layout per grid step (candidate block c):
    q   [B,  W] uint32   resident across all steps (constant index map)
    r   [Cb, W] uint32   one table tile
    out [B, Cb] float32  distances for this tile

Popcount is the classic SWAR bit-ladder (shift/mask adds) — elementwise
uint32 ops the VPU executes natively; no MXU involvement.

Interpret mode runs the same kernels on CPU (tests, and the virtual
8-device mesh); on a real TPU backend `enabled()` flips them on by
default — set JUBATUS_TPU_PALLAS=0/1 to force either way.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# block of candidate rows per grid step; W is small (hash_num/32 ≤ 16),
# so a [B, CAND_BLOCK] f32 tile per word dominates VMEM: 64×2048×4 = 512 KiB.
# Swept on v5e: 512–2048 within noise of each other, 2048 best.
CAND_BLOCK = 2048


def enabled() -> bool:
    """Route knn distance scans through pallas? Default: only on TPU."""
    flag = os.environ.get("JUBATUS_TPU_PALLAS", "")
    if flag in ("0", "false", "no"):
        return False
    if flag in ("1", "true", "yes"):
        return True
    return jax.default_backend() == "tpu"


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _popcount32(v):
    """SWAR popcount over uint32 (no lax.population_count: keeps the op set
    to shifts/ands/adds that Mosaic lowers everywhere)."""
    v = v - ((v >> jnp.uint32(1)) & jnp.uint32(0x55555555))
    v = (v & jnp.uint32(0x33333333)) + ((v >> jnp.uint32(2)) & jnp.uint32(0x33333333))
    v = (v + (v >> jnp.uint32(4))) & jnp.uint32(0x0F0F0F0F)
    return (v * jnp.uint32(0x01010101)) >> jnp.uint32(24)


def _sig_scan_kernel(q_ref, r_ref, o_ref, *, mode: str, words: int, scale: float):
    """One [B, Cb] output tile; unrolled loop over the signature words."""
    acc = jnp.zeros(o_ref.shape, jnp.uint32)
    for w in range(words):
        qw = q_ref[:, w][:, None]      # [B, 1]
        rw = r_ref[:, w][None, :]      # [1, Cb]
        if mode == "hamming":
            acc += _popcount32(jnp.bitwise_xor(qw, rw))
        else:  # minhash: count matching lanes
            acc += (qw == rw).astype(jnp.uint32)
    # Mosaic has no uint32→f32 cast; counts are ≤ hash_num so int32 is exact
    d = acc.astype(jnp.int32).astype(jnp.float32) * jnp.float32(scale)
    o_ref[:] = (jnp.float32(1.0) - d) if mode == "minhash" else d


@functools.partial(jax.jit, static_argnames=("mode", "hash_num", "block"))
def _sig_scan(q_sigs, row_sigs, *, mode: str, hash_num: int, block: int):
    b, words = q_sigs.shape
    c = row_sigs.shape[0]
    grid = (pl.cdiv(c, block),)
    if mode == "hamming":
        scale = 1.0 / float(hash_num)
    else:
        scale = 1.0 / float(words)  # minhash sigs are one word per hash
    out = pl.pallas_call(
        functools.partial(_sig_scan_kernel, mode=mode, words=words, scale=scale),
        out_shape=jax.ShapeDtypeStruct((b, c), jnp.float32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((b, words), lambda i: (0, 0)),
            pl.BlockSpec((block, words), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((b, block), lambda i: (0, i)),
        interpret=_interpret(),
    )(q_sigs, row_sigs)
    return out


def hamming_distances_batch(q_sigs, row_sigs, *, hash_num: int,
                            block: int = CAND_BLOCK):
    """q_sigs [B, W], row_sigs [C, W] uint32 → [B, C] normalized Hamming."""
    return _sig_scan(q_sigs, row_sigs, mode="hamming", hash_num=hash_num,
                     block=min(block, max(8, row_sigs.shape[0])))


def hamming_distances(q_sig, row_sigs, *, hash_num: int,
                      block: int = CAND_BLOCK):
    """q_sig [W], row_sigs [C, W] → [C]."""
    return hamming_distances_batch(q_sig[None, :], row_sigs,
                                   hash_num=hash_num, block=block)[0]


def minhash_distances_batch(q_sigs, row_sigs, *, block: int = CAND_BLOCK):
    """q_sigs [B, H], row_sigs [C, H] uint32 → [B, C] (1 - match fraction)."""
    return _sig_scan(q_sigs, row_sigs, mode="minhash",
                     hash_num=q_sigs.shape[1],
                     block=min(block, max(8, row_sigs.shape[0])))


def minhash_distances(q_sig, row_sigs, *, block: int = CAND_BLOCK):
    return minhash_distances_batch(q_sig[None, :], row_sigs, block=block)[0]
