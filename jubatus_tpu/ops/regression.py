"""Passive-Aggressive regression kernels (PA / PA1 / PA2).

Rebuild of jubatus_core's regression algorithms (config schema:
/root/reference/config/regression/default.json — method "PA1" with
"sensitivity" epsilon and "regularization_weight" C). Same state layout and
additive-diff mix semantics as ops/classifier.py, with a single weight row.

Update (epsilon-insensitive hinge): err = y - w.x, l = |err| - epsilon;
if l > 0: w += sign(err) * alpha * x with
  PA:  alpha = l / x2
  PA1: alpha = min(C, l / x2)
  PA2: alpha = l / (x2 + 1/(2C))
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

METHODS = ("PA", "PA1", "PA2")


class RegressionState(NamedTuple):
    w: jax.Array   # [D] master weights
    dw: jax.Array  # [D] local diff since last mix


def init_state(dim: int) -> RegressionState:
    return RegressionState(
        w=jnp.zeros((dim,), jnp.float32), dw=jnp.zeros((dim,), jnp.float32)
    )


@jax.jit
def estimate(state: RegressionState, idx: jax.Array, val: jax.Array) -> jax.Array:
    """Batch estimates: [B]."""
    eff = state.w + state.dw
    return jnp.einsum("bk,bk->b", jnp.take(eff, idx), val)


@functools.partial(jax.jit, static_argnames=("method",), donate_argnums=(0,))
def train_batch(
    state: RegressionState,
    idx: jax.Array,      # [B, K]
    val: jax.Array,      # [B, K]
    targets: jax.Array,  # [B]
    sensitivity: float,
    c: float,
    *,
    method: str,
) -> RegressionState:
    def step(carry, ex):
        w, dw = carry
        e_idx, e_val, y = ex
        pred = jnp.sum((jnp.take(w, e_idx) + jnp.take(dw, e_idx)) * e_val)
        err = y - pred
        loss = jnp.abs(err) - sensitivity
        x2 = jnp.maximum(jnp.sum(e_val * e_val), 1e-12)
        if method == "PA":
            alpha = loss / x2
        elif method == "PA1":
            alpha = jnp.minimum(c, loss / x2)
        elif method == "PA2":
            alpha = loss / (x2 + 1.0 / (2.0 * c))
        else:
            raise ValueError(f"unknown regression method {method!r}")
        alpha = jnp.where(loss > 0.0, alpha, 0.0)
        dw = dw.at[e_idx].add(jnp.sign(err) * alpha * e_val)
        return (w, dw), ()

    (w, dw), _ = jax.lax.scan(step, tuple(state), (idx, val, targets))
    return RegressionState(w, dw)


# -- mixable protocol -------------------------------------------------------
def get_diff(state: RegressionState):
    return {"dw": state.dw, "count": jnp.float32(1.0)}


def mix_diffs(lhs, rhs):
    return jax.tree_util.tree_map(lambda a, b: a + b, lhs, rhs)


@jax.jit
def put_diff(state: RegressionState, diff) -> RegressionState:
    n = jnp.maximum(diff["count"], 1.0)
    return RegressionState(w=state.w + diff["dw"] / n, dw=jnp.zeros_like(state.dw))
