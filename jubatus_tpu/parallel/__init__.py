"""Distributed plane: device meshes, the mix collective, sharding helpers.

The reference's distributed training loop is the MIX protocol: a
ZooKeeper-elected master fans out get_diff RPCs, folds diffs pairwise, and
broadcasts put_diff (linear_mixer.cpp:437-559, SURVEY.md §3.3). Here the same
semantics run as one XLA AllReduce over ICI: every replica's diff pytree is
psum'd inside a shard_map'd step, and every replica absorbs the result —
symmetric, no master election, exact for our additive diffs.
"""

from jubatus_tpu.parallel.mesh import replica_mesh  # noqa: F401
from jubatus_tpu.parallel.mix import (  # noqa: F401
    LocalMixGroup,
    Mixable,
    allreduce_diffs,
    tree_sum,
)
from jubatus_tpu.parallel.ring import (  # noqa: F401
    ring_euclid_topk,
    ring_hamming_topk,
    ring_scan,
)
