"""jax API compatibility for the parallel plane.

``shard_map`` graduated from ``jax.experimental.shard_map`` to a
top-level ``jax.shard_map`` export (renaming ``check_rep`` to
``check_vma`` along the way), and ``jax.distributed.is_initialized``
only exists on newer jax; this repo runs against both eras. Import from
here so every collective/SPMD module resolves the same symbols
regardless of the installed jax.
"""

from __future__ import annotations

import inspect

import jax

try:  # jax >= 0.5: public top-level API
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # older jax: the experimental home
    from jax.experimental.shard_map import shard_map as _shard_map  # type: ignore

_PARAMS = frozenset(inspect.signature(_shard_map).parameters)


def shard_map(f=None, **kwargs):
    """``shard_map`` accepting either era's replication-check kwarg
    (``check_vma`` on new jax, ``check_rep`` on old) and translating to
    whatever the installed jax understands."""
    if "check_vma" in kwargs and "check_vma" not in _PARAMS:
        kwargs["check_rep"] = kwargs.pop("check_vma")
    elif "check_rep" in kwargs and "check_rep" not in _PARAMS:
        kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map(f, **kwargs) if f is not None else _shard_map(**kwargs)


def axis_size(axis):
    """``jax.lax.axis_size`` where it exists; the classic
    ``psum(1, axis)`` idiom (constant-folded to a Python int at trace
    time) on older jax."""
    fn = getattr(jax.lax, "axis_size", None)
    if fn is not None:
        return fn(axis)
    return jax.lax.psum(1, axis)


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` where it exists; the
    runtime's client handle otherwise."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src.distributed import global_state

        return global_state.client is not None
    except Exception:  # noqa: BLE001 — layout moved again: assume not init
        return False


__all__ = ["shard_map", "axis_size", "distributed_is_initialized"]
