"""Cross-process diff reduction — the mix's data plane as an XLA collective.

``psum_pytree`` reduces one pytree of numpy arrays across every process
in the ``jax.distributed`` world: each process contributes its local
replica's diff, the reduction runs as a single jitted shard_map psum over
a one-device-per-process 'replica' mesh (ICI/DCN, not TCP fan-out), and
every process reads back the identical total. This is SURVEY.md §7 step
3's north-star shape: the reference's get_diff → pairwise fold →
put_diff (linear_mixer.cpp:437-559) collapses into one AllReduce whose
combiner IS the fold.

Requirements: every process calls with the SAME treedef/shapes/dtypes in
the same order (the collective mixer's prepare phase verifies this before
anyone enters), and the jax runtime must be initialized across the world
(jax.distributed.initialize — parallel/multihost.py). Works single-process
too (world of 1: psum degenerates to identity), which is what the driver
dry run exercises.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _world_mesh() -> Mesh:
    """1-D 'replica' mesh with exactly one device per process (the first
    local device of each), in process order — every process builds the
    identical mesh."""
    per_process: Dict[int, Any] = {}
    for d in jax.devices():
        p = d.process_index
        if p not in per_process or d.id < per_process[p].id:
            per_process[p] = d
    devs = [per_process[p] for p in sorted(per_process)]
    return Mesh(np.array(devs), axis_names=("replica",))


@functools.lru_cache(maxsize=32)
def _reduce_fn(mesh: Mesh, treedef, shapes: Tuple, dtypes: Tuple,
               compress: bool):
    def body(stacked):
        def one(x):
            total = jax.lax.psum(jnp.squeeze(x, 0), "replica")
            # compressed leaves came in as bf16 (half the interconnect
            # bytes — the EQuARX-style tradeoff the in-step psum and the
            # RPC mix already offer); hand back f32 for the f32 master
            if compress and total.dtype == jnp.bfloat16:
                total = total.astype(jnp.float32)
            return total

        return jax.tree_util.tree_map(one, stacked)

    return jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=P("replica"), out_specs=P()),
        out_shardings=NamedSharding(mesh, P()),
    )


def psum_pytree(diff: Any, compress: bool = False) -> Any:
    """AllReduce ``diff`` (pytree of arrays/scalars) across the process
    world; returns the total as host numpy arrays. Every process must
    call this with an identically-shaped pytree (and the same
    ``compress``).

    ``compress=True`` ships f32 leaves over the interconnect as bf16 —
    half the wire bytes per round at ~3 decimal digits of diff
    precision; additive diffs tolerate it because put_diff folds into an
    f32 master (same contract as ``_psum_stacked(compress=True)`` and
    the RPC mix's bf16 option)."""
    mesh = _world_mesh()
    n = mesh.shape["replica"]
    me = jax.local_devices()[0]
    sharding = NamedSharding(mesh, P("replica"))

    leaves, treedef = jax.tree_util.tree_flatten(diff)
    arrs = []
    for leaf in leaves:
        local = np.asarray(leaf)
        if local.dtype in (np.float64, np.int64, np.uint64):
            # a silent downcast would make the collective path less exact
            # than the RPC fold; callers gate these to the fallback
            # (collective_mixer._signature marks them unsupported)
            raise ValueError(
                f"64-bit leaf dtype {local.dtype} cannot ride the "
                "collective exactly; use the RPC mix path")
        if compress and local.dtype == np.float32:
            import ml_dtypes

            local = local.astype(ml_dtypes.bfloat16)
        shard = jax.device_put(local[None, ...], me)
        arrs.append(jax.make_array_from_single_device_arrays(
            (n,) + local.shape, sharding, [shard]))
    stacked = jax.tree_util.tree_unflatten(treedef, arrs)
    shapes = tuple(a.shape for a in arrs)
    dtypes = tuple(str(a.dtype) for a in arrs)
    total = _reduce_fn(mesh, treedef, shapes, dtypes, compress)(stacked)
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x.addressable_shards[0].data), total)


def world_size() -> int:
    return jax.process_count()
