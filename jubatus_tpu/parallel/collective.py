"""Cross-process diff reduction — the mix's data plane as an XLA collective.

``psum_pytree`` reduces one pytree of numpy arrays across every process
in the ``jax.distributed`` world: each process contributes its local
replica's diff, the reduction runs as a single jitted shard_map psum over
a one-device-per-process 'replica' mesh (ICI/DCN, not TCP fan-out), and
every process reads back the identical total. This is SURVEY.md §7 step
3's north-star shape: the reference's get_diff → pairwise fold →
put_diff (linear_mixer.cpp:437-559) collapses into one AllReduce whose
combiner IS the fold.

Requirements: every process calls with the SAME treedef/shapes/dtypes in
the same order (the collective mixer's prepare phase verifies this before
anyone enters), and the jax runtime must be initialized across the world
(jax.distributed.initialize — parallel/multihost.py). Works single-process
too (world of 1: psum degenerates to identity), which is what the driver
dry run exercises.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _world_mesh() -> Mesh:
    """1-D 'replica' mesh with exactly one device per process (the first
    local device of each), in process order — every process builds the
    identical mesh."""
    per_process: Dict[int, Any] = {}
    for d in jax.devices():
        p = d.process_index
        if p not in per_process or d.id < per_process[p].id:
            per_process[p] = d
    devs = [per_process[p] for p in sorted(per_process)]
    return Mesh(np.array(devs), axis_names=("replica",))


@functools.lru_cache(maxsize=32)
def _reduce_fn(mesh: Mesh, treedef, shapes: Tuple, dtypes: Tuple,
               compress: bool):
    def body(stacked):
        def one(x):
            total = jax.lax.psum(jnp.squeeze(x, 0), "replica")
            # compressed leaves came in as bf16 (half the interconnect
            # bytes — the EQuARX-style tradeoff the in-step psum and the
            # RPC mix already offer); hand back f32 for the f32 master
            if compress and total.dtype == jnp.bfloat16:
                total = total.astype(jnp.float32)
            return total

        return jax.tree_util.tree_map(one, stacked)

    return jax.jit(
        jax.shard_map(body, mesh=mesh, in_specs=P("replica"), out_specs=P()),
        out_shardings=NamedSharding(mesh, P()),
    )


def psum_pytree(diff: Any, compress: bool = False,
                phases: dict = None) -> Any:  # type: ignore[assignment]
    """AllReduce ``diff`` (pytree of arrays/scalars) across the process
    world; returns the total as host numpy arrays. Every process must
    call this with an identically-shaped pytree (and the same
    ``compress``).

    ``compress=True`` ships f32 leaves over the interconnect as bf16 —
    half the wire bytes per round at ~3 decimal digits of diff
    precision; additive diffs tolerate it because put_diff folds into an
    f32 master (same contract as ``_psum_stacked(compress=True)`` and
    the RPC mix's bf16 option).

    ``phases`` (optional dict) is filled with this call's per-phase wall
    times so mix rounds log like the reference's per-round time+bytes
    (linear_mixer.cpp:553-558): ``cast_ms`` (host bf16 cast),
    ``ship_ms`` (host->device placement), ``reduce_ms`` (the jitted
    psum — wire and fold are ONE fused collective here, unlike the
    reference's get_diff/fold/put_diff phases), ``readback_ms``
    (device->host), ``payload_mb`` (post-cast bytes this replica
    contributes) and ``wire_mb_ring_model`` (2(n-1)/n x payload — the
    ring-allreduce bytes a replica moves per round; a model, since the
    runtime picks the actual algorithm)."""
    import time

    mesh = _world_mesh()
    n = mesh.shape["replica"]
    me = jax.local_devices()[0]
    sharding = NamedSharding(mesh, P("replica"))

    leaves, treedef = jax.tree_util.tree_flatten(diff)
    t0 = time.perf_counter()
    cast = []
    nbytes = 0
    for leaf in leaves:
        local = np.asarray(leaf)
        if local.dtype in (np.float64, np.int64, np.uint64):
            # a silent downcast would make the collective path less exact
            # than the RPC fold; callers gate these to the fallback
            # (collective_mixer._signature marks them unsupported)
            raise ValueError(
                f"64-bit leaf dtype {local.dtype} cannot ride the "
                "collective exactly; use the RPC mix path")
        if compress and local.dtype == np.float32:
            import ml_dtypes

            local = local.astype(ml_dtypes.bfloat16)
        nbytes += local.nbytes
        cast.append(local)
    t1 = time.perf_counter()
    arrs = []
    for local in cast:
        shard = jax.device_put(local[None, ...], me)
        arrs.append(jax.make_array_from_single_device_arrays(
            (n,) + local.shape, sharding, [shard]))
    stacked = jax.tree_util.tree_unflatten(treedef, arrs)
    shapes = tuple(a.shape for a in arrs)
    dtypes = tuple(str(a.dtype) for a in arrs)
    t2 = time.perf_counter()
    total = _reduce_fn(mesh, treedef, shapes, dtypes, compress)(stacked)
    total = jax.block_until_ready(total)
    t3 = time.perf_counter()
    out = jax.tree_util.tree_map(
        lambda x: np.asarray(x.addressable_shards[0].data), total)
    t4 = time.perf_counter()
    if phases is not None:
        phases.update(
            cast_ms=round((t1 - t0) * 1e3, 2),
            ship_ms=round((t2 - t1) * 1e3, 2),
            reduce_ms=round((t3 - t2) * 1e3, 2),
            readback_ms=round((t4 - t3) * 1e3, 2),
            payload_mb=round(nbytes / 2**20, 2),
            wire_mb_ring_model=round(nbytes * 2 * (n - 1) / n / 2**20, 2),
        )
    return out


def world_size() -> int:
    return jax.process_count()
