"""Cross-process diff reduction — the mix's data plane as an XLA collective.

``psum_pytree`` reduces one pytree of arrays across every process in the
``jax.distributed`` world: each process contributes its local replica's
diff, the reduction runs as jitted shard_map psums over a
one-device-per-process 'replica' mesh (ICI/DCN, not TCP fan-out), and
every process reads back the identical total. This is SURVEY.md §7 step
3's north-star shape: the reference's get_diff → pairwise fold →
put_diff (linear_mixer.cpp:437-559) collapses into AllReduces whose
combiner IS the fold.

The data plane is PIPELINED (docs/PERF_NOTES.md "Mix data plane"):

- Leaves at or above the chunk size are split into fixed-size 1-D chunks
  and streamed with a double buffer, so the host→device ship of chunk
  k+1 overlaps the psum of chunk k and the device→host readback of
  chunk k−1 — instead of the old serial cast-all/ship-all/reduce-all/
  readback-all ("Exploring the limits of Concurrency in ML Training on
  Google TPUs", arxiv 2011.03641: transfer/compute overlap is where TPU
  pipelines recover wall clock). Chunk psums are separate collectives,
  so every process MUST build the identical stream: the plan is a pure
  function of (shapes, dtypes, chunk_bytes, compress) — which the
  collective mixer folds into its prepare signature — never of where a
  leaf happens to live.
- ``compress=True`` casts f32 leaves to bf16 INSIDE the jitted
  collective body (cast-on-device, input buffer donated off-CPU), so the
  wire sees half the bytes without the old full host-side astype copy
  (EQuARX, arxiv 2506.17615: a compressed AllReduce only wins when the
  cast is fused into the collective).
- Leaves that are already device-resident ``jax.Array``s (the models in
  models/ are JAX — their diffs need not round-trip through numpy) take
  a zero-staging path: no host cast, no ``device_put`` from numpy, and
  with ``prefer_device=True`` no readback either — the totals are handed
  back as device arrays for the jitted put_diff to consume directly.

Requirements: every process calls with the SAME treedef/shapes/dtypes in
the same order and the same ``compress``/``chunk_bytes`` (the collective
mixer's prepare phase verifies this before anyone enters), and the jax
runtime must be initialized across the world (jax.distributed.initialize
— parallel/multihost.py). Works single-process too (world of 1: psum
degenerates to identity), which is what the driver dry run exercises.
"""

from __future__ import annotations

import functools
import os
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jubatus_tpu.parallel._compat import shard_map

#: pipeline chunk size in MiB (uncompressed leaf bytes). Leaves at or
#: above this split into chunks and double-buffer; smaller leaves batch
#: into one collective call. 8 MiB won the sweep recorded in
#: docs/PERF_NOTES.md ("Mix data plane"): big enough that per-chunk
#: dispatch overhead (~0.1 ms) is noise against the chunk's transfer,
#: small enough that three in-flight buffers overlap rather than
#: serialize. Override per deployment with JUBATUS_TPU_MIX_CHUNK_MB —
#: every process in a cluster must agree (the prepare signature checks).
DEFAULT_CHUNK_MB = float(os.environ.get("JUBATUS_TPU_MIX_CHUNK_MB", "8"))

#: in-flight chunks beyond the one being collected: 2 = classic double
#: buffer (ship k+1 while chunk k reduces and chunk k−1 reads back)
_PIPELINE_DEPTH = 2

_64BIT = (np.dtype(np.float64), np.dtype(np.int64), np.dtype(np.uint64))


def _world_mesh() -> Mesh:
    """1-D 'replica' mesh with exactly one device per process (the first
    local device of each), in process order — every process builds the
    identical mesh."""
    per_process: Dict[int, Any] = {}
    for d in jax.devices():
        p = d.process_index
        if p not in per_process or d.id < per_process[p].id:
            per_process[p] = d
    devs = [per_process[p] for p in sorted(per_process)]
    return Mesh(np.array(devs), axis_names=("replica",))


def _donate() -> Tuple[int, ...]:
    # donating the stacked input lets XLA reuse its buffer for the
    # on-device bf16 cast; the CPU backend can't honor donation and
    # would warn on every compile
    return () if jax.default_backend() == "cpu" else (0,)


def _psum_body(x, compress: bool):
    y = jnp.squeeze(x, 0)
    if compress and y.dtype == jnp.float32:
        # cast fused into the collective: the wire sees bf16 (half the
        # ICI/DCN bytes), the caller gets f32 back — the EQuARX-style
        # tradeoff without the old host-side astype copy
        y = y.astype(jnp.bfloat16)
        return jax.lax.psum(y, "replica").astype(jnp.float32)
    total = jax.lax.psum(y, "replica")
    if compress and total.dtype == jnp.bfloat16:
        # pre-cast bf16 input under compress keeps the old contract:
        # hand back f32 for the f32 master
        total = total.astype(jnp.float32)
    return total


@functools.lru_cache(maxsize=32)
def _reduce_tree_fn(mesh: Mesh, treedef, shapes: Tuple, dtypes: Tuple,
                    compress: bool):
    """Batched psum of one pytree of small leaves (single collective
    program, like the pre-pipeline engine)."""

    def body(stacked):
        return jax.tree_util.tree_map(
            lambda x: _psum_body(x, compress), stacked)

    return jax.jit(
        shard_map(body, mesh=mesh, in_specs=P("replica"), out_specs=P()),
        out_shardings=NamedSharding(mesh, P()),
        donate_argnums=_donate(),
    )


@functools.lru_cache(maxsize=32)
def _reduce_chunk_fn(mesh: Mesh, elems: int, dtype_str: str, compress: bool):
    """psum of one [world, elems] chunk. All full chunks of a dtype share
    this one compiled program; ragged tails are zero-padded up to it
    (psum of zeros is zeros, sliced off at collection)."""

    def body(x):
        return _psum_body(x, compress)

    return jax.jit(
        shard_map(body, mesh=mesh, in_specs=P("replica"), out_specs=P()),
        out_shardings=NamedSharding(mesh, P()),
        donate_argnums=_donate(),
    )


def _leaf_meta(leaf) -> Tuple[Any, np.dtype, Tuple[int, ...]]:
    """(leaf, dtype, shape) WITHOUT materializing device arrays on the
    host (np.asarray on a jax.Array is a full device→host copy)."""
    dtype = getattr(leaf, "dtype", None)
    shape = getattr(leaf, "shape", None)
    if dtype is None or shape is None:
        leaf = np.asarray(leaf)  # python scalar / list leaf
        dtype, shape = leaf.dtype, leaf.shape
    return leaf, np.dtype(dtype), tuple(shape)


def psum_pytree(diff: Any, compress: bool = False,
                phases: dict = None,  # type: ignore[assignment]
                chunk_mb: Optional[float] = None,
                prefer_device: bool = False) -> Any:
    """AllReduce ``diff`` (pytree of arrays/scalars) across the process
    world. Every process must call this with an identically-shaped
    pytree and the same ``compress`` and ``chunk_mb`` (both ride the
    collective mixer's prepare signature).

    ``compress=True`` ships f32 leaves over the interconnect as bf16 —
    half the wire bytes per round at ~3 decimal digits of diff
    precision; additive diffs tolerate it because put_diff folds into an
    f32 master (same contract as ``_psum_stacked(compress=True)`` and
    the RPC mix's bf16 option). The cast runs on-device inside the
    collective body.

    ``prefer_device=True`` returns totals as device ``jax.Array``s
    (no readback) — callers whose put_diff is jitted consume them
    directly; the default returns host numpy arrays.

    ``phases`` (optional dict) is filled with this call's per-phase wall
    times so mix rounds log like the reference's per-round time+bytes
    (linear_mixer.cpp:553-558): ``cast_ms`` (host cast — ~0 now that the
    compress cast is on-device), ``ship_ms`` (host→device placement;
    the first chunk is measured with an explicit completion barrier so
    async dispatch cannot leak transfer time into ``reduce_ms``),
    ``reduce_ms`` (the jitted psums — wire and fold are ONE fused
    collective, unlike the reference's get_diff/fold/put_diff),
    ``readback_ms`` (device→host; in the pipelined stream this is the
    time BLOCKED on arrival, i.e. whatever the overlap didn't hide),
    ``payload_mb`` (post-cast wire bytes this replica contributes),
    ``wire_mb_ring_model`` (2(n-1)/n × payload — ring-allreduce bytes
    per replica; a model, the runtime picks the algorithm), plus the
    pipeline accounting: ``chunks``, ``chunk_mb``, and
    ``overlap_ms_saved`` — a DIRECT measurement of the overlap win:
    the reader thread's readback blocking that elapsed while the main
    thread was still shipping/reducing later chunks (minus the tail it
    did wait for) — wait the serial path would have eaten inline."""
    mesh = _world_mesh()
    n = mesh.shape["replica"]
    me = jax.local_devices()[0]
    sharding = NamedSharding(mesh, P("replica"))
    if chunk_mb is None:
        chunk_mb = DEFAULT_CHUNK_MB
    chunk_bytes = max(1, int(chunk_mb * 2**20))

    leaves, treedef = jax.tree_util.tree_flatten(diff)
    if phases is not None:
        phases.update(cast_ms=0.0, ship_ms=0.0, reduce_ms=0.0,
                      readback_ms=0.0, payload_mb=0.0,
                      wire_mb_ring_model=0.0, chunks=0,
                      chunk_mb=round(chunk_bytes / 2**20, 2),
                      overlap_ms_saved=0.0)
    if not leaves:
        return diff

    metas = []
    nbytes = 0
    for leaf in leaves:
        leaf, dtype, shape = _leaf_meta(leaf)
        if dtype in _64BIT:
            # a silent downcast would make the collective path less exact
            # than the RPC fold; callers gate these to the fallback
            # (collective_mixer._signature marks them unsupported)
            raise ValueError(
                f"64-bit leaf dtype {dtype} cannot ride the "
                "collective exactly; use the RPC mix path")
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        wire = size * dtype.itemsize
        if compress and dtype == np.float32:
            wire //= 2
        nbytes += wire
        metas.append((leaf, dtype, shape, size))

    # the collective sequence must be identical on every process, so the
    # small/chunked split keys on (size, chunk_bytes) alone — where a
    # leaf lives only changes local staging, never the stream shape
    small_idx = [i for i, (_, dt, _, s) in enumerate(metas)
                 if s * dt.itemsize < chunk_bytes]
    big_idx = [i for i, (_, dt, _, s) in enumerate(metas)
               if s * dt.itemsize >= chunk_bytes]

    out: List[Any] = [None] * len(metas)
    t_ship = t_reduce = t_readback = t_cast = 0.0

    # -- small leaves: one batched collective (the pre-pipeline shape) --
    if small_idx:
        t0 = time.perf_counter()
        arrs = []
        for i in small_idx:
            leaf, dtype, shape, _ = metas[i]
            if isinstance(leaf, jax.Array):
                shard = jax.device_put(leaf[None, ...], me)
            else:
                shard = jax.device_put(np.asarray(leaf)[None, ...], me)
            arrs.append(jax.make_array_from_single_device_arrays(
                (n,) + shape, sharding, [shard]))
        # device_put is async: block before timestamping so transfer
        # cost does not leak into reduce_ms
        jax.block_until_ready(arrs)
        t1 = time.perf_counter()
        stacked = tuple(arrs)
        shapes = tuple(a.shape for a in arrs)
        dtypes = tuple(str(a.dtype) for a in arrs)
        s_treedef = jax.tree_util.tree_structure(stacked)
        total = _reduce_tree_fn(mesh, s_treedef, shapes, dtypes,
                                compress)(stacked)
        total = jax.block_until_ready(total)
        t2 = time.perf_counter()
        for i, tot in zip(small_idx, total):
            local = tot.addressable_shards[0].data
            out[i] = local if prefer_device else np.asarray(local)
        t3 = time.perf_counter()
        t_ship += t1 - t0
        t_reduce += t2 - t1
        t_readback += t3 - t2

    # -- big leaves: chunked double-buffered stream ---------------------
    n_chunks = 0
    overlap_saved = 0.0
    if big_idx:
        stream: List[Tuple[int, int, int]] = []  # (leaf idx, start, stop)
        flats: Dict[int, Any] = {}
        chunks_out: Dict[int, List[Any]] = {}
        for i in big_idx:
            leaf, dtype, shape, size = metas[i]
            celems = max(1, chunk_bytes // dtype.itemsize)
            if isinstance(leaf, jax.Array):
                flats[i] = leaf.reshape(-1)  # device op, zero staging
            else:
                flats[i] = np.ascontiguousarray(
                    np.asarray(leaf)).reshape(-1)
            chunks_out[i] = []
            for start in range(0, size, celems):
                stream.append((i, start, min(start + celems, size)))
        n_chunks = len(stream)

        def ship(entry):
            i, start, stop = entry
            dtype = metas[i][1]
            celems = max(1, chunk_bytes // dtype.itemsize)
            flat = flats[i]
            chunk = flat[start:stop]
            pad = celems - (stop - start)
            if isinstance(flat, jax.Array):
                if pad:
                    chunk = jnp.concatenate(
                        [chunk, jnp.zeros(pad, chunk.dtype)])
                shard = jax.device_put(chunk[None, :], me)
            else:
                if pad:
                    chunk = np.concatenate(
                        [chunk, np.zeros(pad, chunk.dtype)])
                shard = jax.device_put(chunk[None, :], me)
            return jax.make_array_from_single_device_arrays(
                (n, celems), sharding, [shard]), celems

        def reduce_chunk(stacked, celems, dtype):
            return _reduce_chunk_fn(mesh, celems, str(dtype),
                                    compress)(stacked)

        def collect(entry, reduced):
            i, start, stop = entry
            if prefer_device:
                local = reduced.addressable_shards[0].data
                chunks_out[i].append(
                    local[: stop - start] if stop - start != local.shape[0]
                    else local)
            else:
                # fully replicated → np.asarray is legal and reuses the
                # copy_to_host_async started right after dispatch
                host = np.asarray(reduced)
                chunks_out[i].append(host[: stop - start])

        # chunk 0 runs serially with explicit barriers: the block after
        # ship keeps transfer cost out of reduce_ms (the old path's
        # async device_put leaked it there), and its psum doubles as the
        # round's entry barrier — it completes only once EVERY process
        # has entered, so cross-process entry skew lands here, visibly,
        # instead of smearing over the stream
        tp0 = time.perf_counter()
        stacked, celems = ship(stream[0])
        jax.block_until_ready(stacked)
        tp1 = time.perf_counter()
        reduced = reduce_chunk(stacked, celems, metas[stream[0][0]][1])
        reduced = jax.block_until_ready(reduced)
        tp2 = time.perf_counter()
        collect(stream[0], reduced)
        tp3 = time.perf_counter()
        t_ship += tp1 - tp0
        t_reduce += tp2 - tp1
        t_readback += tp3 - tp2
        pipelined = stream[1:]

        # pipelined remainder. The main thread only DISPATCHES ship +
        # psum; a dedicated reader thread blocks on each chunk's arrival
        # and collects it, so D2H(k−1) genuinely overlaps H2D(k+1) and
        # psum(k) — both sides spend their time in GIL-releasing runtime
        # calls. A semaphore bounds chunks in flight to the double
        # buffer; the reader's blocked time that elapsed WHILE the main
        # thread was still streaming is readback latency the serial path
        # would have eaten inline — that measured quantity (minus the
        # tail the main thread did wait for at join) is overlap_ms_saved.
        import threading

        slots = threading.Semaphore(_PIPELINE_DEPTH + 1)
        handoff: deque = deque()
        ready = threading.Semaphore(0)
        state = {"blocked": 0.0, "error": None}

        def _reader():
            while True:
                ready.acquire()
                item = handoff.popleft()
                if item is None:
                    return
                tb = time.perf_counter()
                try:
                    collect(*item)
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    state["error"] = e
                state["blocked"] += time.perf_counter() - tb
                slots.release()

        tpipe0 = time.perf_counter()
        reader = threading.Thread(target=_reader, name="mix-readback",
                                  daemon=True)
        reader.start()
        try:
            for entry in pipelined:
                slots.acquire()
                if state["error"] is not None:
                    break
                t0 = time.perf_counter()
                stacked, celems = ship(entry)
                t1 = time.perf_counter()
                reduced = reduce_chunk(stacked, celems, metas[entry[0]][1])
                if not prefer_device:
                    try:
                        reduced.copy_to_host_async()
                    except Exception:  # noqa: BLE001 — no async D2H here
                        pass
                t2 = time.perf_counter()
                t_ship += t1 - t0
                t_reduce += t2 - t1
                handoff.append((entry, reduced))
                ready.release()
        finally:
            dispatch_done = time.perf_counter()
            handoff.append(None)
            ready.release()
            reader.join()
        if state["error"] is not None:
            raise state["error"]
        t_join = time.perf_counter() - dispatch_done
        t_readback += t_join
        pipe_wall = time.perf_counter() - tpipe0
        # measured, not modeled: readback blocking that ran concurrently
        # with the main thread's ship/reduce stream (clamped at 0 for
        # the degenerate no-pipelined-chunks case)
        overlap_saved = max(0.0, state["blocked"] - t_join)

        for i in big_idx:
            _, dtype, shape, size = metas[i]
            t3 = time.perf_counter()
            parts = chunks_out[i]
            if prefer_device:
                total = parts[0] if len(parts) == 1 else \
                    jnp.concatenate(parts)
                out[i] = total.reshape(shape)
            else:
                total = parts[0] if len(parts) == 1 else \
                    np.concatenate(parts)
                out[i] = total.reshape(shape)
            t_readback += time.perf_counter() - t3

    if phases is not None:
        phases.update(
            cast_ms=round(t_cast * 1e3, 2),
            ship_ms=round(t_ship * 1e3, 2),
            reduce_ms=round(t_reduce * 1e3, 2),
            readback_ms=round(t_readback * 1e3, 2),
            payload_mb=round(nbytes / 2**20, 2),
            wire_mb_ring_model=round(nbytes * 2 * (n - 1) / n / 2**20, 2),
            chunks=n_chunks,
            chunk_mb=round(chunk_bytes / 2**20, 2),
            overlap_ms_saved=round(overlap_saved * 1e3, 2),
        )
    return jax.tree_util.tree_unflatten(treedef, out)


def world_size() -> int:
    return jax.process_count()
