"""Cross-process diff reduction — the mix's data plane as an XLA collective.

``psum_pytree`` reduces one pytree of arrays across every process in the
``jax.distributed`` world: each process contributes its local replica's
diff, the reduction runs as jitted shard_map psums over a
one-device-per-process 'replica' mesh (ICI/DCN, not TCP fan-out), and
every process reads back the identical total. This is SURVEY.md §7 step
3's north-star shape: the reference's get_diff → pairwise fold →
put_diff (linear_mixer.cpp:437-559) collapses into AllReduces whose
combiner IS the fold.

The data plane is PIPELINED (docs/PERF_NOTES.md "Mix data plane"):

- Leaves at or above the chunk size are split into fixed-size 1-D chunks
  and streamed with a double buffer, so the host→device ship of chunk
  k+1 overlaps the psum of chunk k and the device→host readback of
  chunk k−1 — instead of the old serial cast-all/ship-all/reduce-all/
  readback-all ("Exploring the limits of Concurrency in ML Training on
  Google TPUs", arxiv 2011.03641: transfer/compute overlap is where TPU
  pipelines recover wall clock). Chunk psums are separate collectives,
  so every process MUST build the identical stream: the plan is a pure
  function of (shapes, dtypes, chunk_bytes, compress) — which the
  collective mixer folds into its prepare signature — never of where a
  leaf happens to live.
- ``compress`` is a three-state wire mode, ``off | bf16 | int8`` (the
  historical bool still resolves: True == "bf16").

  * ``bf16`` casts f32 chunks to bf16 ON DEVICE in the ship stage (a
    tiny jitted cast right after placement), so the psum's wire sees
    half the bytes, the collective body stays a pure reduce, and the
    host never stages an astype copy (EQuARX, arxiv 2506.17615: a
    compressed AllReduce only wins when the cast is fused off the host).
  * ``int8`` is the EQuARX shape proper: per-block scales computed on
    device, quantize-on-device BEFORE the ship (the collective stages
    int8 + one f32 scale per QUANT_BLOCK elements — ~3.94x fewer bytes
    per chunk), scatter-reduce where receivers DEQUANTIZE and
    accumulate in f32, the segment owner REQUANTIZES the reduced
    total, the int8 representation all-gathers around the ring, and
    readback dequantizes.
    Quantization is biased, and an online learner's weight averages
    feed the next round — so a per-replica ``ErrorFeedback`` residual
    (quantization error added back into the next round's diff) keeps
    the averaged weights unbiased: the shipped sums telescope to the
    true sums minus ONE bounded residual, for any number of rounds.
    Small leaves and non-f32 dtypes stay exact (counts must not drift).
- Leaves that are already device-resident ``jax.Array``s (the models in
  models/ are JAX — their diffs need not round-trip through numpy) take
  a zero-staging path: no host cast, no ``device_put`` from numpy, and
  with ``prefer_device=True`` no readback either — the totals are handed
  back as device arrays for the jitted put_diff to consume directly.
- ``topology`` switches the chunked pipeline into HIERARCHICAL mode
  over the two-tier ``(host, local)`` mesh (parallel/mesh.py
  ``host_topology``): each chunk is first psum'd over the ``local``
  axis (intra-host — ICI/loopback, not the wire), each local lane then
  carries only its 1/M segment of the host total into the inter-host
  reduce over the ``host`` axis, and an intra-host all-gather (a psum
  of lane-placed segments) rebuilds the full chunk. The inter-host
  wire therefore ships ONE copy of the chunk per host — wire bytes per
  host stay proportional to hosts, not total devices (the MLPerf-on-
  TPU-pods / "limits of Concurrency" hierarchical-reduction shape;
  flat all-reduce ships the chunk once per *device*). Wire modes
  compose: bf16 casts and int8 quantizes AFTER the intra-host reduce
  (the intra tier stays exact f32 — its bandwidth is free by
  assumption), so int8 error-feedback residuals correct the HOST sum
  and live one per host, not one per device.

Requirements: every process calls with the SAME treedef/shapes/dtypes in
the same order and the same ``compress``/``chunk_bytes`` (the collective
mixer's prepare phase verifies this before anyone enters), and the jax
runtime must be initialized across the world (jax.distributed.initialize
— parallel/multihost.py). Works single-process too (world of 1: psum
degenerates to identity and the int8 path to one quantize round trip —
which is exactly what the error-feedback drift gates exercise).
"""

from __future__ import annotations

import functools
import math
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jubatus_tpu.parallel._compat import shard_map
from jubatus_tpu.parallel.mesh import HostTopology, host_mesh, host_topology
from jubatus_tpu.utils import faults


class ChunkIntegrityError(RuntimeError):
    """A wire chunk failed its integrity screen (ISSUE 15): ``kind`` is
    ``"crc"`` (a staged chunk's CRC32 no longer matches — corruption in
    the host staging window) or ``"nonfinite"`` (the reduced total
    carries NaN/Inf — some contributor shipped poison, or the fold
    overflowed). The collective mixer catches this, counts it, and
    routes the next round to the RPC mix instead of applying garbage."""

    def __init__(self, kind: str, detail: str = "") -> None:
        super().__init__(f"chunk integrity failure ({kind}): {detail}")
        self.kind = kind

#: pipeline chunk size in MiB (uncompressed leaf bytes). Leaves at or
#: above this split into chunks and double-buffer; smaller leaves batch
#: into one collective call. 8 MiB won the sweep recorded in
#: docs/PERF_NOTES.md ("Mix data plane"): big enough that per-chunk
#: dispatch overhead (~0.1 ms) is noise against the chunk's transfer,
#: small enough that three in-flight buffers overlap rather than
#: serialize. Override per deployment with JUBATUS_TPU_MIX_CHUNK_MB —
#: every process in a cluster must agree (the prepare signature checks).
DEFAULT_CHUNK_MB = float(os.environ.get("JUBATUS_TPU_MIX_CHUNK_MB", "8"))

#: in-flight chunks beyond the one being collected: 2 = classic double
#: buffer (ship k+1 while chunk k reduces and chunk k−1 reads back)
_PIPELINE_DEPTH = 2

#: wire-compression modes psum_pytree understands; the collective
#: mixer's --mix-compress flag and prepare signature speak the same enum
COMPRESS_MODES = ("off", "bf16", "int8")

#: elements per quantization block in int8 mode: one f32 scale (absmax /
#: 127) per QUANT_BLOCK elements, so the wire overhead is 4/QUANT_BLOCK
#: bytes per element (~1.6% at 256 — 3.94x total reduction vs f32).
#: Every process in a cluster must agree (rides the prepare signature).
QUANT_BLOCK = int(os.environ.get("JUBATUS_TPU_MIX_QUANT_BLOCK", "256"))

_64BIT = (np.dtype(np.float64), np.dtype(np.int64), np.dtype(np.uint64))

#: process-wide collective dispatch gate (ISSUE 11). Chunk psums are a
#: SEQUENCE of separate collectives, and XLA matches collectives across
#: processes by dispatch order — two rounds interleaving their dispatch
#: in one process would wedge the world. The gate serializes DISPATCH
#: only: it is released the moment a round's last chunk has been handed
#: to the runtime, before the reader thread drains the readback. Round
#: N+1's early chunk ship/reduce therefore overlaps round N's readback
#: (the ``psum_pytree_start`` streaming shape), while the collective
#: order every process sees stays total.
_DISPATCH_GATE = threading.Lock()


class _Gate:
    """One round's hold on the dispatch gate; release is idempotent so
    the early release at dispatch-complete and the outer safety-net
    finally compose."""

    def __init__(self) -> None:
        self._held = False

    def acquire(self) -> float:
        t0 = time.perf_counter()
        _DISPATCH_GATE.acquire()
        self._held = True
        return time.perf_counter() - t0

    def release(self) -> None:
        if self._held:
            self._held = False
            _DISPATCH_GATE.release()


class PendingReduce:
    """Handle for a streaming round started with ``psum_pytree_start``:
    the reduce is dispatching/draining on a worker thread; ``result()``
    joins it and returns the totals (re-raising any failure). While one
    round's readback drains, the NEXT ``psum_pytree_start`` call's ship
    and reduce dispatch may already run — the dispatch gate keeps the
    collective order total across rounds, which is what makes the
    overlap safe."""

    def __init__(self) -> None:
        self._thread: Optional[threading.Thread] = None
        self._box: Dict[str, Any] = {}

    def done(self) -> bool:
        return self._thread is not None and not self._thread.is_alive()

    def result(self) -> Any:
        self._thread.join()
        if "err" in self._box:
            raise self._box["err"]
        return self._box["out"]


def psum_pytree_start(diff: Any, **kwargs) -> PendingReduce:
    """Begin one AllReduce round on a worker thread and return a
    ``PendingReduce`` immediately. Back-to-back rounds stream: round
    N+1's early chunk ship/reduce overlaps round N's readback, because
    the dispatch gate serializes only the DISPATCH of collectives (a
    hard ordering requirement), never the device→host drain. Callers
    must still collect rounds in the order they started them (every
    process must run rounds in the same order)."""
    pending = PendingReduce()

    def work() -> None:
        try:
            pending._box["out"] = psum_pytree(diff, **kwargs)
        except BaseException as e:  # noqa: BLE001 — re-raised in result()
            pending._box["err"] = e

    t = threading.Thread(target=work, name="mix-round-reduce", daemon=True)
    pending._thread = t
    t.start()
    return pending


def _norm_compress(compress: Any) -> str:
    """Resolve the wire mode: the ``off|bf16|int8`` enum, or the
    historical bool (True meant "ship f32 as bf16") every pre-enum
    caller still passes."""
    if isinstance(compress, str):
        mode = compress.lower() or "off"
        if mode not in COMPRESS_MODES:
            raise ValueError(f"unknown mix compress mode {compress!r}; "
                             f"expected one of {COMPRESS_MODES}")
        return mode
    return "bf16" if compress else "off"


def _norm_topology(topology: Any) -> Optional[HostTopology]:
    """Resolve the hierarchical-mode switch: None/"" / "flat" keep the
    flat single-tier pipeline; a HostTopology rides as-is; an "HxM"
    string (the --mix-topology override) resolves against the runtime's
    devices. Every process in a cluster must resolve the SAME topology —
    the collective mixer signs its prepare with it."""
    if topology is None or topology == "" or topology == "flat":
        return None
    if isinstance(topology, HostTopology):
        return topology
    if topology == "auto":
        return host_topology()
    return host_topology(override=topology)


#: per-(device, shape, dtype) zero staging buffers for the hierarchical
#: path's non-representative lanes and fresh residual chains. Bounded;
#: safe to reuse because device arrays are immutable and the hier
#: programs never donate them.
_ZEROS_CACHE: Dict[Tuple, Any] = {}


def _dev_zeros(dev, shape: Tuple[int, ...], dtype_str: str):
    key = (dev, shape, dtype_str)
    z = _ZEROS_CACHE.get(key)
    if z is None:
        if len(_ZEROS_CACHE) > 64:
            _ZEROS_CACHE.clear()
        z = jax.device_put(np.zeros(shape, np.dtype(dtype_str)), dev)
        _ZEROS_CACHE[key] = z
    return z


class ErrorFeedback:
    """Per-replica error-feedback residual state for the int8 transport.

    Block quantization is biased, and the mix averages weights round
    over round — without correction the per-round bias compounds into a
    random walk on the averaged model. Carrying the residual
    ``e_r = (x_r + e_{r-1}) - dequant(quant(x_r + e_{r-1}))`` between
    rounds telescopes it away: the sum of shipped contributions equals
    the sum of true diffs minus ONE bounded residual, for any number of
    rounds (the drift gate in tests/test_collective_pipeline.py proves
    both directions).

    Two chains per replica, matching the two quantization events in the
    chunk collective: ``contrib`` (this replica's own diff segments,
    quantized once for the scatter) and ``total`` (the requant of the
    reduced segments this replica owns and broadcasts). Residuals stay
    device-resident between rounds, keyed by (leaf index, chunk start),
    and are committed only after the WHOLE collective entry succeeds —
    an aborted, degraded, or mid-psum-failed round leaves the state of
    the last successful round intact."""

    def __init__(self) -> None:
        self.key: Optional[Tuple] = None
        self.contrib: Dict[Tuple[int, int], Any] = {}
        self.total: Dict[Tuple[int, int], Any] = {}
        self.rounds = 0

    def reset(self) -> None:
        self.key = None
        self.contrib.clear()
        self.total.clear()

    def stats(self) -> Dict[str, int]:
        return {"rounds": self.rounds, "chunks": len(self.contrib)}

    def norms(self) -> Dict[str, float]:
        """L2 norm of each residual chain — the model-health plane's
        drift signal (ISSUE 7): a residual norm that GROWS round over
        round means quantization error is being deferred faster than
        the telescoping cancels it. One device reduction per residual
        chunk, so call this once per round (the mixer caches it for
        get_status), not per scrape."""
        out: Dict[str, float] = {}
        for name, chain in (("contrib", self.contrib),
                            ("total", self.total)):
            s = 0.0
            for v in chain.values():
                d = v * 1.0  # promote without a host copy; jnp or numpy
                s += float((d * d).sum())
            out[f"{name}_residual_norm"] = float(math.sqrt(s))
        return out


def _world_mesh() -> Mesh:
    """1-D 'replica' mesh with exactly one device per process (the first
    local device of each), in process order — every process builds the
    identical mesh."""
    per_process: Dict[int, Any] = {}
    for d in jax.devices():
        p = d.process_index
        if p not in per_process or d.id < per_process[p].id:
            per_process[p] = d
    devs = [per_process[p] for p in sorted(per_process)]
    return Mesh(np.array(devs), axis_names=("replica",))


def _donate() -> Tuple[int, ...]:
    # donating the stacked input lets XLA reuse its buffer for the
    # on-device bf16 cast; the CPU backend can't honor donation and
    # would warn on every compile
    return () if jax.default_backend() == "cpu" else (0,)


def _psum_body(x, compress: bool):
    y = jnp.squeeze(x, 0)
    if compress and y.dtype == jnp.float32:
        # cast fused into the collective: the wire sees bf16 (half the
        # ICI/DCN bytes), the caller gets f32 back — the EQuARX-style
        # tradeoff without the old host-side astype copy
        y = y.astype(jnp.bfloat16)
        return jax.lax.psum(y, "replica").astype(jnp.float32)
    total = jax.lax.psum(y, "replica")
    if compress and total.dtype == jnp.bfloat16:
        # pre-cast bf16 input under compress keeps the old contract:
        # hand back f32 for the f32 master
        total = total.astype(jnp.float32)
    return total


@functools.lru_cache(maxsize=32)
def _reduce_tree_fn(mesh: Mesh, treedef, shapes: Tuple, dtypes: Tuple,
                    compress: bool):
    """Batched psum of one pytree of small leaves (single collective
    program, like the pre-pipeline engine)."""

    def body(stacked):
        return jax.tree_util.tree_map(
            lambda x: _psum_body(x, compress), stacked)

    return jax.jit(
        shard_map(body, mesh=mesh, in_specs=P("replica"), out_specs=P()),
        out_shardings=NamedSharding(mesh, P()),
        donate_argnums=_donate(),
    )


@functools.lru_cache(maxsize=32)
def _reduce_chunk_fn(mesh: Mesh, elems: int, dtype_str: str, compress: bool):
    """psum of one [world, elems] chunk. All full chunks of a dtype share
    this one compiled program; ragged tails are zero-padded up to it
    (psum of zeros is zeros, sliced off at collection)."""

    def body(x):
        return _psum_body(x, compress)

    return jax.jit(
        shard_map(body, mesh=mesh, in_specs=P("replica"), out_specs=P()),
        out_shardings=NamedSharding(mesh, P()),
        donate_argnums=_donate(),
    )


@functools.lru_cache(maxsize=8)
def _cast_fn(dtype_str: str):
    """On-device dtype cast for the ship stage (bf16 mode). The wire
    prep must never be a host astype — at the d24 bench shape that copy
    alone cost ~740 ms per round (the codestyle host-cast gate keeps it
    from coming back)."""
    return jax.jit(lambda x: x.astype(jnp.dtype(dtype_str)))


def _block_quant(y, block: int):
    """[m] f32 -> ([m] int8, [m/block] f32 scales); m % block == 0.
    Symmetric per-block absmax scaling (EQuARX's block-wise design: one
    outlier only poisons its own 256 elements, not the tensor)."""
    b = y.reshape(-1, block)
    amax = jnp.max(jnp.abs(b), axis=1, keepdims=True)
    scale = jnp.where(amax > 0, amax / 127.0, jnp.float32(1.0))
    q = jnp.clip(jnp.round(b / scale), -127.0, 127.0).astype(jnp.int8)
    return q.reshape(-1), scale[:, 0]


def _block_dequant(q, scale, block: int):
    return (q.reshape(-1, block).astype(jnp.float32)
            * scale[:, None]).reshape(-1)


def _quant_ring_reduce(q, scales, res_t, axis: str, n: int, block: int):
    """The quantized scatter-reduce + all-gather ring over ``axis``
    (n members), shared by the flat transport (axis="replica", the
    whole world) and the hierarchical inter-host tier (axis="host",
    one lane-segment per host group). ``q`` [m] int8 + ``scales``
    [m/block] f32 are the caller's pre-quantized copy of the full ring
    payload (m divisible by n*block); ``res_t`` [m/n] is the carried
    requant residual of the segment this member owns. Returns the
    dequantized total [m] f32 — bit-identical on every member, because
    everyone dequantizes the same all-gathered int8+scale bits — and
    the new owned-segment residual. n == 1 degenerates to the pure
    dequant → +res → requant round trip the world-1 drift gates ride."""
    m = q.shape[0]
    seg = m // n
    sb = (m // block) // n  # scale blocks per segment
    r = jax.lax.axis_index(axis)
    qsegs = q.reshape(n, seg)
    ssegs = scales.reshape(n, sb)
    acc = _block_dequant(
        jax.lax.dynamic_index_in_dim(qsegs, r, 0, keepdims=False),
        jax.lax.dynamic_index_in_dim(ssegs, r, 0, keepdims=False),
        block)
    for k in range(1, n):
        perm = [(i, (i + k) % n) for i in range(n)]
        sq = jax.lax.dynamic_index_in_dim(
            qsegs, (r + k) % n, 0, keepdims=False)
        ss = jax.lax.dynamic_index_in_dim(
            ssegs, (r + k) % n, 0, keepdims=False)
        acc = acc + _block_dequant(
            jax.lax.ppermute(sq, axis, perm),
            jax.lax.ppermute(ss, axis, perm), block)
    tot = acc + res_t
    tq, ts = _block_quant(tot, block)
    new_res_t = tot - _block_dequant(tq, ts, block)
    out = jnp.zeros((n, seg), jnp.float32)
    out = out.at[r].set(_block_dequant(tq, ts, block))
    cq, cs, idx = tq, ts, r
    fwd = [(i, (i + 1) % n) for i in range(n)]
    for _ in range(n - 1):
        cq = jax.lax.ppermute(cq, axis, fwd)
        cs = jax.lax.ppermute(cs, axis, fwd)
        idx = (idx - 1) % n
        out = out.at[idx].set(_block_dequant(cq, cs, block))
    return out.reshape(m), new_res_t


@functools.lru_cache(maxsize=32)
def _quant_ship_fn(celems: int, block: int):
    """LOCAL (non-collective) per-chunk quantizer for the ship stage:
    ``(x [1, celems] f32, res [1, celems] f32) -> (q int8, scales f32,
    new_res f32)``. Quantize-on-device BEFORE the ship — the collective's
    input arrays are int8 + per-block scales (4x smaller staging than an
    f32 chunk), the error-feedback residual of this replica's own
    contribution is computed here (and never enters the collective), and
    the host never stages a cast."""

    def body(x, res):
        y = jnp.squeeze(x, 0) + jnp.squeeze(res, 0)
        q, scales = _block_quant(y, block)
        new_res = y - _block_dequant(q, scales, block)
        return q[None], scales[None], new_res[None]

    return jax.jit(body)


@functools.lru_cache(maxsize=32)
def _quant_reduce_fn(mesh: Mesh, celems: int, block: int):
    """Quantized all-reduce of one pre-quantized [world, celems] chunk —
    dequant → sum → requant, one jitted program per chunk size:

    - scatter-reduce: for each ring shift k, every replica forwards its
      (already ship-quantized) int8 copy of the RECEIVER's segment; the
      receiver dequantizes and accumulates in f32 on device — the wire
      never sees anything wider than int8 + per-block f32 scales.
    - the segment owner requantizes the reduced total (the second
      error-feedback chain, carried via ``res_t``) and the int8 bits
      all-gather around the ring; EVERY replica — owner included —
      dequantizes the same int8+scale representation on readback, so
      the output is bit-identical everywhere (shard_map cannot prove
      that: check_rep=False).

    World of 1 degenerates to the pure quantize round trip (ship quant
    → dequant → total requant) with both residual chains active — the
    single-process drift gates ride that."""
    n = mesh.shape["replica"]

    def body(q, scales, res_t):
        out, new_res_t = _quant_ring_reduce(
            jnp.squeeze(q, 0), jnp.squeeze(scales, 0),
            jnp.squeeze(res_t, 0), "replica", n, block)
        return out, new_res_t[None]

    return jax.jit(
        shard_map(body, mesh=mesh,
                  in_specs=(P("replica"), P("replica"), P("replica")),
                  out_specs=(P(), P("replica")),
                  check_rep=False),
        out_shardings=(NamedSharding(mesh, P()),
                       NamedSharding(mesh, P("replica"))),
        # only the quantized buffer is donated: the residual input must
        # survive a failed round (feedback commits on success)
        donate_argnums=_donate(),
    )


_SPEC2 = P("host", "local")


@functools.lru_cache(maxsize=32)
def _hier_fns(mesh: Mesh, celems: int, dtype_str: str, mode: str):
    """The two-tier reduce of one [hosts, locals, celems] chunk as TWO
    jitted programs (separately dispatched so the mix can time the
    tiers apart — ``intra_ms`` vs ``inter_ms``):

    - intra: reduce-scatter over ``local`` — each lane receives ONLY
      its 1/M segment of the host sum, (M-1)/M of the chunk on the
      intra wire (a full psum would ship 2(M-1)/M and broadcast a sum
      we immediately discard M-1 of). The bf16 cast happens here, after
      the exact intra fold, when the wire mode asks — the inter tier's
      input is one chunk copy per host, spread over the lanes.
    - inter: psum over ``host`` reduces each lane's segment across
      hosts (M parallel rings, each carrying a DISTINCT segment — the
      per-host wire is the chunk once, not once per device), then an
      intra-host all-gather of the lane segments (lane-order concat)
      rebuilds the full chunk on every device.

    A 1x1 topology degenerates to the identity pipeline — bit-identical
    to the flat path, which the world-1 parity gates assert."""
    compress = mode == "bf16" and dtype_str == "float32"

    def intra(x):
        y = jnp.squeeze(x, (0, 1))
        s = jax.lax.psum_scatter(y, "local", scatter_dimension=0,
                                 tiled=True)
        if compress:
            s = s.astype(jnp.bfloat16)
        return s[None, None]

    def inter(s):
        y = jnp.squeeze(s, (0, 1))
        tot = jax.lax.psum(y, "host")
        if compress:
            tot = tot.astype(jnp.float32)
        return jax.lax.all_gather(tot, "local", tiled=True)

    # no donation on the intra input: its zero lanes come from the
    # shared _dev_zeros cache and must survive the call
    intra_j = jax.jit(
        shard_map(intra, mesh=mesh, in_specs=_SPEC2, out_specs=_SPEC2,
                  check_rep=False),
        out_shardings=NamedSharding(mesh, _SPEC2))
    inter_j = jax.jit(
        shard_map(inter, mesh=mesh, in_specs=_SPEC2, out_specs=P(),
                  check_rep=False),
        out_shardings=NamedSharding(mesh, P()),
        donate_argnums=_donate())
    return intra_j, inter_j


@functools.lru_cache(maxsize=32)
def _hier_quant_fns(mesh: Mesh, celems: int, block: int):
    """int8 over the two-tier mesh. Quantization happens AFTER the
    intra-host reduce (the intra tier is exact f32 — quantizing the
    wire you are not constrained by would only add error), so the
    error-feedback residuals correct the HOST sum: one ``contrib``
    chain entry per (host, lane) segment — per host, not per
    contributing device — and the ring's requant chain per owned
    sub-segment, exactly like the flat transport one tier down."""
    n_host = mesh.shape["host"]

    def intra(x, res_c):
        y = jnp.squeeze(x, (0, 1))
        s = jax.lax.psum_scatter(y, "local", scatter_dimension=0,
                                 tiled=True)
        s = s + jnp.squeeze(res_c, (0, 1))
        q, scales = _block_quant(s, block)
        new_res = s - _block_dequant(q, scales, block)
        return q[None, None], scales[None, None], new_res[None, None]

    def inter(q, scales, res_t):
        out_seg, new_rt = _quant_ring_reduce(
            jnp.squeeze(q, (0, 1)), jnp.squeeze(scales, (0, 1)),
            jnp.squeeze(res_t, (0, 1)), "host", n_host, block)
        return (jax.lax.all_gather(out_seg, "local", tiled=True),
                new_rt[None, None])

    # no donation on intra (zero lanes + residual come from shared /
    # carried buffers); inter donates only the fresh quantized buffer —
    # the residual input must survive a failed round
    intra_j = jax.jit(
        shard_map(intra, mesh=mesh, in_specs=(_SPEC2, _SPEC2),
                  out_specs=(_SPEC2, _SPEC2, _SPEC2), check_rep=False),
        out_shardings=(NamedSharding(mesh, _SPEC2),) * 3)
    inter_j = jax.jit(
        shard_map(inter, mesh=mesh, in_specs=(_SPEC2, _SPEC2, _SPEC2),
                  out_specs=(P(), _SPEC2), check_rep=False),
        out_shardings=(NamedSharding(mesh, P()),
                       NamedSharding(mesh, _SPEC2)),
        donate_argnums=_donate())
    return intra_j, inter_j


@functools.lru_cache(maxsize=8)
def _finite_all_fn():
    """On-device isfinite reduction of one reduced chunk — the
    collective path's half of the fold-time finite screen (the RPC mix
    screens payloads on the host; device-resident totals must be
    screened where they live). Returns a device scalar so the pipeline
    never blocks per chunk; the flags fold into one host readback at
    round end."""
    return jax.jit(lambda x: jnp.isfinite(x).all())


def _finite_flag(arr):
    """Device bool scalar (or None for non-float dtypes, which cannot
    carry NaN/Inf)."""
    if not np.issubdtype(np.dtype(arr.dtype), np.floating):
        return None
    return _finite_all_fn()(arr)


def _crc_stage(chunk: np.ndarray, state: Dict[str, int],
               guard: str) -> np.ndarray:
    """CRC32-bracketed staging of one host wire chunk (ISSUE 15): stamp
    the contribution's checksum, pass through the ``mix.wire.corrupt``
    chaos window (bitflip models transport/DMA corruption), and verify
    before the bytes reach the device. The bracket covers the host
    staging window — device-side transport integrity is the runtime's
    job, and the reduced total's finite screen is the cross-member
    backstop. The chunk also gets the CONTRIBUTION-side finite screen
    here: the int8 transport's requant LAUNDERS a NaN/Inf block into
    zeros (NaN fails the ``amax > 0`` scale test), so poison must be
    caught before it quantizes, not after it reduces. ``quarantine``
    raises (the round dies instead of shipping garbage); ``warn``
    counts and ships."""
    from jubatus_tpu import native

    if np.issubdtype(chunk.dtype, np.floating) and \
            not np.isfinite(chunk).all():
        state["nonfinite"] += 1
        if guard == "quarantine":
            raise ChunkIntegrityError(
                "nonfinite", "staged contribution chunk carries NaN/Inf")
    buf = chunk.tobytes()
    crc0 = native.crc32(buf)
    if faults.is_armed():
        mut = faults.fire_mutate("mix.wire.corrupt")
        if mut is not None and mut[0] == "bitflip":
            buf = faults.flip_byte(buf)
    if native.crc32(buf) != crc0:
        state["crc"] += 1
        if guard == "quarantine":
            raise ChunkIntegrityError(
                "crc", f"staged chunk of {len(buf)} bytes")
        return np.frombuffer(buf, dtype=chunk.dtype)
    return chunk


def _leaf_meta(leaf) -> Tuple[Any, np.dtype, Tuple[int, ...]]:
    """(leaf, dtype, shape) WITHOUT materializing device arrays on the
    host (np.asarray on a jax.Array is a full device→host copy)."""
    dtype = getattr(leaf, "dtype", None)
    shape = getattr(leaf, "shape", None)
    if dtype is None or shape is None:
        leaf = np.asarray(leaf)  # python scalar / list leaf
        dtype, shape = leaf.dtype, leaf.shape
    return leaf, np.dtype(dtype), tuple(shape)


def psum_pytree(diff: Any, compress: Any = False,
                phases: dict = None,  # type: ignore[assignment]
                chunk_mb: Optional[float] = None,
                prefer_device: bool = False,
                feedback: Optional[ErrorFeedback] = None,
                topology: Any = None,
                guard: str = "off") -> Any:
    """AllReduce ``diff`` (pytree of arrays/scalars) across the process
    world. Every process must call this with an identically-shaped
    pytree and the same ``compress`` and ``chunk_mb`` (both ride the
    collective mixer's prepare signature).

    ``compress`` picks the wire mode (``off | bf16 | int8``; the
    historical bool still works, True == "bf16"). ``bf16`` ships f32
    leaves as bf16 — half the wire bytes per round at ~3 decimal digits
    of diff precision; additive diffs tolerate it because put_diff folds
    into an f32 master. The cast runs ON DEVICE in the ship stage (a
    host astype here once cost ~740 ms per d24 round). ``int8`` runs
    chunked f32 leaves through the block-quantized all-reduce
    (``_quant_chunk_fn``): ~3.94x fewer wire bytes; pass a persistent
    ``feedback`` (ErrorFeedback) so the quantization error is carried
    into the next round's diff and the averaged model stays unbiased —
    without it every round's bias walks the weights. Small leaves and
    non-f32 dtypes stay exact under int8.

    ``prefer_device=True`` returns totals as device ``jax.Array``s
    (no readback) — callers whose put_diff is jitted consume them
    directly; the default returns host numpy arrays.

    ``phases`` (optional dict) is filled with this call's per-phase wall
    times so mix rounds log like the reference's per-round time+bytes
    (linear_mixer.cpp:553-558): ``cast_ms`` (host cast — held at ~0 by
    design: compress casts/quantization run on device), ``ship_ms``
    (host→device placement + the on-device wire prep; the first chunk is
    measured with an explicit completion barrier so async dispatch
    cannot leak transfer time into ``reduce_ms``), ``reduce_ms`` (the
    jitted collectives — wire and fold are ONE fused program, unlike the
    reference's get_diff/fold/put_diff), ``readback_ms`` (device→host;
    in the pipelined stream this is the time BLOCKED on arrival, i.e.
    whatever the overlap didn't hide), ``payload_mb`` (post-compress
    wire bytes this replica contributes, including quantization scales
    and block padding under int8), ``wire_mb`` ==
    ``wire_mb_ring_model`` (2(n-1)/n × payload — ring-allreduce bytes
    per replica; exact for the int8 scatter+gather this module
    implements, a model for the runtime-picked psum), ``quant`` (the
    resolved wire mode, stamped into flight-recorder round records),
    plus the pipeline accounting: ``chunks``, ``chunk_mb``, and
    ``overlap_ms_saved`` — a DIRECT measurement of the overlap win:
    the reader thread's readback blocking that elapsed while the main
    thread was still shipping/reducing later chunks (minus the tail it
    did wait for) — wait the serial path would have eaten inline.

    ``topology`` (None | HostTopology | "auto" | "HxM") switches the
    CHUNKED stream into the two-tier hierarchical reduce over the
    (host, local) mesh: intra-host psum first, one chunk copy per host
    on the inter-host wire (see the module docstring). Small leaves
    keep the flat batched collective — their wire share is noise and
    the stream shape must stay a pure function of the plan inputs.
    Hierarchical phases additionally report ``intra_ms``/``inter_ms``
    (per-tier; barriered exactly for chunk 0, dispatch-side for the
    pipelined remainder, like ``reduce_ms``), ``topo`` (the NxM
    signature, "flat" otherwise) and ``wire_bytes_per_host`` (ring-
    model inter-host bytes one HOST ships per round — the scaling
    gate's key: flat grows it with devices, hierarchical holds it at
    the host count)."""
    # model-integrity screens (ISSUE 15; ``guard`` mirrors the owning
    # mixer's --mix-guard): when not "off", every host-staged wire
    # chunk is CRC32-bracketed through the ``mix.wire.corrupt`` chaos
    # window (_crc_stage) and every reduced total gets a finite screen
    # (on device for prefer_device consumers — flags fold into ONE
    # scalar readback at round end, so the pipeline never stalls per
    # chunk). ``quarantine`` raises ChunkIntegrityError — BEFORE the
    # feedback commit, so a poisoned round leaves the EF residuals of
    # the last good round intact; ``warn`` stamps ``finite_ok`` /
    # ``crc_mismatch_chunks`` / ``nonfinite_chunks`` into ``phases``
    # and proceeds.
    guard = (guard or "off").lower() if isinstance(guard, str) else \
        ("quarantine" if guard else "off")
    if guard not in ("off", "warn", "quarantine"):
        raise ValueError(f"unknown guard mode {guard!r}")
    mode = _norm_compress(compress)
    # a 1x1 (trivial) topology still rides the hier code path — the
    # world-1 parity gates prove that path bit-identical to flat
    topo = _norm_topology(topology)
    mesh = _world_mesh()
    n = mesh.shape["replica"]
    me = jax.local_devices()[0]
    sharding = NamedSharding(mesh, P("replica"))
    hier = topo is not None
    if hier:
        mesh2 = host_mesh(topo)
        sharding2 = NamedSharding(mesh2, _SPEC2)
        my_devs = [d for row in topo.grid for d in row
                   if d.process_index == me.process_index]
        if not my_devs:
            raise ValueError(
                f"topology {topo.signature} includes no device of "
                f"process {me.process_index}")
    if chunk_mb is None:
        chunk_mb = DEFAULT_CHUNK_MB
    chunk_bytes = max(1, int(chunk_mb * 2**20))
    block = QUANT_BLOCK

    leaves, treedef = jax.tree_util.tree_flatten(diff)
    if phases is not None:
        phases.update(cast_ms=0.0, ship_ms=0.0, reduce_ms=0.0,
                      readback_ms=0.0, intra_ms=0.0, inter_ms=0.0,
                      payload_mb=0.0,
                      wire_mb=0.0, wire_mb_ring_model=0.0,
                      wire_bytes_per_host=0, chunks=0,
                      chunk_mb=round(chunk_bytes / 2**20, 2),
                      overlap_ms_saved=0.0, dispatch_gate_ms=0.0,
                      quant=mode,
                      guard=guard, finite_ok=True,
                      crc_mismatch_chunks=0, nonfinite_chunks=0,
                      topo=topo.signature if hier else "flat")
    if not leaves:
        return diff

    metas = []
    for leaf in leaves:
        leaf, dtype, shape = _leaf_meta(leaf)
        if dtype in _64BIT:
            # a silent downcast would make the collective path less exact
            # than the RPC fold; callers gate these to the fallback
            # (collective_mixer._signature marks them unsupported)
            raise ValueError(
                f"64-bit leaf dtype {dtype} cannot ride the "
                "collective exactly; use the RPC mix path")
        size = int(np.prod(shape, dtype=np.int64)) if shape else 1
        metas.append((leaf, dtype, shape, size))

    # the collective sequence must be identical on every process, so the
    # small/chunked split keys on (size, chunk_bytes) alone — where a
    # leaf lives only changes local staging, never the stream shape
    small_idx = [i for i, (_, dt, _, s) in enumerate(metas)
                 if s * dt.itemsize < chunk_bytes]
    big_idx = [i for i, (_, dt, _, s) in enumerate(metas)
               if s * dt.itemsize >= chunk_bytes]
    big_set = set(big_idx)

    def _chunk_elems(dtype: np.dtype) -> int:
        ce = max(1, chunk_bytes // dtype.itemsize)
        if hier:
            # every lane owns a 1/M segment of the chunk; int8
            # additionally block-quantizes per host-ring sub-segment
            quantum = topo.locals
            if mode == "int8" and dtype == np.float32:
                quantum = topo.locals * topo.hosts * block
        elif mode == "int8" and dtype == np.float32:
            # every replica-owned segment must block-quantize: pad the
            # chunk up to a multiple of world * QUANT_BLOCK (zeros
            # quantize to zeros; sliced off at collection)
            quantum = n * block
        else:
            quantum = 1
        return ((ce + quantum - 1) // quantum) * quantum

    # wire accounting per leaf: bf16 halves every f32 leaf; int8
    # quantizes only the CHUNKED f32 leaves (small leaves and non-f32
    # dtypes ship exact) at 1 byte/elem + one f32 scale per block,
    # counting the block padding the stream actually ships. Chunked
    # and small bytes are tracked apart: in hierarchical mode only the
    # chunked stream rides the two-tier reduce (small leaves stay on
    # the flat world ring), so their ring models differ.
    nbytes = big_bytes = small_bytes = 0
    for i, (_, dtype, _, size) in enumerate(metas):
        wire = size * dtype.itemsize
        if dtype == np.float32:
            if mode == "bf16":
                wire //= 2
            elif mode == "int8" and i in big_set:
                ce = _chunk_elems(dtype)
                shipped = ((size + ce - 1) // ce) * ce
                wire = shipped + (shipped // block) * 4
        nbytes += wire
        if i in big_set:
            big_bytes += wire
        else:
            small_bytes += wire

    out: List[Any] = [None] * len(metas)
    t_ship = t_reduce = t_readback = t_cast = 0.0

    # dispatch gate (ISSUE 11): held from the first collective dispatch
    # of this round to the last — released BEFORE the readback drain so
    # a back-to-back round (psum_pytree_start) ships/reduces its early
    # chunks while this round's device→host traffic completes. The wait
    # itself is reported as dispatch_gate_ms.
    gate = _Gate()
    gate_wait = gate.acquire()
    try:
        return _reduce_under_gate(
            gate, gate_wait, metas, small_idx, big_idx, big_set, out,
            treedef, mesh, n, me, sharding, hier, topo, chunk_bytes,
            block, mode, prefer_device, feedback, phases,
            _chunk_elems, nbytes, big_bytes, small_bytes,
            t_ship, t_reduce, t_readback, t_cast, guard)
    finally:
        gate.release()


def _reduce_under_gate(gate, gate_wait, metas, small_idx, big_idx,
                       big_set, out, treedef, mesh, n, me, sharding,
                       hier, topo, chunk_bytes, block, mode,
                       prefer_device, feedback, phases, _chunk_elems,
                       nbytes, big_bytes, small_bytes,
                       t_ship, t_reduce, t_readback, t_cast,
                       guard="off"):
    """The collective body of one round, entered with the dispatch gate
    held (see psum_pytree). Split out so the gate's safety-net release
    wraps every exit path without re-indenting the stream logic."""
    if hier:
        mesh2 = host_mesh(topo)
        sharding2 = NamedSharding(mesh2, _SPEC2)
        my_devs = [d for row in topo.grid for d in row
                   if d.process_index == me.process_index]

    # integrity state (ISSUE 15): per-round CRC/finite tallies, plus
    # the deferred on-device finite flags (one readback at round end)
    integ = {"crc": 0, "nonfinite": 0}
    finite_flags: List[Any] = []

    def _screen_total(arr, on_device: bool) -> None:
        """Queue (device) or run (host) the finite screen of one
        reduced total; tallies fold in _finite_verdict."""
        if guard == "off":
            return
        if on_device:
            f = _finite_flag(arr)
            if f is not None:
                finite_flags.append(f)
        elif np.issubdtype(np.dtype(arr.dtype), np.floating) and \
                not np.isfinite(arr).all():
            integ["nonfinite"] += 1

    def _finite_verdict() -> None:
        """Fold the deferred device flags (one blocking readback for
        the whole round), stamp the phases, and — in quarantine mode —
        refuse a poisoned round before anything consumes it (and, for
        int8, before the error-feedback residuals commit)."""
        if guard == "off":
            return
        if finite_flags:
            integ["nonfinite"] += sum(
                0 if bool(f) else 1 for f in finite_flags)
            finite_flags.clear()
        if phases is not None:
            phases.update(crc_mismatch_chunks=integ["crc"],
                          nonfinite_chunks=integ["nonfinite"],
                          finite_ok=not (integ["crc"]
                                         or integ["nonfinite"]))
        if guard == "quarantine" and integ["nonfinite"]:
            raise ChunkIntegrityError(
                "nonfinite", f"{integ['nonfinite']} reduced chunk(s) "
                "carry NaN/Inf")

    # -- small leaves: one batched collective (the pre-pipeline shape) --
    if small_idx:
        t0 = time.perf_counter()
        arrs = []
        for i in small_idx:
            leaf, dtype, shape, _ = metas[i]
            if isinstance(leaf, jax.Array):
                shard = jax.device_put(leaf[None, ...], me)
            else:
                shard = jax.device_put(np.asarray(leaf)[None, ...], me)
            arrs.append(jax.make_array_from_single_device_arrays(
                (n,) + shape, sharding, [shard]))
        # device_put is async: block before timestamping so transfer
        # cost does not leak into reduce_ms
        jax.block_until_ready(arrs)
        t1 = time.perf_counter()
        stacked = tuple(arrs)
        shapes = tuple(a.shape for a in arrs)
        dtypes = tuple(str(a.dtype) for a in arrs)
        s_treedef = jax.tree_util.tree_structure(stacked)
        total = _reduce_tree_fn(mesh, s_treedef, shapes, dtypes,
                                mode == "bf16")(stacked)
        total = jax.block_until_ready(total)
        t2 = time.perf_counter()
        for i, tot in zip(small_idx, total):
            local = tot.addressable_shards[0].data
            out[i] = local if prefer_device else np.asarray(local)
            _screen_total(out[i], on_device=prefer_device)
        t3 = time.perf_counter()
        t_ship += t1 - t0
        t_reduce += t2 - t1
        t_readback += t3 - t2
    if not big_idx:
        # small-only round: every collective completed above — the next
        # round may dispatch while we assemble/return
        gate.release()
        _finite_verdict()

    # -- big leaves: chunked double-buffered stream ---------------------
    n_chunks = 0
    overlap_saved = 0.0
    quant_rounds = 0
    if big_idx:
        stream: List[Tuple[int, int, int]] = []  # (leaf idx, start, stop)
        flats: Dict[int, Any] = {}
        chunks_out: Dict[int, List[Any]] = {}
        for i in big_idx:
            leaf, dtype, shape, size = metas[i]
            celems = _chunk_elems(dtype)
            if isinstance(leaf, jax.Array):
                flats[i] = leaf.reshape(-1)  # device op, zero staging
            else:
                flats[i] = np.ascontiguousarray(
                    np.asarray(leaf)).reshape(-1)
            chunks_out[i] = []
            for start in range(0, size, celems):
                stream.append((i, start, min(start + celems, size)))
        n_chunks = len(stream)

        # error-feedback state: reset on any plan change (shape, chunk,
        # world, topology, or block skew would misalign the carried
        # residuals); fresh residuals commit only after the whole
        # stream succeeds
        plan_key = (str(treedef),
                    tuple((str(m[1]), m[2]) for m in metas),
                    chunk_bytes, n, block,
                    topo.signature if hier else "flat")
        if feedback is not None and feedback.key != plan_key:
            feedback.reset()
        pending_c: Dict[Tuple[int, int], Any] = {}
        pending_t: Dict[Tuple[int, int], Any] = {}
        tiers = {"intra": 0.0, "inter": 0.0}

        def _quantized(i: int) -> bool:
            return mode == "int8" and metas[i][1] == np.float32

        def _hier_global(per_dev_shape, dtype_str, data=None):
            """A (hosts, locals, *per_dev_shape) global array from this
            process's addressable lanes: ``data`` on its FIRST grid
            device (a process contributes its chunk exactly once),
            cached zeros on the rest — the intra psum folds every
            host's real lanes and ignores the zero ones."""
            shards = []
            for j, d in enumerate(my_devs):
                if j == 0 and data is not None:
                    shards.append(jax.device_put(data[None, None], d))
                else:
                    shards.append(
                        _dev_zeros(d, (1, 1) + per_dev_shape, dtype_str))
            return jax.make_array_from_single_device_arrays(
                (topo.hosts, topo.locals) + per_dev_shape, sharding2,
                shards)

        def ship(entry):
            i, start, stop = entry
            dtype = metas[i][1]
            celems = _chunk_elems(dtype)
            flat = flats[i]
            chunk = flat[start:stop]
            pad = celems - (stop - start)
            if isinstance(flat, jax.Array):
                # device-resident leaf: zero host staging, so there is
                # no host window to checksum — the runtime owns the
                # buffer end to end; the contribution's finite screen
                # runs ON DEVICE instead (deferred flag, one readback
                # per round)
                if pad:
                    chunk = jnp.concatenate(
                        [chunk, jnp.zeros(pad, chunk.dtype)])
                if guard != "off":
                    f = _finite_flag(chunk)
                    if f is not None:
                        finite_flags.append(f)
            else:
                if pad:
                    chunk = np.concatenate(
                        [chunk, np.zeros(pad, chunk.dtype)])
                if guard != "off":
                    # CRC-bracketed staging + the mix.wire.corrupt
                    # chaos window (ISSUE 15)
                    chunk = _crc_stage(chunk, integ, guard)
            if hier:
                # the wire prep (bf16 cast / int8 quantization) happens
                # INSIDE the collective, after the exact intra-host
                # fold — the ship stage only places this process's
                # contribution on its representative lane
                return _hier_global((celems,), str(chunk.dtype),
                                    data=chunk), celems
            shard = jax.device_put(chunk[None, :], me)
            if mode == "bf16" and dtype == np.float32:
                # the wire prep IS the ship path: cast on device right
                # after placement, so the collective body reduces
                # pre-cast bf16 and the host never stages an astype
                shard = _cast_fn("bfloat16")(shard)
            elif _quantized(i):
                # quantize-on-device before the ship: the collective's
                # staged inputs are int8 + per-block scales (4x less),
                # and this replica's contribution residual (error
                # feedback chain 1) is computed here, locally — it
                # never enters the collective
                key = (i, start)
                rc = feedback.contrib.get(key) \
                    if feedback is not None else None
                if rc is None:
                    rc = jax.device_put(
                        np.zeros((1, celems), np.float32), me)
                q, scales, new_rc = _quant_ship_fn(celems, block)(shard, rc)
                pending_c[key] = new_rc
                gq = jax.make_array_from_single_device_arrays(
                    (n, celems), sharding, [q])
                gs = jax.make_array_from_single_device_arrays(
                    (n, celems // block), sharding, [scales])
                return (gq, gs), celems
            return jax.make_array_from_single_device_arrays(
                (n, celems), sharding, [shard]), celems

        def _total_residual(entry, celems):
            """The owned-segment requant residual (error feedback chain
            2) as a [world, seg] array — zeros on the first round /
            after a plan change. Stored globals are reused as-is: their
            sharding matches the freshly built (equal) mesh."""
            rt = feedback.total.get((entry[0], entry[1])) \
                if feedback is not None else None
            if rt is None:
                seg = celems // n
                rt = jax.make_array_from_single_device_arrays(
                    (n, seg), sharding,
                    [jax.device_put(np.zeros((1, seg), np.float32), me)])
            return rt

        def reduce_chunk(entry, stacked, celems, barrier=False):
            i = entry[0]
            dtype = metas[i][1]
            if hier:
                return _reduce_chunk_hier(entry, stacked, celems, barrier)
            if _quantized(i):
                gq, gs = stacked
                rt = _total_residual(entry, celems)
                reduced, new_rt = _quant_reduce_fn(
                    mesh, celems, block)(gq, gs, rt)
                pending_t[(i, entry[1])] = new_rt
                return reduced
            dt = ("bfloat16" if mode == "bf16" and dtype == np.float32
                  else str(dtype))
            return _reduce_chunk_fn(mesh, celems, dt,
                                    mode == "bf16")(stacked)

        def _reduce_chunk_hier(entry, stacked, celems, barrier):
            """Two dispatches per chunk — intra-host fold, then the
            inter-host ring + rebuild — so the tiers are timed apart.
            Chunk 0 (``barrier``) blocks between them: its ``intra_ms``
            / ``inter_ms`` are real wall splits; the pipelined
            remainder adds dispatch-side time only (same honesty
            contract as ``reduce_ms``)."""
            i, start, _stop = entry
            key = (i, start)
            t0 = time.perf_counter()
            if _quantized(i):
                seg = celems // topo.locals
                rc = feedback.contrib.get(key) \
                    if feedback is not None else None
                if rc is None:
                    rc = _hier_global((seg,), "float32")
                intra_fn, inter_fn = _hier_quant_fns(mesh2, celems, block)
                q, scales, new_rc = intra_fn(stacked, rc)
                if barrier:
                    jax.block_until_ready((q, scales))
                t1 = time.perf_counter()
                pending_c[key] = new_rc
                rt = feedback.total.get(key) \
                    if feedback is not None else None
                if rt is None:
                    rt = _hier_global((seg // topo.hosts,), "float32")
                reduced, new_rt = inter_fn(q, scales, rt)
                if barrier:
                    jax.block_until_ready(reduced)
                t2 = time.perf_counter()
                pending_t[key] = new_rt
            else:
                dtype = metas[i][1]
                intra_fn, inter_fn = _hier_fns(mesh2, celems,
                                               str(dtype), mode)
                segs = intra_fn(stacked)
                if barrier:
                    jax.block_until_ready(segs)
                t1 = time.perf_counter()
                reduced = inter_fn(segs)
                if barrier:
                    jax.block_until_ready(reduced)
                t2 = time.perf_counter()
            tiers["intra"] += t1 - t0
            tiers["inter"] += t2 - t1
            return reduced

        def collect(entry, reduced):
            i, start, stop = entry
            if prefer_device:
                local = reduced.addressable_shards[0].data
                _screen_total(local, on_device=True)
                chunks_out[i].append(
                    local[: stop - start] if stop - start != local.shape[0]
                    else local)
            else:
                # fully replicated → np.asarray is legal and reuses the
                # copy_to_host_async started right after dispatch
                host = np.asarray(reduced)
                _screen_total(host, on_device=False)
                chunks_out[i].append(host[: stop - start])

        # chunk 0 runs serially with explicit barriers: the block after
        # ship keeps transfer cost out of reduce_ms (the old path's
        # async device_put leaked it there), and its psum doubles as the
        # round's entry barrier — it completes only once EVERY process
        # has entered, so cross-process entry skew lands here, visibly,
        # instead of smearing over the stream
        tp0 = time.perf_counter()
        stacked, celems = ship(stream[0])
        jax.block_until_ready(stacked)
        tp1 = time.perf_counter()
        reduced = reduce_chunk(stream[0], stacked, celems, barrier=True)
        reduced = jax.block_until_ready(reduced)
        tp2 = time.perf_counter()
        collect(stream[0], reduced)
        tp3 = time.perf_counter()
        t_ship += tp1 - tp0
        t_reduce += tp2 - tp1
        t_readback += tp3 - tp2
        pipelined = stream[1:]

        # pipelined remainder. The main thread only DISPATCHES ship +
        # psum; a dedicated reader thread blocks on each chunk's arrival
        # and collects it, so D2H(k−1) genuinely overlaps H2D(k+1) and
        # psum(k) — both sides spend their time in GIL-releasing runtime
        # calls. A semaphore bounds chunks in flight to the double
        # buffer; the reader's blocked time that elapsed WHILE the main
        # thread was still streaming is readback latency the serial path
        # would have eaten inline — that measured quantity (minus the
        # tail the main thread did wait for at join) is overlap_ms_saved.
        import threading

        slots = threading.Semaphore(_PIPELINE_DEPTH + 1)
        handoff: deque = deque()
        ready = threading.Semaphore(0)
        state = {"blocked": 0.0, "error": None}

        def _reader():
            while True:
                ready.acquire()
                item = handoff.popleft()
                if item is None:
                    return
                tb = time.perf_counter()
                try:
                    collect(*item)
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    state["error"] = e
                state["blocked"] += time.perf_counter() - tb
                slots.release()

        tpipe0 = time.perf_counter()
        reader = threading.Thread(target=_reader, name="mix-readback",
                                  daemon=True)
        reader.start()
        try:
            for entry in pipelined:
                slots.acquire()
                if state["error"] is not None:
                    break
                t0 = time.perf_counter()
                stacked, celems = ship(entry)
                t1 = time.perf_counter()
                reduced = reduce_chunk(entry, stacked, celems)
                if not prefer_device:
                    try:
                        reduced.copy_to_host_async()
                    except Exception:  # noqa: BLE001 — no async D2H here
                        pass
                t2 = time.perf_counter()
                t_ship += t1 - t0
                t_reduce += t2 - t1
                handoff.append((entry, reduced))
                ready.release()
        finally:
            dispatch_done = time.perf_counter()
            handoff.append(None)
            ready.release()
            # every collective of this round is dispatched (or the
            # round is dead): open the gate BEFORE draining readback so
            # the next round's ship/reduce overlaps it
            gate.release()
            reader.join()
        if state["error"] is not None:
            raise state["error"]
        t_join = time.perf_counter() - dispatch_done
        t_readback += t_join
        pipe_wall = time.perf_counter() - tpipe0
        # measured, not modeled: readback blocking that ran concurrently
        # with the main thread's ship/reduce stream (clamped at 0 for
        # the degenerate no-pipelined-chunks case)
        overlap_saved = max(0.0, state["blocked"] - t_join)

        # integrity verdict BEFORE the residual commit: a poisoned
        # round must leave the EF state of the last good round intact
        # (quarantine raises here; warn stamps and proceeds)
        _finite_verdict()

        # the whole stream completed: NOW the carried residuals advance
        # (an exception above leaves the last successful round's state)
        if feedback is not None and (pending_c or pending_t):
            feedback.contrib.update(pending_c)
            feedback.total.update(pending_t)
            feedback.key = plan_key
            feedback.rounds += 1
            quant_rounds = 1

        for i in big_idx:
            _, dtype, shape, size = metas[i]
            t3 = time.perf_counter()
            parts = chunks_out[i]
            if prefer_device:
                total = parts[0] if len(parts) == 1 else \
                    jnp.concatenate(parts)
                out[i] = total.reshape(shape)
            else:
                total = parts[0] if len(parts) == 1 else \
                    np.concatenate(parts)
                out[i] = total.reshape(shape)
            t_readback += time.perf_counter() - t3

    # ring-model wire accounting. Flat: every process ships the full
    # post-compress payload around the world ring — bytes per host grow
    # with the device count. Hierarchical: the chunked stream crosses
    # the inter-host wire ONCE per host (2(H-1)/H of the chunked
    # payload, spread over the M lanes), small leaves stay on the world
    # ring — bytes per host stay proportional to hosts.
    if hier:
        h_ring = 2 * (topo.hosts - 1) / topo.hosts
        w_ring = 2 * (n - 1) / n
        wire_per_host = big_bytes * h_ring + \
            topo.locals * small_bytes * w_ring
        wire_mb = (big_bytes * h_ring / topo.locals +
                   small_bytes * w_ring) / 2**20
    else:
        wire_mb = nbytes * 2 * (n - 1) / n / 2**20
        wire_per_host = nbytes * 2 * (n - 1) / n
    if phases is not None:
        # per-tier split: in flat mode EVERY reduced byte crosses the
        # process boundary, so the whole reduce is the inter tier
        intra_s = tiers["intra"] if big_idx and hier else 0.0
        inter_s = tiers["inter"] if big_idx and hier else t_reduce
        phases.update(
            cast_ms=round(t_cast * 1e3, 2),
            ship_ms=round(t_ship * 1e3, 2),
            reduce_ms=round(t_reduce * 1e3, 2),
            readback_ms=round(t_readback * 1e3, 2),
            intra_ms=round(intra_s * 1e3, 2),
            inter_ms=round(inter_s * 1e3, 2),
            payload_mb=round(nbytes / 2**20, 2),
            wire_mb=round(wire_mb, 2),
            wire_mb_ring_model=round(wire_mb, 2),
            wire_bytes_per_host=int(wire_per_host),
            chunks=n_chunks,
            chunk_mb=round(chunk_bytes / 2**20, 2),
            overlap_ms_saved=round(overlap_saved * 1e3, 2),
            dispatch_gate_ms=round(gate_wait * 1e3, 2),
            quant=mode,
            topo=topo.signature if hier else "flat",
        )
        if quant_rounds:
            phases["ef_rounds"] = feedback.rounds
    return jax.tree_util.tree_unflatten(treedef, out)


def world_size() -> int:
    return jax.process_count()
