"""Device mesh construction helpers.

A jubatus_tpu cluster is a static `jax.sharding.Mesh`. The axes in use:

- ``replica``: data-parallel model replicas (the reference's N server
  processes joined in one cluster name). The mix collective psums over it.
- ``shard``: row/feature sharding for instance-based engines (the reference's
  consistent-hash-table row placement, cht.cpp:107-143 — replaced by static
  mesh placement, SURVEY.md §5 "long-context").
- ``host`` / ``local``: the two-tier topology of the hierarchical mix
  (``host_topology()`` / ``host_mesh()``): N hosts × M local devices,
  host-major. Intra-host collectives ride ``local`` (ICI/loopback),
  inter-host ones ``host`` (DCN — the wire whose bytes the hierarchical
  reduce in parallel/collective.py keeps proportional to hosts, not
  total devices).

Multi-host: call jax.distributed.initialize() before building the mesh; the
same code then spans hosts with collectives riding ICI (and DCN across
slices). Single chip degenerates to a 1-device mesh.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def host_major(devices=None) -> list:
    """Devices ordered host-major: grouped by ``process_index``, by id
    within a process. ``jax.devices()`` order is backend-defined and can
    interleave hosts — a mesh axis built over the flat order would then
    span the network where the code expects locality (a "local" slice of
    consecutive devices must be consecutive *on one host*)."""
    devices = list(devices if devices is not None else jax.devices())
    return sorted(devices, key=lambda d: (d.process_index, d.id))


@dataclasses.dataclass(frozen=True)
class HostTopology:
    """Two-tier device topology for the hierarchical mix: ``hosts``
    groups of ``locals`` devices each. ``grid`` is the host-major
    (hosts, locals) device grid as nested tuples (hashable — jitted
    collective programs cache on it via the Mesh they build). ``source``
    records how it was derived (``derived`` from the runtime,
    ``override`` from an explicit ``HxM`` spec)."""

    hosts: int
    locals: int
    grid: Tuple[tuple, ...]
    source: str = "derived"

    @property
    def signature(self) -> str:
        """The ``NxM`` string the collective mixer folds into its
        prepare signature — heterogeneous fleets mismatch here and fall
        back to the RPC mix instead of wedging a skewed collective."""
        return f"{self.hosts}x{self.locals}"

    @property
    def trivial(self) -> bool:
        return self.hosts * self.locals <= 1


def _parse_topology(spec) -> Tuple[int, int]:
    if isinstance(spec, (tuple, list)) and len(spec) == 2:
        h, m = int(spec[0]), int(spec[1])
    else:
        try:
            h_s, _, m_s = str(spec).lower().partition("x")
            h, m = int(h_s), int(m_s)
        except ValueError:
            raise ValueError(
                f"bad topology {spec!r}: expected 'HxM' (hosts x local "
                "devices), e.g. '4x2'") from None
    if h < 1 or m < 1:
        raise ValueError(f"bad topology {spec!r}: both tiers must be >= 1")
    return h, m


def host_topology(devices=None, override=None) -> HostTopology:
    """The runtime's two-tier (host, local) topology.

    Derived (no ``override``): one grid row per process, the process's
    local devices (host-major order) as its row — the pod shape, one
    jax process per host with M chips each. Processes with non-uniform
    device counts degrade to one device per process (``Nx1``), because a
    ragged grid cannot mesh.

    ``override`` (``"HxM"`` / ``(H, M)`` — the test/bench lever, and the
    knob for fleets that co-locate M single-device processes per host):
    regrids the participant list host-major. With multiple processes the
    participants are one device per process (first local each) and
    H*M must equal the process count; single-process worlds regrid the
    local devices themselves (H*M of them), which is how the virtual
    8-device CPU test world exercises real two-tier collectives without
    a cluster."""
    devices = host_major(devices)
    if not devices:
        raise ValueError("no devices")
    by_proc: dict = {}
    for d in devices:
        by_proc.setdefault(d.process_index, []).append(d)
    rows = [tuple(by_proc[p]) for p in sorted(by_proc)]
    if override is not None and override != "":
        h, m = _parse_topology(override)
        if len(rows) > 1:
            if h * m != len(rows):
                raise ValueError(
                    f"topology {h}x{m} needs {h * m} processes, "
                    f"world has {len(rows)}")
            flat = [row[0] for row in rows]
        else:
            if h * m > len(devices):
                raise ValueError(
                    f"topology {h}x{m} needs {h * m} devices, "
                    f"have {len(devices)}")
            flat = devices[: h * m]
        grid = tuple(tuple(flat[i * m:(i + 1) * m]) for i in range(h))
        return HostTopology(h, m, grid, source="override")
    counts = {len(r) for r in rows}
    if len(counts) != 1:
        return HostTopology(len(rows), 1,
                            tuple((row[0],) for row in rows),
                            source="nonuniform")
    return HostTopology(len(rows), counts.pop(), tuple(rows),
                        source="derived")


def host_mesh(topo: Optional[HostTopology] = None, devices=None,
              override=None) -> Mesh:
    """The 2-D ``(host, local)`` mesh of ``host_topology`` — intra-host
    collectives ride the ``local`` axis (ICI / loopback), inter-host
    ones the ``host`` axis (DCN / the real wire)."""
    if topo is None:
        topo = host_topology(devices, override)
    arr = np.empty((topo.hosts, topo.locals), dtype=object)
    for h, row in enumerate(topo.grid):
        for l, d in enumerate(row):
            arr[h, l] = d
    return Mesh(arr, axis_names=("host", "local"))


def replica_mesh(n_replicas: Optional[int] = None, devices=None) -> Mesh:
    """A 1-D mesh of model replicas over the first n devices
    (host-major, so "first n" is the first hosts' devices — never an
    interleaved sample that spans every host)."""
    devices = host_major(devices)
    if n_replicas is not None:
        if n_replicas > len(devices):
            raise ValueError(
                f"requested {n_replicas} replicas but only {len(devices)} devices"
            )
        devices = devices[:n_replicas]
    return Mesh(np.asarray(devices), axis_names=("replica",))


def make_feature_sharding(mesh: Mesh, mesh_axis: str, dim_bits: int,
                          err_cls=ValueError, rank: int = 2):
    """NamedSharding placing the trailing (feature) dim of rank-``rank``
    tables over ``mesh_axis`` — shared by the linear drivers'
    ``--shard-devices`` mode; validates divisibility."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[mesh_axis]
    if (1 << dim_bits) % n:
        raise err_cls(
            f"feature dim 2^{dim_bits} not divisible by {n} shard devices")
    spec = P(*([None] * (rank - 1)), mesh_axis)
    return NamedSharding(mesh, spec)


def grid_mesh(replica: int, shard: int, devices=None) -> Mesh:
    """A 2-D (replica, shard) mesh: data-parallel groups of row-sharded
    stores — the TPU equivalent of N CHT-sharded servers with
    replication. Devices are taken host-major (grouped by process) so
    the trailing ``shard`` axis — the one the row stores all-gather
    over — stays within a host wherever the shape allows, instead of
    striding the network because ``jax.devices()`` interleaved hosts."""
    devices = host_major(devices)
    need = replica * shard
    if need > len(devices):
        raise ValueError(f"mesh {replica}x{shard} needs {need} devices, have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(replica, shard)
    return Mesh(arr, axis_names=("replica", "shard"))
