"""Device mesh construction helpers.

A jubatus_tpu cluster is a static `jax.sharding.Mesh`. The axes in use:

- ``replica``: data-parallel model replicas (the reference's N server
  processes joined in one cluster name). The mix collective psums over it.
- ``shard``: row/feature sharding for instance-based engines (the reference's
  consistent-hash-table row placement, cht.cpp:107-143 — replaced by static
  mesh placement, SURVEY.md §5 "long-context").

Multi-host: call jax.distributed.initialize() before building the mesh; the
same code then spans hosts with collectives riding ICI (and DCN across
slices). Single chip degenerates to a 1-device mesh.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


def replica_mesh(n_replicas: Optional[int] = None, devices=None) -> Mesh:
    """A 1-D mesh of model replicas over the first n devices."""
    devices = list(devices if devices is not None else jax.devices())
    if n_replicas is not None:
        if n_replicas > len(devices):
            raise ValueError(
                f"requested {n_replicas} replicas but only {len(devices)} devices"
            )
        devices = devices[:n_replicas]
    return Mesh(np.asarray(devices), axis_names=("replica",))


def make_feature_sharding(mesh: Mesh, mesh_axis: str, dim_bits: int,
                          err_cls=ValueError, rank: int = 2):
    """NamedSharding placing the trailing (feature) dim of rank-``rank``
    tables over ``mesh_axis`` — shared by the linear drivers'
    ``--shard-devices`` mode; validates divisibility."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = mesh.shape[mesh_axis]
    if (1 << dim_bits) % n:
        raise err_cls(
            f"feature dim 2^{dim_bits} not divisible by {n} shard devices")
    spec = P(*([None] * (rank - 1)), mesh_axis)
    return NamedSharding(mesh, spec)


def grid_mesh(replica: int, shard: int, devices=None) -> Mesh:
    """A 2-D (replica, shard) mesh: data-parallel groups of row-sharded
    stores — the TPU equivalent of N CHT-sharded servers with replication."""
    devices = list(devices if devices is not None else jax.devices())
    need = replica * shard
    if need > len(devices):
        raise ValueError(f"mesh {replica}x{shard} needs {need} devices, have {len(devices)}")
    arr = np.asarray(devices[:need]).reshape(replica, shard)
    return Mesh(arr, axis_names=("replica", "shard"))
