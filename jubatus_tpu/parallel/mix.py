"""The mix engine: model averaging as an XLA collective.

Reference semantics (linear_mixer.cpp:437-559, SURVEY.md §3.3): master pulls
diffs from all replicas, folds them pairwise with mixable->mix, broadcasts the
folded diff, every replica applies it via put_diff and clears its local diff.
The fold is the AllReduce combiner; because every jubatus_tpu diff is a pytree
whose mix is elementwise addition (ops/* keep updates additive by design),
the whole round is exactly `psum(diff)` + local put_diff — symmetric across
replicas, no master election, order-independent.

Two execution paths share the same Mixable protocol:

- ``allreduce_diffs``: the TPU path. Stacked per-replica diffs live sharded
  over the mesh's ``replica`` axis; a shard_map'd psum reduces them over ICI.
- ``LocalMixGroup``: the in-process path used by tests and by multi-engine
  simulation (the reference's linear_communication_stub seam,
  linear_mixer_test.cpp:65-112): N driver instances mix through host memory.

Schema sync: engines whose array rows are keyed by a dynamic vocabulary
(classifier labels) must align rows before arrays can be summed. Mixables may
implement ``sync_schema(union_of_schemas)``; the group/cluster computes the
sorted union of all replicas' schemas first (on TPU pods: a tiny host-side
allgather over DCN, out of the hot path), each replica permutes/grows its
arrays to the canonical schema, then the array psum runs.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Protocol, Sequence, runtime_checkable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jubatus_tpu.parallel._compat import shard_map


@runtime_checkable
class Mixable(Protocol):
    """The linear-mixable protocol (reference core mixable, SURVEY.md §2.9).

    get_diff returns a pytree of arrays/scalars; mix is elementwise addition
    (performed by the engine, not the mixable); put_diff absorbs the reduced
    diff and resets local accumulation, returning False if the local model is
    obsolete (triggers full-model recovery, linear_mixer.cpp:598-632).
    """

    def get_diff(self) -> Any: ...

    def put_diff(self, diff: Any) -> bool: ...

    # Optional: a custom associative combiner ``mix(acc, diff) -> acc``
    # (the reference's mixable->mix, linear_mixer.cpp:481-499). When present
    # the group folds with it instead of elementwise pytree addition —
    # engines with sparse/dict-shaped diffs (bandit) use this to avoid
    # shipping dense zero matrices.


def tree_sum(diffs: Sequence[Any]) -> Any:
    """Host-side fold of diff pytrees (the reference's pairwise fold —
    associative here, so order is irrelevant).

    Leaves whose LEADING dimension disagrees are zero-padded to the
    larger row count before adding: row-trimmed label diffs
    (models/classifier.py _ClassifierMixable) can legitimately differ by
    a row when a replica trained a novel label between the round's
    schema sync and its get_diff — the pad reproduces the old
    full-capacity semantics (absent rows contribute zeros) instead of
    aborting the round on a shape error."""

    def add(a, b):
        an = getattr(a, "shape", None)
        bn = getattr(b, "shape", None)
        if an and bn and len(an) == len(bn) and an != bn and \
                an[1:] == bn[1:]:
            import numpy as _np

            rows = max(an[0], bn[0])
            if an[0] < rows:
                a = _np.concatenate(
                    [_np.asarray(a),
                     _np.zeros((rows - an[0],) + tuple(an[1:]),
                               _np.asarray(a).dtype)])
            if bn[0] < rows:
                b = _np.concatenate(
                    [_np.asarray(b),
                     _np.zeros((rows - bn[0],) + tuple(bn[1:]),
                               _np.asarray(b).dtype)])
        return a + b

    acc = diffs[0]
    for d in diffs[1:]:
        acc = jax.tree_util.tree_map(add, acc, d)
    return acc


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "compress"))
def _psum_stacked(stacked, *, mesh: Mesh, axis: str, compress: bool):
    """psum a pytree whose leaves are stacked [n_replicas, ...] and sharded
    over `axis`; result is replicated (every replica holds the total).

    compress=True moves f32 leaves over the interconnect as bfloat16 —
    half the ICI/DCN bytes per mix round at ~3 decimal digits of diff
    precision (the EQuARX-style quantized-allreduce tradeoff; additive
    diffs tolerate it because put_diff folds into an f32 master)."""

    def body(local):
        def one(x):
            if compress and x.dtype == jnp.float32:
                y = jnp.sum(x, axis=0).astype(jnp.bfloat16)
                return jax.lax.psum(y, axis).astype(jnp.float32)
            return jax.lax.psum(jnp.sum(x, axis=0), axis)

        return jax.tree_util.tree_map(one, local)

    return shard_map(body, mesh=mesh, in_specs=P(axis), out_specs=P())(stacked)


def allreduce_diffs(per_replica_diffs: Sequence[Any], mesh: Mesh,
                    axis: str = "replica", compress: bool = False,
                    phases: Optional[dict] = None):
    """Reduce per-replica diff pytrees to one total via an XLA collective.

    In production each replica contributes its local shard of the stacked
    array; in tests the stack is built host-side and sharded onto the mesh.
    Returns the total diff (as held by replica 0). ``compress=True``
    quantizes f32 leaves to bf16 for the wire (see _psum_stacked; the
    cast happens on-device inside the collective body, same contract as
    the cross-process engine in parallel/collective.py).

    ``phases`` (optional dict) records the same per-phase wall times the
    cross-process plane logs (ship/reduce/readback + payload MB), so the
    in-process and jax.distributed mix paths are accounted identically.
    """
    import time

    n = mesh.shape[axis]
    if len(per_replica_diffs) != n:
        raise ValueError(f"got {len(per_replica_diffs)} diffs for a {n}-replica mesh")
    t0 = time.perf_counter()
    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *per_replica_diffs
    )
    sharding = NamedSharding(mesh, P(axis))
    stacked = jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sharding), stacked
    )
    # device_put is async: block before timestamping so transfer cost
    # does not leak into the reduce phase
    stacked = jax.block_until_ready(stacked)
    t1 = time.perf_counter()
    total = _psum_stacked(stacked, mesh=mesh, axis=axis, compress=compress)
    total = jax.block_until_ready(total)
    t2 = time.perf_counter()
    out = jax.tree_util.tree_map(
        # replicated mix total, not a sharded leaf
        lambda x: jax.device_get(x), total)  # full-gather-ok — readback
    if phases is not None:
        nbytes = sum(
            x.nbytes // (2 if compress and x.dtype == jnp.float32 else 1)
            for x in jax.tree_util.tree_leaves(total))
        phases.update(
            ship_ms=round((t1 - t0) * 1e3, 2),
            reduce_ms=round((t2 - t1) * 1e3, 2),
            readback_ms=round((time.perf_counter() - t2) * 1e3, 2),
            payload_mb=round(nbytes / 2**20, 2),
        )
    return out


class LocalMixGroup:
    """In-process mix over N mixable-bearing drivers (the stub seam).

    Drivers expose ``get_mixables() -> dict[name, Mixable]`` and optionally
    ``get_schema() / sync_schema(union)`` for row-alignment (classifier
    labels). mix() runs schema sync, then per-mixable diff reduction
    (optionally through a real device mesh), then put_diff everywhere.
    """

    def __init__(self, drivers: Sequence[Any], mesh: Optional[Mesh] = None,
                 compress: bool = False):
        if not drivers:
            raise ValueError("LocalMixGroup needs at least one driver")
        self.drivers = list(drivers)
        self.mesh = mesh
        #: ship f32 diffs over the mesh as bf16 (the --mix-bf16 tradeoff
        #: on the in-process path; cast-on-device, f32 handed back)
        self.compress = compress
        #: per-phase wall times of the last mesh-collective reduce this
        #: group ran (same keys as the cross-process engine)
        self.last_phases: Dict[str, Any] = {}

    def mix(self) -> Dict[str, Any]:
        # hold every participant's model lock for the round (deadlock-free:
        # consistent acquisition order; drivers only ever take their own)
        locks = sorted(
            (d.lock for d in self.drivers if hasattr(d, "lock")), key=id
        )
        try:
            for lk in locks:
                lk.acquire()
            return self._mix_locked()
        finally:
            for lk in reversed(locks):
                lk.release()

    def _mix_locked(self) -> Dict[str, Any]:
        # 1. schema sync (label vocab union etc.)
        schemas = [d.get_schema() for d in self.drivers if hasattr(d, "get_schema")]
        if schemas:
            union: List[str] = sorted(set().union(*map(set, schemas)))
            for d in self.drivers:
                d.sync_schema(union)
        # 2. per-mixable reduce + put
        stats: Dict[str, Any] = {}
        names = list(self.drivers[0].get_mixables().keys())
        for name in names:
            mixables = [d.get_mixables()[name] for d in self.drivers]
            diffs = [m.get_diff() for m in mixables]
            custom_mix = getattr(mixables[0], "mix", None)
            # Routing: the mesh collective handles any diff whose combine is
            # elementwise addition over a fixed-shape array pytree — i.e. no
            # custom mix, or one explicitly marked MIX_IS_SUM (WeightManager).
            # Dict-shaped sparse diffs (bandit, row stores) must fold host-side.
            summable = custom_mix is None or getattr(mixables[0], "MIX_IS_SUM", False)
            if (summable and self.mesh is not None
                    and self.mesh.shape.get("replica") == len(diffs)):
                self.last_phases = {}
                total = allreduce_diffs(diffs, self.mesh,
                                        compress=self.compress,
                                        phases=self.last_phases)
            elif custom_mix is not None:
                total = functools.reduce(custom_mix, diffs)
            else:
                total = tree_sum(diffs)
            for m in mixables:
                m.put_diff(total)
            stats[name] = jax.tree_util.tree_map(
                lambda x: getattr(x, "shape", None), total
            )
        return stats
