"""Multi-host runtime init — the DCN half of the communication backend
(SURVEY.md §5 "distributed communication backend": control/ingest stays
RPC; the mix plane is XLA collectives over ICI within a slice and DCN
across slices/hosts).

``initialize()`` wraps ``jax.distributed.initialize`` with the
framework's conventions: the coordinator address can come from the same
``-z`` locator servers already carry (the coordination service stores
the JAX coordinator endpoint under /jubatus/jax_coordinator, so only
process 0 needs static config). After init, ``jax.devices()`` spans all
hosts and the existing mesh builders (parallel/mesh.py) and SPMD steps
(parallel/spmd.py) work unchanged — collectives ride ICI within a slice
and DCN across.

Single-host (or already-initialized) calls are no-ops, so servers can
call this unconditionally at boot.
"""

from __future__ import annotations

import logging
from typing import Optional

import jax

from jubatus_tpu.coord.base import Coordinator
from jubatus_tpu.parallel._compat import distributed_is_initialized

log = logging.getLogger(__name__)

JAX_COORD_PATH = "/jubatus/jax_coordinator"


def enable_cpu_collectives() -> bool:
    """Select the gloo cross-process collectives backend for CPU worlds.

    On jax builds of this era the CPU backend refuses multiprocess
    computations outright ("Multiprocess computations aren't implemented
    on the CPU backend") unless ``jax_cpu_collectives_implementation``
    is switched to gloo BEFORE the backend initializes — without it,
    every CPU-world psum raises, members ack failure, and the collective
    mix silently degrades to broken rounds. gloo also carries the
    collective_permute the int8 quantized transport's scatter/gather
    ring rides (parallel/collective._quant_chunk_fn), so one switch
    covers every wire mode. Must be called before anything touches the
    XLA backend; returns True if the option was set. No-op (False) on
    jax versions without the option (their CPU collectives work out of
    the box)."""
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        return True
    except Exception:  # noqa: BLE001 — option renamed/removed upstream
        return False


def collective_capabilities() -> dict:
    """What the initialized runtime can carry for the mix plane — the
    ops-facing answer to "can this member ride --mix-compress int8?".
    Keys: ``backend`` (cpu/tpu/...), ``distributed`` (one jax world
    spans the fleet), ``world`` (process count), ``local_devices``
    (devices THIS process contributes — the intra-host tier the
    hierarchical mix folds before the wire), ``topology`` (the derived
    ``NxM`` two-tier shape, processes x local devices — `jubactl -c
    status`/`watch` show it per member, so a fleet whose tier shapes
    disagree is diagnosable BEFORE its rounds mismatch into the RPC
    fallback), ``quantized_transport`` (the int8 ring's requirements
    are met: every backend this repo targets carries psum +
    collective_permute once the world is up — CPU via gloo, TPU
    natively — so this tracks ``distributed`` or a world of one).
    Surfaced in the collective mixer's get_status."""
    init = distributed_is_initialized()
    world = jax.process_count() if init else 1
    local = len(jax.local_devices())
    backend = jax.default_backend()
    quantized = True
    if backend == "cpu" and world > 1:
        # a CPU world that skipped enable_cpu_collectives() has no
        # cross-process collectives AT ALL — psum and the int8 ring's
        # collective_permute both raise at dispatch. config.read is the
        # only access path this option supports on this jax (attribute
        # access returns nothing for it).
        try:
            impl = jax.config.read("jax_cpu_collectives_implementation")
        except Exception:  # noqa: BLE001 — option renamed/removed upstream
            impl = None
        quantized = impl == "gloo"
    return {
        "backend": backend,
        "distributed": init,
        "world": world,
        "local_devices": local,
        "topology": f"{world}x{local}",
        "quantized_transport": quantized,
    }


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    coord: Optional[Coordinator] = None,
    resolve_timeout: float = 60.0,
) -> bool:
    """Join the multi-host JAX runtime. Returns True if distributed init
    ran, False when single-host / already initialized.

    Endpoint resolution order: explicit ``coordinator_address``, then the
    coordination store (process 0 publishes, others poll until
    ``resolve_timeout``), then give up (single-host).

    NOTE: must run before anything initializes the XLA backend — even
    ``jax.process_count()``/``jax.devices()`` would do that, which is why
    the already-initialized check uses ``jax.distributed.is_initialized``.
    """
    if distributed_is_initialized():
        return False
    if not num_processes or num_processes <= 1:
        return False  # single-host: never poll or raise
    if coord is not None:
        if process_id == 0:
            if not coordinator_address:
                raise ValueError("process 0 must pass coordinator_address "
                                 "(its own reachable host:port) to publish")
            publish_endpoint(coord, coordinator_address)  # BEFORE peers join
        elif coordinator_address is None:
            # fleets boot unordered: poll until process 0 publishes.
            # Timing out RAISES — a silent single-host fallback would
            # leave the rest of the fleet hanging in the init barrier.
            import time

            deadline = time.monotonic() + resolve_timeout
            while True:
                raw = coord.read(JAX_COORD_PATH)
                if raw:
                    coordinator_address = raw.decode()
                    break
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"no JAX coordinator endpoint published within "
                        f"{resolve_timeout:.0f}s (is process 0 up?)")
                time.sleep(0.5)
    if not coordinator_address:
        return False
    enable_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    log.info("joined multi-host runtime: process %d/%d via %s",
             jax.process_index(), jax.process_count(), coordinator_address)
    return True


def publish_endpoint(coord: Coordinator, address: str) -> None:
    """Process 0 publishes the JAX coordinator endpoint for the fleet.
    The node is EPHEMERAL (owned by process 0's coordinator session): a
    crashed fleet's endpoint disappears instead of pointing late-booting
    workers at a dead coordinator from the previous incarnation."""
    coord.remove(JAX_COORD_PATH)
    if not coord.create(JAX_COORD_PATH, address.encode(), ephemeral=True):
        # a silent publish failure would surface as timeouts on every
        # OTHER host — fail here, where the cause is
        raise RuntimeError(
            f"cannot publish JAX coordinator endpoint at {JAX_COORD_PATH} "
            "(stale node owned by another session, or session closed)")
