"""Ring all-to-all scans — context parallelism for sharded row tables.

``sharded_knn`` keeps queries replicated and merges tiny per-shard top-k
candidates with one all_gather; that is the right shape when the query
batch is small. When BOTH the query batch and the row table are too large
to replicate, this module provides the ring-attention-structured
alternative (the reference has no analog — its closest mechanism is CHT
row sharding + RPC fan-out, cht.cpp:107-143, SURVEY.md §5 "long-context"):

- queries stay put, sharded over the mesh axis (each device owns B/S);
- table blocks ROTATE around the ring with ``jax.lax.ppermute`` — S-1
  hops, each hop moving C/S rows to the neighbor over ICI while every
  device scans the block it currently holds;
- each device keeps a running top-k merge, so after S steps every query
  shard has seen the whole table without any device ever materializing
  it, and without any all_gather of candidates.

Per-device HBM footprint is O(B/S + 2·C/S) and the ICI traffic per hop is
exactly one block — the same overlap-compute-with-neighbor-transfer
pipeline ring attention uses for KV blocks.

``ring_scan`` is the generic building block (any per-block kernel +
associative carry merge); ``ring_hamming_topk`` / ``ring_euclid_topk``
instantiate it for the LSH/minhash and euclid_lsh engine backends.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jubatus_tpu.parallel._compat import axis_size, shard_map

from jubatus_tpu.parallel.sharded_knn import shard_table as shard_rows  # noqa: F401


def ring_scan(step_fn: Callable, carry, block, axis: str):
    """Rotate ``block`` once around the ring axis (must run inside
    shard_map). ``step_fn(carry, block, origin) -> carry`` is applied S
    times; ``origin`` is the shard index the block started on, so kernels
    can reconstruct global row ids. Returns the final carry.

    The ppermute send executes concurrently with the next step's compute
    (XLA schedules the collective-permute async on TPU), which is the
    whole point of the ring shape: the wire hides behind the scan.
    """
    s = axis_size(axis)
    me = jax.lax.axis_index(axis)
    perm = [(i, (i + 1) % s) for i in range(s)]

    def body(state, t):
        blk, c = state
        origin = (me - t) % s
        c = step_fn(c, blk, origin)
        # unconditional hop (the S-th rotation returns blocks home; a
        # lax.cond around a collective is not SPMD-safe)
        blk = jax.lax.ppermute(blk, axis, perm)
        return (blk, c), None

    (_, carry), _ = jax.lax.scan(body, (block, carry), jnp.arange(s))
    return carry


def _topk_merge(best_neg, best_idx, neg, idx, k: int):
    """Merge running [B, k] candidates with new [B, kk] ones."""
    negs = jnp.concatenate([best_neg, neg], axis=1)
    idxs = jnp.concatenate([best_idx, idx], axis=1)
    top, pos = jax.lax.top_k(negs, k)
    return top, jnp.take_along_axis(idxs, pos, axis=1)


def _ring_topk(mesh, queries, blocks, local_scores, k: int, axis: str):
    """Shared driver: ``local_scores(q_block, row_block) -> [b, c] scores``
    (HIGHER = better; negate distances before passing). ``blocks`` is any
    pytree of [C, ...] arrays row-sharded over ``axis`` (ppermute rotates
    pytrees whole). Returns (scores [B, k], global row ids [B, k]) with B
    sharded over ``axis``."""
    n_shards = mesh.shape[axis]
    c_total = jax.tree_util.tree_leaves(blocks)[0].shape[0]
    if c_total % n_shards:
        raise ValueError(
            f"row count {c_total} not divisible by {n_shards} ring shards "
            "(pad the table to a multiple and mask the padding rows)")
    c_local = c_total // n_shards
    # never return more candidates than the table holds — padding slots
    # would carry +inf distance but a fabricated row id 0
    # (sharded_knn.sharded_hamming_topk clamps the same way)
    k = min(k, c_local * n_shards)

    def shard_fn(q, blk):
        kk = min(k, c_local)
        init = (
            jnp.full((q.shape[0], k), -jnp.inf, jnp.float32),
            jnp.zeros((q.shape[0], k), jnp.int32),
        )

        def step(carry, block, origin):
            sc = local_scores(q, block).astype(jnp.float32)  # [b, c_local]
            neg, idx = jax.lax.top_k(sc, kk)
            gidx = idx + origin * c_local
            return _topk_merge(carry[0], carry[1], neg, gidx, k)

        best_neg, best_idx = ring_scan(step, init, blk, axis)
        return best_neg, best_idx

    q_spec = P(axis, *([None] * (queries.ndim - 1)))
    blk_specs = jax.tree_util.tree_map(
        lambda x: P(axis, *([None] * (x.ndim - 1))), blocks)
    fn = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(q_spec, blk_specs),
        out_specs=(P(axis, None), P(axis, None)),
        check_vma=False,
    )
    return fn(queries, blocks)


@functools.partial(jax.jit, static_argnames=("mesh", "hash_num", "k", "axis"))
def ring_hamming_topk(
    mesh: Mesh,
    q_sigs: jax.Array,    # [B, W] uint32, sharded over `axis`
    row_sigs: jax.Array,  # [C, W] uint32, sharded over `axis`
    *,
    hash_num: int,
    k: int,
    axis: str = "shard",
    valid: Optional[jax.Array] = None,  # [C] bool, sharded over `axis`
) -> Tuple[jax.Array, jax.Array]:
    """Global top-k smallest hamming distance, both operands sharded.
    Returns (distances [B, k], global row ids [B, k]), B-sharded. ``valid``
    masks dead/padding rows out (it rotates with the table blocks)."""
    from jubatus_tpu.ops import knn

    if valid is None:
        def scores(q, blk):
            return -knn._hamming_distances_batch_xla(
                q, blk, hash_num=hash_num)

        blocks = row_sigs
    else:
        def scores(q, blk):
            sigs, v = blk
            d = knn._hamming_distances_batch_xla(q, sigs, hash_num=hash_num)
            return jnp.where(v[None, :], -d, -jnp.inf)

        blocks = (row_sigs, valid)

    neg, gidx = _ring_topk(mesh, q_sigs, blocks, scores, k, axis)
    return -neg, gidx


@functools.partial(jax.jit, static_argnames=("mesh", "k", "axis"))
def ring_euclid_topk(
    mesh: Mesh,
    q_dense: jax.Array,   # [B, D] float32, sharded over `axis`
    row_idx: jax.Array,   # [C, nnz] int32, sharded over `axis`
    row_val: jax.Array,   # [C, nnz] float32, sharded over `axis`
    *,
    k: int,
    axis: str = "shard",
    valid: Optional[jax.Array] = None,  # [C] bool, sharded over `axis`
) -> Tuple[jax.Array, jax.Array]:
    """Global top-k smallest euclidean distance over a sparse row table,
    both operands sharded. Returns (distances [B, k], ids [B, k]).
    ``valid`` masks dead/padding rows out (it rotates with the blocks),
    mirroring ring_hamming_topk; masked-out slots surface as +inf."""
    from jubatus_tpu.ops import knn

    if valid is None:
        def scores(q, blk):
            idx, val = blk
            return -jax.vmap(lambda q1: knn.euclid_distances(idx, val, q1))(q)

        blocks = (row_idx, row_val)
    else:
        def scores(q, blk):
            idx, val, v = blk
            d = jax.vmap(lambda q1: knn.euclid_distances(idx, val, q1))(q)
            return jnp.where(v[None, :], -d, -jnp.inf)

        blocks = (row_idx, row_val, valid)

    neg, gidx = _ring_topk(mesh, q_dense, blocks, scores, k, axis)
    return -neg, gidx
