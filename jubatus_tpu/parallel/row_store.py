"""General sharded row store — fixed-capacity per-shard row arenas.

ISSUE 13 tentpole (2): `parallel/sharded_knn.py` grown into the row
store the instance engines need at 10⁸-row capacity. One store, five
consumers:

- **Placement** is CHT-compatible: ``coord.cht.shard_for(row_id, S)``
  picks the owning shard — the same stable hash the migration plane
  (PR 10, framework/migration.py) and the elastic ring use, so an
  ``NNRowMigration`` row pushed over the wire lands DIRECTLY in the
  owning shard's arena, and ``serve_range`` walks shard arenas without
  ever materializing the device table.
- **Layout**: global slot = ``shard * capacity_per_shard + local_slot``.
  The [S*C, K] host mirror is therefore shard-contiguous by
  construction: ``shard_table`` (parallel/sharded_knn.py) places rows
  ``[s*C, (s+1)*C)`` on device ``s`` with no permutation, and the
  signature tables the NN backend aligns to slots inherit the same
  placement for free.
- **Queries**: per-shard partial top-k on device, merged with the
  log-depth on-device reduction (sharded_knn.merge_topk) — O(S·k)
  candidates over the interconnect, never O(rows).
- **Mix**: ``updated_since_mix`` rides per shard
  (``pop_update_diff_sharded``) so each shard's diff enters the mix
  pipeline independently; rows applied from a mix/migration are
  excluded from the next diff exactly like the flat store.
- **Capacity**: per-shard arenas grow by doubling (bounded recompiles,
  like core/row_store.py); ``max_size`` keeps the reference's LRU
  unlearner semantics globally.

API-compatible with core/row_store.RowStore (same pack format — flat
and sharded checkpoints interchange; restore re-places by shard_for).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from jubatus_tpu.coord.cht import shard_for
from jubatus_tpu.core.sparse import SparseVector

_INITIAL_CAPACITY = 64   # per shard
_INITIAL_WIDTH = 8


def _pow2_at_least(n: int, minimum: int) -> int:
    p = minimum
    while p < n:
        p *= 2
    return p


class ShardedRowStore:
    """Drop-in RowStore with S fixed-capacity per-shard arenas."""

    def __init__(self, n_shards: int = 1, max_size: Optional[int] = None,
                 keep_datum: bool = False,
                 capacity_per_shard: int = _INITIAL_CAPACITY) -> None:
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self.n_shards = int(n_shards)
        self.max_size = max_size
        self.keep_datum = keep_datum
        self._initial_cap = int(capacity_per_shard)
        self._init()

    def _init(self) -> None:
        self.cap_per_shard = self._initial_cap
        self.width = _INITIAL_WIDTH
        s, c = self.n_shards, self.cap_per_shard
        self.idx = np.zeros((s * c, self.width), np.int32)
        self.val = np.zeros((s * c, self.width), np.float32)
        self.ids: List[str] = [""] * (s * c)   # slot -> id ("" = dead)
        self.slots: Dict[str, int] = {}        # id -> global slot
        self._free: List[List[int]] = [[] for _ in range(s)]
        self._fill: List[int] = [0] * s        # per-shard high-water mark
        self._clock = 0
        self._touch: Dict[str, int] = {}       # id -> last-touch tick (LRU)
        self.datums: Dict[str, Any] = {}
        self.updated_since_mix: Dict[str, None] = {}
        self.version = 0
        self._dev_cache: Optional[Tuple[int, Any, Any, Any]] = None

    # -- sizing ---------------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.n_shards * self.cap_per_shard

    def __len__(self) -> int:
        return len(self.slots)

    def __contains__(self, row_id: str) -> bool:
        return row_id in self.slots

    def shard_of(self, row_id: str) -> int:
        """The owning shard — CHT-stable, shared with the migration
        plane's ring math."""
        return shard_for(row_id, self.n_shards)

    def shard_slot(self, row_id: str) -> Optional[Tuple[int, int]]:
        """(shard, local slot) of a live row; None when absent."""
        g = self.slots.get(row_id)
        if g is None:
            return None
        return divmod(g, self.cap_per_shard)

    def _grow_capacity(self) -> None:
        """Double every shard arena, remapping global slots (local slots
        are preserved, so per-shard contents never move between shards)."""
        old_c, s = self.cap_per_shard, self.n_shards
        new_c = old_c * 2
        idx = np.zeros((s * new_c, self.width), np.int32)
        val = np.zeros((s * new_c, self.width), np.float32)
        ids: List[str] = [""] * (s * new_c)
        for sh in range(s):
            idx[sh * new_c: sh * new_c + old_c] = \
                self.idx[sh * old_c: (sh + 1) * old_c]
            val[sh * new_c: sh * new_c + old_c] = \
                self.val[sh * old_c: (sh + 1) * old_c]
            ids[sh * new_c: sh * new_c + old_c] = \
                self.ids[sh * old_c: (sh + 1) * old_c]
        self.idx, self.val, self.ids = idx, val, ids
        self.cap_per_shard = new_c
        self.slots = {rid: (g // old_c) * new_c + (g % old_c)
                      for rid, g in self.slots.items()}
        self._free = [[(g // old_c) * new_c + (g % old_c) for g in fl]
                      for fl in self._free]

    def _grow_width(self, need: int) -> None:
        new_w = _pow2_at_least(need, self.width * 2)
        pad = new_w - self.width
        self.idx = np.pad(self.idx, ((0, 0), (0, pad)))
        self.val = np.pad(self.val, ((0, 0), (0, pad)))
        self.width = new_w

    def _free_slot(self, shard: int) -> int:
        if self._free[shard]:
            return self._free[shard].pop()
        if self._fill[shard] < self.cap_per_shard:
            slot = shard * self.cap_per_shard + self._fill[shard]
            self._fill[shard] += 1
            return slot
        self._grow_capacity()
        slot = shard * self.cap_per_shard + self._fill[shard]
        self._fill[shard] += 1
        return slot

    # -- writes ---------------------------------------------------------------
    def set_row(self, row_id: str, vec: SparseVector,
                datum: Any = None) -> int:
        """Insert or overwrite a row in its OWNING shard's arena;
        returns its global slot. Evicts the least recently touched row
        (globally) first when max_size is reached."""
        slot = self.slots.get(row_id)
        if slot is None:
            if self.max_size is not None and len(self.slots) >= self.max_size:
                self._evict_lru()
            slot = self._free_slot(self.shard_of(row_id))
            self.ids[slot] = row_id
            self.slots[row_id] = slot
        if len(vec) > self.width:
            self._grow_width(len(vec))
        self.idx[slot].fill(0)
        self.val[slot].fill(0.0)
        k = len(vec)
        if k:
            self.idx[slot, :k] = [i for i, _ in vec]
            self.val[slot, :k] = [w for _, w in vec]
        if self.keep_datum and datum is not None:
            self.datums[row_id] = datum
        self.touch(row_id)
        self.updated_since_mix[row_id] = None
        self.version += 1
        return slot

    def remove_row(self, row_id: str) -> bool:
        slot = self.slots.pop(row_id, None)
        if slot is None:
            return False
        self.ids[slot] = ""
        self.idx[slot].fill(0)
        self.val[slot].fill(0.0)
        self._free[slot // self.cap_per_shard].append(slot)
        self._touch.pop(row_id, None)
        self.datums.pop(row_id, None)
        self.updated_since_mix.pop(row_id, None)
        self.version += 1
        return True

    def clear(self) -> None:
        self._init()

    def touch(self, row_id: str) -> None:
        self._clock += 1
        self._touch[row_id] = self._clock

    def _evict_lru(self) -> None:
        victim = min(self._touch, key=self._touch.get)
        self.remove_row(victim)

    # -- reads ----------------------------------------------------------------
    def get_row(self, row_id: str) -> Optional[SparseVector]:
        slot = self.slots.get(row_id)
        if slot is None:
            return None
        order = np.nonzero(self.val[slot])[0]
        return [(int(self.idx[slot, j]), float(self.val[slot, j]))
                for j in order]

    def all_ids(self) -> List[str]:
        return list(self.slots.keys())

    def shard_ids(self, shard: int) -> List[str]:
        """Live row ids in one shard arena — the per-shard walk
        serve_range and the drain handoff ride (host metadata only; the
        device table is never touched)."""
        lo = shard * self.cap_per_shard
        hi = lo + self.cap_per_shard
        return [rid for rid in self.ids[lo:hi] if rid]

    def iter_rows(self) -> Iterator[Tuple[str, int]]:
        return iter(self.slots.items())

    def live_mask(self) -> np.ndarray:
        m = np.zeros(self.capacity, bool)
        if self.slots:
            m[np.fromiter(self.slots.values(), dtype=np.int64,
                          count=len(self.slots))] = True
        return m

    def rows_per_shard(self) -> List[int]:
        counts = [0] * self.n_shards
        for g in self.slots.values():
            counts[g // self.cap_per_shard] += 1
        return counts

    def bytes_in_use(self) -> int:
        """Host-mirror bytes of the padded arenas (idx int32 + val f32);
        the device table costs the same per dtype."""
        return int(self.idx.nbytes + self.val.nbytes)

    def shard_stats(self) -> Dict[str, Any]:
        per = self.rows_per_shard()
        return {"count": self.n_shards, "rows": len(self.slots),
                "rows_per_shard": per,
                "capacity_per_shard": self.cap_per_shard,
                "bytes_in_use": self.bytes_in_use()}

    def device_view(self):
        """(idx, val, live_mask) as device arrays, cached per version."""
        if self._dev_cache is None or self._dev_cache[0] != self.version:
            self._dev_cache = (
                self.version,
                jnp.asarray(self.idx),
                jnp.asarray(self.val),
                jnp.asarray(self.live_mask()),
            )
        return self._dev_cache[1], self._dev_cache[2], self._dev_cache[3]

    # -- mix / persistence ----------------------------------------------------
    def pop_update_diff(self) -> Dict[str, Tuple[list, list, Any]]:
        """Rows written since the last mix as {id: (idx_list, val_list,
        datum)}; clears the tracker. Wire-identical to the flat store."""
        out = {}
        for rid in self.updated_since_mix:
            slot = self.slots.get(rid)
            if slot is None:
                continue
            nz = np.nonzero(self.val[slot])[0]
            out[rid] = (
                self.idx[slot, nz].tolist(),
                self.val[slot, nz].tolist(),
                self.datums.get(rid),
            )
        self.updated_since_mix = {}
        return out

    def pop_update_diff_sharded(self) -> List[Dict[str, Tuple[list, list, Any]]]:
        """The same diff grouped by owning shard (one dict per shard) —
        each shard's chunk enters the mix pipeline independently."""
        out: List[Dict[str, Tuple[list, list, Any]]] = \
            [{} for _ in range(self.n_shards)]
        flat = self.pop_update_diff()
        for rid, row in flat.items():
            out[self.shard_of(rid)][rid] = row
        return out

    def apply_update_diff(self, diff: Dict[str, Tuple[list, list, Any]]) -> None:
        for rid, (ii, vv, datum) in diff.items():
            rid = rid.decode() if isinstance(rid, bytes) else rid
            vec = [(int(i), float(v)) for i, v in zip(ii, vv)]
            self.set_row(rid, vec, datum=datum)
        # rows arriving via mix are not "local updates" for the next round
        self.updated_since_mix = {}

    def pack(self) -> Any:
        return {
            "rows": {
                rid: (
                    self.idx[s][np.nonzero(self.val[s])].tolist(),
                    self.val[s][np.nonzero(self.val[s])].tolist(),
                )
                for rid, s in self.slots.items()
            },
            "datums": {rid: d.to_msgpack() if hasattr(d, "to_msgpack") else d
                       for rid, d in self.datums.items()}
            if self.keep_datum else {},
        }

    def unpack(self, obj: Any, datum_decoder=None) -> None:
        """Restore from the shared pack format. Reshard-on-restore falls
        out of placement being a pure function of the id: a checkpoint
        written at N shards (or by the flat store) re-places every row
        into the CURRENT n_shards' owning arenas."""
        self._init()
        for rid, (ii, vv) in obj["rows"].items():
            rid = rid.decode() if isinstance(rid, bytes) else rid
            self.set_row(rid, [(int(i), float(v)) for i, v in zip(ii, vv)])
        for rid, d in (obj.get("datums") or {}).items():
            rid = rid.decode() if isinstance(rid, bytes) else rid
            self.datums[rid] = datum_decoder(d) if datum_decoder else d
        self.updated_since_mix = {}


class CellArenas:
    """Per-shard IVF cell arenas layered over a row store (ISSUE 16).

    The physical arenas above stay the single source of truth — rows
    never move when their cell changes, checkpoints and migration are
    untouched. CellArenas is an INDEX over them: a host-side
    ``id → cell`` map plus per-cell insertion-ordered member sets,
    materialized on demand as the fixed-shape device table the IVF
    probe gathers from:

        tables[s * n_cells + c] = int32 LOCAL slots of shard s's live
                                  members of cell c, −1-padded to a
                                  pow2 ``cell_cap``

    Sharded P(axis) over the leading dim, each device sees exactly its
    own [n_cells, cap] block, and a gathered local slot indexes the
    shard's own arena block directly. Flat stores (no mesh) are the
    S = 1 special case with local slot == global slot.

    Liveness is LAZY: the store can evict/remove rows without telling
    us (LRU eviction fires inside ``set_row``); dead ids are pruned at
    the next table build, and the ``(store.version, version)`` cache
    key guarantees a build happens before any query sees the change.
    ``cell_cap`` is pow2-bucketed so online insertion only recompiles
    the query when a cell DOUBLES, not on every append.
    """

    _MIN_CAP = 8

    def __init__(self, store: Any, n_cells: int) -> None:
        if n_cells < 1:
            raise ValueError(f"n_cells must be >= 1, got {n_cells}")
        self.store = store
        self.n_shards = int(getattr(store, "n_shards", 1))
        self._members: List[Dict[str, None]] = [{} for _ in range(n_cells)]
        self._cell_of: Dict[str, int] = {}
        self.version = 0
        self._table_cache: Optional[Tuple[Tuple[int, int], Any, int]] = None

    @property
    def n_cells(self) -> int:
        return len(self._members)

    def __len__(self) -> int:
        return len(self._cell_of)

    def cell_of(self, row_id: str) -> Optional[int]:
        return self._cell_of.get(row_id)

    def assign(self, row_id: str, cell: int) -> None:
        """Bind a row to a cell (moving it if already bound elsewhere) —
        online insertion appends to the owning cell's member set."""
        old = self._cell_of.get(row_id)
        if old == cell:
            return
        if old is not None:
            self._members[old].pop(row_id, None)
        self._members[cell][row_id] = None
        self._cell_of[row_id] = cell
        self.version += 1

    def remove(self, row_id: str) -> bool:
        cell = self._cell_of.pop(row_id, None)
        if cell is None:
            return False
        self._members[cell].pop(row_id, None)
        self.version += 1
        return True

    def add_cell(self) -> int:
        """Append an empty cell (re-split target); returns its id."""
        self._members.append({})
        self.version += 1
        return len(self._members) - 1

    def members(self, cell: int) -> List[str]:
        return list(self._members[cell])

    def sizes(self) -> List[int]:
        """Member count per cell (may include not-yet-pruned dead ids;
        exact again after any table build)."""
        return [len(m) for m in self._members]

    def clear(self) -> None:
        self._members = [{} for _ in self._members]
        self._cell_of = {}
        self.version += 1
        self._table_cache = None

    def _shard_slot(self, row_id: str) -> Optional[Tuple[int, int]]:
        if hasattr(self.store, "shard_slot"):
            return self.store.shard_slot(row_id)
        g = self.store.slots.get(row_id)
        return None if g is None else (0, g)

    def device_tables(self) -> Tuple[Any, int]:
        """(tables [S*n_cells, cap] int32 device array, cap). Dead ids
        are pruned as a side effect; cached per (store, index) version."""
        key = (self.store.version, self.version)
        if self._table_cache is not None and self._table_cache[0] == key:
            return self._table_cache[1], self._table_cache[2]
        buckets: List[List[List[int]]] = \
            [[[] for _ in self._members] for _ in range(self.n_shards)]
        dead: List[Tuple[str, int]] = []
        for cell, mem in enumerate(self._members):
            for rid in mem:
                loc = self._shard_slot(rid)
                if loc is None:
                    dead.append((rid, cell))
                    continue
                buckets[loc[0]][cell].append(loc[1])
        for rid, cell in dead:
            self._members[cell].pop(rid, None)
            self._cell_of.pop(rid, None)
        widest = max((len(b) for per in buckets for b in per), default=0)
        cap = _pow2_at_least(max(widest, 1), self._MIN_CAP)
        tab = np.full((self.n_shards * len(self._members), cap), -1,
                      np.int32)
        for s, per in enumerate(buckets):
            for cell, slots in enumerate(per):
                if slots:
                    tab[s * len(self._members) + cell, :len(slots)] = slots
        dev = jnp.asarray(tab)
        self._table_cache = (key, dev, cap)
        return dev, cap
