"""General sharded row store — fixed-capacity per-shard row arenas.

ISSUE 13 tentpole (2): `parallel/sharded_knn.py` grown into the row
store the instance engines need at 10⁸-row capacity. One store, five
consumers:

- **Placement** is CHT-compatible: ``coord.cht.shard_for(row_id, S)``
  picks the owning shard — the same stable hash the migration plane
  (PR 10, framework/migration.py) and the elastic ring use, so an
  ``NNRowMigration`` row pushed over the wire lands DIRECTLY in the
  owning shard's arena, and ``serve_range`` walks shard arenas without
  ever materializing the device table.
- **Layout**: global slot = ``shard * capacity_per_shard + local_slot``.
  The [S*C, K] host mirror is therefore shard-contiguous by
  construction: ``shard_table`` (parallel/sharded_knn.py) places rows
  ``[s*C, (s+1)*C)`` on device ``s`` with no permutation, and the
  signature tables the NN backend aligns to slots inherit the same
  placement for free.
- **Queries**: per-shard partial top-k on device, merged with the
  log-depth on-device reduction (sharded_knn.merge_topk) — O(S·k)
  candidates over the interconnect, never O(rows).
- **Mix**: ``updated_since_mix`` rides per shard
  (``pop_update_diff_sharded``) so each shard's diff enters the mix
  pipeline independently; rows applied from a mix/migration are
  excluded from the next diff exactly like the flat store.
- **Capacity**: per-shard arenas grow by doubling (bounded recompiles,
  like core/row_store.py); ``max_size`` keeps the reference's LRU
  unlearner semantics globally.

API-compatible with core/row_store.RowStore (same pack format — flat
and sharded checkpoints interchange; restore re-places by shard_for).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from jubatus_tpu.coord.cht import shard_for
from jubatus_tpu.core.sparse import SparseVector

_INITIAL_CAPACITY = 64   # per shard
_INITIAL_WIDTH = 8


def _pow2_at_least(n: int, minimum: int) -> int:
    p = minimum
    while p < n:
        p *= 2
    return p


class ShardedRowStore:
    """Drop-in RowStore with S fixed-capacity per-shard arenas."""

    def __init__(self, n_shards: int = 1, max_size: Optional[int] = None,
                 keep_datum: bool = False,
                 capacity_per_shard: int = _INITIAL_CAPACITY) -> None:
        if n_shards < 1:
            raise ValueError(f"n_shards must be >= 1, got {n_shards}")
        self.n_shards = int(n_shards)
        self.max_size = max_size
        self.keep_datum = keep_datum
        self._initial_cap = int(capacity_per_shard)
        self._init()

    def _init(self) -> None:
        self.cap_per_shard = self._initial_cap
        self.width = _INITIAL_WIDTH
        s, c = self.n_shards, self.cap_per_shard
        self.idx = np.zeros((s * c, self.width), np.int32)
        self.val = np.zeros((s * c, self.width), np.float32)
        self.ids: List[str] = [""] * (s * c)   # slot -> id ("" = dead)
        self.slots: Dict[str, int] = {}        # id -> global slot
        self._free: List[List[int]] = [[] for _ in range(s)]
        self._fill: List[int] = [0] * s        # per-shard high-water mark
        self._clock = 0
        self._touch: Dict[str, int] = {}       # id -> last-touch tick (LRU)
        self.datums: Dict[str, Any] = {}
        self.updated_since_mix: Dict[str, None] = {}
        self.version = 0
        self._dev_cache: Optional[Tuple[int, Any, Any, Any]] = None

    # -- sizing ---------------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.n_shards * self.cap_per_shard

    def __len__(self) -> int:
        return len(self.slots)

    def __contains__(self, row_id: str) -> bool:
        return row_id in self.slots

    def shard_of(self, row_id: str) -> int:
        """The owning shard — CHT-stable, shared with the migration
        plane's ring math."""
        return shard_for(row_id, self.n_shards)

    def shard_slot(self, row_id: str) -> Optional[Tuple[int, int]]:
        """(shard, local slot) of a live row; None when absent."""
        g = self.slots.get(row_id)
        if g is None:
            return None
        return divmod(g, self.cap_per_shard)

    def _grow_capacity(self) -> None:
        """Double every shard arena, remapping global slots (local slots
        are preserved, so per-shard contents never move between shards)."""
        old_c, s = self.cap_per_shard, self.n_shards
        new_c = old_c * 2
        idx = np.zeros((s * new_c, self.width), np.int32)
        val = np.zeros((s * new_c, self.width), np.float32)
        ids: List[str] = [""] * (s * new_c)
        for sh in range(s):
            idx[sh * new_c: sh * new_c + old_c] = \
                self.idx[sh * old_c: (sh + 1) * old_c]
            val[sh * new_c: sh * new_c + old_c] = \
                self.val[sh * old_c: (sh + 1) * old_c]
            ids[sh * new_c: sh * new_c + old_c] = \
                self.ids[sh * old_c: (sh + 1) * old_c]
        self.idx, self.val, self.ids = idx, val, ids
        self.cap_per_shard = new_c
        self.slots = {rid: (g // old_c) * new_c + (g % old_c)
                      for rid, g in self.slots.items()}
        self._free = [[(g // old_c) * new_c + (g % old_c) for g in fl]
                      for fl in self._free]

    def _grow_width(self, need: int) -> None:
        new_w = _pow2_at_least(need, self.width * 2)
        pad = new_w - self.width
        self.idx = np.pad(self.idx, ((0, 0), (0, pad)))
        self.val = np.pad(self.val, ((0, 0), (0, pad)))
        self.width = new_w

    def _free_slot(self, shard: int) -> int:
        if self._free[shard]:
            return self._free[shard].pop()
        if self._fill[shard] < self.cap_per_shard:
            slot = shard * self.cap_per_shard + self._fill[shard]
            self._fill[shard] += 1
            return slot
        self._grow_capacity()
        slot = shard * self.cap_per_shard + self._fill[shard]
        self._fill[shard] += 1
        return slot

    # -- writes ---------------------------------------------------------------
    def set_row(self, row_id: str, vec: SparseVector,
                datum: Any = None) -> int:
        """Insert or overwrite a row in its OWNING shard's arena;
        returns its global slot. Evicts the least recently touched row
        (globally) first when max_size is reached."""
        slot = self.slots.get(row_id)
        if slot is None:
            if self.max_size is not None and len(self.slots) >= self.max_size:
                self._evict_lru()
            slot = self._free_slot(self.shard_of(row_id))
            self.ids[slot] = row_id
            self.slots[row_id] = slot
        if len(vec) > self.width:
            self._grow_width(len(vec))
        self.idx[slot].fill(0)
        self.val[slot].fill(0.0)
        k = len(vec)
        if k:
            self.idx[slot, :k] = [i for i, _ in vec]
            self.val[slot, :k] = [w for _, w in vec]
        if self.keep_datum and datum is not None:
            self.datums[row_id] = datum
        self.touch(row_id)
        self.updated_since_mix[row_id] = None
        self.version += 1
        return slot

    def remove_row(self, row_id: str) -> bool:
        slot = self.slots.pop(row_id, None)
        if slot is None:
            return False
        self.ids[slot] = ""
        self.idx[slot].fill(0)
        self.val[slot].fill(0.0)
        self._free[slot // self.cap_per_shard].append(slot)
        self._touch.pop(row_id, None)
        self.datums.pop(row_id, None)
        self.updated_since_mix.pop(row_id, None)
        self.version += 1
        return True

    def clear(self) -> None:
        self._init()

    def touch(self, row_id: str) -> None:
        self._clock += 1
        self._touch[row_id] = self._clock

    def _evict_lru(self) -> None:
        victim = min(self._touch, key=self._touch.get)
        self.remove_row(victim)

    # -- reads ----------------------------------------------------------------
    def get_row(self, row_id: str) -> Optional[SparseVector]:
        slot = self.slots.get(row_id)
        if slot is None:
            return None
        order = np.nonzero(self.val[slot])[0]
        return [(int(self.idx[slot, j]), float(self.val[slot, j]))
                for j in order]

    def all_ids(self) -> List[str]:
        return list(self.slots.keys())

    def shard_ids(self, shard: int) -> List[str]:
        """Live row ids in one shard arena — the per-shard walk
        serve_range and the drain handoff ride (host metadata only; the
        device table is never touched)."""
        lo = shard * self.cap_per_shard
        hi = lo + self.cap_per_shard
        return [rid for rid in self.ids[lo:hi] if rid]

    def iter_rows(self) -> Iterator[Tuple[str, int]]:
        return iter(self.slots.items())

    def live_mask(self) -> np.ndarray:
        m = np.zeros(self.capacity, bool)
        if self.slots:
            m[np.fromiter(self.slots.values(), dtype=np.int64,
                          count=len(self.slots))] = True
        return m

    def rows_per_shard(self) -> List[int]:
        counts = [0] * self.n_shards
        for g in self.slots.values():
            counts[g // self.cap_per_shard] += 1
        return counts

    def bytes_in_use(self) -> int:
        """Host-mirror bytes of the padded arenas (idx int32 + val f32);
        the device table costs the same per dtype."""
        return int(self.idx.nbytes + self.val.nbytes)

    def shard_stats(self) -> Dict[str, Any]:
        per = self.rows_per_shard()
        return {"count": self.n_shards, "rows": len(self.slots),
                "rows_per_shard": per,
                "capacity_per_shard": self.cap_per_shard,
                "bytes_in_use": self.bytes_in_use()}

    def device_view(self):
        """(idx, val, live_mask) as device arrays, cached per version."""
        if self._dev_cache is None or self._dev_cache[0] != self.version:
            self._dev_cache = (
                self.version,
                jnp.asarray(self.idx),
                jnp.asarray(self.val),
                jnp.asarray(self.live_mask()),
            )
        return self._dev_cache[1], self._dev_cache[2], self._dev_cache[3]

    # -- mix / persistence ----------------------------------------------------
    def pop_update_diff(self) -> Dict[str, Tuple[list, list, Any]]:
        """Rows written since the last mix as {id: (idx_list, val_list,
        datum)}; clears the tracker. Wire-identical to the flat store."""
        out = {}
        for rid in self.updated_since_mix:
            slot = self.slots.get(rid)
            if slot is None:
                continue
            nz = np.nonzero(self.val[slot])[0]
            out[rid] = (
                self.idx[slot, nz].tolist(),
                self.val[slot, nz].tolist(),
                self.datums.get(rid),
            )
        self.updated_since_mix = {}
        return out

    def pop_update_diff_sharded(self) -> List[Dict[str, Tuple[list, list, Any]]]:
        """The same diff grouped by owning shard (one dict per shard) —
        each shard's chunk enters the mix pipeline independently."""
        out: List[Dict[str, Tuple[list, list, Any]]] = \
            [{} for _ in range(self.n_shards)]
        flat = self.pop_update_diff()
        for rid, row in flat.items():
            out[self.shard_of(rid)][rid] = row
        return out

    def apply_update_diff(self, diff: Dict[str, Tuple[list, list, Any]]) -> None:
        for rid, (ii, vv, datum) in diff.items():
            rid = rid.decode() if isinstance(rid, bytes) else rid
            vec = [(int(i), float(v)) for i, v in zip(ii, vv)]
            self.set_row(rid, vec, datum=datum)
        # rows arriving via mix are not "local updates" for the next round
        self.updated_since_mix = {}

    def pack(self) -> Any:
        return {
            "rows": {
                rid: (
                    self.idx[s][np.nonzero(self.val[s])].tolist(),
                    self.val[s][np.nonzero(self.val[s])].tolist(),
                )
                for rid, s in self.slots.items()
            },
            "datums": {rid: d.to_msgpack() if hasattr(d, "to_msgpack") else d
                       for rid, d in self.datums.items()}
            if self.keep_datum else {},
        }

    def unpack(self, obj: Any, datum_decoder=None) -> None:
        """Restore from the shared pack format. Reshard-on-restore falls
        out of placement being a pure function of the id: a checkpoint
        written at N shards (or by the flat store) re-places every row
        into the CURRENT n_shards' owning arenas."""
        self._init()
        for rid, (ii, vv) in obj["rows"].items():
            rid = rid.decode() if isinstance(rid, bytes) else rid
            self.set_row(rid, [(int(i), float(v)) for i, v in zip(ii, vv)])
        for rid, d in (obj.get("datums") or {}).items():
            rid = rid.decode() if isinstance(rid, bytes) else rid
            self.datums[rid] = datum_decoder(d) if datum_decoder else d
        self.updated_since_mix = {}
