"""Mesh-sharded two-phase IVF queries — the ANN tier over the sharded
row store (ISSUE 16).

Same data placement as the exact scan (parallel/sharded_knn.py): the
signature table is sharded over the mesh's ``shard`` axis, queries are
replicated, results merge through the identical log-depth
``merge_topk`` tree. What changes is what each device SCANS:

  exact   every live row in the local arena        O(C/S) per query
  ivf     probe top-``nprobe`` cells against the   O(K + P·cap)
          replicated centroid table (one [B, K]×[K, E] matmul), gather
          ONLY those cells' member slots from the local cell table
          ([n_cells, cap] int32, −1-padded; parallel/row_store.py
          CellArenas), rescore the gathered rows with the method's
          EXACT distance math

Each shard probes its OWN top-P cells — cell population differs per
shard, so the probe set does too; no cross-shard coordination is
needed because the merge is over exact distances either way. The
cross-shard wire cost is unchanged: one all_gather of [S, B, kk]
candidates, log2(S) merge levels.

The cell-slot table is sharded P(axis) on its leading [S·n_cells] dim,
so device ``s`` sees exactly its own [n_cells, cap] block and gathered
LOCAL slots index the local arena block directly; global ids come out
as ``local_slot + s · capacity_per_shard`` exactly like the exact path.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from jubatus_tpu.ops.ivf import candidate_sig_distances, pairwise_sq_dists
from jubatus_tpu.parallel._compat import shard_map
from jubatus_tpu.parallel.sharded_knn import merge_topk


@functools.partial(
    jax.jit,
    static_argnames=("mesh", "method", "hash_num", "k", "nprobe", "axis"))
def sharded_ivf_topk(
    mesh: Mesh,
    q_sigs: jax.Array,      # [B, W/H] replicated (method signature space)
    q_emb: jax.Array,       # [B, E] float32 replicated (probe space)
    row_sigs: jax.Array,    # [C, W/H] sharded over `axis`
    centroids: jax.Array,   # [n_cells, E] float32 replicated
    cell_slots: jax.Array,  # [S*n_cells, cap] int32 sharded over `axis`
    *,
    method: str,
    hash_num: int,
    k: int,
    nprobe: int,
    axis: str = "shard",
) -> Tuple[jax.Array, jax.Array]:
    """Global approximate top-k over the sharded table: per-shard cell
    probe + gathered exact rescore, merged with the log-depth tree.

    Returns (distances [B, k'], global row ids [B, k']) replicated;
    k' = min(k, S · min(k, nprobe·cap)). Slots short of k rows carry
    non-finite distances (their ids are meaningless) — same contract as
    the exact path's dead-slot masking."""
    n_shards = mesh.shape[axis]
    c_local = row_sigs.shape[0] // n_shards
    n_cells = cell_slots.shape[0] // n_shards
    nprobe = min(nprobe, n_cells)

    def scan(qs, qe, rows, cents, cells):
        # phase 1 — probe: rank this shard's centroid table (replicated,
        # tiny) and take the nprobe nearest cells per query
        d2 = pairwise_sq_dists(qe, cents)                  # [B, n_cells]
        _, sel = jax.lax.top_k(-d2, nprobe)                # [B, P]
        # phase 2 — gather only the probed cells' member slots and
        # rescore them with the exact signature distance
        cand = cells[sel].reshape(qs.shape[0], -1)         # [B, P·cap]
        ok = cand >= 0
        safe = jnp.maximum(cand, 0)
        d = candidate_sig_distances(qs, rows[safe], method=method,
                                    hash_num=hash_num)
        sc = jnp.where(ok, -d.astype(jnp.float32), -jnp.inf)
        kk = min(k, sc.shape[-1])
        neg, pos = jax.lax.top_k(sc, kk)                   # [B, kk]
        lslot = jnp.take_along_axis(safe, pos, axis=-1)
        gidx = lslot + jax.lax.axis_index(axis) * c_local
        negs = jax.lax.all_gather(neg, axis, tiled=False)  # [S, B, kk]
        gidxs = jax.lax.all_gather(gidx, axis, tiled=False)
        return merge_topk(negs, gidxs, k)

    fn = shard_map(
        scan, mesh=mesh,
        in_specs=(P(), P(), P(axis, None), P(), P(axis, None)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    neg, gidx = fn(q_sigs, q_emb, row_sigs, centroids, cell_slots)
    return -neg, gidx
