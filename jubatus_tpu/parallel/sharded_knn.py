"""Mesh-sharded similarity scans — the TPU replacement for CHT row
sharding (SURVEY.md §5 "long-context": the reference scales a dimension
across nodes with consistent-hash row placement, cht.cpp:107-143; on a
pod the same capacity scaling is a static shard of the signature table
over the mesh's ``shard`` axis).

One query batch fans out to every shard implicitly (the table is sharded,
the query replicated), each device scans its slice of the table with the
same kernels the single-chip path uses (ops/knn; pallas on TPU), takes a
LOCAL top-k, and one tiny all_gather of [k]-sized candidates feeds the
log-depth on-device tree merge (``merge_topk``) — O(shards·k) bytes over
ICI and log2(shards) selection passes instead of O(rows). All three
hash methods (lsh/minhash/euclid_lsh) ride the same driver; an optional
``valid`` row mask keeps dead/padding slots out of the results (the
single-chip path's live-mask, models/_nn_backend.py).

For batches where the QUERIES don't fit replicated either, use the ring
strategy (parallel/ring.py) instead.

Row placement: ``coord.cht.shard_for(row_id, n_shards)`` keeps placement
stable and hash-based like the ring; slot index within the shard is the
store's business. Global ids returned by queries are ``shard * capacity +
local_slot`` — decode with ``divmod(gid, capacity)``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jubatus_tpu.parallel._compat import shard_map


def shard_table(mesh: Mesh, table, axis: str = "shard"):
    """Place [C, ...] rows sharded over the mesh axis (C must be a multiple
    of the axis size; pad the store capacity to match). Shared by the
    all-gather (this module) and ring (parallel/ring.py) scan strategies."""
    spec = P(axis, *([None] * (table.ndim - 1)))
    return jax.device_put(table, NamedSharding(mesh, spec))


def replicate(mesh: Mesh, x):
    return jax.device_put(x, NamedSharding(mesh, P()))


def merge_topk(scores, ids, k: int):
    """Log-depth on-device merge of per-shard top-k candidate sets.

    ``scores``/``ids``: [S, B, kk] partials (HIGHER score = better).
    Pairwise tree reduction: each level merges shard pairs with one
    top_k over the concatenated 2·kk candidates, halving S per level —
    log2(S) selection passes over O(k)-sized sets instead of one flat
    [B, S·kk] sort whose cost grows linearly with the shard count.
    Selection is associative (top-k of a union == top-k of per-part
    top-ks), so the result matches the flat merge exactly; equal-score
    ties are pinned to ascending id so the result is deterministic and
    independent of shard pairing order. Returns ([B, k'], [B, k'])
    with k' = min(k, S·kk)."""
    s = scores.shape[0]
    k = min(k, s * scores.shape[2])
    while s > 1:
        half = s // 2
        lo_s, hi_s = scores[:half], scores[half: 2 * half]
        lo_i, hi_i = ids[:half], ids[half: 2 * half]
        cat_s = jnp.concatenate([lo_s, hi_s], axis=-1)     # [half, B, 2kk]
        cat_i = jnp.concatenate([lo_i, hi_i], axis=-1)
        kk = min(k, cat_s.shape[-1])
        merged_s, pos = jax.lax.top_k(cat_s, kk)
        merged_i = jnp.take_along_axis(cat_i, pos, axis=-1)
        if s % 2:                                          # odd: carry last
            carry_s, carry_i = scores[-1:], ids[-1:]
            if carry_s.shape[-1] > kk:    # keep the carry's own top-kk
                carry_s, pos = jax.lax.top_k(carry_s, kk)
                carry_i = jnp.take_along_axis(carry_i, pos, axis=-1)
            pad = kk - carry_s.shape[-1]
            if pad > 0:    # widen with -inf sentinels (never selected)
                carry_s = jnp.pad(carry_s, ((0, 0), (0, 0), (0, pad)),
                                  constant_values=-jnp.inf)
                carry_i = jnp.pad(carry_i, ((0, 0), (0, 0), (0, pad)))
            merged_s = jnp.concatenate([merged_s, carry_s], axis=0)
            merged_i = jnp.concatenate([merged_i, carry_i], axis=0)
        scores, ids = merged_s, merged_i
        s = scores.shape[0]
    out_s, out_i = scores[0], ids[0]
    if out_s.shape[-1] > k:
        out_s, pos = jax.lax.top_k(out_s, k)
        out_i = jnp.take_along_axis(out_i, pos, axis=-1)
    # pin tie order: score desc, then id asc (−(−inf) = +inf keeps
    # dead/padding sentinels last) — deterministic across shard counts
    order = jnp.lexsort((out_i, -out_s), axis=-1)
    out_s = jnp.take_along_axis(out_s, order, axis=-1)
    out_i = jnp.take_along_axis(out_i, order, axis=-1)
    return out_s, out_i


def _sharded_topk(mesh, q, table, local_scores, k: int, axis: str,
                  valid=None):
    """Generic all-gather-merge driver. ``local_scores(q, rows) -> [B, c]``
    (HIGHER = better; negate distances). Returns (scores [B, k'],
    global ids [B, k']) replicated, k' = min(k, C)."""
    n_shards = mesh.shape[axis]
    c_local = table.shape[0] // n_shards
    k = min(k, c_local * n_shards)

    def scan(q, rows, *v):
        sc = local_scores(q, rows).astype(jnp.float32)     # [B, c_local]
        if v:
            sc = jnp.where(v[0][None, :], sc, -jnp.inf)
        kk = min(k, c_local)
        neg, idx = jax.lax.top_k(sc, kk)                   # [B, kk]
        shard_id = jax.lax.axis_index(axis)
        gidx = idx + shard_id * c_local                    # global ids
        # merge across shards: gather the tiny candidate sets, then the
        # log-depth tree merge (O(S·k) wire bytes, log2(S) selections)
        negs = jax.lax.all_gather(neg, axis, tiled=False)  # [S, B, kk]
        gidxs = jax.lax.all_gather(gidx, axis, tiled=False)
        return merge_topk(negs, gidxs, k)

    in_specs = [P(), P(axis, *([None] * (table.ndim - 1)))]
    args = [q, table]
    if valid is not None:
        in_specs.append(P(axis))
        args.append(valid)
    fn = shard_map(
        scan, mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return fn(*args)


@functools.partial(jax.jit,
                   static_argnames=("mesh", "method", "hash_num", "axis"))
def sharded_distances(
    mesh: Mesh,
    q_sigs: jax.Array,    # [B, W/H] replicated
    row_sigs: jax.Array,  # [C, W/H] sharded over `axis`
    *,
    method: str,
    hash_num: int,
    axis: str = "shard",
) -> jax.Array:
    """FULL distance matrix [B, C] from a sharded table — each device
    scans its slice, one all_gather assembles the rows. For consumers
    that need every distance (LOF's lrd cache), not just top-k: HBM holds
    only C/S signature rows per device; the [B, C] float result is the
    caller's to size."""
    from jubatus_tpu.ops import knn

    scorer = {
        "lsh": lambda q, r: knn._hamming_distances_batch_xla(
            q, r, hash_num=hash_num),
        "minhash": lambda q, r: knn._minhash_distances_batch_xla(q, r),
        "euclid_lsh": lambda q, r: knn.euclid_lsh_distances_batch(
            q, r, hash_num=hash_num),
    }[method]

    def scan(q, rows):
        d = scorer(q, rows).astype(jnp.float32)            # [B, c_local]
        parts = jax.lax.all_gather(d, axis, tiled=False)   # [S, B, c_local]
        return jnp.transpose(parts, (1, 0, 2)).reshape(q.shape[0], -1)

    fn = shard_map(
        scan, mesh=mesh,
        in_specs=(P(), P(axis, None)),
        out_specs=P(),
        check_vma=False,
    )
    return fn(q_sigs, row_sigs)


@functools.partial(jax.jit,
                   static_argnames=("mesh", "hash_num", "k", "axis"))
def sharded_hamming_topk(
    mesh: Mesh,
    q_sigs: jax.Array,    # [B, W] uint32, replicated
    row_sigs: jax.Array,  # [C, W] uint32, sharded over `axis`
    *,
    hash_num: int,
    k: int,
    axis: str = "shard",
    valid: Optional[jax.Array] = None,  # [C] bool, sharded over `axis`
) -> Tuple[jax.Array, jax.Array]:
    """Global top-k nearest (smallest hamming distance) over the sharded
    table. Returns (distances [B, k], global row indices [B, k])."""
    from jubatus_tpu.ops import knn

    def scores(q, rows):
        return -knn._hamming_distances_batch_xla(q, rows, hash_num=hash_num)

    neg, gidx = _sharded_topk(mesh, q_sigs, row_sigs, scores, k, axis, valid)
    return -neg, gidx


@functools.partial(jax.jit, static_argnames=("mesh", "k", "axis"))
def sharded_minhash_topk(
    mesh: Mesh,
    q_sigs: jax.Array,    # [B, H] uint32, replicated
    row_sigs: jax.Array,  # [C, H] uint32, sharded over `axis`
    *,
    k: int,
    axis: str = "shard",
    valid: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k smallest (1 - weighted-Jaccard estimate) distance."""
    from jubatus_tpu.ops import knn

    def scores(q, rows):
        return -knn._minhash_distances_batch_xla(q, rows)

    neg, gidx = _sharded_topk(mesh, q_sigs, row_sigs, scores, k, axis, valid)
    return -neg, gidx


@functools.partial(jax.jit,
                   static_argnames=("mesh", "hash_num", "k", "axis"))
def sharded_euclid_lsh_topk(
    mesh: Mesh,
    q_projs: jax.Array,   # [B, H] float32, replicated
    row_projs: jax.Array, # [C, H] float32, sharded over `axis`
    *,
    hash_num: int,
    k: int,
    axis: str = "shard",
    valid: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k smallest JL-estimated euclidean distance."""
    from jubatus_tpu.ops import knn

    def scores(q, rows):
        return -knn.euclid_lsh_distances_batch(q, rows, hash_num=hash_num)

    neg, gidx = _sharded_topk(mesh, q_projs, row_projs, scores, k, axis, valid)
    return -neg, gidx
