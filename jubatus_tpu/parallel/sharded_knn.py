"""Mesh-sharded similarity scans — the TPU replacement for CHT row
sharding (SURVEY.md §5 "long-context": the reference scales a dimension
across nodes with consistent-hash row placement, cht.cpp:107-143; on a
pod the same capacity scaling is a static shard of the signature table
over the mesh's ``shard`` axis).

One query batch fans out to every shard implicitly (the table is sharded,
the query replicated), each device scans its slice of the table with the
same kernels the single-chip path uses (ops/knn; pallas on TPU), takes a
LOCAL top-k, and one tiny all_gather of [k]-sized candidates merges the
global top-k — O(shards·k) bytes over ICI instead of O(rows).

Row placement: ``coord.cht.shard_for(row_id, n_shards)`` keeps placement
stable and hash-based like the ring; slot index within the shard is the
store's business. Global ids returned by queries are ``shard * capacity +
local_slot`` — decode with ``divmod(gid, capacity)``.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_table(mesh: Mesh, table, axis: str = "shard"):
    """Place [C, ...] rows sharded over the mesh axis (C must be a multiple
    of the axis size; pad the store capacity to match). Shared by the
    all-gather (this module) and ring (parallel/ring.py) scan strategies."""
    spec = P(axis, *([None] * (table.ndim - 1)))
    return jax.device_put(table, NamedSharding(mesh, spec))


def replicate(mesh: Mesh, x):
    return jax.device_put(x, NamedSharding(mesh, P()))


@functools.partial(jax.jit, static_argnames=("mesh", "hash_num", "k", "axis"))
def sharded_hamming_topk(
    mesh: Mesh,
    q_sigs: jax.Array,    # [B, W] uint32, replicated
    row_sigs: jax.Array,  # [C, W] uint32, sharded over `axis`
    *,
    hash_num: int,
    k: int,
    axis: str = "shard",
) -> Tuple[jax.Array, jax.Array]:
    """Global top-k nearest (smallest hamming distance) over the sharded
    table. Returns (distances [B, k], global row indices [B, k])."""
    from jubatus_tpu.ops import knn

    n_shards = mesh.shape[axis]
    c_local = row_sigs.shape[0] // n_shards

    def scan(q, rows):
        # per-device: full scan of my slice + local top-k
        d = knn._hamming_distances_batch_xla(q, rows, hash_num=hash_num)
        kk = min(k, rows.shape[0])
        neg, idx = jax.lax.top_k(-d, kk)                    # [B, kk]
        shard_id = jax.lax.axis_index(axis)
        gidx = idx + shard_id * c_local                     # global ids
        # merge across shards: gather the tiny candidate sets
        negs = jax.lax.all_gather(neg, axis, tiled=False)   # [S, B, kk]
        gidxs = jax.lax.all_gather(gidx, axis, tiled=False)
        s = negs.shape[0]
        negs = jnp.transpose(negs, (1, 0, 2)).reshape(q.shape[0], s * kk)
        gidxs = jnp.transpose(gidxs, (1, 0, 2)).reshape(q.shape[0], s * kk)
        top_neg, pos = jax.lax.top_k(negs, min(k, s * kk))
        return -top_neg, jnp.take_along_axis(gidxs, pos, axis=1)

    spec_rows = P(axis, None)
    fn = jax.shard_map(
        scan, mesh=mesh,
        in_specs=(P(), spec_rows),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return fn(q_sigs, row_sigs)
