"""Mesh-sharded similarity scans — the TPU replacement for CHT row
sharding (SURVEY.md §5 "long-context": the reference scales a dimension
across nodes with consistent-hash row placement, cht.cpp:107-143; on a
pod the same capacity scaling is a static shard of the signature table
over the mesh's ``shard`` axis).

One query batch fans out to every shard implicitly (the table is sharded,
the query replicated), each device scans its slice of the table with the
same kernels the single-chip path uses (ops/knn; pallas on TPU), takes a
LOCAL top-k, and one tiny all_gather of [k]-sized candidates merges the
global top-k — O(shards·k) bytes over ICI instead of O(rows). All three
hash methods (lsh/minhash/euclid_lsh) ride the same driver; an optional
``valid`` row mask keeps dead/padding slots out of the results (the
single-chip path's live-mask, models/_nn_backend.py).

For batches where the QUERIES don't fit replicated either, use the ring
strategy (parallel/ring.py) instead.

Row placement: ``coord.cht.shard_for(row_id, n_shards)`` keeps placement
stable and hash-based like the ring; slot index within the shard is the
store's business. Global ids returned by queries are ``shard * capacity +
local_slot`` — decode with ``divmod(gid, capacity)``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jubatus_tpu.parallel._compat import shard_map


def shard_table(mesh: Mesh, table, axis: str = "shard"):
    """Place [C, ...] rows sharded over the mesh axis (C must be a multiple
    of the axis size; pad the store capacity to match). Shared by the
    all-gather (this module) and ring (parallel/ring.py) scan strategies."""
    spec = P(axis, *([None] * (table.ndim - 1)))
    return jax.device_put(table, NamedSharding(mesh, spec))


def replicate(mesh: Mesh, x):
    return jax.device_put(x, NamedSharding(mesh, P()))


def _sharded_topk(mesh, q, table, local_scores, k: int, axis: str,
                  valid=None):
    """Generic all-gather-merge driver. ``local_scores(q, rows) -> [B, c]``
    (HIGHER = better; negate distances). Returns (scores [B, k'],
    global ids [B, k']) replicated, k' = min(k, C)."""
    n_shards = mesh.shape[axis]
    c_local = table.shape[0] // n_shards
    k = min(k, c_local * n_shards)

    def scan(q, rows, *v):
        sc = local_scores(q, rows).astype(jnp.float32)     # [B, c_local]
        if v:
            sc = jnp.where(v[0][None, :], sc, -jnp.inf)
        kk = min(k, c_local)
        neg, idx = jax.lax.top_k(sc, kk)                   # [B, kk]
        shard_id = jax.lax.axis_index(axis)
        gidx = idx + shard_id * c_local                    # global ids
        # merge across shards: gather the tiny candidate sets
        negs = jax.lax.all_gather(neg, axis, tiled=False)  # [S, B, kk]
        gidxs = jax.lax.all_gather(gidx, axis, tiled=False)
        s = negs.shape[0]
        negs = jnp.transpose(negs, (1, 0, 2)).reshape(q.shape[0], s * kk)
        gidxs = jnp.transpose(gidxs, (1, 0, 2)).reshape(q.shape[0], s * kk)
        top_neg, pos = jax.lax.top_k(negs, min(k, s * kk))
        return top_neg, jnp.take_along_axis(gidxs, pos, axis=1)

    in_specs = [P(), P(axis, *([None] * (table.ndim - 1)))]
    args = [q, table]
    if valid is not None:
        in_specs.append(P(axis))
        args.append(valid)
    fn = shard_map(
        scan, mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return fn(*args)


@functools.partial(jax.jit,
                   static_argnames=("mesh", "method", "hash_num", "axis"))
def sharded_distances(
    mesh: Mesh,
    q_sigs: jax.Array,    # [B, W/H] replicated
    row_sigs: jax.Array,  # [C, W/H] sharded over `axis`
    *,
    method: str,
    hash_num: int,
    axis: str = "shard",
) -> jax.Array:
    """FULL distance matrix [B, C] from a sharded table — each device
    scans its slice, one all_gather assembles the rows. For consumers
    that need every distance (LOF's lrd cache), not just top-k: HBM holds
    only C/S signature rows per device; the [B, C] float result is the
    caller's to size."""
    from jubatus_tpu.ops import knn

    scorer = {
        "lsh": lambda q, r: knn._hamming_distances_batch_xla(
            q, r, hash_num=hash_num),
        "minhash": lambda q, r: knn._minhash_distances_batch_xla(q, r),
        "euclid_lsh": lambda q, r: knn.euclid_lsh_distances_batch(
            q, r, hash_num=hash_num),
    }[method]

    def scan(q, rows):
        d = scorer(q, rows).astype(jnp.float32)            # [B, c_local]
        parts = jax.lax.all_gather(d, axis, tiled=False)   # [S, B, c_local]
        return jnp.transpose(parts, (1, 0, 2)).reshape(q.shape[0], -1)

    fn = shard_map(
        scan, mesh=mesh,
        in_specs=(P(), P(axis, None)),
        out_specs=P(),
        check_vma=False,
    )
    return fn(q_sigs, row_sigs)


@functools.partial(jax.jit,
                   static_argnames=("mesh", "hash_num", "k", "axis"))
def sharded_hamming_topk(
    mesh: Mesh,
    q_sigs: jax.Array,    # [B, W] uint32, replicated
    row_sigs: jax.Array,  # [C, W] uint32, sharded over `axis`
    *,
    hash_num: int,
    k: int,
    axis: str = "shard",
    valid: Optional[jax.Array] = None,  # [C] bool, sharded over `axis`
) -> Tuple[jax.Array, jax.Array]:
    """Global top-k nearest (smallest hamming distance) over the sharded
    table. Returns (distances [B, k], global row indices [B, k])."""
    from jubatus_tpu.ops import knn

    def scores(q, rows):
        return -knn._hamming_distances_batch_xla(q, rows, hash_num=hash_num)

    neg, gidx = _sharded_topk(mesh, q_sigs, row_sigs, scores, k, axis, valid)
    return -neg, gidx


@functools.partial(jax.jit, static_argnames=("mesh", "k", "axis"))
def sharded_minhash_topk(
    mesh: Mesh,
    q_sigs: jax.Array,    # [B, H] uint32, replicated
    row_sigs: jax.Array,  # [C, H] uint32, sharded over `axis`
    *,
    k: int,
    axis: str = "shard",
    valid: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k smallest (1 - weighted-Jaccard estimate) distance."""
    from jubatus_tpu.ops import knn

    def scores(q, rows):
        return -knn._minhash_distances_batch_xla(q, rows)

    neg, gidx = _sharded_topk(mesh, q_sigs, row_sigs, scores, k, axis, valid)
    return -neg, gidx


@functools.partial(jax.jit,
                   static_argnames=("mesh", "hash_num", "k", "axis"))
def sharded_euclid_lsh_topk(
    mesh: Mesh,
    q_projs: jax.Array,   # [B, H] float32, replicated
    row_projs: jax.Array, # [C, H] float32, sharded over `axis`
    *,
    hash_num: int,
    k: int,
    axis: str = "shard",
    valid: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Top-k smallest JL-estimated euclidean distance."""
    from jubatus_tpu.ops import knn

    def scores(q, rows):
        return -knn.euclid_lsh_distances_batch(q, rows, hash_num=hash_num)

    neg, gidx = _sharded_topk(mesh, q_projs, row_projs, scores, k, axis, valid)
    return -neg, gidx
