"""Feature-sharded linear model state — first-class shard_map programs.

ROADMAP item 1 / ISSUE 13 tentpole: the weight matrix of the linear
engines ([L, D] classifier tables, [D] regression vector) sharded over
the FEATURE axis of a device mesh, with train/classify executing where
the shard lives. "Large Scale Distributed Linear Algebra With Tensor
Processing Units" (PAPERS.md) is the shape: distribute the matrix over
the mesh, move compute to the shard, reduce only the tiny per-example
scalars over the interconnect.

Execution model (one shard_map'd jitted program per op):

- Every shard receives the full CSR batch (idx/val [B, K] — kilobytes,
  vs gigabytes of weight state) and masks it to its OWNED column range
  ``[shard * D/S, (shard+1) * D/S)`` — the column-range partitioner that
  routes each batch entry to the owning shard. Unowned entries
  contribute exact zeros.
- Partial scores from the local [L, D/S] slice are reduced with a
  single ``psum`` over the shard axis — the ONLY cross-shard traffic
  per step is [B, L] logits (+ [B] norms), never weight state.
- Updates scatter into the local ``dw`` slice only. The weight matrix
  is never gathered: per-device footprint stays (full size / n_shards)
  + O(batch).

The same decision kernel as the single-chip path
(ops/classifier.decide_updates) keeps sharded and unsharded results
identical to f32 rounding; parallel/spmd.py stacks this body under a
data-parallel replica axis for the pod path.

Mix integration: ``shard_chunks`` / ``assemble_chunks`` convert a
feature-sharded leaf to/from per-shard host chunks keyed by start
column (``c0``, ``c8388608``, ...), so each shard's diff enters the
chunked/tiered/quantized mix pipeline independently and no step of a
mix round materializes the full matrix in one buffer.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from jubatus_tpu.ops.classifier import (
    CONFIDENCE_METHODS,
    ClassifierState,
    decide_updates,
)
from jubatus_tpu.parallel._compat import shard_map

DEFAULT_AXIS = "shard"


def feature_shard_mesh(n_shards: int, err_cls=ValueError,
                       axis: str = DEFAULT_AXIS) -> Mesh:
    """A 1-D feature-shard mesh over the first ``n_shards`` LOCAL
    devices (host-major — device_put on non-addressable devices fails
    on a multi-host runtime). The ``--shard-features`` mesh builder."""
    from jubatus_tpu.parallel.mesh import host_major

    devs = host_major(jax.local_devices())[:n_shards]
    if len(devs) < n_shards:
        raise err_cls(
            f"feature sharding needs {n_shards} local devices, "
            f"have {len(devs)}")
    return Mesh(np.asarray(devs), axis_names=(axis,))


def mesh_for_features(dim: int, d_per_shard: int,
                      err_cls=ValueError) -> Optional[Mesh]:
    """The ``--shard-features D_PER_SHARD`` resolver: shard count =
    dim / d_per_shard (must divide; one shard or fewer means no mesh).
    The per-device feature budget is the HBM-capacity knob — pick the
    widest slice one device holds and the layout follows."""
    if d_per_shard <= 0:
        raise err_cls(f"--shard-features must be > 0, got {d_per_shard}")
    if dim % d_per_shard:
        raise err_cls(
            f"--shard-features {d_per_shard} does not divide the feature "
            f"dim {dim} (pick a power-of-two slice of 2^dim_bits)")
    n = dim // d_per_shard
    if n <= 1:
        return None
    return feature_shard_mesh(n, err_cls)


def state_spec(leaf, dim: int, axis: str = DEFAULT_AXIS) -> P:
    """PartitionSpec for one state leaf: trailing (feature) dim sharded
    when it spans the model dim; (1, 1) placeholders and scalars stay
    replicated."""
    shape = getattr(leaf, "shape", ())
    if len(shape) >= 1 and shape[-1] == dim:
        return P(*([None] * (len(shape) - 1)), axis)
    return P()


def place_state(mesh: Mesh, state, dim: int, axis: str = DEFAULT_AXIS):
    """Pin every feature-spanning leaf of a state pytree to the sharded
    layout (NamedSharding over ``axis``); other leaves replicate."""
    def put(a):
        return jax.device_put(
            a, NamedSharding(mesh, state_spec(a, dim, axis)))

    return jax.tree_util.tree_map(put, state)


def _owned(idx, val, d_local, axis):
    """Column-range partition of one CSR batch: local indices + values
    for the entries this shard owns, zeros elsewhere."""
    lo = jax.lax.axis_index(axis) * d_local
    li_raw = idx - lo
    owned = (li_raw >= 0) & (li_raw < d_local)
    li = jnp.where(owned, li_raw, 0)
    lv = jnp.where(owned, val, 0.0)
    return li, lv, owned


@functools.partial(
    jax.jit, static_argnames=("mesh", "axis", "method"), donate_argnums=(1,))
def train_batch(mesh: Mesh, state: ClassifierState, idx: jax.Array,
                val: jax.Array, labels: jax.Array, label_mask: jax.Array,
                param: float, *, method: str,
                axis: str = DEFAULT_AXIS) -> ClassifierState:
    """Feature-sharded vectorized microbatch update (the shard_map'd
    mirror of ops.train_batch_parallel; parallel/spmd.py runs the same
    body under an extra replica axis). Batch arrays are replicated (the
    batch is kilobytes; the state is the thing that must not move);
    state leaves are sharded over ``axis``. One psum of [B, L] partial
    scores (+ [B] norms) per step — weight state never crosses shards."""
    confidence = method in CONFIDENCE_METHODS
    n_shards = mesh.shape[axis]
    dim = state.w.shape[-1]

    def body(w, dw, prec, dprec, idx, val, labels, label_mask):
        d_local = w.shape[1]
        li, lv, owned = _owned(idx, val, d_local, axis)

        eff = w + dw
        g = jnp.take(eff, li, axis=1)                      # [L, B, K]
        s = jax.lax.psum(jnp.einsum("lbk,bk->bl", g, lv), axis)
        x2_vec_l = lv * lv
        x2 = jax.lax.psum(jnp.sum(x2_vec_l, axis=1), axis)

        if confidence:
            p = prec + dprec
            pg = jnp.take(p, li, axis=1)                   # [L, B, K]
            p_c = jnp.take_along_axis(pg, labels[None, :, None], axis=0)[0]
            sig_c = jnp.where(owned, 1.0 / p_c, 0.0)
            wrong0, _, _, _ = decide_updates(
                s, labels, label_mask, x2, jnp.zeros_like(x2), x2_vec_l,
                param, method=method)
            p_w = jnp.take_along_axis(pg, wrong0[None, :, None], axis=0)[0]
            no_rival = jnp.sum(label_mask) < 2
            sig_w = jnp.where(owned,
                              jnp.where(no_rival, 1.0, 1.0 / p_w), 0.0)
            v = jax.lax.psum(
                jnp.sum((sig_c + sig_w) * x2_vec_l, axis=1), axis)
        else:
            sig_c = sig_w = jnp.where(owned, 1.0, 0.0)
            v = jnp.zeros_like(x2)

        wrong, alpha, alpha_w, dp = decide_updates(
            s, labels, label_mask, x2, v, x2_vec_l, param, method=method)

        up_c = alpha[:, None] * sig_c * lv
        up_w = alpha_w[:, None] * sig_w * lv
        dw = dw.at[labels[:, None], li].add(jnp.where(owned, up_c, 0.0))
        dw = dw.at[wrong[:, None], li].add(jnp.where(owned, -up_w, 0.0))
        if confidence:
            dp = jnp.where(owned, dp, 0.0)
            dprec = dprec.at[labels[:, None], li].add(dp)
            dprec = dprec.at[wrong[:, None], li].add(
                jnp.where((alpha_w > 0.0)[:, None], dp, 0.0))
        return w, dw, prec, dprec

    specs = tuple(state_spec(a, dim, axis) for a in state)
    out = shard_map(
        body, mesh=mesh,
        in_specs=specs + (P(), P(), P(), P()),
        out_specs=specs,
        check_vma=False,
    )(state.w, state.dw, state.prec, state.dprec,
      idx, val, labels, label_mask)
    return ClassifierState(*out)


@functools.partial(jax.jit, static_argnames=("mesh", "axis"))
def scores(mesh: Mesh, state: ClassifierState, idx: jax.Array,
           val: jax.Array, label_mask: jax.Array,
           axis: str = DEFAULT_AXIS) -> jax.Array:
    """Feature-sharded batch classify: each shard scores its column
    range, one psum assembles the [B, L] logits (replicated out). Same
    -inf dead-label convention as ops.scores."""
    dim = state.w.shape[-1]
    neg = jnp.float32(-1e30)

    def body(w, dw, idx, val, label_mask):
        d_local = w.shape[1]
        li, lv, _ = _owned(idx, val, d_local, axis)
        eff = w + dw
        g = jnp.take(eff, li, axis=1)                      # [L, B, K]
        s = jax.lax.psum(jnp.einsum("lbk,bk->bl", g, lv), axis)
        return jnp.where(label_mask[None, :], s, neg)

    spec = state_spec(state.w, dim, axis)
    return shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, P(), P(), P()),
        out_specs=P(),
        check_vma=False,
    )(state.w, state.dw, idx, val, label_mask)


# -- regression (single weight row) ------------------------------------------

@functools.partial(
    jax.jit, static_argnames=("mesh", "axis", "method"), donate_argnums=(1,))
def regression_train_batch(mesh: Mesh, state, idx: jax.Array,
                           val: jax.Array, targets: jax.Array,
                           sensitivity: float, c: float, *, method: str,
                           axis: str = DEFAULT_AXIS):
    """Feature-sharded PA regression train: the per-example sequential
    scan of ops/regression.train_batch with the prediction reduced over
    the shard axis each step (exact reference semantics preserved —
    the scan stays sequential; only the dot products are sharded)."""
    from jubatus_tpu.ops.regression import RegressionState

    dim = state.w.shape[-1]

    def body(w, dw, idx, val, targets):
        d_local = w.shape[0]

        def step(carry, ex):
            w, dw = carry
            e_idx, e_val, y = ex
            lo = jax.lax.axis_index(axis) * d_local
            li_raw = e_idx - lo
            owned = (li_raw >= 0) & (li_raw < d_local)
            li = jnp.where(owned, li_raw, 0)
            lv = jnp.where(owned, e_val, 0.0)
            pred = jax.lax.psum(
                jnp.sum((jnp.take(w, li) + jnp.take(dw, li)) * lv), axis)
            err = y - pred
            loss = jnp.abs(err) - sensitivity
            x2 = jnp.maximum(
                jax.lax.psum(jnp.sum(lv * lv), axis), 1e-12)
            if method == "PA":
                alpha = loss / x2
            elif method == "PA1":
                alpha = jnp.minimum(c, loss / x2)
            elif method == "PA2":
                alpha = loss / (x2 + 1.0 / (2.0 * c))
            else:
                raise ValueError(f"unknown regression method {method!r}")
            alpha = jnp.where(loss > 0.0, alpha, 0.0)
            dw = dw.at[li].add(jnp.sign(err) * alpha * lv)
            return (w, dw), ()

        (w, dw), _ = jax.lax.scan(step, (w, dw), (idx, val, targets))
        return w, dw

    spec = P(axis)
    out = shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, P(), P(), P()),
        out_specs=(spec, spec),
        check_vma=False,
    )(state.w, state.dw, idx, val, targets)
    return RegressionState(*out)


@functools.partial(jax.jit, static_argnames=("mesh", "axis"))
def regression_estimate(mesh: Mesh, state, idx: jax.Array, val: jax.Array,
                        axis: str = DEFAULT_AXIS) -> jax.Array:
    """Feature-sharded batch estimates: [B], one psum of the per-shard
    partial dot products."""
    def body(w, dw, idx, val):
        d_local = w.shape[0]
        li, lv, _ = _owned(idx, val, d_local, axis)
        eff = jnp.take(w, li) + jnp.take(dw, li)
        return jax.lax.psum(jnp.einsum("bk,bk->b", eff, lv), axis)

    spec = P(axis)
    return shard_map(
        body, mesh=mesh,
        in_specs=(spec, spec, P(), P()),
        out_specs=P(),
        check_vma=False,
    )(state.w, state.dw, idx, val)


# -- per-shard diff chunking (mix-plane integration) -------------------------

def shard_chunks(arr, rows: Optional[int] = None) -> Dict[str, np.ndarray]:
    """A feature-sharded array as per-shard HOST chunks keyed by start
    column (``c0``, ``c<D/S>``, ...). Each shard's slice copies
    device→host independently — the full matrix is never materialized
    in one buffer, and each chunk enters the mix pipeline (tiered +
    quantized, PR 1/6/9) on its own. ``rows`` trims to the active label
    rows (the wire cut the classifier mixable already makes)."""
    out: Dict[str, np.ndarray] = {}
    for sh in arr.addressable_shards:
        sl = sh.index[-1]
        start = sl.start or 0
        chunk = np.asarray(sh.data)
        if rows is not None and chunk.ndim == 2 and rows < chunk.shape[0]:
            chunk = chunk[:rows]
        out[f"c{start}"] = chunk
    return out


def is_chunked(leaf) -> bool:
    """Does this diff leaf carry the per-shard chunk wire shape?"""
    return isinstance(leaf, dict) and leaf and \
        all(isinstance(k, (str, bytes))
            and (k.decode() if isinstance(k, bytes) else k).startswith("c")
            for k in leaf)


def assemble_chunks(chunks: Dict[str, np.ndarray], sharding) -> jax.Array:
    """Per-shard wire chunks back to one feature-sharded device array
    (the receive half of ``shard_chunks``): each chunk is placed
    directly on its owning shard's device — no host concatenation of
    the full matrix, no device gather. Raises ValueError on a layout
    mismatch (a peer sharded differently — the mix must not fold
    misaligned columns)."""
    items = sorted(
        ((int((k.decode() if isinstance(k, bytes) else k)[1:]), np.asarray(v))
         for k, v in chunks.items()),
        key=lambda kv: kv[0])
    widths = [v.shape[-1] for _, v in items]
    total = sum(widths)
    mesh = sharding.mesh
    devices = list(mesh.devices.flat)
    if len(items) != len(devices):
        raise ValueError(
            f"shard layout mismatch: {len(items)} wire chunks for a "
            f"{len(devices)}-shard mesh (peers must share one "
            "--shard-devices/--shard-features layout)")
    expect = 0
    for (start, v), dev in zip(items, devices):
        if start != expect:
            raise ValueError(
                f"shard layout mismatch: chunk starts at column {start}, "
                f"expected {expect}")
        expect += v.shape[-1]
    shape = items[0][1].shape[:-1] + (total,)
    return jax.make_array_from_single_device_arrays(
        shape, sharding,
        [jax.device_put(v, dev) for (_, v), dev in zip(items, devices)])


def chunk_sharding(mesh: Mesh, rank: int = 2,
                   axis: str = DEFAULT_AXIS) -> NamedSharding:
    """The trailing-dim feature sharding ``assemble_chunks`` re-places
    into (rank 2 for [L, D] tables, 1 for [D] vectors)."""
    return NamedSharding(mesh, P(*([None] * (rank - 1)), axis))
