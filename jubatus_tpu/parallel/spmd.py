"""SPMD multi-chip training step: data-parallel replicas × feature-sharded
weight tables.

This is the pod-scale execution path for the linear engines. The mesh has two
axes (parallel/mesh.py):

- ``replica`` (dp): each replica trains on its own microbatch stream — the
  reference's N servers (SURVEY.md §0). The mix is a psum of diffs over this
  axis.
- ``shard`` (tp): the hashed feature dimension D is sharded, so each chip
  holds [L, D/S] of every label row — the reference's CHT key-space
  partitioning (cht.cpp:107-143) as static mesh placement. Scores are
  computed as shard-local partial dot products psum'd over ``shard`` —
  collectives ride ICI.

All computation is inside one shard_map'd jitted step: per-replica vectorized
train (same math as ops/classifier.train_batch_parallel), optionally followed
by the mix collective — so a mix round costs one AllReduce, no host round
trips (the north-star design, SURVEY.md §2.2).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jubatus_tpu.parallel._compat import shard_map

from jubatus_tpu.ops.classifier import (
    CONFIDENCE_METHODS,
    decide_updates,
)


def _state_pspec(mesh: Mesh) -> P:
    return P("replica", None, "shard") if "shard" in mesh.axis_names else P("replica")


class SpmdClassifierState(NamedTuple):
    """Stacked-over-replicas classifier state.

    w, dw, prec, dprec: [R, L, D] — sharded P('replica', None, 'shard').
    """

    w: jax.Array
    dw: jax.Array
    prec: jax.Array
    dprec: jax.Array


def init_spmd_state(
    mesh: Mesh, num_labels: int, dim: int, confidence: bool = True
) -> SpmdClassifierState:
    r = mesh.shape["replica"]
    spec = NamedSharding(mesh, _state_pspec(mesh))
    shape = (r, num_labels, dim)
    zeros = jax.device_put(jnp.zeros(shape, jnp.float32), spec)
    ones = jax.device_put(jnp.ones(shape, jnp.float32), spec)
    return SpmdClassifierState(
        w=zeros, dw=zeros, prec=ones if confidence else zeros, dprec=zeros
    )


def make_spmd_train_step(mesh: Mesh, *, method: str = "AROW", param: float = 1.0,
                         mix: bool = True):
    """Build the jitted multi-chip train(+mix) step.

    Returned fn: (state, idx [R,B,K], val [R,B,K], labels [R,B],
    label_mask [L]) -> state. Batch arrays are sharded over 'replica';
    label_mask is replicated.
    """
    confidence = method in CONFIDENCE_METHODS
    n_shards = mesh.shape.get("shard", 1)
    n_replicas = mesh.shape["replica"]

    def _shard_psum(x):
        return jax.lax.psum(x, "shard") if n_shards > 1 else x

    def body(w, dw, prec, dprec, idx, val, labels, label_mask):
        # local leaves: w [1, L, Dl]; idx/val [1, B, K]; labels [1, B]
        w, dw, prec, dprec = w[0], dw[0], prec[0], dprec[0]
        idx, val, labels = idx[0], val[0], labels[0]
        d_local = w.shape[1]
        lo = jax.lax.axis_index("shard") * d_local if n_shards > 1 else 0
        li_raw = idx - lo
        owned = (li_raw >= 0) & (li_raw < d_local)
        li = jnp.where(owned, li_raw, 0)
        lv = jnp.where(owned, val, 0.0)  # unowned features contribute 0 here

        # partial scores from the local feature shard, reduced over ICI
        eff = w + dw
        g = jnp.take(eff, li, axis=1)                      # [L, B, K]
        s = _shard_psum(jnp.einsum("lbk,bk->bl", g, lv))
        x2_vec_l = lv * lv
        x2 = _shard_psum(jnp.sum(x2_vec_l, axis=1))

        if confidence:
            p = prec + dprec
            pg = jnp.take(p, li, axis=1)                   # [L, B, K]
            p_c = jnp.take_along_axis(pg, labels[None, :, None], axis=0)[0]
            sig_c = jnp.where(owned, 1.0 / p_c, 0.0)
            # first pass only to identify the competing label for sigma_w
            wrong0, _, _, _ = decide_updates(
                s, labels, label_mask, x2, jnp.zeros_like(x2), x2_vec_l,
                param, method=method,
            )
            p_w = jnp.take_along_axis(pg, wrong0[None, :, None], axis=0)[0]
            # nonexistent rival carries the unit precision prior
            no_rival = jnp.sum(label_mask) < 2
            sig_w = jnp.where(owned, jnp.where(no_rival, 1.0, 1.0 / p_w), 0.0)
            v = _shard_psum(jnp.sum((sig_c + sig_w) * x2_vec_l, axis=1))
        else:
            sig_c = sig_w = jnp.where(owned, 1.0, 0.0)
            v = jnp.zeros_like(x2)

        # the one shared decision kernel (ops/classifier.decide_updates)
        wrong, alpha, alpha_w, dp = decide_updates(
            s, labels, label_mask, x2, v, x2_vec_l, param, method=method
        )

        up_c = alpha[:, None] * sig_c * lv
        up_w = alpha_w[:, None] * sig_w * lv
        dw = dw.at[labels[:, None], li].add(jnp.where(owned, up_c, 0.0))
        dw = dw.at[wrong[:, None], li].add(jnp.where(owned, -up_w, 0.0))
        if confidence:
            dp = jnp.where(owned, dp, 0.0)
            dprec = dprec.at[labels[:, None], li].add(dp)
            dprec = dprec.at[wrong[:, None], li].add(
                jnp.where((alpha_w > 0.0)[:, None], dp, 0.0)
            )

        if mix:
            # THE mix round: one AllReduce over the replica axis
            total_dw = jax.lax.psum(dw, "replica")
            w = w + total_dw / n_replicas
            dw = jnp.zeros_like(dw)
            if confidence:
                total_dp = jax.lax.psum(dprec, "replica")
                prec = prec + total_dp
                dprec = jnp.zeros_like(dprec)

        return (w[None], dw[None], prec[None], dprec[None])

    state_spec = _state_pspec(mesh)
    batch_spec = P("replica")

    @jax.jit
    def step(state: SpmdClassifierState, idx, val, labels, label_mask):
        out = shard_map(
            body,
            mesh=mesh,
            in_specs=(state_spec, state_spec, state_spec, state_spec,
                      batch_spec, batch_spec, batch_spec, P()),
            out_specs=(state_spec, state_spec, state_spec, state_spec),
        )(state.w, state.dw, state.prec, state.dprec, idx, val, labels, label_mask)
        return SpmdClassifierState(*out)

    return step
