"""Built-in fv_converter plugins (≙ plugin/src/fv_converter/).

Loaded by name or path through the dynamic type mechanism
(jubatus_tpu.core.fv.plugins). The reference ships three:

- ``mecab_splitter``  — Japanese morphological tokenizer (needs MeCab)
- ``ux_splitter``     — dictionary keyword extraction (trie scan)
- ``image_feature``   — image descriptors over binary values (needs OpenCV)

Each module exposes ``create(params)`` like the reference's
``extern "C" create`` (mecab_splitter.cpp:203-230).
"""
