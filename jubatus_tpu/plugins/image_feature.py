"""Image feature extractor (≙ plugin/src/fv_converter/image_feature.cpp) —
binary-rule plugin over image bytes, wraps OpenCV when installed.

config:
    "binary_types": {"image": {"method": "dynamic", "path": "image_feature",
                               "function": "create", "algorithm": "orb",
                               "resize": "true", "width": "64",
                               "height": "64"}},
    "binary_rules": [{"key": "image", "type": "image"}]

``orb`` emits the pooled ORB descriptor (256 dims, mean over keypoints);
``dense`` emits the resized grayscale pixel grid (the reference's RANDOM
dense sampler reduces to fixed-grid patches).
Feature names: ``<key>$<algorithm>/<i>``.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Tuple


class ImageFeature:
    def __init__(self, algorithm: str = "orb", resize: bool = False,
                 width: int = 64, height: int = 64) -> None:
        try:
            import cv2  # noqa: PLC0415
            import numpy as np  # noqa: PLC0415
        except ImportError as e:  # pragma: no cover - env without opencv
            raise RuntimeError(
                "image_feature requires the 'opencv-python' package") from e
        self.cv2 = cv2
        self.np = np
        if algorithm not in ("orb", "dense"):
            raise ValueError(f"unknown image_feature algorithm {algorithm!r}")
        self.algorithm = algorithm
        self.resize = resize
        self.size = (int(width), int(height))

    def _decode(self, data: bytes):
        buf = self.np.frombuffer(data, dtype=self.np.uint8)
        img = self.cv2.imdecode(buf, self.cv2.IMREAD_GRAYSCALE)
        if img is None:
            raise ValueError("image_feature: cannot decode image bytes")
        if self.resize:
            img = self.cv2.resize(img, self.size)
        return img

    def extract(self, key: str, data: bytes) -> Iterable[Tuple[str, float]]:
        img = self._decode(data)
        out: List[Tuple[str, float]] = []
        if self.algorithm == "orb":
            orb = self.cv2.ORB_create()
            _kp, desc = orb.detectAndCompute(img, None)
            if desc is None or not len(desc):
                return out
            pooled = desc.astype("float32").mean(axis=0) / 255.0
            for i, v in enumerate(pooled):
                out.append((f"{key}$orb/{i}", float(v)))
        else:  # dense pixel grid
            grid = (img if self.resize
                    else self.cv2.resize(img, self.size)).astype("float32") / 255.0
            for i, v in enumerate(grid.reshape(-1)):
                out.append((f"{key}$dense/{i}", float(v)))
        return out


def create(params: Dict[str, str]) -> ImageFeature:
    return ImageFeature(
        algorithm=params.get("algorithm", "orb"),
        resize=params.get("resize", "false") == "true",
        width=int(params.get("width", "64")),
        height=int(params.get("height", "64")),
    )
