"""Japanese morphological tokenizer (≙ plugin/src/fv_converter/
mecab_splitter.cpp) — wraps MeCab when the binding is installed.

config (same params as the reference, mecab_splitter.cpp:203-230):
    {"method": "dynamic", "path": "mecab_splitter", "function": "create",
     "arg": "", "ngram": "1", "base": "false"}

``arg`` passes through to the MeCab tagger; ``ngram`` joins consecutive
surface forms into token n-grams; ``base`` emits base forms (feature
column 7) instead of surfaces.
"""

from __future__ import annotations

from typing import Dict, List


class MecabSplitter:
    def __init__(self, arg: str = "", ngram: int = 1, base: bool = False) -> None:
        try:
            import MeCab  # noqa: PLC0415
        except ImportError as e:  # pragma: no cover - env without mecab
            raise RuntimeError(
                "mecab_splitter requires the 'mecab-python3' package"
            ) from e
        self.tagger = MeCab.Tagger(arg)
        self.ngram = max(1, ngram)
        self.base = base

    def _tokens(self, text: str) -> List[str]:
        out = []
        node = self.tagger.parseToNode(text)
        while node is not None:
            if node.surface:
                if self.base:
                    feats = node.feature.split(",")
                    out.append(feats[6] if len(feats) > 6 and feats[6] != "*"
                               else node.surface)
                else:
                    out.append(node.surface)
            node = node.next
        return out

    def split(self, text: str) -> List[str]:
        toks = self._tokens(text)
        n = self.ngram
        if n == 1:
            return toks
        return ["".join(toks[i : i + n]) for i in range(len(toks) - n + 1)]


def create(params: Dict[str, str]) -> MecabSplitter:
    base_str = params.get("base", "false")
    if base_str not in ("true", "false"):
        raise ValueError("base must be a boolean value")
    return MecabSplitter(
        arg=params.get("arg", ""),
        ngram=int(params.get("ngram", "1")),
        base=base_str == "true",
    )
