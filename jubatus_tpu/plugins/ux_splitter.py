"""Dictionary keyword extractor (≙ plugin/src/fv_converter/ux_splitter.cpp).

The reference builds a ux-trie from a keyword file (one keyword per line)
and emits every occurrence of any dictionary keyword in the text. Here the
trie is a plain prefix map; matching is the same greedy scan over all
start offsets, emitting every dictionary hit (overlaps included).

config:
    {"method": "dynamic", "path": "ux_splitter", "function": "create",
     "dict_path": "/path/keywords.txt"}
"""

from __future__ import annotations

from typing import Dict, List


class _Trie:
    __slots__ = ("children", "terminal")

    def __init__(self) -> None:
        self.children: Dict[str, "_Trie"] = {}
        self.terminal = False

    def insert(self, word: str) -> None:
        node = self
        for ch in word:
            node = node.children.setdefault(ch, _Trie())
        node.terminal = True


class UxSplitter:
    def __init__(self, keywords: List[str]) -> None:
        self.root = _Trie()
        for kw in keywords:
            if kw:
                self.root.insert(kw)

    def split(self, text: str) -> List[str]:
        out: List[str] = []
        n = len(text)
        for start in range(n):
            node = self.root
            for i in range(start, n):
                node = node.children.get(text[i])
                if node is None:
                    break
                if node.terminal:
                    out.append(text[start : i + 1])
        return out


def create(params: Dict[str, str]) -> UxSplitter:
    dict_path = params.get("dict_path")
    if not dict_path:
        raise ValueError('ux_splitter needs "dict_path"')
    with open(dict_path, encoding="utf-8") as f:
        keywords = [line.rstrip("\r\n") for line in f]
    return UxSplitter(keywords)
