"""MessagePack-RPC transport (≙ jubatus/server/common/mprpc/, SURVEY.md §2.2).

Wire-compatible with the reference's msgpack-rpc dialect so existing jubatus
clients work unchanged:

- request  = [0, msgid, method, params]
- response = [1, msgid, error, result]
- notify   = [2, method, params]

Two planes (SURVEY.md §2.2 "TPU equivalent"):

- the *client/ingest plane* is this module: ``RpcServer`` (threaded dispatcher
  with typed invokers) and ``RpcClient`` / ``RpcMClient`` (parallel fan-out +
  reducer fold, the reference's rpc_mclient.hpp:261-312);
- the *mix plane* does NOT use RPC fan-out on a pod: it is an XLA collective
  (``jubatus_tpu.parallel.mix``). ``RpcMClient`` remains for multi-host
  control traffic and the degraded/elastic gossip path.
"""

from jubatus_tpu.rpc.errors import (  # noqa: F401
    BreakerOpen,
    DeadlineExceeded,
    RpcError,
    RpcMethodNotFound,
    RpcTypeError,
    RpcCallError,
    RpcIoError,
    RpcTimeoutError,
    RpcNoResult,
    RpcNoClient,
    HostError,
    MultiRpcError,
    is_retryable,
)
from jubatus_tpu.rpc.deadline import deadline_after  # noqa: F401
from jubatus_tpu.rpc.server import RpcServer  # noqa: F401
from jubatus_tpu.rpc.client import RpcClient, RpcMClient  # noqa: F401
