"""Fan-out reducers (≙ framework/aggregators.hpp:27-63).

Used by the proxy's broadcast/cht routes and by RpcMClient.call_fold. The IDL
decorators #@merge/#@concat/#@pass/#@all_and/#@all_or name these; `add`
exists in the reference's aggregator library (aggregators.hpp:51) but no
shipped .idl uses it.
"""

from __future__ import annotations

from typing import Any, Dict, List


def merge(a: Dict, b: Dict) -> Dict:
    out = dict(a)
    out.update(b)
    return out


def concat(a: List, b: List) -> List:
    return list(a) + list(b)


def pass_(a: Any, b: Any) -> Any:  # noqa: ARG001 — keep first, per reference
    return a


def add(a: Any, b: Any) -> Any:
    return a + b


def all_and(a: Any, b: Any) -> bool:
    return bool(a) and bool(b)


def all_or(a: Any, b: Any) -> bool:
    return bool(a) or bool(b)


BY_NAME = {
    "merge": merge,
    "concat": concat,
    "pass": pass_,
    "add": add,
    "all_and": all_and,
    "all_or": all_or,
}
