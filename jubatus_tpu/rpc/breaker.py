"""Per-backend circuit breakers (the proxy's self-healing routing core).

The reference's only reaction to a sick backend is cache invalidation
(proxy_common.cpp watch → re-read actives) — a backend that is REGISTERED
but limping (accepting connections, timing out calls) keeps receiving its
share of traffic and every request pays the full timeout. A breaker turns
repeated transport failures into an immediate routing decision:

- **closed** — traffic flows; failures land in a rolling window.
- **open** — ``failure_threshold`` transport failures inside
  ``window_sec`` trip the breaker: calls are refused instantly
  (``BreakerOpen``) for ``cooldown_sec``, so routing skips the backend
  and idempotent calls fail over to a healthy replica without burning a
  timeout each.
- **half-open** — after the cooldown, ONE probe call is admitted; its
  success closes the breaker (window cleared), its failure re-opens it
  for another cooldown. Probes are serialized (a thundering re-admit
  would re-melt a barely-recovered backend).

Only TRANSPORT failures count (``errors.is_retryable``): an application
error from a healthy backend proves the backend is alive and must not
open its breaker. State transitions bump counters in the owning
registry (``<prefix>_open`` / ``<prefix>_close``) and every decision
point fires a fault-injection site, so chaos tests can drive the state
machine deterministically.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Hashable, Optional

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """One backend's failure window + state machine. Thread-safe."""

    __slots__ = ("window_sec", "failure_threshold", "cooldown_sec",
                 "_lock", "_failures", "_state", "_opened_at",
                 "_probe_in_flight", "opened_total", "name",
                 "_half_open_evt")

    def __init__(self, *, window_sec: float = 30.0,
                 failure_threshold: int = 5,
                 cooldown_sec: float = 5.0, name: str = "") -> None:
        self.window_sec = float(window_sec)
        self.failure_threshold = int(failure_threshold)
        self.cooldown_sec = float(cooldown_sec)
        self.name = name
        self._lock = threading.Lock()
        self._failures: deque = deque()  # monotonic timestamps
        self._state = CLOSED  # no-event — initial state, not a transition
        self._opened_at = 0.0
        self._probe_in_flight = False
        self.opened_total = 0
        #: set on the open→half_open transition inside allow(); the
        #: owning BreakerBoard consumes it to emit the half_open event
        self._half_open_evt = False

    def _prune(self, now: float) -> None:
        horizon = now - self.window_sec
        while self._failures and self._failures[0] < horizon:
            self._failures.popleft()

    def allow(self) -> bool:
        """May a call be sent to this backend right now? Half-open grants
        exactly one in-flight probe; the caller MUST follow up with
        record_success/record_failure (probe bookkeeping depends on it)."""
        now = time.monotonic()
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if now - self._opened_at < self.cooldown_sec:
                    return False
                self._state = HALF_OPEN  # no-event — surfaced by the board
                self._probe_in_flight = True
                self._half_open_evt = True
                return True
            # HALF_OPEN: one probe at a time
            if self._probe_in_flight:
                return False
            self._probe_in_flight = True
            return True

    def available(self) -> bool:
        """Peek: would a call be routable here? Unlike ``allow`` this
        NEVER claims the half-open probe slot — use it to FILTER
        candidates, then ``allow`` only on the node actually called
        (an unclaimed probe slot would wedge the breaker half-open)."""
        return self.state != OPEN

    @property
    def state(self) -> str:
        # surface open→half_open lazily so status views don't show a
        # breaker as "open" past its cooldown
        with self._lock:
            if self._state == OPEN and \
                    time.monotonic() - self._opened_at >= self.cooldown_sec:
                return HALF_OPEN
            return self._state

    def pop_half_open(self) -> bool:
        """Consume the open→half_open transition flag (board-side event
        emission; at most one per transition)."""
        with self._lock:
            v = self._half_open_evt
            self._half_open_evt = False
            return v

    def record_success(self) -> bool:
        """Returns True when this success CLOSED a half-open breaker."""
        with self._lock:
            self._probe_in_flight = False
            if self._state in (HALF_OPEN, OPEN):
                # OPEN can still see a success: a call admitted before the
                # trip returning late — treat it as the probe's evidence
                self._state = CLOSED  # no-event — surfaced by the board
                self._failures.clear()
                return True
            return False

    def record_failure(self) -> bool:
        """Returns True when this failure OPENED the breaker."""
        now = time.monotonic()
        with self._lock:
            self._probe_in_flight = False
            if self._state == HALF_OPEN:
                self._state = OPEN  # no-event — surfaced by the board
                self._opened_at = now
                self.opened_total += 1
                return True
            self._failures.append(now)
            self._prune(now)
            if self._state == CLOSED and \
                    len(self._failures) >= self.failure_threshold:
                self._state = OPEN  # no-event — surfaced by the board
                self._opened_at = now
                self.opened_total += 1
                return True
            return False

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            state = self._state
            if state == OPEN and \
                    time.monotonic() - self._opened_at >= self.cooldown_sec:
                state = HALF_OPEN
            return {"state": state,
                    "failures_in_window": len(self._failures),
                    "opened_total": self.opened_total,
                    "window_sec": self.window_sec,
                    "failure_threshold": self.failure_threshold,
                    "cooldown_sec": self.cooldown_sec}


class BreakerBoard:
    """Breakers keyed by backend identity (host, port), sharing one
    config. Owned by a Proxy (counter prefix ``proxy.breaker``) or a
    mixer communication seam (``mix.breaker``); transitions count into
    the supplied tracing registry."""

    def __init__(self, *, window_sec: float = 30.0,
                 failure_threshold: int = 5, cooldown_sec: float = 5.0,
                 registry: Optional[Any] = None,
                 counter_prefix: str = "proxy.breaker") -> None:
        self.window_sec = window_sec
        self.failure_threshold = failure_threshold
        self.cooldown_sec = cooldown_sec
        self.registry = registry
        self.counter_prefix = counter_prefix
        self._lock = threading.Lock()
        self._breakers: Dict[Hashable, CircuitBreaker] = {}

    def get(self, key: Hashable) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(key)
            if b is None:
                b = self._breakers[key] = CircuitBreaker(
                    window_sec=self.window_sec,
                    failure_threshold=self.failure_threshold,
                    cooldown_sec=self.cooldown_sec, name=str(key))
            return b

    def _count(self, name: str) -> None:
        if self.registry is not None:
            self.registry.count(name)

    def _emit(self, etype: str, key: Hashable, severity: str = "info",
              **fields: Any) -> None:
        """One breaker state-transition event (ISSUE 14) into the
        owning registry's journal (proxy/mixer attribution) — or the
        process default journal for registry-less boards."""
        from jubatus_tpu.utils import events

        journal = self.registry.events if self.registry is not None \
            else events.default_journal()
        journal.emit("breaker", etype, severity=severity,
                     backend=str(key), plane=self.counter_prefix, **fields)

    def allow(self, key: Hashable) -> bool:
        from jubatus_tpu.utils import faults

        if faults.is_armed():
            faults.fire(f"breaker.allow.{key}")
        b = self.get(key)
        admitted = b.allow()
        if b.pop_half_open():
            self._emit("half_open", key)
        return admitted

    def available(self, key: Hashable) -> bool:
        """Peek (no probe claim) — candidate filtering."""
        return self.get(key).available()

    def record(self, key: Hashable, ok: bool) -> None:
        """Fold one call outcome into the backend's breaker; counts
        ``<prefix>_open`` on a trip and ``<prefix>_close`` on a
        half-open probe's success, emitting the matching breaker event."""
        b = self.get(key)
        if ok:
            if b.record_success():
                self._count(f"{self.counter_prefix}_close")
                self._emit("close", key)
        else:
            if b.record_failure():
                self._count(f"{self.counter_prefix}_open")
                self._emit("open", key, severity="warning",
                           opened_total=b.opened_total)

    def any_open(self) -> bool:
        with self._lock:
            breakers = list(self._breakers.values())
        return any(b.state == OPEN for b in breakers)

    def open_keys(self) -> list:
        with self._lock:
            items = list(self._breakers.items())
        return [k for k, b in items if b.state == OPEN]

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            items = list(self._breakers.items())
        return {str(k): b.snapshot() for k, b in items}
