"""MessagePack-RPC clients (≙ mprpc/rpc_mclient.{hpp,cpp} + client plumbing).

``RpcClient`` — one-host sync client with reconnect, msgid correlation, and
timeout (the reference's per-call msgpack-rpc session). Beyond the
reference: IDEMPOTENT calls (framework/idl.py's tables) retry on
transport failures (``RpcIoError``/``RpcTimeoutError``/injected faults)
with capped exponential backoff + full jitter, governed by a per-client
retry budget (rpc/retry.py) so a degraded cluster sees at most ~10%
retry amplification; and an active deadline (rpc/deadline.py) rides the
envelope as its optional 6th element, capping every attempt's socket
timeout at the remaining budget.

``RpcMClient`` — parallel fan-out: fire the same call at N hosts, then either
fold the results pairwise through a reducer (rpc_mclient.hpp:261-312 — this
fold IS the allreduce combiner the mix plane replaces with psum) or collect
per-host results+errors (rpc_result_object, rpc_mclient.hpp:314-318).
An optional breaker board (rpc/breaker.py) lets the fan-out skip hosts
whose circuit is open instead of burning a timeout on them every round.
"""

from __future__ import annotations

import contextlib
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, FrozenSet, List, Optional, Sequence, Tuple

import msgpack

from jubatus_tpu.framework.idl import CLIENT_SAFE_RETRY
from jubatus_tpu.rpc import deadline as deadlines
from jubatus_tpu.rpc import principal as principals
from jubatus_tpu.rpc.breaker import BreakerBoard
from jubatus_tpu.rpc.errors import (
    BreakerOpen,
    DeadlineExceeded,
    HostError,
    MultiRpcError,
    RpcIoError,
    RpcNoClient,
    RpcNoResult,
    RpcTimeoutError,
    is_retryable,
    wire_to_error,
)
from jubatus_tpu.rpc.retry import DEFAULT_POLICY, RetryBudget, RetryPolicy
from jubatus_tpu.rpc.server import REQUEST, RESPONSE, _to_wire
from jubatus_tpu.utils import faults, tracing, usage

#: transport-level failures an idempotent call may retry (FaultInjected
#: included: injected faults stand in for the IO errors they model)
_RETRYABLE = (RpcIoError, RpcTimeoutError, faults.FaultInjected)


class RpcClient:
    def __init__(self, host: str, port: int, timeout: float = 10.0, *,
                 retry_policy: Optional[RetryPolicy] = None,
                 retry_budget: Optional[RetryBudget] = None,
                 retry_methods: Optional[FrozenSet[str]] = None,
                 registry: Optional[tracing.Registry] = None) -> None:
        self.host = host
        self.port = port
        self.timeout = timeout
        #: retry plane: which methods are idempotent (engine-agnostic
        #: conservative table by default), how to back off, and the token
        #: bucket bounding retry amplification. Pass retry_methods=
        #: frozenset() to disable retries entirely (coord heartbeats etc.
        #: are simply not in the table, so they never retry anyway).
        self.retry_policy = retry_policy or DEFAULT_POLICY
        self.retry_budget = retry_budget or RetryBudget()
        self.retry_methods = (CLIENT_SAFE_RETRY if retry_methods is None
                              else retry_methods)
        self._registry = registry or tracing.default_registry()
        self._sock: Optional[socket.socket] = None
        self._msgid = 0
        # RLock: call() holds it and calls close() on failure paths
        self._lock = threading.RLock()

    # -- connection ----------------------------------------------------------
    def _connect(self) -> socket.socket:
        if self._sock is None:
            try:
                # injected connect faults take the same RpcIoError path a
                # refused/reset connection would — callers see the real
                # failure taxonomy
                if faults.is_armed():
                    faults.fire(f"rpc.connect.{self.host}:{self.port}")
                s = socket.create_connection((self.host, self.port), timeout=self.timeout)
            except (OSError, faults.FaultInjected) as e:
                raise RpcIoError(f"connect {self.host}:{self.port}: {e}") from e
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- retry plane ---------------------------------------------------------
    def _with_retries(self, method: str, once: Callable[[], Any]) -> Any:
        """Run ``once`` with the retry loop: idempotent methods retry on
        transport failures (budget-gated, jittered backoff, bounded by
        the remaining deadline); everything else propagates first error —
        a duplicate of an effectful call could double-apply."""
        retryable_method = method in self.retry_methods
        if retryable_method:
            self.retry_budget.deposit()
        attempt = 0
        while True:
            try:
                return once()
            except _RETRYABLE:
                attempt += 1
                if not retryable_method or \
                        attempt >= self.retry_policy.max_attempts:
                    raise
                rem = deadlines.remaining()
                if rem is not None and rem <= 0:
                    raise
                if not self.retry_budget.try_withdraw():
                    self._registry.count("rpc.retry_budget_exhausted")
                    raise
                self._registry.count("rpc.retries")
                # retry attribution (ISSUE 19): the server just sees
                # another request — only the client knows this attempt
                # is amplification, so bill it here
                usage.note_retry(method)
                sleep = self.retry_policy.sleep_for(attempt, rem)
                if sleep > 0:
                    time.sleep(sleep)

    def _effective_timeout(self, method: str) -> float:
        """Per-attempt socket timeout: the flat client timeout, tightened
        to the remaining deadline budget when one is active. Raises
        DeadlineExceeded pre-flight when the budget is already gone
        (sending work nobody can wait for wastes the backend)."""
        rem = deadlines.remaining()
        if rem is None:
            return self.timeout
        if rem <= 0:
            self._registry.count("rpc.deadline_expired")
            raise DeadlineExceeded(
                f"{method} @ {self.host}:{self.port}: "
                "deadline expired before send")
        return min(self.timeout, rem)

    def _timeout_error(self, method: str) -> Exception:
        """socket.timeout -> taxonomy: a timeout caused by the DEADLINE
        (not the flat client timeout) is the budget running out —
        DeadlineExceeded, not a retryable RpcTimeoutError."""
        if deadlines.expired():
            self._registry.count("rpc.deadline_expired")
            return DeadlineExceeded(
                f"{method} @ {self.host}:{self.port}: deadline expired")
        return RpcTimeoutError(f"{method} @ {self.host}:{self.port}")

    # -- calls ---------------------------------------------------------------
    def call(self, method: str, *args: Any) -> Any:
        return self._with_retries(method, lambda: self._call_once(method, args))

    def _call_once(self, method: str, args: Sequence[Any]) -> Any:
        # injection site (utils/faults.py): e.g. "rpc.call.mix_get_diff.*" —
        # the is_armed() guard keeps the disarmed hot path at one flag read.
        # Fired per ATTEMPT, so @N fault rules interact with retries the
        # way real transient failures would.
        if faults.is_armed():
            faults.fire(f"rpc.call.{method}.{self.host}:{self.port}")
        # trace context rides the envelope as an OPTIONAL 5th element
        # ({"t": trace_id, "s": span_id}), the remaining deadline budget
        # as an OPTIONAL 6th (seconds, float), the principal (tenant id)
        # as an OPTIONAL 7th (string). Each is attached only when this
        # thread carries one; plain client calls stay wire-identical to
        # msgpack-rpc. The wire element carries a fresh
        # CHILD span id — the call itself is a span (rpc.client.<method>
        # in this registry, so the forensics tree shows the hop's wire+
        # queue time between the caller's dispatch and the callee's)
        ctx = tracing.current_trace()
        child = tracing.child_of(ctx) if ctx is not None else None
        eff_timeout = self._effective_timeout(method)
        dl = deadlines.to_wire()
        pr = principals.to_wire()
        with self._lock:
            self._msgid = (self._msgid + 1) & 0xFFFFFFFF
            msgid = self._msgid
            env: list = [REQUEST, msgid, method, list(args)]
            # nil-pad earlier absent slots: the principal is positional
            # (7th), so a tagged-but-untraced call still ships
            # [.., None, None, principal]
            if child is not None or dl is not None or pr is not None:
                env.append(tracing.to_wire(child)
                           if child is not None else None)
            if dl is not None or pr is not None:
                env.append(dl)
            if pr is not None:
                env.append(pr)
            # surrogateescape: params a proxy forwards may hold surrogate-
            # bearing strings (legacy non-UTF8 raw decoded upstream); they
            # must re-encode to the original bytes, not raise pre-send
            payload = msgpack.packb(
                env, default=_to_wire,
                unicode_errors="surrogateescape"
            )
            sock = self._connect()
            try:
                with contextlib.ExitStack() as stk:
                    if child is not None:
                        stk.enter_context(tracing.use_trace(child))
                        stk.enter_context(
                            self._registry.span(f"rpc.client.{method}"))
                    sock.settimeout(eff_timeout)
                    sock.sendall(payload)
                    msg = self._read_response(sock, msgid)
            except socket.timeout as e:
                self.close()
                raise self._timeout_error(method) from e
            except OSError as e:
                self.close()
                raise RpcIoError(f"{method} @ {self.host}:{self.port}: {e}") from e
        _, _, error, result = msg
        if error is not None:
            raise wire_to_error(error, method)
        return result

    def call_raw(self, method: str, raw_params: bytes) -> bytes:
        """Forward ``raw_params`` (an already-encoded msgpack params
        object) and return the response's raw RESULT span — the proxy's
        zero-decode relay (≙ the reference proxy's C++ forwarding, which
        never materializes Python-level objects either, proxy.hpp:64-186).
        A non-nil error in the response raises the usual taxonomy (the
        caller falls back to the generic path for retry semantics)."""
        return self._with_retries(
            method, lambda: self._call_raw_once(method, raw_params))

    def _call_raw_once(self, method: str, raw_params: bytes) -> bytes:
        if faults.is_armed():
            faults.fire(f"rpc.call.{method}.{self.host}:{self.port}")
        ctx = tracing.current_trace()
        child = tracing.child_of(ctx) if ctx is not None else None
        eff_timeout = self._effective_timeout(method)
        dl = deadlines.to_wire()
        pr = principals.to_wire()
        with self._lock:
            self._msgid = (self._msgid + 1) & 0xFFFFFFFF
            msgid = self._msgid
            # method name deliberately encoded as str8 (valid modern
            # msgpack even for short names): the first relayed frame on a
            # pooled connection would otherwise fingerprint the BACKEND's
            # view of this connection from the CLIENT's bytes — a legacy-
            # era span could latch the shared connection legacy and
            # degrade other clients' responses. str8 pins it modern.
            mb = method.encode()
            # trailing elements: 5-element envelope with a trace span,
            # 6-element with trace + deadline, 7-element with trace +
            # deadline + principal (earlier absent slots pack nil — the
            # elements are positional and the backend splits them all
            # off the params span)
            n_extra = 3 if pr is not None else \
                (2 if dl is not None else (1 if child is not None else 0))
            env0 = bytes([0x94 + n_extra]) + b"\x00"
            head = (env0 + msgpack.packb(msgid)
                    + b"\xd9" + bytes([len(mb)]) + mb)
            bufs = [head, raw_params]
            if n_extra >= 1:
                bufs.append(msgpack.packb(tracing.to_wire(child))
                            if child is not None else b"\xc0")
            if n_extra >= 2:
                bufs.append(msgpack.packb(float(dl))
                            if dl is not None else b"\xc0")
            if n_extra == 3:
                bufs.append(msgpack.packb(pr))
            sock = self._connect()
            try:
                with contextlib.ExitStack() as stk:
                    if child is not None:
                        stk.enter_context(tracing.use_trace(child))
                        stk.enter_context(
                            self._registry.span(f"rpc.client.{method}"))
                    sock.settimeout(eff_timeout)
                    # scatter-gather: no head+params concat copy of a
                    # possibly multi-megabyte span (sendmsg may write
                    # short — finish with sendall on each remainder)
                    sent = sock.sendmsg(bufs)
                    if sent < sum(len(b) for b in bufs):
                        off = sent
                        for b in bufs:
                            if off >= len(b):
                                off -= len(b)
                                continue
                            sock.sendall(memoryview(b)[off:])
                            off = 0
                    frame = self._read_raw_response(sock, msgid, eff_timeout)
            except socket.timeout as e:
                self.close()
                raise self._timeout_error(method) from e
            except OSError as e:
                self.close()
                raise RpcIoError(f"{method} @ {self.host}:{self.port}: {e}") from e
        # frame = [1, msgid, error, result]; locate the error span
        from jubatus_tpu.rpc.server import _parse_response_envelope, \
            msgpack_span_end

        off = _parse_response_envelope(frame)
        err_end = msgpack_span_end(frame, off)
        if frame[off:err_end] != b"\xc0":
            error = msgpack.unpackb(frame[off:err_end], raw=False,
                                    unicode_errors="surrogateescape")
            raise wire_to_error(error, method)
        return frame[err_end:]

    def _read_raw_response(self, sock: socket.socket, msgid: int,
                           eff_timeout: Optional[float] = None) -> bytes:
        """Read one complete response frame as BYTES (no payload decode);
        frames are delimited with the C-speed skip. Out-of-order replies
        cannot happen here — call_raw holds the lock, so exactly one
        request is in flight."""
        framer = msgpack.Unpacker()
        buf = bytearray()
        sock.settimeout(eff_timeout if eff_timeout is not None
                        else self.timeout)
        while True:
            try:
                framer.skip()
                end = framer.tell()
                return bytes(buf[:end])
            except msgpack.OutOfData:
                pass
            data = sock.recv(65536)
            if not data:
                self.close()
                raise RpcIoError(f"connection closed by {self.host}:{self.port}")
            framer.feed(data)
            buf += data

    def notify(self, method: str, *args: Any) -> None:
        payload = msgpack.packb([2, method, list(args)], default=_to_wire,
                                unicode_errors="surrogateescape")
        with self._lock:
            sock = self._connect()
            try:
                sock.sendall(payload)
            except OSError as e:
                self.close()
                raise RpcIoError(str(e)) from e

    def _read_response(self, sock: socket.socket, msgid: int) -> Any:
        unpacker = msgpack.Unpacker(raw=False, strict_map_key=False,
                                    unicode_errors="surrogateescape")
        while True:
            data = sock.recv(65536)
            if not data:
                self.close()
                raise RpcIoError(f"connection closed by {self.host}:{self.port}")
            unpacker.feed(data)
            for msg in unpacker:
                if (
                    isinstance(msg, (list, tuple))
                    and len(msg) == 4
                    and msg[0] == RESPONSE
                    and msg[1] == msgid
                ):
                    return msg
                # stale response from a timed-out earlier call: drop it


class RpcMClient:
    """Parallel fan-out with reducer fold (≙ rpc_mclient).

    Keeps one persistent connection per host across calls (the reference's
    session_pool) — call ``close()`` when done, or use as a context manager.
    ``set_hosts`` reshapes the pool on membership change without dropping
    still-valid sessions. An optional ``breakers`` board short-circuits
    hosts whose circuit is open (their slot in the fan-out becomes an
    instant ``BreakerOpen`` host error) and re-admits them via half-open
    probes — the mix master stops paying a full timeout per round for a
    member that has been dead for minutes.
    """

    def __init__(
        self, hosts: Sequence[Tuple[str, int]], timeout: float = 10.0,
        breakers: Optional[BreakerBoard] = None,
    ) -> None:
        self.timeout = timeout
        self.breakers = breakers
        self._pool: dict = {}
        self.hosts: List[Tuple[str, int]] = []
        self._executor = ThreadPoolExecutor(
            max_workers=64, thread_name_prefix="rpc-fanout"
        )
        self.set_hosts(hosts)

    def set_hosts(self, hosts: Sequence[Tuple[str, int]]) -> None:
        if not hosts:
            raise RpcNoClient("empty host list")
        hosts = [tuple(h) for h in hosts]
        for hp in list(self._pool):
            if hp not in hosts:
                self._pool.pop(hp).close()
        self.hosts = list(hosts)

    def close(self) -> None:
        for c in self._pool.values():
            c.close()
        self._pool.clear()
        self._executor.shutdown(wait=False)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _client(self, hp: Tuple[str, int]) -> RpcClient:
        c = self._pool.get(hp)
        if c is None:
            c = self._pool[hp] = RpcClient(hp[0], hp[1], self.timeout)
        return c

    def _fan_out(self, method: str, args: Sequence[Any]):
        results: List[Tuple[Tuple[str, int], Any]] = []
        errors: List[HostError] = []
        # the fan-out hops threads: carry the caller's trace context,
        # deadline AND principal into the executor so every per-host
        # call ships the same trace_id (a mix round's get_diff spans
        # assemble under the round's trace), derives its timeout from
        # the shared budget, and bills to the same tenant
        ctx = tracing.current_trace()
        dl = deadlines.current()
        pr = principals.current()

        def one(hp: Tuple[str, int]):
            with tracing.use_trace(ctx), deadlines.use(dl), \
                    principals.use(pr):
                return self._client(hp).call(method, *args)

        futs = {}
        for hp in self.hosts:
            if self.breakers is not None and not self.breakers.allow(hp):
                # open circuit: instant failure, no timeout burned — the
                # caller's skip/abort semantics see it like a dead host
                errors.append(HostError(
                    hp[0], hp[1], BreakerOpen(f"{hp[0]}:{hp[1]}")))
                continue
            futs[self._executor.submit(one, hp)] = hp
        for fut, hp in futs.items():
            try:
                results.append((hp, fut.result()))
                if self.breakers is not None:
                    self.breakers.record(hp, True)
            except Exception as e:  # broad-ok — per-host failure is data
                errors.append(HostError(hp[0], hp[1], e))
                if self.breakers is not None:
                    # only transport failures count against the breaker:
                    # an application error proves the backend is alive
                    self.breakers.record(hp, not is_retryable(e))
        return results, errors

    def call_fold(
        self,
        method: str,
        *args: Any,
        reducer: Callable[[Any, Any], Any],
    ) -> Any:
        """Fold all successful results pairwise left-to-right
        (rpc_mclient::join_ — '(4+(3+(2+1)))' order per linear_mixer_test)."""
        results, errors = self._fan_out(method, args)
        if not results:
            raise MultiRpcError(errors) if errors else RpcNoResult(method)
        acc = results[0][1]
        for _, r in results[1:]:
            acc = reducer(acc, r)
        return acc

    def call_collect(self, method: str, *args: Any):
        """Raw per-host results + errors (≙ rpc_result_object)."""
        return self._fan_out(method, args)
