"""Deadline propagation — the request plane's time budget.

A caller sets a deadline for a whole operation; every hop it fans into
(client → proxy → server → peer) inherits the REMAINING budget instead of
its own flat timeout, and servers reject work whose budget is already
gone at dispatch (``DeadlineExceeded``, counted as
``rpc.deadline_rejected``) rather than computing an answer nobody is
waiting for. This is the piece the reference never had: its per-session
timeouts compound across hops (client 10 s over a proxy whose backend
call gets 10 s *again*), so a slow backend burns 2x the caller's patience.

Mechanics mirror PR 2's trace context exactly:

- **in-process**: a thread-local ABSOLUTE monotonic deadline
  (``time.monotonic()`` domain — wall-clock is not usable across NTP
  steps). ``deadline_after(seconds)`` opens a scope; nested scopes can
  only tighten (min), never extend.
- **on the wire**: the envelope's OPTIONAL 6th element carries the
  REMAINING budget in seconds (a float — relative, like gRPC's
  grpc-timeout, because hosts share no clock). The receiver re-anchors it
  against its own monotonic clock; transit latency is therefore not
  charged, which errs toward doing work rather than dropping it.
- both transports adopt it in dispatch exactly like the trace element;
  the C++ front-end relays 6-element frames verbatim.

``swap`` is the primitive for dispatch pools (threads are reused — a
leaked deadline would time out the NEXT request); ``use`` / ``after`` are
the context-manager forms.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Iterator, Optional

_tls = threading.local()

#: clamp for wire values: a deadline further out than this (or NaN/inf,
#: or from a confused clock) is treated as "effectively none" rather than
#: scheduling work years ahead
MAX_WIRE_SECONDS = 3600.0


def current() -> Optional[float]:
    """This thread's absolute monotonic deadline, or None."""
    return getattr(_tls, "deadline", None)


def remaining() -> Optional[float]:
    """Seconds left in the budget (may be <= 0), or None when unlimited."""
    d = getattr(_tls, "deadline", None)
    return None if d is None else d - time.monotonic()


def expired() -> bool:
    d = getattr(_tls, "deadline", None)
    return d is not None and time.monotonic() >= d


def swap(deadline: Optional[float]) -> Optional[float]:
    """Install an absolute monotonic deadline; returns the previous one
    (restore in a finally — dispatch pool threads are reused)."""
    prev = getattr(_tls, "deadline", None)
    _tls.deadline = deadline
    return prev


@contextlib.contextmanager
def use(deadline: Optional[float]) -> Iterator[None]:
    """Scope an ABSOLUTE deadline (None = explicitly unlimited)."""
    prev = swap(deadline)
    try:
        yield
    finally:
        swap(prev)


@contextlib.contextmanager
def deadline_after(seconds: float) -> Iterator[None]:
    """Scope a deadline ``seconds`` from now; nested scopes only tighten
    (the enclosing budget still binds — min, never max)."""
    mine = time.monotonic() + float(seconds)
    prev = current()
    if prev is not None:
        mine = min(mine, prev)
    with use(mine):
        yield


def to_wire() -> Optional[float]:
    """The remaining budget as the envelope's 6th element, or None when
    no deadline is active (the envelope then stays 4/5 elements — old
    peers never see a shape they don't know)."""
    rem = remaining()
    return None if rem is None else max(0.0, float(rem))


def adopt_wire(rem: Any) -> Optional[float]:
    """A wire remaining-seconds value -> absolute monotonic deadline on
    THIS host's clock; None for absent/garbage values (a malformed
    deadline must degrade to 'no deadline', never kill the dispatch)."""
    try:
        rem = float(rem)
    except (TypeError, ValueError):
        return None
    if not (0.0 <= rem <= MAX_WIRE_SECONDS):  # NaN fails this too
        if rem > MAX_WIRE_SECONDS:
            rem = MAX_WIRE_SECONDS
        elif rem < 0.0:
            rem = 0.0
        else:
            return None
    return time.monotonic() + rem
