"""RPC error taxonomy (≙ mprpc/rpc_error.hpp + mprpc/exception.hpp).

The reference maps msgpack-rpc failures to typed exceptions
(rpc_mclient.hpp:36-93 JUBATUS_MSGPACKRPC_EXCEPTION_DEFAULT_HANDLER); we keep
the same taxonomy so server/proxy code can branch on failure class, and the
same on-wire integer codes as the msgpack-rpc C++ implementation for
method-not-found (1) and argument errors (2) so reference clients see the
errors they expect.

Beyond the reference, every class carries a **retryable** axis: transport
failures where the request may simply be re-issued (`RpcIoError`,
`RpcTimeoutError`) are retryable — but only for IDEMPOTENT methods (the
idempotency tables live in framework/idl.py, the retry machinery in
rpc/retry.py); application errors from a healthy server and expired
deadlines are not. ``DeadlineExceeded`` gets its own on-wire code (3 — an
extension; legacy peers see an unknown code and map it to a generic call
error) so deadline rejections survive a proxy hop as themselves.
"""

from __future__ import annotations

from typing import Any, List, Tuple

#: on-wire error codes (msgpack-rpc convention, used by the reference servers)
NO_METHOD_ERROR = 1
ARGUMENT_ERROR = 2
#: extension code: the server refused/abandoned the call because its
#: deadline had already expired (rpc/deadline.py)
DEADLINE_EXCEEDED_ERROR = 3
#: extension codes (elastic membership, ISSUE 10): the server refused the
#: call BEFORE applying it — because it is draining (4) or because the
#: caller's ring view is from another membership epoch (5). Both are
#: safe to re-route (nothing was applied) and both tell the caller to
#: refresh its member/ring view first. Legacy peers see an unknown code
#: and map it to a generic call error.
NODE_DRAINING_ERROR = 4
EPOCH_MISMATCH_ERROR = 5


class RpcError(RuntimeError):
    """Base of all RPC failures (≙ mprpc/exception.hpp rpc_error).

    ``retryable``: True when the failure is a transport-level loss where
    the server may never have seen (or finished) the request — re-issuing
    it can succeed and, for idempotent methods, is safe.
    """

    retryable = False


class RpcMethodNotFound(RpcError):
    def __init__(self, method: str = "") -> None:
        super().__init__(f"method not found: {method}")
        self.method = method


class RpcTypeError(RpcError):
    """Argument arity/type mismatch (≙ rpc_type_error)."""


class RpcCallError(RpcError):
    """Server raised while executing the method (≙ rpc_call_error)."""


class RpcIoError(RpcError):
    """Connection failed / reset mid-call (≙ rpc_io_error)."""

    retryable = True


class RpcTimeoutError(RpcError):
    """Call exceeded the client timeout (≙ rpc_timeout_error)."""

    retryable = True


class DeadlineExceeded(RpcError):
    """The call's propagated deadline expired (client pre-flight, server
    dispatch rejection, or proxy fan-out budget exhaustion). NOT
    retryable: the budget is gone — retrying would spend work the caller
    can no longer use."""


class BreakerOpen(RpcError):
    """A circuit breaker refused the call without touching the backend
    (rpc/breaker.py). Retryable against a DIFFERENT backend — the proxy's
    failover path treats it like an instantaneous IO failure."""

    retryable = True

    def __init__(self, target: str = "") -> None:
        super().__init__(f"circuit breaker open for {target}")
        self.target = target


class NodeDraining(RpcError):
    """The target refused an effectful call at dispatch because it is
    draining (elastic membership, ISSUE 10). The call was NEVER applied,
    so re-routing it to another replica is safe even for effectful
    methods — retryable, with a membership refresh first."""

    retryable = True

    def __init__(self, detail: str = "") -> None:
        super().__init__(detail or "node draining")


class EpochMismatch(RpcError):
    """Caller and callee disagree on the membership epoch — the caller's
    ring view is stale (or the callee's is). Rejected BEFORE any state
    change: refresh the ring and re-route. Retryable for the same
    reason as NodeDraining."""

    retryable = True

    def __init__(self, detail: str = "", expected: int = 0,
                 got: int = 0) -> None:
        super().__init__(
            detail or f"membership epoch mismatch (mine {expected}, "
                      f"caller {got})")
        self.expected = expected
        self.got = got


class RpcNoResult(RpcError):
    """Fan-out completed but produced no usable result (≙ rpc_no_result)."""


class RpcNoClient(RpcError):
    """No host reachable for a fan-out call (≙ rpc_no_client)."""


class HostError(RpcError):
    """One host's failure inside a fan-out (≙ rpc_error{host, port, exc})."""

    def __init__(self, host: str, port: int, cause: BaseException) -> None:
        super().__init__(f"{host}:{port}: {cause}")
        self.host = host
        self.port = port
        self.cause = cause


class MultiRpcError(RpcError):
    """Aggregate of per-host failures (≙ error_multi_rpc)."""

    def __init__(self, errors: List[HostError]) -> None:
        super().__init__("; ".join(str(e) for e in errors) or "all hosts failed")
        self.errors = errors


def is_retryable(exc: BaseException) -> bool:
    """Transport-level failure where a retry can succeed. Injected faults
    (utils/faults.py) count: they stand in for the IO errors they model."""
    if isinstance(exc, RpcError):
        return exc.retryable
    from jubatus_tpu.utils import faults

    return isinstance(exc, (faults.FaultInjected, OSError))


def error_to_wire(exc: BaseException) -> Any:
    """Server-side: map an exception to the response 'error' field."""
    if isinstance(exc, RpcMethodNotFound):
        return NO_METHOD_ERROR
    if isinstance(exc, (RpcTypeError, TypeError)):
        return ARGUMENT_ERROR
    if isinstance(exc, DeadlineExceeded):
        return DEADLINE_EXCEEDED_ERROR
    if isinstance(exc, NodeDraining):
        return NODE_DRAINING_ERROR
    if isinstance(exc, EpochMismatch):
        return EPOCH_MISMATCH_ERROR
    return str(exc)


def wire_to_error(err: Any, method: str = "") -> RpcError:
    """Client-side: map the response 'error' field to a typed exception."""
    if err == NO_METHOD_ERROR:
        return RpcMethodNotFound(method)
    if err == ARGUMENT_ERROR:
        return RpcTypeError(f"argument error calling {method}")
    if err == DEADLINE_EXCEEDED_ERROR:
        return DeadlineExceeded(f"{method}: deadline exceeded at server")
    if err == NODE_DRAINING_ERROR:
        return NodeDraining(f"{method}: node draining")
    if err == EPOCH_MISMATCH_ERROR:
        return EpochMismatch(f"{method}: membership epoch mismatch")
    return RpcCallError(f"{method}: {err!r}")
