"""Legacy (pre-str8/bin) msgpack decoder — the old clients' view of the wire.

The reference vendors a msgpack that predates the 2013 str8/bin/ext type
additions (see /root/reference/jubatus/client/common/client.hpp:30-87 — the
client links jubatus_msgpack-rpc whose unpacker rejects unknown type bytes;
our C++ client template documents the same constraint,
codegen/templates/jubatus_tpu_client.hpp:16-19). A server that answers with
str8 (0xd9) or bin (0xc4-0xc6) bytes breaks every deployed jubatus client
with any string >= 32 bytes (e.g. get_config).

This module reproduces that old unpacker *faithfully, including the
rejection*: any post-2013 type byte raises ``LegacyFormatError``. Tests use
it to prove that responses emitted in legacy wire mode
(``rpc.server.build_response(..., legacy=True)``) parse under the old
format; it also documents exactly which type bytes are forbidden.

Old-format mapping: str and bytes are both "raw" (fixraw/raw16/raw32) and
decode to ``bytes`` here — exactly what the old C++ client sees
(std::string of bytes).
"""

from __future__ import annotations

import struct
from typing import Any, Tuple

#: type bytes that did not exist in pre-2013 msgpack: bin8/16/32
#: (0xc4-0xc6), ext8/16/32 (0xc7-0xc9), fixext1..16 (0xd4-0xd8),
#: str8 (0xd9); 0xc1 has never been assigned.
FORBIDDEN_TYPE_BYTES = frozenset(
    {0xC1} | set(range(0xC4, 0xCA)) | set(range(0xD4, 0xDA))
)


class LegacyFormatError(ValueError):
    """Wire bytes a legacy jubatus client cannot parse."""


def unpackb(buf: bytes) -> Any:
    """Decode one msgpack object the way the old vendored library did."""
    obj, off = _decode(memoryview(buf), 0)
    if off != len(buf):
        raise LegacyFormatError(f"{len(buf) - off} trailing bytes")
    return obj


#: payload byte lengths of the fixed-width legacy scalar types
_SCALAR_WIDTH = {0xC0: 0, 0xC2: 0, 0xC3: 0, 0xCA: 4, 0xCB: 8, 0xCC: 1,
                 0xCD: 2, 0xCE: 4, 0xCF: 8, 0xD0: 1, 0xD1: 2, 0xD2: 4,
                 0xD3: 8}


def scan_is_legacy(buf: bytes, budget: int = 1 << 14) -> bool:
    """Walk ONE msgpack object's type bytes without building any values:
    True iff every type byte seen existed in pre-2013 msgpack (i.e. a
    vendored-msgpack client could have produced the buffer). This is the
    skip-style fingerprint the servers run per request while a connection
    is provisionally legacy — unpackb would construct a multi-megabyte
    object tree just to throw it away on bulk train calls.

    ``budget`` caps the walk at that many type bytes; on exhaustion the
    verdict is True ("no modern evidence so far") — sound, because a
    vendored client can never emit a modern byte ANYWHERE, so sampling a
    prefix can only delay a modern client's upgrade to a later (usually
    small) request, never mislabel a legacy one. Keeps the per-request
    cost on bulk ingest O(budget), not O(elements)."""
    b = memoryview(buf)
    n = len(b)
    i = 0
    remaining = 1  # objects still to skip
    while remaining:
        budget -= 1
        if budget < 0:
            return True  # prefix shows no modern byte; cost cap reached
        if i >= n:
            return False  # truncated: not a well-formed legacy object
        t = b[i]
        i += 1
        remaining -= 1
        if t <= 0x7F or t >= 0xE0:
            continue
        if 0x80 <= t <= 0x8F:          # fixmap
            remaining += (t & 0x0F) * 2
        elif 0x90 <= t <= 0x9F:        # fixarray
            remaining += t & 0x0F
        elif 0xA0 <= t <= 0xBF:        # fixraw
            i += t & 0x1F
        elif t in _SCALAR_WIDTH:
            i += _SCALAR_WIDTH[t]
        elif t == 0xDA or t == 0xDB:   # raw16/32
            w = 2 if t == 0xDA else 4
            if i + w > n:
                return False
            i += w + int.from_bytes(b[i:i + w], "big")
        elif t in (0xDC, 0xDD, 0xDE, 0xDF):  # array16/32, map16/32
            w = 2 if t in (0xDC, 0xDE) else 4
            if i + w > n:
                return False
            count = int.from_bytes(b[i:i + w], "big")
            if count > n - i:  # cannot possibly fit: hostile/corrupt
                return False
            i += w
            remaining += count * (2 if t in (0xDE, 0xDF) else 1)
        else:
            return False  # post-2013 type byte (or reserved)
    return i == n


def _unpack(fmt: str, b: memoryview, i: int):
    """struct.unpack_from with the truncation contract this module
    documents: short input is LegacyFormatError, never struct.error
    (the streaming framing loop in clients keys on 'truncated')."""
    if i + struct.calcsize(fmt) > len(b):
        raise LegacyFormatError("truncated input")
    return struct.unpack_from(fmt, b, i)[0]


def _raw(b: memoryview, i: int, n: int) -> Tuple[bytes, int]:
    if i + n > len(b):
        raise LegacyFormatError("truncated raw")
    return bytes(b[i:i + n]), i + n


def _arr(b: memoryview, i: int, n: int) -> Tuple[list, int]:
    out = []
    for _ in range(n):
        v, i = _decode(b, i)
        out.append(v)
    return out, i


def _map(b: memoryview, i: int, n: int) -> Tuple[dict, int]:
    out = {}
    for _ in range(n):
        k, i = _decode(b, i)
        v, i = _decode(b, i)
        out[k] = v
    return out, i


def _decode(b: memoryview, i: int) -> Tuple[Any, int]:
    if i >= len(b):
        raise LegacyFormatError("truncated input")
    t = b[i]
    i += 1
    if t <= 0x7F:                      # positive fixint
        return t, i
    if t >= 0xE0:                      # negative fixint
        return t - 0x100, i
    if 0x80 <= t <= 0x8F:              # fixmap
        return _map(b, i, t & 0x0F)
    if 0x90 <= t <= 0x9F:              # fixarray
        return _arr(b, i, t & 0x0F)
    if 0xA0 <= t <= 0xBF:              # fixraw
        return _raw(b, i, t & 0x1F)
    if t in FORBIDDEN_TYPE_BYTES:
        raise LegacyFormatError(
            f"type byte 0x{t:02x} does not exist in legacy msgpack")
    if t == 0xC0:
        return None, i
    if t == 0xC2:
        return False, i
    if t == 0xC3:
        return True, i
    if t == 0xCA:
        return _unpack(">f", b, i), i + 4
    if t == 0xCB:
        return _unpack(">d", b, i), i + 8
    if t == 0xCC:
        if i >= len(b):
            raise LegacyFormatError("truncated input")
        return b[i], i + 1
    if t == 0xCD:
        return _unpack(">H", b, i), i + 2
    if t == 0xCE:
        return _unpack(">I", b, i), i + 4
    if t == 0xCF:
        return _unpack(">Q", b, i), i + 8
    if t == 0xD0:
        return _unpack(">b", b, i), i + 1
    if t == 0xD1:
        return _unpack(">h", b, i), i + 2
    if t == 0xD2:
        return _unpack(">i", b, i), i + 4
    if t == 0xD3:
        return _unpack(">q", b, i), i + 8
    if t == 0xDA:                      # raw16
        n = _unpack(">H", b, i)
        return _raw(b, i + 2, n)
    if t == 0xDB:                      # raw32
        n = _unpack(">I", b, i)
        return _raw(b, i + 4, n)
    if t == 0xDC:                      # array16
        n = _unpack(">H", b, i)
        return _arr(b, i + 2, n)
    if t == 0xDD:                      # array32
        n = _unpack(">I", b, i)
        return _arr(b, i + 4, n)
    if t == 0xDE:                      # map16
        n = _unpack(">H", b, i)
        return _map(b, i + 2, n)
    if t == 0xDF:                      # map32
        n = _unpack(">I", b, i)
        return _map(b, i + 4, n)
    raise LegacyFormatError(f"unhandled type byte 0x{t:02x}")
