"""Native-transport RPC server: the C++ front-end (native/rpc_frontend.cpp)
owns sockets, buffering, and msgpack framing; Python owns dispatch and
response serialization — the same split as the reference, whose transport
plane (mpio event loop + msgpack-rpc framing) is C++ under C++ handlers
(SURVEY.md §2.2).

``NativeRpcServer`` is interface-compatible with ``RpcServer`` (register /
listen / start / serve_background / stop / port / trace); it is the
DEFAULT transport (``JUBATUS_TPU_NATIVE_RPC=0`` forces the Python one).
Requests arrive via a ctypes callback carrying (conn, msgid, method, raw
params span). SMALL requests dispatch inline on the connection's reader
thread (lowest latency for sync clients); BULK requests (params >=
_POOL_THRESHOLD) dispatch on a worker pool so a PIPELINED connection's
queued train calls are all in flight at once and join the same device
flush. Either way responses are msgid-correlated and per-connection
request ordering is NOT guaranteed — the same msgpack-rpc pipelining
contract as the Python transport's worker pool (rpc/server.py docstring).

Measured on the shared single-core host (pre-encoded pipelined clients,
same-process A/B): the C++ framing + bulk pool beats the Python
transport ~1.1-1.2x; round-2's inline-only design LOST that A/B under
pipelining because one blocked reader capped each connection at one
in-flight request.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading
from typing import Any, Callable, Dict, Optional

import msgpack

from jubatus_tpu import native as native_build
from jubatus_tpu.rpc.errors import error_to_wire
from jubatus_tpu.rpc.server import RpcServer, build_response
from jubatus_tpu.utils.tracing import Registry

log = logging.getLogger(__name__)

# method is POINTER(c_char), NOT c_char_p: the span is not NUL-terminated
# (params bytes follow immediately) and c_char_p would strlen past it.
# Trailing c_int32: envelope flags — bit 0: the C++ framer saw a str8
# method name, proof of a post-2013 client (RpcClient.call_raw's era
# pin); bit 1: 5-element traced envelope (the params span ends with a
# trace element this side splits off).
_REQUEST_CB = ctypes.CFUNCTYPE(
    None, ctypes.c_uint64, ctypes.c_uint64, ctypes.POINTER(ctypes.c_char),
    ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
    ctypes.c_int32)

_lib_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _load_lib() -> Optional[ctypes.CDLL]:
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        src = os.path.join(native_build.NATIVE_DIR, "rpc_frontend.cpp")
        out = os.path.join(native_build.BUILD_DIR, "librpc_frontend.so")
        if not os.path.exists(src):
            return None
        if native_build._stale(src, out) and not native_build._compile(src, out):
            return None
        try:
            lib = ctypes.CDLL(out)
        except OSError:
            return None
        lib.jt_rpc_create.restype = ctypes.c_void_p
        lib.jt_rpc_create.argtypes = [_REQUEST_CB]
        lib.jt_rpc_listen.restype = ctypes.c_int
        lib.jt_rpc_listen.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                      ctypes.c_int, ctypes.c_int]
        lib.jt_rpc_respond.restype = ctypes.c_int
        lib.jt_rpc_respond.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                       ctypes.c_char_p, ctypes.c_int64]
        lib.jt_rpc_stop.restype = None
        lib.jt_rpc_stop.argtypes = [ctypes.c_void_p]
        lib.jt_rpc_destroy.restype = None
        lib.jt_rpc_destroy.argtypes = [ctypes.c_void_p]
        lib.jt_rpc_relay_config.restype = ctypes.c_int
        lib.jt_rpc_relay_config.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_double, ctypes.c_double]
        lib.jt_rpc_relay_stats.restype = ctypes.c_int64
        lib.jt_rpc_relay_stats.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                           ctypes.c_int64]
        _lib = lib
        return _lib


def available() -> bool:
    return _load_lib() is not None


class NativeRpcServer:
    """RpcServer drop-in over the C++ transport."""

    def __init__(self, timeout: float = 10.0,
                 trace: Optional[Registry] = None,
                 legacy_wire: bool = False,
                 wire_detect: bool = False) -> None:
        self._methods: Dict[str, Callable[..., Any]] = {}
        self._arity: Dict[str, Optional[int]] = {}
        self.legacy_wire = legacy_wire
        self.wire_detect = wire_detect
        #: conn_id -> first-request fingerprint ({"legacy": bool}).
        #: Entries die with their connection (the C++ front-end announces
        #: closes via the _CLOSE msgid sentinel); the size cap is only a
        #: backstop against >4096 LIVE connections, where an eviction
        #: costs a re-fingerprint on that connection's next request.
        self._conn_wire: Dict[int, dict] = {}
        self._wire_lock = threading.Lock()
        self._binary_methods: set = set()
        self._raw_methods: Dict[str, Callable[[bytes], Any]] = {}
        self.timeout = timeout
        self.trace = trace or Registry()
        self.port: Optional[int] = None
        #: bulk requests (>= _POOL_THRESHOLD bytes of params) dispatch on
        #: this pool instead of inline: inline blocks the connection's
        #: reader in co.submit, capping a PIPELINED client at one
        #: in-flight request — the pool lets a connection's queued train
        #: calls all join the same device flush (deeper coalescing).
        #: Small requests stay inline (the executor hop measured ~35%
        #: slower for ping-sized sync traffic).
        from concurrent.futures import ThreadPoolExecutor

        # 64, not 32: a PROXY's bulk handler BLOCKS its worker on the
        # backend round trip (call_raw), so the pool must cover the full
        # in-flight depth (16 pipelined clients x depth 4) or pipelining
        # silently halves at the relay tier; blocked threads are cheap
        self._bulk_pool = ThreadPoolExecutor(
            max_workers=64, thread_name_prefix="native-rpc-bulk")
        #: usage ledger (utils/usage.py, ISSUE 19) — same contract as
        #: RpcServer.usage_recorder (the borrowed _execute* note errors
        #: into it; the dispatch paths here note bytes)
        self.usage_recorder: Optional[Any] = None
        self._lib = _load_lib()
        if self._lib is None:
            raise RuntimeError("native rpc front-end unavailable (no g++?)")
        # keep the callback object alive for the server's lifetime
        self._cb = _REQUEST_CB(self._on_request)
        self._handle = self._lib.jt_rpc_create(self._cb)
        self._stopped = False

    # -- method table (same contract as RpcServer.register) ------------------
    register = RpcServer.register
    register_raw = RpcServer.register_raw
    method_names = RpcServer.method_names
    _invoke = RpcServer._invoke
    _execute = RpcServer._execute
    _execute_fast = RpcServer._execute_fast
    _check_deadline = RpcServer._check_deadline
    response_legacy = RpcServer.response_legacy

    # -- C++ → Python dispatch ------------------------------------------------
    def _on_request(self, conn_id, msgid, method, method_len, params_ptr,
                    params_len, envelope_flags) -> None:
        """Runs on the connection's C++ reader thread. Small requests
        dispatch INLINE (an executor hop measured ~35% slower for
        ping-sized sync traffic); bulk requests hop to the worker pool in
        _dispatch (see module docstring for the ordering contract)."""
        if msgid == self._CLOSE:
            with self._wire_lock:
                self._conn_wire.pop(conn_id, None)
            return
        try:
            method_name = ctypes.string_at(method, method_len).decode(
                "utf-8", "replace")
            raw = ctypes.string_at(params_ptr, params_len)  # copy the span
        except Exception:  # broad-ok — never raise into C++
            return
        try:
            self._dispatch(conn_id, msgid, method_name, raw,
                           int(envelope_flags))
        except Exception:  # broad-ok — never raise into C++
            log.exception("native rpc dispatch failed for %s", method_name)

    #: msgid sentinel the C++ side uses for notifications
    _NOTIFY = (1 << 64) - 1
    #: msgid sentinel the C++ side sends when a connection closes
    _CLOSE = (1 << 64) - 2
    #: params size from which raw requests dispatch on the bulk pool
    _POOL_THRESHOLD = 4096

    def _dispatch_fast_bulk(self, conn_id, msgid, method, raw,
                            conn_state, trace=None, dl=None,
                            pr=None) -> None:
        try:
            from jubatus_tpu.rpc import deadline as deadlines
            from jubatus_tpu.rpc import principal as principals
            from jubatus_tpu.utils import tracing

            prev = tracing.swap_trace(tracing.from_wire(trace))
            prev_dl = deadlines.swap(deadlines.adopt_wire(dl))
            p_req = principals.adopt_wire(pr)
            prev_pr = principals.swap(p_req)
            try:
                error, result = self._execute_fast(method, raw, conn_state)
            finally:
                tracing.swap_trace(prev)
                deadlines.swap(prev_dl)
                principals.swap(prev_pr)
            if self._stopped:
                return  # teardown: the C++ handle may be going away
            payload = build_response(
                msgid, error, result,
                legacy=self.response_legacy(method, conn_state))
            rec = self.usage_recorder
            if rec is not None:
                rec.account(method, principal=p_req, resolve=False,
                            bytes_in=float(len(raw)),
                            bytes_out=float(len(payload)))
            self._lib.jt_rpc_respond(self._handle, conn_id, payload,
                                     len(payload))
        except Exception:  # broad-ok — never die silently on the pool
            log.exception("native rpc bulk dispatch failed for %s", method)

    def _dispatch(self, conn_id: int, msgid: int, method: str,
                  raw: bytes, envelope_flags: int = 0) -> None:
        from jubatus_tpu.rpc import deadline as deadlines
        from jubatus_tpu.rpc import principal as principals
        from jubatus_tpu.utils import tracing

        envelope_modern = bool(envelope_flags & 1)
        trace = dl = pr = None
        nbytes = len(raw)
        if envelope_flags & 2:
            # extended (5/6/7-element) envelope: the C++ framer hands us
            # params [+ trace [+ deadline [+ principal]]] as one span;
            # split at the params boundary (rpc/server.py owns the walk)
            from jubatus_tpu.rpc.server import split_extras

            raw, trace, dl, pr = split_extras(raw, 0)
        conn_state = None
        if self.wire_detect and not self.legacy_wire:
            with self._wire_lock:
                conn_state = self._conn_wire.get(conn_id)
            if conn_state is None or conn_state.get("legacy"):
                from jubatus_tpu.rpc.server import wire_is_legacy

                # Fingerprint = envelope evidence (str8 method name — the
                # C++ framer strips the envelope, so it reports the era
                # pin RpcClient.call_raw relies on) OR a modern type byte
                # in the params span. A legacy verdict stays PROVISIONAL:
                # the connection keeps being re-scanned — every small
                # request, power-of-2-numbered bulk ones (an every-request
                # scan of pipelined bulk traffic measured a ~3x e2e hit;
                # same sampling as the Python transport) — and only the
                # modern verdict latches.
                if conn_state is None:
                    conn_state = {"legacy": (not envelope_modern)
                                  and wire_is_legacy(raw), "nreq": 1}
                    with self._wire_lock:
                        if len(self._conn_wire) >= 4096:
                            self._conn_wire.pop(next(iter(self._conn_wire)))
                        self._conn_wire[conn_id] = conn_state
                elif envelope_modern:
                    conn_state["legacy"] = False
                else:
                    nreq = conn_state["nreq"] = conn_state.get("nreq", 1) + 1
                    if len(raw) <= 1024 or (nreq & (nreq - 1)) == 0:
                        conn_state["legacy"] = wire_is_legacy(raw)
        # raw fast path: the C++ front-end already isolated the params
        # span; registered raw handlers consume it without Python decode
        if method in self._raw_methods and msgid != self._NOTIFY:
            if len(raw) >= self._POOL_THRESHOLD and not self._stopped:
                self._bulk_pool.submit(self._dispatch_fast_bulk, conn_id,
                                       msgid, method, raw, conn_state,
                                       trace, dl, pr)
                return
            prev = tracing.swap_trace(tracing.from_wire(trace))
            prev_dl = deadlines.swap(deadlines.adopt_wire(dl))
            p_req = principals.adopt_wire(pr)
            prev_pr = principals.swap(p_req)
            try:
                error, result = self._execute_fast(method, raw, conn_state)
            finally:
                tracing.swap_trace(prev)
                deadlines.swap(prev_dl)
                principals.swap(prev_pr)
            payload = build_response(
                msgid, error, result,
                legacy=self.response_legacy(method, conn_state))
            rec = self.usage_recorder
            if rec is not None:
                rec.account(method, principal=p_req, resolve=False,
                            bytes_in=float(nbytes),
                            bytes_out=float(len(payload)))
            self._lib.jt_rpc_respond(self._handle, conn_id, payload,
                                     len(payload))
            return
        try:
            params = msgpack.unpackb(raw, raw=False, strict_map_key=False,
                                     use_list=True,
                                     unicode_errors="surrogateescape")
        except Exception as e:  # broad-ok — undecodable params must answer
            error, result = error_to_wire(e), None
        else:
            prev = tracing.swap_trace(tracing.from_wire(trace))
            prev_dl = deadlines.swap(deadlines.adopt_wire(dl))
            p_req = principals.adopt_wire(pr)
            prev_pr = principals.swap(p_req)
            try:
                error, result = self._execute(method, params)
            finally:
                tracing.swap_trace(prev)
                deadlines.swap(prev_dl)
                principals.swap(prev_pr)
        if msgid == self._NOTIFY:
            return  # notification: no response on the wire
        payload = build_response(
            msgid, error, result,
            legacy=self.response_legacy(method, conn_state))
        rec = self.usage_recorder
        if rec is not None:
            rec.account(method, principal=principals.adopt_wire(pr),
                        resolve=False, bytes_in=float(nbytes),
                        bytes_out=float(len(payload)))
        self._lib.jt_rpc_respond(self._handle, conn_id, payload, len(payload))

    # -- C++ relay plane (proxies only) ---------------------------------------
    def relay_config(self, methods, clusters, timeout: float = 10.0,
                     idle_expire: float = 60.0) -> bool:
        """Route ``methods`` for ``clusters`` entirely in C++: the request
        frame forwards verbatim to a backend on a per-(client-connection,
        cluster) pipe and the response streams back without entering
        Python (rpc_frontend.cpp relay plane). ``clusters`` maps cluster
        name -> [(host, port), ...]; the table is replaced wholesale.
        Anything the C++ side declines (unknown cluster, dead pipe)
        falls back to the registered Python handler."""
        if self._stopped:
            return False
        spec = "\n".join(
            f"{name}\t" + ",".join(f"{h}:{p}" for h, p in nodes)
            for name, nodes in clusters.items() if nodes)
        rc = self._lib.jt_rpc_relay_config(
            self._handle, "\n".join(methods).encode(), spec.encode(),
            float(timeout), float(idle_expire))
        return rc == 0

    def relay_stats(self) -> Dict[str, int]:
        """Per-method relayed-request counts (merged into the proxy's
        get_status counters — relayed requests never reach Python). The
        reserved "__errors__" key counts synthesized backend-loss
        responses (folds into forward_errors)."""
        cap = 1 << 16
        while True:
            buf = ctypes.create_string_buffer(cap)
            n = self._lib.jt_rpc_relay_stats(self._handle, buf, cap)
            if n >= 0:
                out: Dict[str, int] = {}
                for line in buf.raw[:n].decode().splitlines():
                    m, _, c = line.partition("\t")
                    if m:
                        out[m] = int(c)
                return out
            cap = -int(n) + 16

    # -- lifecycle (RpcServer-compatible) -------------------------------------
    def listen(self, port: int, host: str = "0.0.0.0") -> int:
        rc = self._lib.jt_rpc_listen(self._handle, host.encode(), port, 128)
        if rc < 0:
            raise OSError(-rc, os.strerror(-rc))
        self.port = rc
        return rc

    def start(self, nthreads: int = 2) -> None:
        """Compat no-op: concurrency comes from the C++ per-connection
        reader threads, not a Python worker pool."""

    def serve_background(self, port: int = 0, nthreads: int = 2,
                         host: str = "0.0.0.0") -> int:
        self.start(nthreads)
        return self.listen(port, host)

    def stop(self) -> None:
        if self._stopped:
            return
        self._stopped = True
        # drop queued bulk work; in-flight tasks check _stopped before
        # responding (the C++ handle must outlive any jt_rpc_respond)
        self._bulk_pool.shutdown(wait=False, cancel_futures=True)
        self._lib.jt_rpc_stop(self._handle)

    def __del__(self):  # noqa: D105
        try:
            if getattr(self, "_handle", None):
                self.stop()
                # a respond against a STOPPED handle is a safe no-op (the
                # C++ conns map is empty), but the handle must not be
                # DESTROYED under an in-flight bulk task — drain first
                self._bulk_pool.shutdown(wait=True)
                self._lib.jt_rpc_destroy(self._handle)
                self._handle = None
        except Exception:  # broad-ok — interpreter teardown
            pass


def create_rpc_server(timeout: float = 10.0, trace: Optional[Registry] = None,
                      legacy_wire: bool = False, wire_detect: bool = True):
    """RpcServer factory for the jubatus-facing planes (engine servers,
    proxies): the C++ transport is the DEFAULT when its library builds —
    it wins the serving A/B (round 3: C++ framing beats the Python
    reader's feed/skip/slice per request), and the shipped default must
    be the one that wins the capture (VERDICT r2 weak 3). Set
    JUBATUS_TPU_NATIVE_RPC=0 to force the Python transport (or it is the
    automatic fallback when no toolchain can build the front-end).
    Per-connection legacy-wire autodetection defaults ON here — an
    unmodified deployed client works with no flags; internal services
    construct RpcServer directly and stay modern-only."""
    if os.environ.get("JUBATUS_TPU_NATIVE_RPC", "") not in \
            ("0", "false", "no"):
        try:
            return NativeRpcServer(timeout=timeout, trace=trace,
                                   legacy_wire=legacy_wire,
                                   wire_detect=wire_detect)
        except RuntimeError as e:
            log.warning("native rpc unavailable (%s); using python transport", e)
    return RpcServer(timeout=timeout, trace=trace, legacy_wire=legacy_wire,
                     wire_detect=wire_detect)
