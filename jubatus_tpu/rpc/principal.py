"""Principal propagation — who a request is FOR (ISSUE 19).

A **principal** is the tenant id a request is billed to. Clients stamp
it once at the edge; every hop it fans into (client → proxy → server →
peer) inherits it, and the usage ledger (``utils/usage.py``) attributes
CPU-seconds, device time, and bytes to it at dispatch. Traffic that
never names one folds into ``(untagged)``; the system's own work (mix,
telemetry, store uploads) folds into ``(system)`` — so the books always
close, no request is unaccounted.

Mechanics mirror the trace (PR 2) and deadline (PR 9) planes exactly:

- **in-process**: a thread-local string. ``use(p)`` opens a scope;
  ``swap`` is the primitive for dispatch pools (threads are reused — a
  leaked principal would bill the NEXT request to the wrong tenant).
- **on the wire**: the envelope's OPTIONAL 7th element carries the
  principal as a string. Absent principal + absent deadline + absent
  trace keeps the envelope at 4 elements — old peers never see a shape
  they don't know; earlier absent slots nil-pad (msgpack ``\\xc0``).
- both transports adopt it in dispatch exactly like the trace and
  deadline elements; the C++ front-end relays 7-element frames
  verbatim.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Iterator, Optional

_tls = threading.local()

#: the ledger row untagged traffic folds into — requests whose envelope
#: carried no principal (old clients, curl, internal tooling)
UNTAGGED = "(untagged)"
#: the ledger row the system's own work folds into — mix, telemetry,
#: store, migration: traffic no tenant sent
SYSTEM = "(system)"
#: clamp for wire values: a principal longer than this is truncated
#: rather than trusted — tenant ids are short identifiers, and a
#: megabyte "principal" must not become a ledger key
MAX_WIRE_CHARS = 128


def current() -> Optional[str]:
    """This thread's principal, or None when untagged."""
    return getattr(_tls, "principal", None)


def swap(principal: Optional[str]) -> Optional[str]:
    """Install a principal; returns the previous one (restore in a
    finally — dispatch pool threads are reused)."""
    prev = getattr(_tls, "principal", None)
    _tls.principal = principal
    return prev


@contextlib.contextmanager
def use(principal: Optional[str]) -> Iterator[None]:
    """Scope a principal (None = explicitly untagged)."""
    prev = swap(principal)
    try:
        yield
    finally:
        swap(prev)


def to_wire() -> Optional[str]:
    """The current principal as the envelope's 7th element, or None when
    none is set (the envelope then stays 4/5/6 elements — old peers
    never see a shape they don't know)."""
    p = current()
    if p is None:
        return None
    p = str(p)
    return p[:MAX_WIRE_CHARS] if p else None


def adopt_wire(value: Any) -> Optional[str]:
    """A wire principal value -> in-process principal; None for absent/
    garbage values (a malformed principal must degrade to 'untagged',
    never kill the dispatch)."""
    if value is None:
        return None
    if isinstance(value, bytes):
        # "replace" never raises: undecodable bytes become U+FFFD and
        # the request still bills to a (mangled) principal, not a crash
        value = value.decode("utf-8", "replace")
    if not isinstance(value, str) or not value:
        return None
    return value[:MAX_WIRE_CHARS]
