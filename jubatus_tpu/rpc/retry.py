"""Retry policy + retry budget for the request plane.

Two guards stand between a transient failure and a retry storm:

- **RetryPolicy** — capped exponential backoff with FULL jitter
  (sleep ~ U(0, min(cap, base * 2^attempt)), the AWS-architecture result:
  full jitter de-synchronizes a thundering herd better than equal
  jitter). The sleep is additionally clamped to the call's remaining
  deadline budget (rpc/deadline.py) — no sleeping past the point where
  the answer is useless.
- **RetryBudget** — a token bucket that caps RETRY traffic to a fraction
  of real traffic (default 10%, the gRPC/Finagle convention): every
  first attempt deposits ``ratio`` tokens, every retry withdraws one.
  Under a healthy cluster the bucket stays full and every transient blip
  gets its retry; under a degraded cluster retries self-limit to ~10%
  extra load instead of multiplying the overload. A denied withdrawal is
  counted (``rpc.retry_budget_exhausted``) and the original error
  propagates.

Only IDEMPOTENT methods are ever retried (framework/idl.py owns the
per-method classification); effectful calls keep propagate-don't-
double-apply semantics no matter what these knobs say.
"""

from __future__ import annotations

import dataclasses
import random
import threading
from typing import Optional


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Backoff shape for one logical call's retry loop."""

    #: total attempts including the first (3 = first try + 2 retries)
    max_attempts: int = 3
    #: backoff base before the exponential (seconds)
    base_sleep: float = 0.025
    #: backoff ceiling (seconds)
    max_sleep: float = 0.25

    def sleep_for(self, attempt: int,
                  remaining: Optional[float] = None,
                  rng: Optional[random.Random] = None) -> float:
        """Full-jitter sleep before retry number ``attempt`` (1-based),
        clamped to the remaining deadline budget."""
        cap = min(self.max_sleep, self.base_sleep * (2.0 ** attempt))
        sleep = (rng or _rng).uniform(0.0, cap)
        if remaining is not None:
            # leave some budget for the attempt itself
            sleep = max(0.0, min(sleep, remaining * 0.5))
        return sleep


#: module RNG for jitter: deterministic seeding is pointless here (tests
#: assert on counts, not sleep values) but a shared instance avoids
#: reseeding per call
_rng = random.Random()

DEFAULT_POLICY = RetryPolicy()


class RetryBudget:
    """Token bucket capping retries to ``ratio`` of first-attempt traffic.

    Thread-safe; one instance per client (RpcClient) or per routing tier
    (Proxy). Starts full so cold clients can retry their very first
    failures (min_tokens also bounds how negative a quiet client's
    goodwill can get: zero)."""

    def __init__(self, ratio: float = 0.1, max_tokens: float = 10.0) -> None:
        self.ratio = float(ratio)
        self.max_tokens = float(max_tokens)
        # ratio 0 means retries are OFF: start (and stay) empty
        self._tokens = float(max_tokens) if self.ratio > 0 else 0.0
        self._lock = threading.Lock()
        #: lifetime counters (status/debugging)
        self.deposits = 0
        self.withdrawals = 0
        self.denials = 0

    def deposit(self) -> None:
        """A first attempt happened: grow the budget by ``ratio``."""
        with self._lock:
            self._tokens = min(self.max_tokens, self._tokens + self.ratio)
            self.deposits += 1

    def try_withdraw(self) -> bool:
        """Spend one token for a retry; False when the budget is dry."""
        with self._lock:
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self.withdrawals += 1
                return True
            self.denials += 1
            return False

    def tokens(self) -> float:
        with self._lock:
            return self._tokens

    def status(self) -> dict:
        with self._lock:
            return {"tokens": round(self._tokens, 3),
                    "ratio": self.ratio,
                    "deposits": self.deposits,
                    "withdrawals": self.withdrawals,
                    "denials": self.denials}
