"""Threaded MessagePack-RPC server (≙ mprpc/rpc_server.{hpp,cpp}).

The reference dispatches by a name→invoker hash (rpc_server.cpp:44-82) with
typed sync invokers (rpc_server.hpp:109-240) on an mpio event loop with N
worker threads. Here: a TCP accept loop + per-connection reader threads over a
shared bounded worker pool — Python-idiomatic, same semantics (N concurrent
in-flight calls, per-connection response ordering is NOT guaranteed, matching
msgpack-rpc's msgid-correlated pipelining).

Arity checking reproduces the typed-invoker behavior: a call with the wrong
number of params gets ARGUMENT_ERROR, an unknown method NO_METHOD_ERROR.
"""

from __future__ import annotations

import inspect
import logging
import socket
import struct
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Optional

import msgpack

from jubatus_tpu.rpc import deadline as deadlines
from jubatus_tpu.rpc import principal as principals
from jubatus_tpu.rpc.errors import (
    DeadlineExceeded,
    RpcMethodNotFound,
    error_to_wire,
)
from jubatus_tpu.utils import faults, tracing
from jubatus_tpu.utils.tracing import Registry

log = logging.getLogger(__name__)

REQUEST, RESPONSE, NOTIFY = 0, 1, 2

#: sentinel a raw-registered handler returns to decline the fast path —
#: the request is then decoded generically and served by the normal handler
RAW_FALLBACK = object()


def wire_is_legacy(raw: bytes) -> bool:
    """Fingerprint one request: True when it contains NO post-2013 msgpack
    type bytes — i.e. the reference's vendored-msgpack client could have
    produced it. Such connections are answered in the legacy raw format,
    which modern unpackers also accept (old raw16/raw32 are modern
    str16/str32), so a false positive only costs the str/bytes distinction
    a modern client never relied on for the jubatus API. A single modern
    type byte (str8/bin/ext) proves a modern client and pins the
    connection to the modern format. Skip-style scan — no values are
    built, and the walk is budget-capped (scan_is_legacy), so a
    provisionally-legacy connection pays a small bounded cost per
    request, not an O(elements) walk of every bulk train call."""
    from jubatus_tpu.rpc import legacy as _legacy

    return _legacy.scan_is_legacy(raw)


#: payload widths of fixed-size msgpack types (modern family included)
_MP_SCALAR_WIDTH = {0xC0: 0, 0xC2: 0, 0xC3: 0, 0xCA: 4, 0xCB: 8, 0xCC: 1,
                    0xCD: 2, 0xCE: 4, 0xCF: 8, 0xD0: 1, 0xD1: 2, 0xD2: 4,
                    0xD3: 8, 0xD4: 2, 0xD5: 3, 0xD6: 5, 0xD7: 9, 0xD8: 17}


def msgpack_span_end(buf: bytes, i: int = 0) -> int:
    """End offset of the msgpack object starting at ``buf[i]`` — a type-
    byte walk that builds no values (the raw relay path needs to split an
    envelope into spans without decoding multi-megabyte payloads).
    Raises ValueError on truncated/unknown bytes."""
    n = len(buf)
    remaining = 1
    while remaining:
        if i >= n:
            raise ValueError("truncated msgpack object")
        t = buf[i]
        i += 1
        remaining -= 1
        if t <= 0x7F or t >= 0xE0:
            continue
        if 0x80 <= t <= 0x8F:
            remaining += (t & 0x0F) * 2
        elif 0x90 <= t <= 0x9F:
            remaining += t & 0x0F
        elif 0xA0 <= t <= 0xBF:
            i += t & 0x1F
        elif t in _MP_SCALAR_WIDTH:
            i += _MP_SCALAR_WIDTH[t]
        elif t in (0xC4, 0xC7, 0xD9):     # bin8/ext8/str8
            if i >= n:
                raise ValueError("truncated msgpack object")
            i += 1 + buf[i] + (1 if t == 0xC7 else 0)
        elif t in (0xC5, 0xC8, 0xDA):     # bin16/ext16/str16
            if i + 2 > n:
                raise ValueError("truncated msgpack object")
            i += 2 + int.from_bytes(buf[i:i + 2], "big") + \
                (1 if t == 0xC8 else 0)
        elif t in (0xC6, 0xC9, 0xDB):     # bin32/ext32/str32
            if i + 4 > n:
                raise ValueError("truncated msgpack object")
            i += 4 + int.from_bytes(buf[i:i + 4], "big") + \
                (1 if t == 0xC9 else 0)
        elif t in (0xDC, 0xDD, 0xDE, 0xDF):
            w = 2 if t in (0xDC, 0xDE) else 4
            if i + w > n:
                raise ValueError("truncated msgpack object")
            count = int.from_bytes(buf[i:i + w], "big")
            if count > n - i:
                raise ValueError("impossible msgpack length")
            i += w
            remaining += count * (2 if t in (0xDE, 0xDF) else 1)
        else:
            raise ValueError(f"unknown msgpack type byte 0x{t:02x}")
    if i > n:
        raise ValueError("truncated msgpack object")
    return i


class RawResult:
    """A handler result that is ALREADY msgpack-encoded (a relayed
    backend response span): build_response splices it into the response
    frame without a decode/encode round trip."""

    __slots__ = ("span",)

    def __init__(self, span: bytes) -> None:
        self.span = span


def _parse_response_envelope(raw: bytes) -> int:
    """Offset of the ERROR object in a response frame
    ``[1, msgid, error, result]``; ValueError on anything else."""
    if len(raw) < 3 or raw[0] != 0x94 or raw[1] != 0x01:
        raise ValueError("not a msgpack-rpc response frame")
    t = raw[2]
    if t <= 0x7F:
        return 3
    if t == 0xCC:
        return 4
    if t == 0xCD:
        return 5
    if t == 0xCE:
        return 7
    raise ValueError("unexpected msgid encoding")


def _parse_envelope(raw: bytes):
    """Request envelope without decoding params: ``[0, msgid, method,
    params]``, the traced 5-element variant ``[..., trace]``, the
    deadline-bearing 6-element variant ``[..., trace, deadline]``, or
    the principal-bearing 7-element variant ``[..., trace, deadline,
    principal]`` -> (msgid, method, params_offset, n_extra), or None
    for anything else (notify, malformed, exotic headers) — those take
    the generic decode path."""
    try:
        if raw[0] not in (0x94, 0x95, 0x96, 0x97) or raw[1] != 0x00:  # REQUEST
            return None
        n_extra = raw[0] - 0x94
        i = 2
        t = raw[i]
        if t <= 0x7F:
            msgid, i = t, i + 1
        elif t == 0xCC:
            msgid, i = raw[i + 1], i + 2
        elif t == 0xCD:
            msgid, i = int.from_bytes(raw[i + 1:i + 3], "big"), i + 3
        elif t == 0xCE:
            msgid, i = int.from_bytes(raw[i + 1:i + 5], "big"), i + 5
        else:
            return None
        t = raw[i]
        if 0xA0 <= t <= 0xBF:  # fixstr/fixraw
            n, i = t & 0x1F, i + 1
        elif t == 0xD9:        # str8
            n, i = raw[i + 1], i + 2
        elif t == 0xDA:        # raw16/str16
            n, i = int.from_bytes(raw[i + 1:i + 3], "big"), i + 3
        else:
            return None
        method = raw[i:i + n].decode("utf-8", "surrogateescape")
        return msgid, method, i + n, n_extra
    except IndexError:
        return None


def split_extras(raw: bytes, off: int):
    """Split a request's params span from its OPTIONAL trailing envelope
    elements (trace, then deadline, then principal) — shared by both
    transports. Returns (params_span, trace_wire, deadline_wire,
    principal_wire); a malformed tail degrades to (everything, None,
    None, None) — a bad extra element must not 500 the request."""
    try:
        pend = msgpack_span_end(raw, off)
        trace_w = dl_w = pr_w = None
        if pend < len(raw):
            tend = msgpack_span_end(raw, pend)
            trace_w = msgpack.unpackb(raw[pend:tend], raw=False)
            if tend < len(raw):
                dend = msgpack_span_end(raw, tend)
                dl_w = msgpack.unpackb(raw[tend:dend], raw=False)
                if dend < len(raw):
                    pr_w = msgpack.unpackb(raw[dend:], raw=False)
        return raw[off:pend], trace_w, dl_w, pr_w
    except Exception:  # broad-ok — a bad trailing element must not 500
        return raw[off:], None, None, None


class RpcServer:
    """Dispatcher + listener. register() then listen() then start().

    Lifecycle mirrors the reference (listen → start(nthreads) → join → end,
    rpc_server.hpp): ``serve_background()`` is listen+start, ``stop()`` is end.
    """

    def __init__(self, timeout: float = 10.0,
                 trace: Optional[Registry] = None,
                 legacy_wire: bool = False,
                 wire_detect: bool = False) -> None:
        self._methods: Dict[str, Callable[..., Any]] = {}
        self._arity: Dict[str, Optional[int]] = {}
        #: pack responses in the pre-str8/bin msgpack format old jubatus
        #: clients understand (--legacy-wire; see rpc/legacy.py). Methods
        #: registered with binary=True (mixer internals shipping packed
        #: model bytes) keep the modern format — legacy clients never call
        #: them, and old-raw would lose the str/bytes distinction for our
        #: own peers.
        self.legacy_wire = legacy_wire
        #: per-connection autodetection: fingerprint each connection's
        #: FIRST request (wire_is_legacy) and answer legacy-format when it
        #: carries no post-2013 type bytes — an unmodified deployed
        #: jubatus client works against a server started with NO flags
        #: (the reference speaks old-format on every connection,
        #: client/common/client.hpp:30-87). Engine servers and proxies
        #: enable this; internal planes (coordd) stay modern-only so bytes
        #: payloads keep their type.
        self.wire_detect = wire_detect
        self._binary_methods: set = set()
        #: raw-span fast paths: method -> fn(raw_params bytes) -> result
        #: (or RAW_FALLBACK to decode generically). Served straight off the
        #: wire framing without building Python param objects.
        self._raw_methods: Dict[str, Callable[[bytes], Any]] = {}
        self.timeout = timeout
        #: per-server span aggregates (multi-server processes must not
        #: merge each other's counters)
        self.trace = trace or Registry()
        self._sock: Optional[socket.socket] = None
        self._threads: list[threading.Thread] = []
        self._pool: Optional[ThreadPoolExecutor] = None
        self._running = False
        self.port: Optional[int] = None
        #: elastic-membership gate (ISSUE 10): called with the method
        #: name before every dispatch; raising rejects the request
        #: BEFORE any state change (the drain plane rejects effectful
        #: methods with the retryable NodeDraining so proxies re-route).
        #: Shared by both transports (NativeRpcServer borrows _invoke).
        self.dispatch_gate: Optional[Callable[[str], None]] = None
        #: usage ledger (utils/usage.py, ISSUE 19): the dispatch layer
        #: notes per-method errors and bytes in/out into it; CPU-seconds
        #: arrive via the registry's usage_sink, not here. Shared by
        #: both transports (NativeRpcServer borrows _execute*).
        self.usage_recorder: Optional[Any] = None

    # -- method table (≙ rpc_server::add<T>) --------------------------------
    def register(self, name: str, fn: Callable[..., Any],
                 arity: Optional[int] = None,
                 binary: bool = False) -> None:
        if binary:
            self._binary_methods.add(name)
        if arity is None:
            try:
                sig = inspect.signature(fn)
                if not any(
                    p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
                    for p in sig.parameters.values()
                ):
                    arity = len(
                        [
                            p
                            for p in sig.parameters.values()
                            if p.default is p.empty
                            and p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                        ]
                    )
            except (TypeError, ValueError):
                arity = None
        self._methods[name] = fn
        self._arity[name] = arity

    def register_raw(self, name: str, fn: Callable[[bytes], Any]) -> None:
        """Fast path for ``name``: ``fn`` receives the request's raw params
        msgpack bytes (no Python decode) and returns the result — or
        ``RAW_FALLBACK`` to route the request through the generic decode +
        registered handler (e.g. a wire shape the native parser rejects).
        The generic handler must also be registered (fallback + arity)."""
        self._raw_methods[name] = fn

    def method_names(self):
        return sorted(self._methods)

    # -- lifecycle -----------------------------------------------------------
    def listen(self, port: int, host: str = "0.0.0.0") -> int:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        sock.bind((host, port))
        sock.listen(128)
        self._sock = sock
        self.port = sock.getsockname()[1]
        return self.port

    def start(self, nthreads: int = 2) -> None:
        assert self._sock is not None, "listen() first"
        self._running = True
        self._pool = ThreadPoolExecutor(
            max_workers=max(2, nthreads), thread_name_prefix="rpc-worker"
        )
        t = threading.Thread(target=self._accept_loop, daemon=True, name="rpc-accept")
        t.start()
        self._threads.append(t)

    def serve_background(self, port: int = 0, nthreads: int = 2, host: str = "0.0.0.0") -> int:
        port = self.listen(port, host)
        self.start(nthreads)
        return port

    def stop(self) -> None:
        self._running = False
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    # -- wire loop -----------------------------------------------------------
    def _accept_loop(self) -> None:
        while self._running and self._sock is not None:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(
                target=self._conn_loop, args=(conn,), daemon=True, name="rpc-conn"
            )
            t.start()

    def _conn_loop(self, conn: socket.socket) -> None:
        # Frame messages by span (Unpacker.skip + tell — C-speed, builds
        # no objects), keep a mirror of the bytes, and decode per message:
        # raw-registered methods get the params span directly (zero Python
        # object churn on the hot path); everything else goes through one
        # unpackb. surrogateescape: legacy clients pack datum binary_values
        # as old-raw, which may not be UTF-8 — a decode error must not kill
        # the connection. Datum.from_msgpack re-encodes surrogate-bearing
        # strings back to the exact original bytes.
        framer = msgpack.Unpacker()
        buf = bytearray()
        base = 0       # stream offset of buf[0]
        msg_start = 0  # stream offset of the next undelivered message
        wlock = threading.Lock()
        #: requests fingerprint the peer's wire era (skipped when
        #: --legacy-wire already forces every answer legacy). A legacy
        #: verdict is PROVISIONAL: a modern client whose early calls are
        #: all fixtypes (short method, small args — e.g. get_status) emits
        #: zero post-2013 bytes, so the connection keeps being re-scanned
        #: and upgrades to modern the first time a SCANNED request carries
        #: a modern type byte. Only the modern verdict latches — a
        #: vendored-msgpack client can never send one. Re-scans are
        #: SAMPLED: every small request (<= 1 KB — status/row reads, where
        #: the str/bytes distinction actually bites) but only
        #: power-of-2-numbered bulk ones; an every-request scan measured a
        #: ~3x e2e train throughput hit for genuinely-legacy-looking
        #: pipelined bulk traffic.
        try:
            peer = "%s:%s" % conn.getpeername()[:2]
        except (OSError, TypeError):
            peer = ""
        conn_state = {"legacy": False, "peer": peer}
        scanning = self.wire_detect and not self.legacy_wire
        nreq = 0
        try:
            while self._running:
                data = conn.recv(65536)
                if not data:
                    return
                framer.feed(data)
                buf += data
                while True:
                    try:
                        framer.skip()
                    except msgpack.OutOfData:
                        break
                    end = framer.tell()
                    raw = bytes(buf[msg_start - base:end - base])
                    msg_start = end
                    if scanning:
                        nreq += 1
                        if len(raw) <= 1024 or (nreq & (nreq - 1)) == 0:
                            conn_state["legacy"] = wire_is_legacy(raw)
                            scanning = conn_state["legacy"]
                    self._handle_raw(conn, wlock, raw, conn_state)
                del buf[:msg_start - base]
                base = msg_start
        # RuntimeError: pool.submit after stop() — a hard-killed server's
        # surviving connection threads must die quietly, not traceback
        except (OSError, ValueError, struct.error, RuntimeError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _handle_raw(self, conn: socket.socket, wlock: threading.Lock,
                    raw: bytes, conn_state: Optional[dict] = None) -> None:
        env = _parse_envelope(raw)
        if env is not None:
            msgid, method, off, n_extra = env
            params_span, trace, dl, pr = raw[off:], None, None, None
            if n_extra:
                # traced/deadlined/principal envelope: split the params
                # span from the trailing elements (the walk is paid only
                # on extended requests)
                params_span, trace, dl, pr = split_extras(raw, off)
            if method in self._raw_methods and self._pool is not None:
                self._pool.submit(self._dispatch_fast, conn, wlock, msgid,
                                  method, params_span, conn_state, trace,
                                  dl, pr)
                return
        msg = msgpack.unpackb(raw, raw=False, strict_map_key=False,
                              use_list=True,
                              unicode_errors="surrogateescape")
        self._handle(conn, wlock, msg, conn_state, nbytes=len(raw))

    def _dispatch_fast(self, conn, wlock, msgid, method,
                       raw_params: bytes,
                       conn_state: Optional[dict] = None,
                       trace: Any = None, dl: Any = None,
                       pr: Any = None) -> None:
        # adopt the caller's trace context (or root a fresh one), its
        # deadline AND its principal for the duration of the dispatch;
        # restore after — pool threads are reused
        ctx = tracing.from_wire(trace)
        if conn_state is not None:
            ctx.peer = conn_state.get("peer", "")
        prev = tracing.swap_trace(ctx)
        prev_dl = deadlines.swap(deadlines.adopt_wire(dl))
        p_req = principals.adopt_wire(pr)
        prev_pr = principals.swap(p_req)
        try:
            error, result = self._execute_fast(method, raw_params, conn_state)
        finally:
            tracing.swap_trace(prev)
            deadlines.swap(prev_dl)
            principals.swap(prev_pr)
        payload = build_response(
            msgid, error, result,
            legacy=self.response_legacy(method, conn_state))
        rec = self.usage_recorder
        if rec is not None:
            rec.account(method, principal=p_req, resolve=False,
                        bytes_in=float(len(raw_params)),
                        bytes_out=float(len(payload)))
        try:
            with wlock:
                conn.sendall(payload)
        except OSError:
            pass

    def _execute_fast(self, method: str, raw_params: bytes,
                      conn_state: Optional[dict] = None):
        """Raw-span invoke; falls back to the generic decode + handler when
        the fast fn declines (RAW_FALLBACK). Handlers marked
        ``modern_only`` (the proxy's verbatim relays) are skipped for
        legacy-era connections — their spans must be decoded and
        re-encoded modern, not forwarded as-is. The trace span is recorded
        here only when the fast path served the request — a fallback
        cancels the span handle so the request is counted once, by
        _invoke's span."""
        fn = self._raw_methods[method]
        if conn_state is not None and conn_state.get("legacy") and \
                getattr(fn, "modern_only", False):
            params = msgpack.unpackb(raw_params, raw=False,
                                     strict_map_key=False, use_list=True,
                                     unicode_errors="surrogateescape")
            return self._execute(method, params)
        with self.trace.span(f"rpc.{method}") as sp:
            try:
                if faults.is_armed():
                    faults.fire(f"rpc.dispatch.{method}")
                self._check_deadline(method)
                gate = getattr(self, "dispatch_gate", None)
                if gate is not None:
                    gate(method)
                result = fn(raw_params)
            except Exception as e:  # broad-ok — every failure must answer
                log.debug("rpc raw method %s raised", method, exc_info=True)
                self.trace.count(f"rpc.{method}.errors")
                rec = getattr(self, "usage_recorder", None)
                if rec is not None:
                    rec.note_error(method)
                return error_to_wire(e), None
            if result is not RAW_FALLBACK:
                return None, result
            sp.cancel()
        params = msgpack.unpackb(raw_params, raw=False, strict_map_key=False,
                                 use_list=True,
                                 unicode_errors="surrogateescape")
        return self._execute(method, params)

    def _handle(self, conn: socket.socket, wlock: threading.Lock, msg: Any,
                conn_state: Optional[dict] = None,
                nbytes: int = 0) -> None:
        if not isinstance(msg, (list, tuple)) or not msg:
            return
        if msg[0] == REQUEST and len(msg) in (4, 5, 6, 7):
            # 5th element: optional trace context ({"t","s"}); 6th:
            # optional deadline budget (remaining seconds); 7th:
            # optional principal (tenant id) — see rpc/client.py; plain
            # msgpack-rpc peers send 4
            _, msgid, method, params = msg[:4]
            trace = msg[4] if len(msg) >= 5 else None
            dl = msg[5] if len(msg) >= 6 else None
            pr = msg[6] if len(msg) == 7 else None
            if self._pool is not None:
                self._pool.submit(self._dispatch, conn, wlock, msgid, method,
                                  params, conn_state, trace, dl, pr, nbytes)
        elif msg[0] == NOTIFY and len(msg) == 3:
            _, method, params = msg
            if self._pool is not None:
                self._pool.submit(self._invoke_silent, method, params)

    def _dispatch(self, conn, wlock, msgid, method, params,
                  conn_state: Optional[dict] = None,
                  trace: Any = None, dl: Any = None,
                  pr: Any = None, nbytes: int = 0) -> None:
        ctx = tracing.from_wire(trace)
        if conn_state is not None:
            ctx.peer = conn_state.get("peer", "")
        prev = tracing.swap_trace(ctx)
        prev_dl = deadlines.swap(deadlines.adopt_wire(dl))
        p_req = principals.adopt_wire(pr)
        prev_pr = principals.swap(p_req)
        try:
            error, result = self._execute(method, params)
        finally:
            tracing.swap_trace(prev)
            deadlines.swap(prev_dl)
            principals.swap(prev_pr)
        payload = build_response(
            msgid, error, result,
            legacy=self.response_legacy(method, conn_state))
        rec = self.usage_recorder
        if rec is not None:
            rec.account(method, principal=p_req, resolve=False,
                        bytes_in=float(nbytes),
                        bytes_out=float(len(payload)))
        try:
            with wlock:
                conn.sendall(payload)
        except OSError:
            pass

    def _execute(self, method: str, params: Any):
        """Invoke + error taxonomy, shared by every transport."""
        error, result = None, None
        try:
            result = self._invoke(method, params)
        except Exception as e:  # broad-ok — every failure must answer
            if not isinstance(e, RpcMethodNotFound):
                log.debug("rpc method %s raised", method, exc_info=True)
            # per-method failure counter: the dispatch span times success
            # and failure identically, so error RATE needs its own series
            self.trace.count(f"rpc.{method}.errors")
            rec = getattr(self, "usage_recorder", None)
            if rec is not None:
                rec.note_error(method)
            error = error_to_wire(e)
        return error, result

    def _invoke(self, method: str, params: Any) -> Any:
        fn = self._methods.get(method)
        if fn is None:
            raise RpcMethodNotFound(method)
        params = list(params) if isinstance(params, (list, tuple)) else [params]
        want = self._arity.get(method)
        if want is not None and len(params) != want:
            raise TypeError(f"{method}: expected {want} params, got {len(params)}")
        # injection site for dispatch-side chaos (queueing delay, worker
        # stalls); fired BEFORE the deadline gate so an injected delay can
        # deterministically expire a propagated budget
        if faults.is_armed():
            faults.fire(f"rpc.dispatch.{method}")
        self._check_deadline(method)
        gate = getattr(self, "dispatch_gate", None)
        if gate is not None:
            gate(method)
        with self.trace.span(f"rpc.{method}"):
            return fn(*params)

    def _check_deadline(self, method: str) -> None:
        """Reject already-expired work at dispatch: computing an answer
        nobody is waiting for only steals capacity from live requests.
        Counted per server (``rpc.deadline_rejected``)."""
        if deadlines.expired():
            self.trace.count("rpc.deadline_rejected")
            raise DeadlineExceeded(f"{method}: deadline expired at dispatch")

    def _invoke_silent(self, method: str, params: Any) -> None:
        try:
            self._invoke(method, params)
        except Exception:  # broad-ok
            log.debug("rpc notify %s raised", method, exc_info=True)

    def response_legacy(self, method: str,
                        conn_state: Optional[dict] = None) -> bool:
        """Whether this method's responses go out in the old wire format:
        forced globally by --legacy-wire, or detected per connection from
        its first request's fingerprint (wire_detect)."""
        if method in self._binary_methods:
            return False
        if self.legacy_wire:
            return True
        return bool(conn_state and conn_state.get("legacy"))


def build_response(msgid: int, error: Any, result: Any,
                   legacy: bool = False) -> bytes:
    """Pack one msgpack-rpc response message (shared by all transports).

    ``legacy=True`` packs in the pre-2013 format (no str8/bin type bytes:
    strings and bytes both go out as old "raw") so the reference's vendored
    msgpack — and therefore every deployed jubatus client — can parse it
    (client/common/client.hpp:30-87 links that old library).
    """
    if isinstance(result, RawResult):
        if error is None and not legacy:
            # splice the pre-encoded span: fixarray(4) + RESPONSE + msgid
            # + nil error + the span, no decode/encode of the payload
            return (b"\x94\x01" + msgpack.packb(msgid) + b"\xc0"
                    + result.span)
        # error path or legacy-era connection: materialize and fall
        # through to the normal packer (legacy needs old-raw re-encoding)
        result = msgpack.unpackb(result.span, raw=False,
                                 strict_map_key=False, use_list=True,
                                 unicode_errors="surrogateescape")
    # surrogateescape mirrors the request-decode side: surrogate-bearing
    # strings (legacy non-UTF8 raw admitted by the unpacker, e.g. stored
    # as labels) must re-encode to their original bytes, not raise after
    # dispatch with the client left hanging
    return msgpack.packb([RESPONSE, msgid, error, result], default=_to_wire,
                         use_bin_type=not legacy,
                         unicode_errors="surrogateescape")


def _to_wire(obj: Any) -> Any:
    """msgpack fallback: tuples of dataclass-ish objects → lists; numpy/JAX
    scalars → Python scalars (the serving plane never ships device arrays)."""
    if hasattr(obj, "to_msgpack"):
        return obj.to_msgpack()
    if hasattr(obj, "item"):
        return obj.item()
    if hasattr(obj, "tolist"):
        return obj.tolist()
    raise TypeError(f"cannot msgpack {type(obj)!r}")
