"""Engine servers (≙ jubatus/server/server/ + framework/, SURVEY.md §2.3-2.4).

`EngineServer` is the reference's server_base + server_helper collapsed: it
owns a driver, a mixer, and the RPC binding, and serves the engine's IDL
surface plus the built-ins (get_config/save/load/get_status/do_mix) over
MessagePack-RPC, wire-compatible with jubatus clients.

Boot path (≙ run_server<Impl,Serv>, server_util.hpp:139-176):

    python -m jubatus_tpu.server classifier -f config.json -p 9199
    python -m jubatus_tpu.server classifier -f config.json --name c1 \
        --coordinator /tmp/cluster   # distributed: join + background mix
"""

from jubatus_tpu.server.factory import create_driver, DRIVER_CLASSES  # noqa: F401
from jubatus_tpu.server.base import EngineServer  # noqa: F401
from jubatus_tpu.server.args import ServerArgs, parse_server_args  # noqa: F401
