"""Server main (≙ run_server<Impl,Serv>, server_util.hpp:139-176).

    python -m jubatus_tpu.server classifier -f config/classifier/arow.json -p 9199
    python -m jubatus_tpu.server classifier --config-test -f conf.json
    python -m jubatus_tpu.server classifier -z /shared/cluster -n c1   # distributed
"""

from __future__ import annotations

import signal
import sys

from jubatus_tpu.server.args import parse_server_args
from jubatus_tpu.server.base import EngineServer


def main(argv=None) -> int:
    from jubatus_tpu.cmd import apply_platform_override

    apply_platform_override()
    args = parse_server_args(argv)
    from jubatus_tpu.utils.logger import install_sighup_reload, setup

    setup(f"juba{args.engine}", args.eth, args.rpc_port,
          logdir=args.logdir, log_config=args.log_config)
    install_sighup_reload(args.log_config)
    if args.config_test:
        # dry-construct and exit (server_util.hpp:142-152) — a LOCAL
        # check: never joins the jax world (that would block on the rest
        # of the fleet booting)
        srv = None
        try:
            srv = EngineServer.from_args(args)
        except Exception as e:  # broad-ok
            print(f"config error: {e}", file=sys.stderr)
            return 1
        finally:
            if srv is not None and srv.coord is not None:
                srv.coord.close()
        print("config ok")
        return 0
    coord = None
    if args.jax_processes > 1:
        # must run BEFORE anything initializes the XLA backend. The
        # coordinator session stays open and is handed to the server:
        # process 0's published jax endpoint is an ephemeral owned by it.
        from jubatus_tpu.coord import create_coordinator
        from jubatus_tpu.parallel import multihost

        coord = (create_coordinator(args.coordinator)
                 if not args.is_standalone else None)
        multihost.initialize(
            coordinator_address=args.jax_coordinator or None,
            num_processes=args.jax_processes,
            process_id=args.jax_process_id,
            coord=coord,
        )
    server = EngineServer.from_args(args, coord=coord)
    signal.signal(signal.SIGTERM, lambda *_: server.stop())
    signal.signal(signal.SIGINT, lambda *_: server.stop())
    server.start()
    server.join()
    return 0


if __name__ == "__main__":
    sys.exit(main())
