"""Server command-line flags (≙ server_argv, framework/server_util.cpp:183-378).

Same flag names and defaults as the reference's servers, with `--zookeeper`
generalized to `--coordinator` (a locator string: a shared directory or
"memory"; see jubatus_tpu.coord.create_coordinator). `-z` stays as an alias.
Standalone mode ⇔ empty coordinator (server_util.hpp:100-102).
"""

from __future__ import annotations

import argparse
import dataclasses
import socket
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class ServerArgs:
    engine: str = ""
    rpc_port: int = 9199                # -p (server_util.cpp:188)
    listen_addr: str = ""               # -b
    thread: int = 2                     # -c (server_util.cpp:193-194)
    timeout: float = 10.0               # -t
    datadir: str = "/tmp"               # -d
    logdir: str = ""                    # -l
    log_config: str = ""                # -g (server_util.cpp:70-127)
    configpath: str = ""                # -f
    model_file: str = ""                # -m
    daemon: bool = False                # -D
    config_test: bool = False
    coordinator: str = ""               # -z; "" = standalone
    name: str = ""                      # -n cluster name
    mixer: str = "linear_mixer"         # -x
    interval_sec: float = 16.0          # (server_util.cpp:223-225)
    interval_count: int = 512           # (server_util.cpp:226-228)
    coordinator_timeout: float = 10.0   # --zookeeper_timeout
    interconnect_timeout: float = 10.0
    #: coalesce concurrent train RPCs into one device batch up to this
    #: many examples (server/microbatch.py); 0 = direct per-RPC path
    microbatch_max: int = 8192
    #: span the model over this many local devices (0/1 = single
    #: device): feature-sharded tables for linear classifier/regression
    #: (shard_map'd train/classify), row-sharded arenas + signature
    #: tables for NN/recommender/anomaly hash methods
    shard_devices: int = 0
    #: features per shard for the linear engines: the per-device HBM
    #: budget form of --shard-devices (shard count = D / D_PER_SHARD);
    #: mutually exclusive with --shard-devices
    shard_features: int = 0
    #: approximate-NN tier for the instance engines (NN/recommender/
    #: anomaly): "off" = every query is the exact scan (baseline);
    #: "ivf" = coarse k-means cells, probe top-P + exact rescore over
    #: only the probed candidates (ops/ivf.py, parallel/sharded_ivf.py)
    ann: str = "off"
    #: IVF cell count; 0 = auto (power of two near √rows)
    ann_cells: int = 0
    #: cells probed per query — the recall/latency dial (higher = more
    #: exact, slower)
    ann_nprobe: int = 8
    #: FORCE every response into the pre-str8/bin msgpack format deployed
    #: jubatus clients require (their vendored msgpack predates those
    #: types); mixer internals keep the modern format (rpc/legacy.py).
    #: Without it, servers AUTODETECT per connection from the first
    #: request's fingerprint — unmodified old clients just work.
    legacy_wire: bool = False
    #: disable the per-connection legacy-wire autodetection (answer every
    #: non-forced connection in modern msgpack)
    modern_wire: bool = False
    #: jax.distributed world for --mixer collective_mixer: every replica
    #: process must join one runtime so the mix's diff psum can span them
    #: (parallel/multihost.py). Process 0's address doubles as the
    #: coordinator endpoint; peers may omit it when the coordination
    #: store publishes it.
    jax_coordinator: str = ""       # host:port of jax process 0
    jax_processes: int = 0          # world size; 0 = no distributed init
    jax_process_id: int = -1
    #: --mix-quorum: minimum fraction of members whose diffs must arrive
    #: for a master round to proceed (framework/linear_mixer.py); rounds
    #: below 100% but at/above quorum run DEGRADED (counted + stamped in
    #: the flight recorder), below it they abort
    mix_quorum: float = 0.5
    #: --mix-compress: the collective mixer's wire mode. ``off`` ships
    #: diffs at their native dtype; ``bf16`` casts f32 diffs to bf16 ON
    #: DEVICE in the ship path (half the interconnect bytes; additive
    #: diffs fold into an f32 master); ``int8`` runs the block-quantized
    #: collective (~4x fewer wire bytes, one f32 scale per 256 elements)
    #: with a per-replica error-feedback residual carried between rounds
    #: so the averaged weights stay unbiased. All members must agree —
    #: a mixed cluster falls back to the RPC mix.
    mix_compress: str = "off"
    #: --mix-bf16: deprecated alias for ``--mix-compress bf16`` (kept so
    #: existing deployments' argv keeps working); an explicit
    #: --mix-compress wins when both are given.
    mix_bf16: bool = False
    #: --mix-topology: hierarchical mix tier shape (collective mixer
    #: only). ``""`` = flat single-tier psum; ``auto`` derives N hosts
    #: x M local devices from the runtime (hierarchical when M > 1);
    #: explicit ``HxM`` groups the process world. The two-tier reduce
    #: psums intra-host first and ships ONE chunk copy per host on the
    #: inter-host wire — wire bytes per host stay proportional to
    #: hosts, not total devices. The resolved NxM rides the prepare
    #: signature: heterogeneous fleets fall back to the RPC mix.
    mix_topology: str = ""
    #: --mix-async: asynchronous staleness-bounded mix
    #: (framework/async_mixer.py; linear_mixer only). Rounds stream in
    #: the background: members PUSH diffs to the master's inbox on
    #: their own cadence, the master folds whatever arrived with
    #: per-member staleness weights, and nothing on the serving path
    #: waits for a round — no gather barrier, no quorum abort.
    mix_async: bool = False
    #: --mix-staleness-bound: rounds-stale past which a submitted diff
    #: is dropped from the fold (weight decays 2**-staleness up to the
    #: bound). The async plane's correctness governor: a straggler
    #: degrades its own contribution instead of stalling the fleet.
    mix_staleness_bound: int = 8
    #: --mix-guard: model-integrity admission guard
    #: (framework/model_guard.py, ISSUE 15). ``off`` = no screening;
    #: ``warn`` (default) = screen every contribution for non-finite
    #: leaves and update-norm outliers, count + emit, fold anyway;
    #: ``quarantine`` = drop flagged contributions from the fold,
    #: refuse non-finite folded totals (auto-rollback to the last-good
    #: snapshot), and trip a per-member quarantine breaker on repeat
    #: offenders (released after K clean rounds). The collective path
    #: additionally CRC32-checks every staged wire chunk and finite-
    #: screens reduced totals under any non-off mode.
    mix_guard: str = "warn"
    #: --mix-norm-bound: norm-outlier multiplier — a contribution whose
    #: update norm exceeds this multiple of its PEERS' median norm is
    #: flagged (leave-one-out median; a quiet fleet judges nothing)
    mix_norm_bound: float = 10.0
    #: --auto-tune: the self-tuning performance plane
    #: (coord/perf_tuner.py, ISSUE 20). ``off`` = no tuner; ``observe``
    #: = run every tuner core off the telemetry tick and journal
    #: dry-run recommendations (``jubactl -c tune``) without touching a
    #: knob; ``on`` = actuate — wire mode + chunk size through the
    #: re-signed prepare plan, microbatch depth via Little's law, and
    #: the async-mix cadence within the floor/ceiling below.
    auto_tune: str = "off"
    #: --tune-interval-floor/-ceiling: operator bounds (seconds) the
    #: cadence tuner must stay inside when retargeting the mix interval
    tune_interval_floor: float = 1.0
    tune_interval_ceiling: float = 120.0
    #: --model-snapshot-interval: seconds between in-process model
    #: snapshots into the rollback ring (save_load envelope + CRC32,
    #: bounded depth). 0 = off. The snapshots are what
    #: ``jubactl -c rollback`` and the non-finite-total auto-rollback
    #: restore.
    model_snapshot_interval: float = 0.0
    #: --fault (repeatable): arm a fault-injection rule at boot
    #: (utils/faults.py; SITE:MODE[:ARG], MODE in {error,delay,drop}) —
    #: the chaos lever for drills and the straggler/partition tests.
    #: Also armable via the JUBATUS_TPU_FAULTS env var.
    fault: List[str] = dataclasses.field(default_factory=list)
    #: Prometheus /metrics + /healthz HTTP port (utils/metrics_http.py):
    #: -1 = off (default), 0 = ephemeral (actual port in get_status)
    metrics_port: int = -1
    #: --slowlog-*: tail-based slow-request capture (utils/slowlog.py) —
    #: an RPC at/above this quantile of its OWN span histogram lands in
    #: a bounded ring (queryable: get_slow_log RPC, jubadump --slow-log)
    #: and stamps a Prometheus exemplar on its histogram bucket.
    #: capacity 0 disables capture; no capture below min_count samples.
    slowlog_capacity: int = 256
    slowlog_quantile: float = 0.99
    slowlog_min_count: int = 64
    #: runtime telemetry sampler period (utils/runtime_telemetry.py):
    #: RSS/FDs/threads/GC + JAX compile+cache+device-memory signals into
    #: get_status (runtime.*), /metrics, /healthz; 0 disables the thread
    telemetry_interval: float = 10.0
    #: --fv-cache-size: bound (entries) for the feature pipeline's
    #: tokenization/filter/name memo caches (core/fv/converter.py) — hot
    #: repeated strings skip re-splitting/re-hashing; 0 disables
    #: memoization
    fv_cache_size: int = 65536
    #: --slo (repeatable): declarative SLOs evaluated as multi-window
    #: burn rates over the metric time-series ring (utils/slo.py).
    #: Grammar: ``latency:<span>:p<QQ>:<threshold_ms>[:<objective>]``,
    #: ``error_rate:<span|*>:<objective>``, ``gauge:<key>:<ceiling>``;
    #: optional ``name=`` prefix. Firing alerts surface as ``slo.*``
    #: gauges on /metrics, degrade /healthz, and list under
    #: ``jubactl -c alerts``.
    slo: List[str] = dataclasses.field(default_factory=list)
    #: --slo-fast/slow-window: the multi-window burn-rate pair (s) —
    #: the fast window proves the burn is CURRENT (and clears alerts
    #: quickly after recovery), the slow one that it is significant
    slo_fast_window: float = 300.0
    slo_slow_window: float = 3600.0
    #: --slo-burn-threshold: fire when BOTH windows burn error budget
    #: at/above this multiple of the sustainable rate
    slo_burn_threshold: float = 2.0
    #: --timeseries-capacity: points retained in the per-process metric
    #: time-series ring (one point per telemetry tick; the default is
    #: 1 h of history at the 10 s interval). 0 disables the ring (and
    #: with it SLO evaluation and get_timeseries).
    timeseries_capacity: int = 360
    #: --profile-hz: always-on stack sampling rate (utils/profiler.py) —
    #: a daemon thread samples every thread's stack at this rate into a
    #: bounded windowed store served by ``get_profile`` / ``jubactl -c
    #: profile`` / ``jubadump --profile``. 0 disables the sampler (no
    #: thread); the default ~67 Hz stays inside the <2% overhead budget
    #: (bench_serving run_profiling_overhead).
    profile_hz: float = 67.0
    #: --profile-dir: artifacts directory for on-demand device captures
    #: (``profile_device`` RPC wrapping jax.profiler.trace); empty =
    #: <datadir>/jubatus_profile_<engine>_<port>. Capped — old captures
    #: are pruned.
    profile_dir: str = ""
    #: --profile-trigger-breaches: this many slow-log captures of the
    #: SAME span inside --profile-trigger-window auto-capture a short
    #: sampling-profile snapshot stamped with the breaching trace_ids
    #: (once per window; 0 disables the tail trigger)
    profile_trigger_breaches: int = 3
    profile_trigger_window: float = 10.0
    #: elastic membership (ISSUE 10): a joining replica automatically
    #: streams its owned key ranges from the current owners (drivers
    #: exposing the row-migration hooks only); disable to join cold and
    #: repair later with ``jubactl -c rebalance``
    auto_rebalance: bool = True
    #: --drain-grace: seconds the drain state machine waits for
    #: in-flight work (RPC workers + coalescer queues) after the
    #: dispatch gate flips, before handing rows off
    drain_grace: float = 1.0
    #: --event-capacity: events retained in the cluster event journal
    #: (utils/events.py, ISSUE 14) — typed HLC-stamped state-transition
    #: events served by ``get_events`` / ``jubactl -c timeline``;
    #: 0 disables emission entirely
    event_capacity: int = 2048
    #: --incident-window: debounce window (seconds) for automatic
    #: incident forensics bundles (utils/incidents.py): an SLO
    #: transitioning to firing or /healthz going degraded captures ONE
    #: correlated forensic snapshot per window; 0 disables auto-capture
    incident_window: float = 300.0
    #: --incident-dir: capped artifacts dir for incident bundles
    #: (oldest pruned); empty = <datadir>/jubatus_incidents_<engine>_<port>
    incident_dir: str = ""
    #: --quality-sample: fraction of train/FV batches the data-quality
    #: plane (utils/quality.py, ISSUE 17) records into its drift
    #: sketches and scores prequentially; 0 disarms the plane
    quality_sample: float = 0.05
    #: --quality-window: seconds per quality window — the live sketch
    #: rolls into the reference-vs-live ring at this cadence and drift
    #: (PSI) is recomputed against the pinned reference
    quality_window: float = 60.0
    #: --quality-ref-windows: completed windows merged into the pinned
    #: reference before drift scoring starts
    quality_ref_windows: int = 2
    #: --usage-top: principals tracked EXACTLY by the usage ledger
    #: (utils/usage.py, ISSUE 19) before the long tail folds into
    #: ``(other)`` (the sketch lane still ranks everyone); 0 disarms
    #: the attribution plane entirely
    usage_top: int = 64
    #: --usage-gauge-principals: top-demand principals published as
    #: ``usage.<principal>.*`` gauges per telemetry tick (bounds the
    #: gauge namespace under high tenant cardinality)
    usage_gauge_principals: int = 8
    #: --store-dir: root of the shared snapshot store (the durable
    #: model plane, framework/model_store.py, ISSUE 18) — a directory
    #: every member and jubactl can reach (NFS/fuse mount stands in for
    #: an object store; the backend API is shaped for one). Empty
    #: disables the plane: no uploads, no warm-boot, save/load stay
    #: node-local.
    store_dir: str = ""
    #: --store-interval: seconds between background store uploads
    #: (full snapshot first, then incremental diff records vs the
    #: uploaded chain); 0 disables the uploader (the store still serves
    #: save/load/restore)
    store_interval: float = 0.0
    #: --store-compact-every: diff records per chain before the
    #: uploader re-anchors with a fresh full snapshot and the store
    #: folds the old chain (bounds restore cost AND the lossy tail
    #: under --store-compress int8)
    store_compact_every: int = 8
    #: --store-compress: diff-record encoding. ``off`` ships lossless
    #: f32 deltas (bit-exact replay); ``int8`` block-quantizes float
    #: deltas (~4x smaller, same scheme as --mix-compress int8) with an
    #: uploader-held error-feedback residual so chain replay error is
    #: bounded by ONLY the last diff's quantization
    store_compress: str = "off"
    #: --no-store-warmboot: boot cold even when --store-dir is set (the
    #: store still receives uploads). Default: a booting replica loads
    #: the freshest store snapshot + diff chain BEFORE entering the
    #: ring, then catches up via the normal mix plane
    store_warmboot: bool = True

    @property
    def is_standalone(self) -> bool:
        return self.coordinator == ""

    @property
    def bind_host(self) -> str:
        return self.listen_addr or "0.0.0.0"

    @property
    def eth(self) -> str:
        """Our address as seen by peers (reference common/network get_ip)."""
        if self.listen_addr and self.listen_addr != "0.0.0.0":
            return self.listen_addr
        try:
            s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
            s.connect(("10.255.255.255", 1))
            ip = s.getsockname()[0]
            s.close()
            return ip
        except OSError:
            return "127.0.0.1"

    def flags_status(self) -> Dict[str, Any]:
        """Flag dump for get_status (server_helper.hpp:119-219)."""
        return {f"argv.{f.name}": getattr(self, f.name)
                for f in dataclasses.fields(self)}


def build_parser(prog: str = "jubatus_tpu.server") -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog=prog, description="jubatus_tpu engine server"
    )
    p.add_argument("engine", help="engine type (classifier, recommender, ...)")
    p.add_argument("-p", "--rpc-port", type=int, default=9199)
    p.add_argument("-b", "--listen-addr", default="")
    p.add_argument("-c", "--thread", type=int, default=2)
    p.add_argument("-t", "--timeout", type=float, default=10.0)
    p.add_argument("-d", "--datadir", default="/tmp")
    p.add_argument("-l", "--logdir", default="")
    p.add_argument("-g", "--log-config", default="",
                   help="logging dictConfig JSON; hot-reloaded on SIGHUP")
    p.add_argument("-f", "--configpath", default="")
    p.add_argument("-m", "--model-file", default="")
    p.add_argument("-D", "--daemon", action="store_true")
    p.add_argument("--config-test", action="store_true")
    p.add_argument("-z", "--coordinator", default="",
                   help="coordination backend: tcp://host:port (coordd), "
                        "zk://host:port[,host:port...] (a real ZooKeeper "
                        "ensemble — drop-in for existing deployments), a "
                        "shared dir path, or 'memory'; empty = standalone")
    p.add_argument("-n", "--name", default="")
    p.add_argument("-x", "--mixer", default="linear_mixer",
                   choices=["linear_mixer", "collective_mixer",
                            "random_mixer", "broadcast_mixer",
                            "skip_mixer", "dummy_mixer"])
    p.add_argument("-s", "--interval-sec", type=float, default=16.0)
    p.add_argument("-i", "--interval-count", type=int, default=512)
    p.add_argument("--coordinator-timeout", "--zookeeper-timeout",
                   dest="coordinator_timeout", type=float, default=10.0)
    p.add_argument("--interconnect-timeout", type=float, default=10.0)
    p.add_argument("--microbatch-max", type=int, default=8192,
                   help="coalesce concurrent train RPCs into one device "
                        "batch up to this many examples; 0 = direct path. "
                        "Depth is bounded by -c (RPC workers) — raise -c "
                        "toward client concurrency for real batching")
    p.add_argument("--shard-devices", type=int, default=0,
                   help="span the model over this many local devices (0/1 = "
                        "single device): feature-sharded tables for linear "
                        "classifier/regression (shard_map'd train/classify "
                        "— per-device weight state is D/N), row-sharded "
                        "arenas + signature tables for NN/recommender/"
                        "anomaly hash methods (rows land in their "
                        "CHT-owned shard; per-shard top-k with a "
                        "log-depth on-device merge)")
    p.add_argument("--shard-features", type=int, default=0, metavar="D_PER_SHARD",
                   help="feature-shard the linear engines by per-device "
                        "budget instead of device count: shard count = "
                        "feature dim / D_PER_SHARD (must divide; needs "
                        "that many local devices). The HBM-capacity "
                        "spelling of --shard-devices — pick the widest "
                        "slice one device holds and the layout follows. "
                        "Mutually exclusive with --shard-devices")
    p.add_argument("--ann", choices=("off", "ivf"), default="off",
                   help="approximate-NN tier for the instance engines "
                        "(nearest_neighbor/recommender/anomaly): 'off' "
                        "(default) keeps every query on the exact "
                        "brute-force scan; 'ivf' partitions rows into "
                        "k-means cells and answers queries by probing "
                        "the nearest cells + an exact rescore of only "
                        "their rows — the 10^8-row p99 drops ~50x at "
                        ">=0.95 recall@10 (PERF_NOTES.md Round 16). "
                        "LOF density scans and anomaly scores stay "
                        "exact either way")
    p.add_argument("--ann-cells", type=int, default=0, metavar="K",
                   help="IVF cell count for --ann ivf; 0 (default) "
                        "auto-sizes to a power of two near sqrt(rows) "
                        "— the classical probe-cost/rescore-cost "
                        "balance point")
    p.add_argument("--ann-nprobe", type=int, default=8, metavar="P",
                   help="cells probed per query for --ann ivf — the "
                        "recall/latency dial: each probed cell adds "
                        "~rows/cells candidates to the exact rescore; "
                        "raise toward the cell count to converge on "
                        "the exact result")
    p.add_argument("--legacy-wire", action="store_true",
                   help="FORCE all RPC responses into the pre-str8/bin "
                        "msgpack format legacy jubatus clients (vendored "
                        "pre-2013 msgpack) parse; without it the server "
                        "autodetects per connection from the first "
                        "request's type bytes")
    p.add_argument("--modern-wire", action="store_true",
                   help="disable the per-connection legacy-wire "
                        "autodetection: always answer in modern msgpack "
                        "(str8/bin) unless --legacy-wire forces otherwise")
    p.add_argument("--jax-coordinator", default="",
                   help="jax.distributed coordinator host:port (process "
                        "0's reachable address) for --mixer "
                        "collective_mixer")
    p.add_argument("--jax-processes", type=int, default=0,
                   help="jax.distributed world size (replica process "
                        "count); 0 disables distributed jax init")
    p.add_argument("--jax-process-id", type=int, default=-1,
                   help="this process's rank in the jax world")
    p.add_argument("--mix-quorum", type=float, default=0.5,
                   help="minimum fraction of members whose diffs must "
                        "arrive for a mix round to proceed; rounds above "
                        "quorum but below 100%% run degraded (counted as "
                        "mix.quorum_degraded)")
    p.add_argument("--mix-compress", default="off",
                   choices=["off", "bf16", "int8"],
                   help="collective mixer wire mode: off = native "
                        "dtypes; bf16 = cast f32 diffs to bf16 on "
                        "device (half the bytes per round); int8 = "
                        "block-quantized collective (~4x fewer wire "
                        "bytes, one f32 scale per 256 elements) with an "
                        "error-feedback residual carried between rounds "
                        "so averaged weights stay unbiased. All members "
                        "must agree or the round falls back to the RPC "
                        "mix")
    p.add_argument("--mix-bf16", action="store_true",
                   help="deprecated alias for --mix-compress bf16 (an "
                        "explicit --mix-compress wins when both are "
                        "given)")
    p.add_argument("--mix-topology", default="",
                   help="hierarchical mix tier shape (collective mixer): "
                        "'' = flat single-tier psum; 'auto' = derive N "
                        "hosts x M local devices from the runtime and go "
                        "hierarchical when M > 1; explicit 'HxM' groups "
                        "the process world (co-located processes per "
                        "host). Intra-host reduce first, one chunk copy "
                        "per host on the inter-host wire; the resolved "
                        "NxM rides the prepare signature so mismatched "
                        "fleets fall back to the RPC mix")
    p.add_argument("--mix-async", action="store_true",
                   help="stream mix rounds asynchronously (linear "
                        "mixer only): members push diffs to the "
                        "master's inbox in the background and the "
                        "master folds whatever arrived with per-member "
                        "bounded-staleness weights — no gather "
                        "barrier on the serving path, no quorum "
                        "aborts; a straggler's contribution decays "
                        "instead of stalling the round")
    p.add_argument("--mix-staleness-bound", type=int, default=8,
                   help="rounds-stale past which a submitted diff is "
                        "dropped from the async fold (its weight "
                        "decays 2**-staleness up to the bound); the "
                        "async plane's correctness governor")
    p.add_argument("--mix-guard", default="warn",
                   choices=["off", "warn", "quarantine"],
                   help="model-integrity admission guard: screen every "
                        "mix contribution for non-finite leaves and "
                        "update-norm outliers before it enters a fold "
                        "(and every folded total before it applies). "
                        "off = no screening; warn = count + emit, fold "
                        "anyway; quarantine = drop flagged "
                        "contributions, refuse non-finite totals with "
                        "auto-rollback to the last-good snapshot, and "
                        "exclude repeat offenders until they screen "
                        "clean. The collective path also CRC32-checks "
                        "staged wire chunks under any non-off mode")
    p.add_argument("--mix-norm-bound", type=float, default=10.0,
                   help="norm-outlier multiplier for the mix guard: a "
                        "contribution whose update norm exceeds this "
                        "multiple of its peers' median norm this round "
                        "is flagged (leave-one-out median — robust "
                        "from 2 contributors up; a quiet fleet judges "
                        "nothing)")
    p.add_argument("--auto-tune", default="off",
                   choices=["off", "observe", "on"],
                   help="self-tuning performance plane "
                        "(coord/perf_tuner.py): close the loop from "
                        "telemetry to knobs. off = static flags only; "
                        "observe = journal dry-run recommendations "
                        "(jubactl -c tune) without touching anything; "
                        "on = actuate — mix wire mode + chunk size "
                        "(re-signed prepare plan, at most one RPC-"
                        "fallback round per transition), microbatch "
                        "depth (Little's-law residency target), and "
                        "the async-mix cadence")
    p.add_argument("--tune-interval-floor", type=float, default=1.0,
                   help="cadence tuner floor (seconds): auto-tune "
                        "never quickens the mix interval below this")
    p.add_argument("--tune-interval-ceiling", type=float, default=120.0,
                   help="cadence tuner ceiling (seconds): auto-tune "
                        "never relaxes the mix interval above this")
    p.add_argument("--model-snapshot-interval", type=float, default=0.0,
                   help="seconds between in-process model snapshots "
                        "into the bounded rollback ring (save_load "
                        "envelope format, CRC32-validated on restore); "
                        "0 disables. The ring is what jubactl -c "
                        "rollback and the guard's non-finite-total "
                        "auto-rollback restore")
    p.add_argument("--fault", action="append", default=None,
                   metavar="SITE:MODE[:ARG]",
                   help="arm a fault-injection rule at boot "
                        "(repeatable; utils/faults.py). SITE is a "
                        "dotted glob (e.g. mix.comm.put_diff, "
                        "rpc.call.train.*), MODE in {error, delay, "
                        "drop}; delay takes seconds, error a "
                        "probability, @N suffixes bound firings "
                        "(e.g. 'mix.put_diff:error@3'). Also armable "
                        "via JUBATUS_TPU_FAULTS")
    p.add_argument("--metrics-port", type=int, default=-1,
                   help="serve Prometheus /metrics + /healthz on this "
                        "HTTP port (0 = ephemeral; default off)")
    p.add_argument("--slowlog-capacity", type=int, default=256,
                   help="slow-request ring size (tail-based capture of "
                        "RPCs at/above --slowlog-quantile of their own "
                        "latency histogram; 0 disables)")
    p.add_argument("--slowlog-quantile", type=float, default=0.99,
                   help="per-span histogram quantile at/above which a "
                        "request is captured in the slow log (and "
                        "exemplar-stamped on /metrics)")
    p.add_argument("--slowlog-min-count", type=int, default=64,
                   help="samples a span needs before slow-log "
                        "thresholding starts (early on, everything "
                        "is 'p99')")
    p.add_argument("--telemetry-interval", type=float, default=10.0,
                   help="runtime telemetry sampling period in seconds "
                        "(RSS/FDs/threads/GC + JAX compile/cache/device-"
                        "memory into get_status, /metrics, /healthz; "
                        "0 disables the sampler thread)")
    p.add_argument("--fv-cache-size", type=int, default=65536,
                   help="entry bound for the feature pipeline's "
                        "tokenization/filter/name memo caches (repeated "
                        "hot strings skip re-splitting and re-hashing); "
                        "0 disables memoization")
    p.add_argument("--slo", action="append", default=None, metavar="SPEC",
                   help="declarative SLO evaluated as a multi-window "
                        "burn rate (repeatable). SPEC is "
                        "latency:<span>:p<QQ>:<threshold_ms>[:<objective>]"
                        " (e.g. latency:rpc.classify:p99:50), "
                        "error_rate:<span|*>:<objective> "
                        "(e.g. error_rate:*:0.01), or "
                        "gauge:<key>:<ceiling>; an optional name= prefix "
                        "names the alert. Firing alerts surface as "
                        "slo.* gauges on /metrics, degrade /healthz, and "
                        "list under jubactl -c alerts")
    p.add_argument("--slo-fast-window", type=float, default=300.0,
                   help="fast burn-rate window in seconds (proves the "
                        "burn is current; clears alerts after recovery)")
    p.add_argument("--slo-slow-window", type=float, default=3600.0,
                   help="slow burn-rate window in seconds (proves the "
                        "burn is significant, not one blip)")
    p.add_argument("--slo-burn-threshold", type=float, default=2.0,
                   help="fire an alert when BOTH windows burn error "
                        "budget at/above this multiple of the "
                        "sustainable rate")
    p.add_argument("--timeseries-capacity", type=int, default=360,
                   help="points retained in the metric time-series ring "
                        "(one per telemetry tick; default = 1 h at the "
                        "10 s interval). 0 disables the ring, SLO "
                        "evaluation, and get_timeseries")
    p.add_argument("--profile-hz", type=float, default=67.0,
                   help="always-on stack sampling rate (Hz): a daemon "
                        "thread folds every thread's stack into a "
                        "bounded windowed store served by get_profile, "
                        "jubactl -c profile, and jubadump --profile; "
                        "0 disables the sampler thread entirely")
    p.add_argument("--profile-dir", default="",
                   help="artifacts directory for on-demand device "
                        "captures (profile_device RPC wrapping "
                        "jax.profiler.trace; jubactl -c profile "
                        "--device); empty = under --datadir. Old "
                        "captures are pruned past a fixed cap")
    p.add_argument("--profile-trigger-breaches", type=int, default=3,
                   help="slow-log captures of the SAME span inside "
                        "--profile-trigger-window that auto-capture a "
                        "short sampling-profile snapshot stamped with "
                        "the breaching trace_ids (once per window; "
                        "0 disables the tail trigger)")
    p.add_argument("--profile-trigger-window", type=float, default=10.0,
                   help="breach-counting window (seconds) for the "
                        "tail-triggered profile snapshot")
    p.add_argument("--no-auto-rebalance", dest="auto_rebalance",
                   action="store_false",
                   help="do NOT stream owned key ranges from the current "
                        "owners on join (elastic membership): join cold "
                        "and repair later with jubactl -c rebalance")
    p.add_argument("--drain-grace", type=float, default=1.0,
                   help="seconds the drain state machine waits for "
                        "in-flight work after new effectful calls start "
                        "being rejected, before handing rows off to the "
                        "new ring owners")
    p.add_argument("--event-capacity", type=int, default=2048,
                   help="events retained in the cluster event journal "
                        "(typed, HLC-stamped state-transition events "
                        "served by get_events / jubactl -c timeline); "
                        "0 disables emission entirely")
    p.add_argument("--incident-window", type=float, default=300.0,
                   help="debounce window (seconds) for automatic "
                        "incident forensics bundles: an SLO firing or "
                        "/healthz going degraded captures ONE correlated "
                        "snapshot (event window, timeseries, slow log, "
                        "flight records, profiler tail) per window; "
                        "0 disables auto-capture")
    p.add_argument("--incident-dir", default="",
                   help="capped artifacts dir for incident bundles "
                        "(oldest pruned past a fixed cap; jubactl -c "
                        "incident lists/pulls them); empty = under "
                        "--datadir")
    p.add_argument("--quality-sample", type=float, default=0.05,
                   help="fraction of train/FV batches the data-quality "
                        "plane records into its drift sketches and "
                        "scores prequentially (test-then-train); "
                        "0 disarms the plane")
    p.add_argument("--quality-window", type=float, default=60.0,
                   help="seconds per data-quality window: the live "
                        "sketches roll into the reference-vs-live ring "
                        "at this cadence and PSI drift is recomputed "
                        "against the pinned reference")
    p.add_argument("--quality-ref-windows", type=int, default=2,
                   help="completed windows merged into the pinned "
                        "reference before drift scoring starts")
    p.add_argument("--usage-top", type=int, default=64,
                   help="principals (tenant ids) the usage ledger "
                        "tracks exactly before the long tail folds "
                        "into (other); the heavy-hitter sketch still "
                        "ranks everyone. 0 disarms per-tenant "
                        "attribution entirely")
    p.add_argument("--usage-gauge-principals", type=int, default=8,
                   help="top-demand principals published as "
                        "usage.<principal>.* gauges per telemetry "
                        "tick (bounds the gauge namespace under high "
                        "tenant cardinality)")
    p.add_argument("--store-dir", default="",
                   help="root of the shared snapshot store (durable "
                        "model plane, framework/model_store.py): a "
                        "directory every member and jubactl can reach "
                        "(NFS stands in for an object store). Enables "
                        "warm-boot, background diff-chain uploads "
                        "(--store-interval), store-backed save/load, "
                        "and jubactl -c restore. Empty = node-local "
                        "durability only")
    p.add_argument("--store-interval", type=float, default=0.0,
                   help="seconds between background snapshot uploads "
                        "to --store-dir (a full envelope first, then "
                        "incremental diff records against the chain); "
                        "0 disables the uploader thread while the "
                        "store still serves save/load/restore")
    p.add_argument("--store-compact-every", type=int, default=8,
                   help="diff records per chain before the uploader "
                        "re-anchors with a fresh full snapshot and the "
                        "store folds the chain (bounds restore cost "
                        "and the int8 tail)")
    p.add_argument("--store-compress", default="off",
                   choices=["off", "int8"],
                   help="diff-record encoding: off = lossless f32 "
                        "deltas (bit-exact chain replay); int8 = "
                        "block-quantized deltas (~4x smaller, the "
                        "--mix-compress int8 scheme) with an error-"
                        "feedback residual so replay error is bounded "
                        "by only the LAST diff's quantization")
    p.add_argument("--no-store-warmboot", dest="store_warmboot",
                   action="store_false",
                   help="boot cold even when --store-dir is set: skip "
                        "the warm-boot ladder (load freshest store "
                        "snapshot + diff chain before entering the "
                        "ring) and rely on join migration alone")
    return p


def parse_server_args(argv: Optional[List[str]] = None) -> ServerArgs:
    ns = build_parser().parse_args(argv)
    ns.slo = ns.slo or []  # argparse append default stays None (shared
    ns.fault = ns.fault or []  # mutable [] would leak across parses)
    args = ServerArgs(**{
        f.name: getattr(ns, f.name) for f in dataclasses.fields(ServerArgs)
    })
    if args.thread < 1:
        raise SystemExit("--thread must be >= 1")
    if args.microbatch_max < 0:
        raise SystemExit("--microbatch-max must be >= 0")
    if args.shard_devices < 0:
        raise SystemExit("--shard-devices must be >= 0")
    if args.shard_features < 0:
        raise SystemExit("--shard-features must be >= 0")
    if args.shard_features and args.shard_devices:
        raise SystemExit(
            "--shard-features and --shard-devices are mutually exclusive "
            "(the former derives the device count from the per-device "
            "feature budget)")
    if args.ann_cells < 0:
        raise SystemExit("--ann-cells must be >= 0 (0 = auto)")
    if args.ann_nprobe < 1:
        raise SystemExit("--ann-nprobe must be >= 1")
    if args.ann_cells and args.ann_nprobe > args.ann_cells:
        raise SystemExit("--ann-nprobe cannot exceed --ann-cells")
    if args.rpc_port < 0 or args.rpc_port > 65535:
        raise SystemExit("--rpc-port out of range")
    if args.metrics_port > 65535:
        raise SystemExit("--metrics-port out of range")
    if args.slowlog_capacity < 0:
        raise SystemExit("--slowlog-capacity must be >= 0")
    if not 0.0 < args.slowlog_quantile <= 1.0:
        raise SystemExit("--slowlog-quantile must be in (0, 1]")
    if args.telemetry_interval < 0:
        raise SystemExit("--telemetry-interval must be >= 0")
    if args.fv_cache_size < 0:
        raise SystemExit("--fv-cache-size must be >= 0")
    if args.timeseries_capacity < 0:
        raise SystemExit("--timeseries-capacity must be >= 0")
    if args.slo_fast_window <= 0 or args.slo_slow_window <= 0:
        raise SystemExit("--slo-*-window must be > 0")
    if args.slo_burn_threshold <= 0:
        raise SystemExit("--slo-burn-threshold must be > 0")
    if args.profile_hz < 0 or args.profile_hz > 1000:
        raise SystemExit("--profile-hz must be in [0, 1000]")
    if args.profile_trigger_breaches < 0:
        raise SystemExit("--profile-trigger-breaches must be >= 0")
    if args.profile_trigger_window <= 0:
        raise SystemExit("--profile-trigger-window must be > 0")
    if args.event_capacity < 0:
        raise SystemExit("--event-capacity must be >= 0")
    if args.incident_window < 0:
        raise SystemExit("--incident-window must be >= 0")
    if not 0.0 <= args.quality_sample <= 1.0:
        raise SystemExit("--quality-sample must be in [0, 1]")
    if args.quality_window <= 0:
        raise SystemExit("--quality-window must be > 0")
    if args.quality_ref_windows < 1:
        raise SystemExit("--quality-ref-windows must be >= 1")
    if args.usage_top < 0:
        raise SystemExit("--usage-top must be >= 0")
    if args.usage_gauge_principals < 1:
        raise SystemExit("--usage-gauge-principals must be >= 1")
    for spec in args.slo:
        from jubatus_tpu.utils.slo import parse_slo

        try:  # reject bad grammar at argv time, not at first tick
            parse_slo(spec)
        except ValueError as e:
            raise SystemExit(str(e))
    if args.mix_staleness_bound < 0:
        raise SystemExit("--mix-staleness-bound must be >= 0")
    if args.tune_interval_floor <= 0:
        raise SystemExit("--tune-interval-floor must be > 0")
    if args.tune_interval_floor > args.tune_interval_ceiling:
        raise SystemExit("--tune-interval-floor must not exceed "
                         "--tune-interval-ceiling")
    if args.mix_norm_bound <= 0:
        raise SystemExit("--mix-norm-bound must be > 0")
    if args.model_snapshot_interval < 0:
        raise SystemExit("--model-snapshot-interval must be >= 0")
    if args.mix_async and args.mixer != "linear_mixer":
        raise SystemExit(
            "--mix-async requires -x linear_mixer (push mixers are "
            "already leaderless; the collective is a barrier by "
            "construction)")
    for rule in args.fault:
        from jubatus_tpu.utils.faults import parse_rule

        try:  # reject bad grammar at argv time, not at first firing
            parse_rule(rule)
        except ValueError as e:
            raise SystemExit(str(e))
    if args.store_interval < 0:
        raise SystemExit("--store-interval must be >= 0")
    if args.store_compact_every < 1:
        raise SystemExit("--store-compact-every must be >= 1")
    if args.store_interval > 0 and not args.store_dir:
        raise SystemExit("--store-interval requires --store-dir")
    if args.mix_bf16 and args.mix_compress == "off":
        args.mix_compress = "bf16"  # deprecated alias resolves here
    if not args.is_standalone and not args.name:
        raise SystemExit("distributed mode (-z) requires --name")
    return args
