"""EngineServer — lifecycle + built-ins (≙ framework/server_base.{hpp,cpp} +
server_helper.{hpp,cpp} collapsed into one class).

Owns: driver, mixer, RPC server, optional coordinator session. Serves the
engine's IDL methods (bound by server/service.py) plus the reference's
built-ins — get_config / save / load / get_status / do_mix — and, when
distributed, the mixer's internal API and membership registration with the
suicide watcher (server_helper.cpp:96-112).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Dict, Optional

from jubatus_tpu.coord import create_coordinator, membership
from jubatus_tpu.coord.base import Coordinator, NodeInfo
from jubatus_tpu.coord.idgen import IdGenerator
from jubatus_tpu.framework.linear_mixer import RpcLinearMixer
from jubatus_tpu.framework.push_mixer import PushCommunication, create_mixer
from jubatus_tpu.framework.save_load import load_model, save_model
from jubatus_tpu.server.args import ServerArgs
from jubatus_tpu.server.factory import create_driver
from jubatus_tpu.version import __version__

log = logging.getLogger(__name__)


class EngineServer:
    def __init__(
        self,
        engine: str,
        config: Any,
        args: Optional[ServerArgs] = None,
        coord: Optional[Coordinator] = None,
    ) -> None:
        self.engine = engine
        self.args = args or ServerArgs(engine=engine)
        if isinstance(config, dict):
            config = json.dumps(config)
        self.config_json: str = config
        mesh = None
        if getattr(self.args, "shard_devices", 0) > 1:
            import jax
            from jax.sharding import Mesh

            # local_devices: on a multi-host runtime jax.devices() spans
            # every process, and device_put on non-addressable devices fails
            devs = jax.local_devices()[: self.args.shard_devices]
            if len(devs) < self.args.shard_devices:
                raise ValueError(
                    f"--shard-devices {self.args.shard_devices} but only "
                    f"{len(devs)} local devices present")
            mesh = Mesh(devs, axis_names=("shard",))
        # --fault: arm boot-time fault-injection rules (utils/faults.py;
        # process-global by design — the chaos plane models the process,
        # not one server object, exactly like the env-var path)
        fault_rules = getattr(self.args, "fault", None) or []
        if fault_rules:
            from jubatus_tpu.utils import faults

            faults.arm(*fault_rules)
            log.warning("fault injection armed from --fault: %s",
                        ", ".join(fault_rules))
        self.driver = create_driver(
            engine, json.loads(config), mesh=mesh,
            shard_features=getattr(self.args, "shard_features", 0),
            ann=getattr(self.args, "ann", "off"),
            ann_cells=getattr(self.args, "ann_cells", 0),
            ann_nprobe=getattr(self.args, "ann_nprobe", 8))
        # --fv-cache-size: rebound the converter's tokenization/name memo
        # caches (core/fv/converter.py; default matches the flag default)
        conv = getattr(self.driver, "converter", None)
        if conv is not None and hasattr(conv, "set_cache_size"):
            conv.set_cache_size(getattr(self.args, "fv_cache_size", 65536))
        self.start_time = time.time()  # wall-clock
        self.last_saved = 0.0
        self.last_loaded = 0.0
        #: train-path microbatch coalescers by method name (service.py
        #: populates; stats surface in get_status)
        self.coalescers: Dict[str, Any] = {}
        # transport: python sockets, or the C++ front-end when
        # JUBATUS_TPU_NATIVE_RPC=1 (rpc/native_server.py)
        from jubatus_tpu.rpc.native_server import create_rpc_server

        self.rpc = create_rpc_server(
            timeout=self.args.timeout,
            legacy_wire=getattr(self.args, "legacy_wire", False),
            wire_detect=not getattr(self.args, "modern_wire", False))
        # forensics plane (ISSUE 4): slow-request ring tuning off the
        # --slowlog-* flags, and the runtime telemetry sampler thread
        self.rpc.trace.slowlog.configure(
            capacity=getattr(self.args, "slowlog_capacity", 256),
            quantile=getattr(self.args, "slowlog_quantile", 0.99),
            min_count=getattr(self.args, "slowlog_min_count", 64))
        from jubatus_tpu.utils.runtime_telemetry import RuntimeTelemetry

        self.telemetry = RuntimeTelemetry(
            self.rpc.trace,
            interval_sec=getattr(self.args, "telemetry_interval", 10.0))
        # continuous profiling plane (ISSUE 8): always-on stack sampler
        # + capped device-capture dir + the slowlog tail trigger that
        # snapshots the sampler when one span breaches repeatedly
        from jubatus_tpu.utils.profiler import SamplingProfiler

        self.profiler = SamplingProfiler(
            self.rpc.trace, hz=getattr(self.args, "profile_hz", 67.0))
        #: created lazily (_device_capture()): the default artifacts dir
        #: carries the BOUND rpc port, which an ephemeral-port start
        #: only resolves at serve time
        self.device_capture = None
        trig = getattr(self.args, "profile_trigger_breaches", 3)
        if trig > 0 and self.profiler.enabled:
            self.rpc.trace.slowlog.set_trigger(
                self.profiler.tail_snapshot, breaches=trig,
                window_s=getattr(self.args, "profile_trigger_window", 10.0))
        # model-health plane (ISSUE 7): the metric time-series ring +
        # the SLO burn-rate engine, both ticked by the telemetry
        # sampler (one thread owns all periodic observability work)
        from jubatus_tpu.utils.slo import SloEngine, parse_slo
        from jubatus_tpu.utils.timeseries import TimeSeriesRing

        ts_cap = getattr(self.args, "timeseries_capacity", 360)
        interval = self.telemetry.interval_sec
        self.timeseries: Optional[TimeSeriesRing] = None
        self.slo: Optional[SloEngine] = None
        if ts_cap > 0:
            self.timeseries = TimeSeriesRing(
                capacity=ts_cap,
                min_spacing_s=min(1.0, interval / 2) if interval > 0
                else 0.0)
            self.slo = SloEngine(
                [parse_slo(s) for s in getattr(self.args, "slo", []) or []],
                self.timeseries, self.rpc.trace,
                fast_window_s=getattr(self.args, "slo_fast_window", 300.0),
                slow_window_s=getattr(self.args, "slo_slow_window", 3600.0),
                burn_threshold=getattr(
                    self.args, "slo_burn_threshold", 2.0))
            self.telemetry.hooks.append(self._model_health_tick)
        # cluster event plane + incident bundles (ISSUE 14): bound the
        # journal from the flag, and arm the two incident triggers —
        # SLO transitioning to firing, /healthz transitioning degraded
        from jubatus_tpu.utils.incidents import IncidentManager

        self.rpc.trace.events.set_capacity(
            getattr(self.args, "event_capacity", 2048))
        self.incidents = IncidentManager(
            self.rpc.trace, self._incident_state, self._incident_dir,
            window_s=getattr(self.args, "incident_window", 300.0),
            journal=self.rpc.trace.events)
        if self.slo is not None:
            self.slo.on_fire = self._on_slo_fire
        self._was_degraded = False
        # data-quality plane (ISSUE 17): mergeable drift sketches +
        # prequential accuracy, sampled by --quality-sample and ticked
        # by the same telemetry thread (gauges land BEFORE the ring
        # samples, so quality.drift.* is SLO-able with zero new grammar)
        from jubatus_tpu.utils.quality import QualityPlane

        self.quality: Optional[QualityPlane] = None
        qs = getattr(self.args, "quality_sample", 0.05)
        if qs > 0:
            self.quality = QualityPlane(
                sample=qs,
                window_s=getattr(self.args, "quality_window", 60.0),
                ref_windows=getattr(self.args, "quality_ref_windows", 2),
                registry=self.rpc.trace)
            conv = getattr(self.driver, "converter", None)
            if conv is not None and hasattr(conv, "quality_hook"):
                conv.quality_hook = self.quality.record_named
        # usage-attribution plane (ISSUE 19): per-principal resource
        # ledger. Wired three ways: the registry's usage_sink feeds it
        # every rpc.<method> span's CPU-seconds while the dispatch
        # thread still holds the request's principal; the transport's
        # usage_recorder notes errors + bytes; service.py binds the
        # coalescer usage_hook for queue/device attribution. Ticked by
        # the telemetry thread (gauges land BEFORE the ring samples, so
        # capacity.saturation is SLO-able with zero new grammar).
        from jubatus_tpu.utils import usage as usage_mod

        self.usage: Optional[usage_mod.UsageLedger] = None
        ut = getattr(self.args, "usage_top", 64)
        if ut > 0:
            self.usage = usage_mod.UsageLedger(
                top=ut,
                gauge_principals=getattr(
                    self.args, "usage_gauge_principals", 8),
                registry=self.rpc.trace)
            self.rpc.usage_recorder = self.usage
            self.rpc.trace.usage_sink = self.usage.span_sink
            usage_mod.attach(self.usage)
        #: re-entrancy guard: the incident collector reads _health(),
        #: whose telemetry.status() re-runs the sampler hooks — the
        #: tick must not recurse into itself mid-capture
        self._in_health_tick = False
        self._stop_event = threading.Event()
        self._stop_once = threading.Lock()  # first stop() wins; rest no-op
        # elastic membership (ISSUE 10): migration counters + the drain
        # state machine + a cached membership-epoch view (refreshed by
        # the same actives watch that invalidates the CHT snapshot)
        from jubatus_tpu.framework.migration import (DrainController,
                                                     MigrationStats)

        self.migration = MigrationStats(registry=self.rpc.trace)
        self.drain_ctl = DrainController(
            self, grace_sec=getattr(self.args, "drain_grace", 1.0))
        self._epoch_cache: Optional[int] = None
        # model-integrity plane (ISSUE 15): bounded ring of periodic
        # in-process model snapshots (save_load envelope + CRC32) —
        # the "last good" that jubactl -c rollback and the guard's
        # non-finite-total auto-rollback restore. Ticked by the same
        # telemetry thread that owns all periodic observability work.
        from jubatus_tpu.framework.model_guard import ModelSnapshotRing

        self.snapshots = ModelSnapshotRing()
        self._snapshot_interval = getattr(
            self.args, "model_snapshot_interval", 0.0)
        self._last_snapshot = 0.0
        self.rollbacks = 0
        self._last_rollback_ts = 0.0
        if self._snapshot_interval > 0:
            self.telemetry.hooks.append(self._model_snapshot_tick)
        # durable model plane (ISSUE 18): the shared snapshot store +
        # the background diff-chain uploader (created at start(), once
        # the bound port names this node) + warm-boot bookkeeping
        self.store = None
        self.store_uploader = None
        self.warmboot: Dict[str, Any] = {}
        self._store_interval = getattr(self.args, "store_interval", 0.0)
        self._last_store_upload = 0.0
        store_dir = getattr(self.args, "store_dir", "")
        if store_dir:
            from jubatus_tpu.framework.model_store import (LocalDirBackend,
                                                           ModelStore)

            self.store = ModelStore(
                LocalDirBackend(store_dir),
                cluster=self.args.name or "standalone", engine=engine,
                counter=self.rpc.trace.count)
            if self._store_interval > 0:
                self.telemetry.hooks.append(self._store_upload_tick)
        #: Prometheus /metrics + /healthz endpoint (--metrics-port >= 0)
        self.metrics = None
        #: pooled peer clients for server-side replicated writes
        self._peers: Dict[str, Any] = {}
        self._peer_lock = threading.Lock()
        #: watch-invalidated CHT snapshot (cluster_cht)
        self._cht_cache = None
        self._cht_expiry = 0.0
        self._cht_watched = False
        self._cht_lock = threading.Lock()

        # distributed wiring (server_helper ctor path, server_helper.cpp:48-78)
        self.coord = coord
        self.mixer: Optional[RpcLinearMixer] = None
        if not self.args.is_standalone or coord is not None:
            if self.coord is None:
                self.coord = create_coordinator(self.args.coordinator)
            comm = PushCommunication(
                self.coord, engine, self.args.name,
                timeout=self.args.interconnect_timeout,
            )
            # mixer strategy by --mixer flag (≙ mixer_factory)
            self.mixer = create_mixer(
                self.args.mixer, self.driver, comm,
                self_node=NodeInfo(self.args.eth, self.args.rpc_port),
                interval_sec=self.args.interval_sec,
                interval_count=self.args.interval_count,
                mix_compress=getattr(self.args, "mix_compress", "off"),
                mix_bf16=getattr(self.args, "mix_bf16", False),
                mix_topology=getattr(self.args, "mix_topology", ""),
                quorum_fraction=getattr(self.args, "mix_quorum", 0.5),
                mix_async=getattr(self.args, "mix_async", False),
                mix_staleness_bound=getattr(
                    self.args, "mix_staleness_bound", 8),
                mix_guard=getattr(self.args, "mix_guard", "warn"),
                mix_norm_bound=getattr(
                    self.args, "mix_norm_bound", 10.0),
            )
            self.mixer.set_trace_registry(self.rpc.trace)
            # model-integrity plane (ISSUE 15): a put_diff refusing a
            # non-finite folded total auto-rolls back to last-good
            if hasattr(self.mixer, "on_poisoned_total"):
                self.mixer.on_poisoned_total = self._auto_rollback
            # cluster-unique id minting for the engines that mint ids
            # (≙ global_id_generator_zk: anomaly add, graph create_node/edge)
            if hasattr(self.driver, "set_id_generator"):
                self.driver.set_id_generator(IdGenerator(
                    self.coord,
                    f"{membership.actor_path(engine, self.args.name)}/id_generator",
                ))
            # count updates into the mixer (server_base.cpp:214-219)
            driver_event = self.driver.event_model_updated

            def chained(n: int = 1) -> None:
                driver_event(n)
                self.mixer.updated(n)

            self.driver.event_model_updated = chained  # type: ignore[assignment]

        # self-tuning performance plane (ISSUE 20): the telemetry-to-
        # knobs loop (coord/perf_tuner.py) rides the same telemetry tick
        # as every other periodic plane. Created AFTER the mixer block —
        # its adapter reads self.mixer/self.coalescers as they exist now.
        from jubatus_tpu.coord.perf_tuner import (PerfTuner,
                                                  ServerTuneAdapter,
                                                  TunerConfig)

        self.tuner: Optional[PerfTuner] = None
        tune_mode = getattr(self.args, "auto_tune", "off")
        if tune_mode != "off":
            self.tuner = PerfTuner(
                TunerConfig(
                    mode=tune_mode,
                    interval_floor_s=getattr(
                        self.args, "tune_interval_floor", 1.0),
                    interval_ceiling_s=getattr(
                        self.args, "tune_interval_ceiling", 120.0)),
                ServerTuneAdapter(self), registry=self.rpc.trace)
            self.telemetry.hooks.append(self._tune_tick)

    def _tune_tick(self) -> None:
        """One perf-tuner pass per telemetry tick (PerfTuner.tick never
        raises — a sick adapter must not kill the telemetry thread)."""
        if self.tuner is not None:
            self.tuner.tick()

    def get_tune(self, _name: str = "") -> Dict[str, Any]:
        """This node's self-tuning state (coord/perf_tuner.py): mode,
        per-plane core state, backoff, and the decision journal — the
        per-node half of ``jubactl -c tune``."""
        node = NodeInfo(self.args.eth, self.rpc.port or self.args.rpc_port)
        if self.tuner is None:
            return {node.name: {}}
        return {node.name: self.tuner.status()}

    # -- construction from files/argv (run_server, server_util.hpp:139-176) --
    @classmethod
    def from_args(cls, args: ServerArgs,
                  coord: Optional[Coordinator] = None) -> "EngineServer":
        if args.configpath:
            with open(args.configpath) as f:
                config = f.read()
        elif not args.is_standalone:
            if coord is None:
                coord = create_coordinator(args.coordinator)
            raw = coord.read(membership.config_path(args.engine, args.name))
            if raw is None:
                raise RuntimeError(
                    f"no config registered for {args.engine}/{args.name} "
                    "(use jubaconfig to write one)"
                )
            return cls(args.engine, raw.decode(), args, coord=coord)
        else:
            raise RuntimeError("standalone mode requires -f/--configpath")
        srv = cls(args.engine, config, args, coord=coord)
        if args.model_file:
            srv.load_file(args.model_file)
        return srv

    # -- peer RPC (server-side replicated writes, anomaly_serv.cpp:275-297) --
    def self_nodeinfo(self) -> NodeInfo:
        return NodeInfo(self.args.eth, self.rpc.port or self.args.rpc_port)

    def peer_client(self, node: NodeInfo):
        """Pooled RPC client to a cluster peer (≙ the reference's
        client-to-peer sessions in selective_update)."""
        from jubatus_tpu.rpc.client import RpcClient

        with self._peer_lock:
            cli = self._peers.get(node.name)
            if cli is None:
                cli = RpcClient(node.host, node.port,
                                self.args.interconnect_timeout)
                self._peers[node.name] = cli
            return cli

    def _close_peers(self) -> None:
        with self._peer_lock:
            peers = list(self._peers.values())
            self._peers.clear()
        for cli in peers:
            try:
                cli.close()
            except Exception:  # broad-ok — teardown
                pass

    def drop_peer_client(self, node: NodeInfo) -> None:
        with self._peer_lock:
            cli = self._peers.pop(node.name, None)
        if cli is not None:
            try:
                cli.close()
            except Exception:  # broad-ok
                pass

    def cluster_cht(self):
        """CHT over the current actives (cht.cpp:107-143); None in
        standalone mode. Cached: the ring is a pure function of
        membership, so it rebuilds only when the membership watcher fires
        (or on TTL expiry for coordinators with best-effort watches) —
        never per write (replicated add/create_node is the ingest hot
        path)."""
        if self.coord is None:
            return None
        from jubatus_tpu.coord.cht import CHT

        now = time.monotonic()
        with self._cht_lock:
            if self._cht_cache is not None and now < self._cht_expiry:
                return self._cht_cache
        cht = CHT.from_coordinator(self.coord, self.engine, self.args.name)
        with self._cht_lock:
            self._cht_cache = cht
            self._cht_expiry = now + 2.0
            if not self._cht_watched:
                self._cht_watched = True
                path = membership.actor_path(
                    self.engine, self.args.name) + "/actives"
                try:
                    self.coord.watch_children(
                        path, lambda _p: self._invalidate_cht())
                except NotImplementedError:
                    pass
        return cht

    def _invalidate_cht(self) -> None:
        with self._cht_lock:
            self._cht_cache = None
            self._epoch_cache = None

    # -- elastic membership (ISSUE 10) ---------------------------------------
    def membership_epoch(self) -> int:
        """Current membership epoch, cached alongside the CHT snapshot
        (both invalidate on the same actives watch). Standalone: 0."""
        if self.coord is None:
            return 0
        with self._cht_lock:
            cached = self._epoch_cache
        if cached is not None:
            return cached
        epoch = membership.get_epoch(self.coord, self.engine, self.args.name)
        with self._cht_lock:
            self._epoch_cache = epoch
        self.rpc.trace.gauge("cluster.epoch", float(epoch))
        return epoch

    def get_epoch(self, _name: str = "") -> int:
        # the CHT cache TTL (2 s) bounds staleness; a watch-driven
        # invalidation makes it immediate
        self.cluster_cht()
        return self.membership_epoch()

    def migrate_range(self, _name: str, epoch: int, target: str,
                      cursor: str = "", limit: int = 0) -> Dict[str, Any]:
        """SOURCE side of the state-migration plane: rows after
        ``cursor`` that ``target`` owns under the current ring. The
        caller's epoch must match mine — a mismatch is the retryable
        ``EpochMismatch`` that forces a ring refresh on the puller
        (framework/migration.py)."""
        from jubatus_tpu.framework.migration import (DEFAULT_CHUNK_BYTES,
                                                     serve_range)
        from jubatus_tpu.rpc.errors import EpochMismatch

        mine = self.get_epoch()
        if int(epoch) != mine:
            raise EpochMismatch(expected=mine, got=int(epoch))
        target = target.decode() if isinstance(target, bytes) else str(target)
        cursor = cursor.decode() if isinstance(cursor, bytes) else str(cursor)
        ring = self.cluster_cht()
        if ring is None:
            return {"rows": [], "cursor": "", "done": True, "epoch": mine}
        if target not in {m.name for m in ring.members}:
            # the joiner may register between my watch ticks: extend the
            # ring view rather than reject (same members → same ring)
            from jubatus_tpu.coord.cht import CHT

            try:
                node = NodeInfo.from_name(target)
            except (ValueError, IndexError):
                return {"rows": [], "cursor": "", "done": True,
                        "epoch": mine}
            ring = CHT(list(ring.members) + [node], epoch=ring.epoch)
        with self.driver.lock:
            doc = serve_range(self.driver, ring, target, cursor,
                              int(limit) or DEFAULT_CHUNK_BYTES)
        doc["epoch"] = mine
        return doc

    def put_rows(self, _name: str, rows: Any) -> int:
        """Apply migrated rows (already-hashed vectors — no reconvert).
        Drivers without row hooks accept nothing (0)."""
        if not hasattr(self.driver, "put_rows"):
            return 0
        with self.driver.lock:
            n = int(self.driver.put_rows(rows or []))
        return n

    def get_row_count(self, _name: str = "") -> int:
        if hasattr(self.driver, "row_ids"):
            with self.driver.lock:
                return len(self.driver.row_ids())
        return 0

    def drain(self, _name: str = "", stop_after: bool = False) -> Dict[str, Any]:
        """Begin the drain state machine (framework/migration.py):
        reject new effectful work (retryable ``NodeDraining`` — proxies
        re-route), finish in-flight, hand rows to their new owners,
        unregister. Idempotent; returns the current state doc."""
        if self.coord is None:
            return {"state": "active", "error": "standalone: nothing to drain"}
        self.drain_ctl.start(stop_after=bool(stop_after))
        return self.drain_status()

    def drain_status(self, _name: str = "") -> Dict[str, Any]:
        doc = self.drain_ctl.status()
        doc["epoch"] = self.membership_epoch()
        return doc

    def rebalance(self, _name: str = "") -> Dict[str, Any]:
        """Pull every row this member owns under the CURRENT ring from
        the other actives — the joining member's half of the migration
        plane (also the ``jubactl -c rebalance`` repair action). Safe to
        re-run: rows apply as overwrites."""
        if self.coord is None or not hasattr(self.driver, "put_rows"):
            return {"rows": 0, "bytes": 0, "seconds": 0.0,
                    "mb_per_sec": 0.0, "sources_failed": []}
        from jubatus_tpu.framework.migration import RangePuller

        me = self.self_nodeinfo()
        sources = [m for m in membership.get_all_actives(
            self.coord, self.engine, self.args.name) if m.name != me.name]
        if not sources:
            return {"rows": 0, "bytes": 0, "seconds": 0.0,
                    "mb_per_sec": 0.0, "sources_failed": []}

        def apply_rows(rows) -> int:
            with self.driver.lock:
                return int(self.driver.put_rows(rows))

        puller = RangePuller(
            self.args.name, me.name, apply_rows,
            client_factory=self.peer_client, stats=self.migration,
            epoch_of=lambda: self.get_epoch())
        return puller.pull(sources)

    def _join_migration(self) -> None:
        """Background join-time pull: a freshly-registered replica
        streams its owned ranges from the current owners. Best-effort —
        a failed pull leaves the replica serving what the mix plane
        replicates; ``jubactl -c rebalance`` repairs."""
        try:
            out = self.rebalance(self.args.name)
            if out.get("rows"):
                log.info("join migration: pulled %d rows (%.2f MB) in %.2fs",
                         out["rows"], out["bytes"] / 2 ** 20, out["seconds"])
        except Exception:  # broad-ok — join must not die on migration
            log.warning("join migration failed", exc_info=True)

    # -- model-integrity plane: snapshots + rollback (ISSUE 15) --------------
    def _model_snapshot_tick(self) -> None:
        """One telemetry tick: take a model snapshot into the rollback
        ring when the interval elapsed (the first tick seeds the
        baseline — a poisoning incident in the first minutes of a
        process's life still has a last-good to return to)."""
        now = time.monotonic()
        if self._last_snapshot and \
                now - self._last_snapshot < self._snapshot_interval:
            return
        try:
            self.take_snapshot()
        except Exception:  # broad-ok — a failed snapshot must not kill
            log.warning("model snapshot failed", exc_info=True)  # the tick

    def take_snapshot(self) -> Dict[str, Any]:
        """Capture one in-process model snapshot (CRC-stamped save_load
        envelope) into the bounded rollback ring."""
        version = getattr(self.mixer, "model_version", 0) \
            if self.mixer is not None else 0
        with self.driver.lock:
            entry = self.snapshots.snapshot(self.driver, version)
        self._last_snapshot = time.monotonic()
        self.rpc.trace.gauge("mix.snapshots",
                             float(self.snapshots.stats()["count"]))
        return {k: v for k, v in entry.items() if k != "blob"}

    def rollback(self, _name: str = "", reason: str = "") -> Dict[str, Any]:
        """Restore the newest last-good snapshot into the live model
        (``jubactl -c rollback --target`` / the guard's auto-rollback).
        The restore revalidates the envelope CRC; the mixer's model
        version rebases to the snapshot's — in a healthy cluster the
        next round's version gate then pulls this node forward again,
        while in a poisoning incident (every guarded member refused the
        same total) the fleet stays consistently on last-good."""
        reason = reason.decode() if isinstance(reason, bytes) \
            else str(reason or "operator")
        entry = self.snapshots.latest()
        if entry is None:
            return {"rolled_back": False,
                    "error": "no model snapshot retained "
                             "(--model-snapshot-interval off?)"}
        with self.driver.lock:
            version = self.snapshots.restore(self.driver)
        if self.mixer is not None and \
                hasattr(self.mixer, "model_version"):
            self.mixer.model_version = version
        self.rollbacks += 1
        self._last_rollback_ts = time.monotonic()
        self.rpc.trace.count("mix.rollbacks")
        self.rpc.trace.events.emit(
            "mix", "rollback", severity="error", reason=reason,
            model_version=version)
        # a rollback is a forensics moment: bundle the window around it
        self.incidents.trigger(f"rollback:{reason}")
        log.error("model rolled back to snapshot v%d (%s)", version,
                  reason)
        return {"rolled_back": True, "model_version": version,
                "snapshot_ts": entry["ts"], "reason": reason,
                "snapshots": self.snapshots.stats()}

    def _auto_rollback(self) -> None:
        """Wired as the mixer's on_poisoned_total callback: put_diff
        refused a non-finite folded total — return to last-good."""
        out = self.rollback(self.args.name, reason="nonfinite_total")
        if not out.get("rolled_back"):
            log.error("auto-rollback unavailable: %s", out.get("error"))

    # -- durable model plane: store uploads + warm-boot + restore (ISSUE 18) --
    def _store_node_name(self) -> str:
        return NodeInfo(self.args.eth,
                        self.rpc.port or self.args.rpc_port).name

    def _store_upload_tick(self) -> None:
        """One telemetry tick of the background uploader: snapshot →
        diff vs the chain's belief → upload (full every
        --store-compact-every diffs, with store-side compaction).
        Upload failures are counted by the store and must never touch
        the serving path."""
        if self.store_uploader is None:
            return
        now = time.monotonic()
        if self._last_store_upload and \
                now - self._last_store_upload < self._store_interval:
            return
        self._last_store_upload = now
        # upload clock: local training progress + mix progress — either
        # one advancing means the model changed (a mix-only replica has
        # update_count 0; a mix-never fleet has model_version 0)
        version = int(self.driver.update_count)
        if self.mixer is not None:
            version += int(getattr(self.mixer, "model_version", 0) or 0)
        if version == 0 and not self.last_loaded:
            return  # pristine model: nothing worth a store record yet
        try:
            self.store_uploader.tick(self.driver, version)
        except Exception:  # broad-ok — a flaky store must not kill the tick
            log.warning("store upload failed", exc_info=True)

    def _warm_boot(self) -> None:
        """The warm-boot ladder (boot-time, BEFORE the ring sees this
        node): load the freshest store snapshot + diff chain into the
        driver, rebase the mixer's model version to the chain head, and
        let the normal mix plane (put_diff version gate → obsolete
        recovery) catch the tail up. ANY failure — no snapshot, CRC
        refusal, config mismatch, flaky store — degrades to cold boot +
        join migration, never a partial model (counted + evented)."""
        from jubatus_tpu.framework.save_load import (SaveLoadError,
                                                     load_model_bytes)

        t0 = time.monotonic()
        self.rpc.trace.count("warmboot.attempts")
        outcome = "cold"
        meta: Dict[str, Any] = {}
        try:
            got = self.store.latest()
            if got is None:
                if self.store.records(kind="full"):
                    # records exist but NONE materialized (corrupt/flaky
                    # store): that is a degrade, not a clean cold boot
                    raise SaveLoadError(
                        "store records present but none materializable")
                self.rpc.trace.count("warmboot.no_snapshot")
            else:
                blob, meta = got
                with self.driver.lock:
                    load_model_bytes(blob, self.driver,
                                     where=f"store:{meta['key']}",
                                     expected_config=self.config_json)
                if self.mixer is not None and \
                        hasattr(self.mixer, "model_version"):
                    self.mixer.model_version = int(meta["model_version"])
                self.last_loaded = time.time()  # wall-clock
                outcome = "warm"
                self.rpc.trace.count("warmboot.warm")
        except Exception as e:  # broad-ok — ANY failure degrades to cold
            outcome = "degraded_to_cold"
            self.rpc.trace.count("warmboot.degraded_to_cold")
            self.rpc.trace.events.emit(
                "warmboot", "degraded_to_cold", severity="warning",
                error=str(e)[:200])
            log.warning("warm boot degraded to cold: %s", e)
        seconds = round(time.monotonic() - t0, 3)
        self.rpc.trace.gauge("warmboot.seconds", seconds)
        self.warmboot = {
            "outcome": outcome, "seconds": seconds,
            "model_version": int(meta.get("model_version", 0)),
            "chain_len": int(meta.get("chain_len", 0)),
            "hlc": int(meta.get("hlc", 0)),
        }
        if outcome == "warm":
            self.rpc.trace.events.emit(
                "warmboot", "loaded", model_version=meta["model_version"],
                chain_len=meta["chain_len"], seconds=seconds)
            log.info("warm boot: model v%d (+%d diffs) in %.3fs",
                     meta["model_version"], meta["chain_len"], seconds)

    def store_restore(self, _name: str = "", at: int = 0) -> Dict[str, Any]:
        """Point-in-time restore from the store (``jubactl -c restore
        --at HLC|latest`` fans this out fleet-wide). Loads the freshest
        snapshot at/before ``at`` (0 = latest) as this node's model,
        then — for row-holding drivers — unions in the rows THIS node
        owns under the CURRENT ring from every other uploading node's
        snapshot: an N-shard fleet snapshot restores onto an M-shard
        fleet (reshard-on-restore through the store)."""
        from jubatus_tpu.framework.save_load import (SaveLoadError,
                                                     load_model_bytes)

        if self.store is None:
            return {"restored": False, "error": "no --store-dir configured"}
        hlc_at = int(at or 0) or None
        t0 = time.monotonic()
        got = self.store.latest(at=hlc_at)
        if got is None:
            return {"restored": False,
                    "error": "no store snapshot"
                             + (f" at hlc<={hlc_at}" if hlc_at else "")}
        blob, meta = got
        try:
            with self.driver.lock:
                load_model_bytes(blob, self.driver,
                                 where=f"store:{meta['key']}",
                                 expected_config=self.config_json)
        except SaveLoadError as e:
            return {"restored": False, "error": str(e)[:300]}
        if self.mixer is not None and hasattr(self.mixer, "model_version"):
            self.mixer.model_version = int(meta["model_version"])
        rows = self._restore_rows(hlc_at, skip_node=meta["node"])
        self.last_loaded = time.time()  # wall-clock
        self.rpc.trace.count("store.restores")
        doc = {"restored": True, "model_version": int(meta["model_version"]),
               "hlc": int(meta["hlc"]), "chain_len": int(meta["chain_len"]),
               "primary_node": meta["node"], "rows_imported": rows,
               "seconds": round(time.monotonic() - t0, 3)}
        self.rpc.trace.events.emit("store", "restored", **doc)
        return doc

    def _restore_rows(self, hlc_at: Optional[int], skip_node: str) -> int:
        """Reshard-on-restore: walk every OTHER uploading node's
        materialized snapshot through a scratch driver and put_rows the
        rows this member owns under the current ring (standalone: all
        of them). Row-less drivers import nothing — the primary
        envelope already carried the whole model."""
        if not hasattr(self.driver, "put_rows"):
            return 0
        from jubatus_tpu.framework.migration import serve_range
        from jubatus_tpu.server.factory import create_driver
        from jubatus_tpu.utils.serialization import unpack_obj

        ring = self.cluster_cht()
        me = self._store_node_name()
        imported = 0
        for node, (blob, _meta) in sorted(
                self.store.materialize_all(at=hlc_at).items()):
            if node == skip_node:
                continue
            try:
                from jubatus_tpu.framework.save_load import read_envelope

                _sys, user_bytes = read_envelope(blob, f"store:{node}")
                _uv, state = unpack_obj(user_bytes)
                scratch = create_driver(self.engine,
                                        json.loads(self.config_json))
                scratch.unpack(state)
            except Exception:  # broad-ok — a sick snapshot skips, never aborts
                log.warning("restore: skipping node %s snapshot", node,
                            exc_info=True)
                continue
            if not hasattr(scratch, "row_ids"):
                continue
            if ring is None:
                ids = sorted(scratch.row_ids())
                rows = scratch.get_rows(ids)
                with self.driver.lock:
                    imported += int(self.driver.put_rows(rows))
                continue
            cursor = ""
            while True:
                doc = serve_range(scratch, ring, me, cursor)
                if doc["rows"]:
                    with self.driver.lock:
                        imported += int(self.driver.put_rows(doc["rows"]))
                if doc["done"]:
                    break
                cursor = doc["cursor"]
        return imported

    def get_store_status(self, _name: str = "") -> Dict[str, Any]:
        """The durable plane's view, keyed like get_status: record
        counts, head HLC, per-node chains, this node's warm-boot
        outcome — what ``jubactl -c restore`` consults for --at."""
        node = NodeInfo(self.args.eth, self.rpc.port or self.args.rpc_port)
        if self.store is None:
            return {node.name: {}}
        doc: Dict[str, Any] = dict(self.store.stats())
        doc["warmboot"] = dict(self.warmboot)
        doc["store_dir"] = getattr(self.args, "store_dir", "")
        doc["records"] = [
            {"kind": r.kind, "hlc": r.hlc, "version": r.version,
             "node": r.node} for r in self.store.records()[-64:]]
        return {node.name: doc}

    # -- built-in RPCs (server_base.hpp:41-109, client.hpp:30-87) ------------
    def get_config(self, _name: str = "") -> str:
        return self.config_json

    def model_path(self, model_id: str) -> str:
        """<datadir>/<ip>_<port>_<type>_<id>.jubatus (server_base.cpp:41-49)."""
        node = NodeInfo(self.args.eth, self.args.rpc_port)
        return os.path.join(
            self.args.datadir, f"{node.name}_{self.engine}_{model_id}.jubatus"
        )

    def save(self, _name: str, model_id: str) -> Dict[str, str]:
        """Write the node-local envelope AND (durable model plane,
        ISSUE 18) upload the same bytes to the shared store, so the
        snapshot survives the node that took it. The reply carries the
        per-node path plus the store id under ``store:<node>`` — a
        later ``load`` on ANY member accepts ``store:<key>``."""
        model_id = model_id.decode() if isinstance(model_id, bytes) \
            else str(model_id)
        path = self.model_path(model_id)
        with self.driver.lock:
            save_model(path, self.driver, model_id=model_id,
                       config=self.config_json)
        self.last_saved = time.time()  # wall-clock
        node = NodeInfo(self.args.eth, self.args.rpc_port)
        out = {node.name: path}
        if self.store is not None:
            version = getattr(self.mixer, "model_version", 0) \
                if self.mixer is not None else int(self.driver.update_count)
            try:
                with open(path, "rb") as f:
                    blob = f.read()
                out[f"store:{node.name}"] = self.store.put_blob(
                    blob, kind="full", node=node.name,
                    model_version=version)
            except Exception:  # broad-ok — local save stands on its own
                log.warning("save: store upload failed", exc_info=True)
        return out

    def load(self, _name: str, model_id: str) -> bool:
        """Load by model id. Accepts a store id from a save reply
        (``store:<key>`` — fetched + CRC-validated from the shared
        store), and falls back to the store when the node-local file is
        missing (a replacement node loading a snapshot its predecessor
        took): the newest full record whose system container carries
        this model id."""
        from jubatus_tpu.framework.save_load import load_model_bytes

        model_id = model_id.decode() if isinstance(model_id, bytes) \
            else str(model_id)
        if model_id.startswith("store:") and self.store is not None:
            key = model_id[len("store:"):]
            blob = self.store.fetch(key)
            with self.driver.lock:
                load_model_bytes(blob, self.driver, where=f"store:{key}",
                                 expected_config=self.config_json)
            self.last_loaded = time.time()  # wall-clock
            return True
        try:
            self.load_file(self.model_path(model_id))
        except FileNotFoundError:
            if self.store is None or not self._load_from_store(model_id):
                raise
        return True

    def _load_from_store(self, model_id: str) -> bool:
        """Store fallback for ``load``: scan the newest full records for
        one saved under ``model_id`` (bounded scan — save-uploaded
        records, not the background chain, carry ids)."""
        from jubatus_tpu.framework.save_load import (SaveLoadError,
                                                     load_model_bytes,
                                                     read_envelope)
        from jubatus_tpu.utils.serialization import unpack_obj

        for rec in reversed(self.store.records(kind="full")[-32:]):
            try:
                blob = self.store.fetch(rec.key)
                system = unpack_obj(read_envelope(blob, rec.key)[0])
                if system.get("id") != model_id:
                    continue
                with self.driver.lock:
                    load_model_bytes(blob, self.driver,
                                     where=f"store:{rec.key}",
                                     expected_config=self.config_json)
            except (SaveLoadError, OSError):
                continue  # corrupt/missing record: keep scanning
            self.last_loaded = time.time()  # wall-clock
            log.info("load: %s restored from store record %s",
                     model_id, rec.key)
            return True
        return False

    def load_file(self, path: str) -> None:
        with self.driver.lock:
            load_model(path, self.driver, expected_config=self.config_json)
        self.last_loaded = time.time()  # wall-clock

    def do_mix(self, _name: str = "") -> bool:
        if self.mixer is None:
            return False
        return self.mixer.mix_now() is not None

    def get_metrics(self, _name: str = "") -> Dict[str, Dict[str, Any]]:
        """Raw mergeable metrics state keyed like get_status: one map per
        node name, holding span histogram buckets + counters. ``jubactl
        metrics`` folds these across the cluster for exact merged
        quantiles (bucket-wise sums — see utils/tracing.py)."""
        node = NodeInfo(self.args.eth, self.rpc.port or self.args.rpc_port)
        return {node.name: self.rpc.trace.snapshot()}

    def get_spans(self, _name: str, trace_id: str) -> Dict[str, Any]:
        """Span records of one trace from THIS node's span store, keyed
        like get_status — the per-node half of ``jubactl -c trace``
        (the proxy broadcasts this and merges the maps)."""
        node = NodeInfo(self.args.eth, self.rpc.port or self.args.rpc_port)
        return {node.name: self.rpc.trace.get_spans(str(trace_id))}

    def get_slow_log(self, _name: str = "") -> Dict[str, Any]:
        """This node's slow-request ring (tail-based capture; see
        utils/slowlog.py), keyed like get_status."""
        node = NodeInfo(self.args.eth, self.rpc.port or self.args.rpc_port)
        return {node.name: self.rpc.trace.slowlog.snapshot()}

    # -- continuous profiling plane (ISSUE 8) --------------------------------
    def get_profile(self, _name: str = "", seconds: float = 0.0
                    ) -> Dict[str, Any]:
        """This node's folded stack profile over the last ``seconds``
        (0 = every retained bucket), keyed like get_status: collapsed
        stacks + sampler stats + the tail-triggered snapshot ring. The
        proxy broadcasts this and folds its own samples in (``jubactl
        -c profile``)."""
        node = NodeInfo(self.args.eth, self.rpc.port or self.args.rpc_port)
        return {node.name: self.profiler.profile(float(seconds or 0.0))}

    def _device_capture(self):
        """The capped device-capture dir, created on first use so the
        default path carries the ACTUAL bound rpc port (multiple
        ephemeral-port servers on one host must not share a dir)."""
        if self.device_capture is None:
            from jubatus_tpu.utils.profiler import DeviceCapture

            prof_dir = getattr(self.args, "profile_dir", "") or os.path.join(
                self.args.datadir,
                f"jubatus_profile_{self.engine}_"
                f"{self.rpc.port or self.args.rpc_port}")
            self.device_capture = DeviceCapture(prof_dir)
        return self.device_capture

    def profile_device(self, _name: str = "", seconds: float = 0.0
                       ) -> Dict[str, Any]:
        """On-demand device capture: ``seconds > 0`` runs one bounded
        ``jax.profiler.trace()`` into the capped ``--profile-dir``
        (blocking this RPC worker for the duration); ``seconds == 0``
        lists existing artifacts. Failures return a structured
        ``error`` — a CPU-only box degrades, it doesn't 500."""
        node = NodeInfo(self.args.eth, self.rpc.port or self.args.rpc_port)
        s = float(seconds or 0.0)
        if s <= 0:
            return {node.name: self._device_capture().list()}
        doc = self._device_capture().capture(s)
        if "artifact" in doc:
            self.rpc.trace.count("profiler.device_captures")
        return {node.name: doc}

    # -- event plane + incident bundles (ISSUE 14) ---------------------------
    def get_events(self, _name: str = "", since: int = 0,
                   grep: str = "") -> Dict[str, Any]:
        """This node's cluster-event view, keyed like get_status: the
        server registry's journal MERGED with the process default
        journal (membership/fault/checkpoint emissions), causally
        ordered by HLC. ``since`` is an HLC cursor (return events
        strictly after it — the ``--follow`` contract); ``grep`` is a
        substring filter applied server-side."""
        from jubatus_tpu.utils import events as ev

        node = NodeInfo(self.args.eth, self.rpc.port or self.args.rpc_port)
        grep = grep.decode() if isinstance(grep, bytes) else str(grep or "")
        recs = ev.merge_events([
            self.rpc.trace.events.snapshot(since=int(since or 0), grep=grep),
            ev.default_journal().snapshot(since=int(since or 0), grep=grep),
        ])
        return {node.name: {"events": recs, "hlc_now": ev.hlc_now(),
                            "stats": self.rpc.trace.events.stats()}}

    def get_incidents(self, _name: str = "",
                      incident_id: str = "") -> Dict[str, Any]:
        """Incident-bundle surface (utils/incidents.py): an empty id
        lists the capped artifacts dir, a concrete id returns that
        bundle's full forensic doc."""
        node = NodeInfo(self.args.eth, self.rpc.port or self.args.rpc_port)
        incident_id = incident_id.decode() \
            if isinstance(incident_id, bytes) else str(incident_id or "")
        if incident_id:
            return {node.name: self.incidents.get(incident_id)}
        return {node.name: self.incidents.list()}

    def _incident_dir(self) -> str:
        return getattr(self.args, "incident_dir", "") or os.path.join(
            self.args.datadir,
            f"jubatus_incidents_{self.engine}_"
            f"{self.rpc.port or self.args.rpc_port}")

    def _on_slo_fire(self, name: str, _state: Dict[str, Any]) -> None:
        """SLO transitioned to firing: capture one incident bundle,
        seeded with the breaching trace_ids from the slow log (the
        requests that spent the error budget)."""
        ids = [r.get("trace_id", "")
               for r in self.rpc.trace.slowlog.snapshot(last=16)]
        self.incidents.trigger(f"slo_firing:{name}",
                               trace_ids=[t for t in ids if t][-8:])

    def _incident_state(self) -> Dict[str, Any]:
        """The correlated forensic snapshot one bundle holds: event
        window, timeseries window, slow log, mix flight records,
        profiler tail snapshots, breaker state, health verdict."""
        from jubatus_tpu.utils import events as ev

        doc: Dict[str, Any] = {
            "node": NodeInfo(self.args.eth,
                             self.rpc.port or self.args.rpc_port).name,
            "events": ev.merge_events([
                self.rpc.trace.events.snapshot(limit=256),
                ev.default_journal().snapshot(limit=64)]),
            "slow_log": self.rpc.trace.slowlog.snapshot(last=64),
            "health": self._health(),
        }
        if self.timeseries is not None:
            doc["timeseries"] = self.timeseries.points(last=60)
        if self.quality is not None:
            # names the top drifting group and carries its reference /
            # live sketch pair — the drift-SLO forensic payload
            doc["quality"] = self.quality.incident_doc()
        if self.usage is not None:
            # who was spending the replica when it breached: top
            # principals by CPU with full rows + the capacity picture
            doc["usage"] = self.usage.incident_doc()
        if self.mixer is not None and \
                getattr(self.mixer, "flight", None) is not None:
            doc["mix_history"] = self.mixer.flight.snapshot(last=32)
            breakers = getattr(getattr(self.mixer, "comm", None),
                               "breakers", None)
            if breakers is not None:
                doc["breakers"] = breakers.snapshot()
        try:
            prof = self.profiler.profile(30.0)
            folded = prof.get("folded") or {}
            top = dict(sorted(folded.items(), key=lambda kv: -kv[1])[:50])
            doc["profile"] = {"folded_top": top,
                              "snapshots": prof.get("snapshots") or [],
                              "stats": prof.get("stats") or {}}
        except Exception:  # broad-ok — a sick profiler must not block capture
            log.debug("incident profile fold failed", exc_info=True)
        return doc

    # -- model-health plane (ISSUE 7) ----------------------------------------
    def _model_health_tick(self) -> None:
        """One telemetry tick: gauge the coalescer load signals, then
        snapshot the registry into the time-series ring and re-evaluate
        every SLO's burn rates against the updated ring."""
        if self.timeseries is None or self._in_health_tick:
            return
        self._in_health_tick = True
        try:
            self._model_health_tick_inner()
        finally:
            self._in_health_tick = False

    def _model_health_tick_inner(self) -> None:
        # ingest backpressure gauges (ISSUE 12): queued examples behind
        # the current flush + trailing arrival rate, summed over every
        # train-plane coalescer — the autoscaler's primary signal, so
        # they must ride /metrics and the time-series ring, not just
        # the microbatch.<name>.* stats lines in get_status
        if self.coalescers:
            depth = arrival = 0.0
            for name, co in self.coalescers.items():
                if hasattr(co, "queue_depth"):
                    depth += co.queue_depth()
                    arrival += co.arrival_per_sec()
                # trailing flush-duration EWMA per queue (ISSUE 20): the
                # one drain-rate estimate the coalescer tuner's Little's-
                # law target and the capacity model both read
                st = co.stats() if hasattr(co, "stats") else {}
                fm = st.get("flush_ms_ewma")
                if isinstance(fm, (int, float)) and fm > 0:
                    self.rpc.trace.gauge(
                        f"microbatch.{name}.flush_ms_ewma", float(fm))
            self.rpc.trace.gauge("microbatch.queue_depth", depth)
            self.rpc.trace.gauge("microbatch.arrival_per_sec",
                                 round(arrival, 1))
        # shard-layout gauges (ISSUE 13): shard count, live rows, bytes
        # per arena, and the last sharded top-k merge wall — the keys
        # jubactl -c status/watch render the layout from
        shard_stats = getattr(self.driver, "shard_stats", None)
        if shard_stats is not None:
            doc = shard_stats()
            if doc:
                self.rpc.trace.gauge("shard.count", float(doc["count"]))
                self.rpc.trace.gauge("shard.rows", float(doc.get("rows", 0)))
                self.rpc.trace.gauge("shard.bytes_in_use",
                                     float(doc.get("bytes_in_use", 0)))
                if doc.get("topk_merge_ms") is not None:
                    self.rpc.trace.gauge("shard.topk_merge_ms",
                                         float(doc["topk_merge_ms"]))
        # ANN index gauges (ISSUE 16): cell count, probe width, rescore
        # candidate budget, and the shadow-query recall estimate
        ann_stats = getattr(self.driver, "ann_stats", None)
        if ann_stats is not None:
            doc = ann_stats()
            if doc:
                self.rpc.trace.gauge("ann.cells", float(doc.get("cells", 0)))
                self.rpc.trace.gauge("ann.probed_cells",
                                     float(doc.get("probed_cells", 0)))
                self.rpc.trace.gauge("ann.rescore_candidates",
                                     float(doc.get("rescore_candidates", 0)))
                if doc.get("recall_probe") is not None:
                    self.rpc.trace.gauge("ann.recall_probe",
                                         float(doc["recall_probe"]))
                    # the SLO grammar alarms on HIGH gauges, so recall
                    # sag trends as a deficit: gauge:ann.recall_probe_
                    # deficit:0.1 fires when shadow recall dips < 0.9
                    self.rpc.trace.gauge(
                        "ann.recall_probe_deficit",
                        round(1.0 - float(doc["recall_probe"]), 4))
        # data-quality plane (ISSUE 17): roll windows, recompute PSI
        # drift + prequential gauges — BEFORE the ring samples, so
        # quality.drift.* is visible to gauge: SLOs this same tick
        if self.quality is not None:
            self.quality.tick()
        # usage-attribution plane (ISSUE 19): per-principal demand vs
        # this replica's measured flush throughput — BEFORE the ring
        # samples, so usage.* / capacity.saturation are SLO-able via
        # gauge: this same tick
        if self.usage is not None:
            self.usage.tick(self._capacity_rows_per_sec())
        self.timeseries.sample(self.rpc.trace.snapshot())
        if self.slo is not None:
            self.slo.evaluate()
        # incident trigger #2 (ISSUE 14): /healthz transitioning
        # ok -> degraded captures a bundle (the SLO on_fire trigger
        # usually beats it; the debounce window keeps it to ONE)
        reasons = self._degraded_reasons()
        if reasons and not self._was_degraded:
            self.incidents.trigger(
                "healthz_degraded:" + ",".join(
                    sorted({str(r.get("kind", "?")) for r in reasons})))
        self._was_degraded = bool(reasons)

    def get_timeseries(self, _name: str = "") -> Dict[str, Any]:
        """This node's metric time-series ring (utils/timeseries.py),
        keyed like get_status: ring stats + the raw points, so callers
        (jubactl -c watch) compute windowed rates/quantiles per node
        and fold across the cluster."""
        node = NodeInfo(self.args.eth, self.rpc.port or self.args.rpc_port)
        if self.timeseries is None:
            return {node.name: {"stats": {}, "points": []}}
        return {node.name: {"stats": self.timeseries.stats(),
                            "points": self.timeseries.points()}}

    def _capacity_rows_per_sec(self) -> float:
        """This replica's capacity estimate: rows the drain plane moves
        per busy second, from each queue's trailing flush EWMA × its
        average batch — the SAME estimate the coalescer tuner's
        Little's-law target reads (one throughput model, two consumers;
        ISSUE 20). 0 until a flush has actually run — a cold replica
        publishes no headroom rather than a fictitious one."""
        total = 0.0
        for co in self.coalescers.values():
            st = co.stats() if hasattr(co, "stats") else {}
            flush_ms = float(st.get("flush_ms_ewma", 0.0))
            avg_batch = float(st.get("avg_batch", 0.0))
            if flush_ms > 0.0 and avg_batch > 0.0:
                total += avg_batch / (flush_ms / 1e3)
        return total

    def get_usage(self, _name: str = "") -> Dict[str, Any]:
        """This node's usage-attribution doc (utils/usage.py): the
        per-principal × method exact table, heavy-hitter sketch state,
        and capacity picture — mergeable, so the proxy folds the fleet
        with merge_usage (sketch merge + table sum, never gauge
        averaging)."""
        node = NodeInfo(self.args.eth, self.rpc.port or self.args.rpc_port)
        if self.usage is None:
            return {node.name: {}}
        return {node.name: self.usage.snapshot()}

    def get_quality(self, _name: str = "") -> Dict[str, Any]:
        """This node's data-quality doc (utils/quality.py): reference
        and live sketch states, drift scores, prequential totals, trend
        — mergeable, so the proxy folds the fleet with merge_quality
        and drift is recomputed exactly from the merged sketches."""
        node = NodeInfo(self.args.eth, self.rpc.port or self.args.rpc_port)
        if self.quality is None:
            return {node.name: {}}
        return {node.name: self.quality.snapshot()}

    def get_alerts(self, _name: str = "") -> Dict[str, Any]:
        """This node's SLO state (utils/slo.py): currently-firing
        alerts plus every configured SLO's last-evaluated burn rates —
        the per-node half of ``jubactl -c alerts``."""
        node = NodeInfo(self.args.eth, self.rpc.port or self.args.rpc_port)
        if self.slo is None:
            return {node.name: {"alerts": [], "slos": []}}
        return {node.name: {"alerts": self.slo.alerts(),
                            "slos": self.slo.status()}}

    def _degraded_reasons(self) -> list:
        """Structured degraded-reason list for /healthz and get_status:
        firing SLOs, open mix breakers, a quorum-degraded last round,
        an obsolete (recovering) model, a torn-down collective plane."""
        reasons: list = []
        if self.slo is not None:
            for a in self.slo.alerts():
                reasons.append({"kind": "slo_firing", "name": a["name"],
                                "burn_fast": a.get("burn_fast"),
                                "burn_slow": a.get("burn_slow")})
        m = self.mixer
        if m is not None:
            breakers = getattr(getattr(m, "comm", None), "breakers", None)
            if breakers is not None:
                open_backends = [k for k, b in breakers.snapshot().items()
                                 if b["state"] == "open"]
                if open_backends:
                    reasons.append({"kind": "mix_breaker_open",
                                    "count": len(open_backends),
                                    "backends": sorted(open_backends)})
            if getattr(m, "last_round_degraded", False):
                reasons.append({"kind": "mix_quorum_degraded"})
            if getattr(m, "_obsolete", False):
                reasons.append({"kind": "model_obsolete",
                                "staleness": getattr(m, "self_staleness", 0)})
            if getattr(m, "collective_dead", False):
                reasons.append({"kind": "collective_dead"})
            # async mix (ISSUE 11): a member lagging past the staleness
            # bound is contributing nothing to the fold — surface it
            # before the ladder demotes it to obsolete
            lag = getattr(m, "async_lag_rounds", 0)
            bound = getattr(m, "staleness_bound", 0)
            if bound and lag > bound:
                reasons.append({"kind": "mix_async_lagging",
                                "lag_rounds": lag,
                                "staleness_bound": bound})
            # model-integrity plane (ISSUE 15): peers behind the
            # quarantine breaker mean part of the fleet's training is
            # being excluded from folds — an operator should look
            guard = getattr(m, "guard", None)
            if guard is not None and guard.enabled:
                q = guard.quarantined()
                if q:
                    reasons.append({"kind": "mix_member_quarantined",
                                    "members": sorted(q)})
        if self.rollbacks and \
                time.monotonic() - self._last_rollback_ts < 600.0:
            # recent rollback (10 min window): the model moved backwards
            # — visible on /healthz while the incident is fresh, then
            # clears (the counter stays in get_status forever)
            reasons.append({"kind": "model_rolled_back",
                            "count": self.rollbacks})
        if self.drain_ctl.state != "active":
            reasons.append({"kind": "draining",
                            "state": self.drain_ctl.state})
        return reasons

    def _health(self) -> Dict[str, Any]:
        """Liveness document for /healthz (utils/metrics_http.py).
        ``status`` degrades to "degraded" with a STRUCTURED reason list
        (ISSUE 7) — orchestration keeps getting its 200 (the process
        serves), operators and the watch view get the why."""
        reasons = self._degraded_reasons()
        doc: Dict[str, Any] = {
            "status": "degraded" if reasons else "ok",
            "degraded_reasons": reasons,
            "engine": self.engine,
            "name": self.args.name,
            "uptime_s": int(time.time() - self.start_time),  # wall-clock
            "rpc_port": self.rpc.port or self.args.rpc_port,
            "update_count": self.driver.update_count,
        }
        if self.slo is not None:
            doc["slo_count"] = len(self.slo.specs)
            doc["slo_firing"] = len(self.slo.alerts())
        if self.mixer is not None:
            doc["mix_count"] = getattr(self.mixer, "mix_count", 0)
        # elastic membership (ISSUE 10): one glance says which ring
        # version this node believes in and whether it is on the way out
        doc["cluster_epoch"] = self.membership_epoch()
        doc["drain_state"] = self.drain_ctl.state
        mig = self.migration.snapshot()
        if mig.get("active") or mig.get("rows_moved"):
            doc["migration_rows_moved"] = mig["rows_moved"]
            doc["migration_active"] = mig["active"]
        # profiler state (ISSUE 8): one glance says whether the sampler
        # is on and collecting (full stats live in get_status)
        pstats = self.profiler.stats()
        doc["profiler_hz"] = pstats["hz"]
        doc["profiler_samples"] = pstats["samples"]
        doc["profiler_snapshots"] = pstats["snapshots_taken"]
        # incident bundles (ISSUE 14): how many forensic snapshots this
        # process has auto-captured (the dir is in get_incidents)
        doc["incidents_captured"] = self.incidents.stats()["captured"]
        # model-integrity plane (ISSUE 15): one glance says whether a
        # last-good exists and whether this model ever rolled back
        doc["model_snapshots"] = self.snapshots.stats()["count"]
        doc["model_rollbacks"] = self.rollbacks
        # runtime telemetry summary (full key set lives in get_status)
        rt = self.telemetry.status()
        for k in ("rss_bytes", "open_fds", "threads",
                  "jax_compile_count", "jax_compile_ms", "slowlog_depth"):
            if k in rt:
                doc[k] = rt[k]
        return doc

    def get_status(self, _name: str = "") -> Dict[str, Dict[str, Any]]:
        """≙ server_helper::get_status (server_helper.hpp:119-219): one map
        keyed by <ip>_<port> with uptime/memory/flags/counters."""
        st: Dict[str, Any] = {
            "timestamp": int(time.time()),  # wall-clock
            "uptime": int(time.time() - self.start_time),  # wall-clock
            "type": self.engine,
            "name": self.args.name,
            "version": __version__,
            "update_count": self.driver.update_count,
            "last_saved": self.last_saved,
            "last_loaded": self.last_loaded,
            "rpc_port": self.rpc.port or self.args.rpc_port,
        }
        try:
            with open("/proc/self/statm") as f:
                pages = f.read().split()
            page = os.sysconf("SC_PAGE_SIZE")
            st["VIRT"] = int(pages[0]) * page
            st["RSS"] = int(pages[1]) * page
            st["SHR"] = int(pages[2]) * page
        except (OSError, IndexError, ValueError):
            pass
        try:
            st["loadavg"] = os.getloadavg()[0]
        except OSError:
            pass
        st.update(self.args.flags_status())
        for nm, co in self.coalescers.items():
            st.update({f"microbatch.{nm}.{k}": v
                       for k, v in co.stats().items()})
        # dense-submatrix (uniform key schema) plan engagement counters
        # (service.py populates when the native fast path is registered)
        for k, v in (getattr(self, "ingest_stats", None) or {}).items():
            st[f"ingest.{k}"] = v
        st.update({f"driver.{k}": v for k, v in self.driver.get_status().items()})
        if self.mixer is not None:
            st.update({f"mixer.{k}": v for k, v in self.mixer.get_status().items()})
        # span histograms + counters (SURVEY §5: tracing the reference
        # never had) — this server's own registry, not the process default
        st.update(self.rpc.trace.trace_status())
        # runtime telemetry sample (RSS, FDs, GC, JAX compile/cache/device
        # memory) + slow-log ring health (utils/runtime_telemetry.py)
        st.update({f"runtime.{k}": v
                   for k, v in self.telemetry.status().items()})
        st.update({f"slowlog.{k}": v
                   for k, v in self.rpc.trace.slowlog.stats().items()})
        # continuous profiling plane (ISSUE 8): sampler health — is it
        # on, how many samples/stacks, how often the tail trigger fired
        st.update({f"profiler.{k}": v
                   for k, v in self.profiler.stats().items()})
        # model-health plane (ISSUE 7): health verdict + time-series
        # ring depth + SLO burn states, so `jubactl -c status --all`
        # and the watch view read one map
        reasons = self._degraded_reasons()
        st["health.status"] = "degraded" if reasons else "ok"
        st["health.reasons"] = reasons
        # elastic membership (ISSUE 10): ring version, drain state, and
        # the migration plane's lifetime counters
        st["cluster.epoch"] = self.membership_epoch()
        st["drain.state"] = self.drain_ctl.state
        st.update({f"migration.{k}": v
                   for k, v in self.migration.snapshot().items()})
        if self.timeseries is not None:
            st.update({f"timeseries.{k}": v
                       for k, v in self.timeseries.stats().items()})
        if self.slo is not None:
            st["slo.configured"] = len(self.slo.specs)
            st["slo.firing"] = len(self.slo.alerts())
        # data-quality plane (ISSUE 17)
        if self.quality is not None:
            st.update({f"quality.{k}": v
                       for k, v in self.quality.stats().items()})
        # usage-attribution plane (ISSUE 19): the per-tenant summary
        # jubactl -c watch's tenant column reads
        if self.usage is not None:
            st.update({f"usage.{k}": v
                       for k, v in self.usage.stats().items()})
        # model-integrity plane (ISSUE 15): snapshot ring + rollbacks
        # (guard state rides mixer.guard_* via the mixer's get_status)
        st.update({f"snapshot.{k}": v
                   for k, v in self.snapshots.stats().items()})
        st["rollback.count"] = self.rollbacks
        # durable model plane (ISSUE 18): store record counts + this
        # node's warm-boot outcome (counters ride trace.counter.store.*)
        if self.store is not None:
            st.update(self.store.stats())
            st.update({f"warmboot.{k}": v
                       for k, v in self.warmboot.items()})
        # event plane + incident bundles (ISSUE 14)
        st.update({f"events.{k}": v
                   for k, v in self.rpc.trace.events.stats().items()})
        st.update({f"incident.{k}": v
                   for k, v in self.incidents.stats().items()})
        # process-wide counters (zk session events, ...) live in the
        # default registry; surface them without clobbering our own
        from jubatus_tpu.utils import tracing as _tracing

        for k, v in _tracing.default_registry().counters().items():
            st.setdefault(f"trace.counter.{k}", v)
        if self.metrics is not None:
            st["metrics_port"] = self.metrics.port
        node = NodeInfo(self.args.eth, self.rpc.port or self.args.rpc_port)
        return {node.name: st}

    # -- lifecycle (server_helper::start, server_helper.hpp:221-262) ---------
    def start(self, port: Optional[int] = None, background: bool = True) -> int:
        from jubatus_tpu.server.service import bind_engine  # cycle-free import

        bind_engine(self.rpc, self)
        if self.mixer is not None:
            self.mixer.register_api(self.rpc)
        # durable model plane (ISSUE 18): warm-boot BEFORE the socket
        # serves and BEFORE membership registration — a spawning
        # replica loads the freshest store snapshot + diff chain, then
        # enters the ring already warm and catches the tail up via the
        # normal mix plane (an autoscaler spawn whose argv carries
        # --store-dir takes this path automatically)
        if self.store is not None \
                and getattr(self.args, "store_warmboot", True) \
                and not self.driver.update_count and not self.last_loaded:
            self._warm_boot()
        actual = self.rpc.serve_background(
            port if port is not None else self.args.rpc_port,
            nthreads=self.args.thread,
            host=self.args.bind_host,
        )
        self.args.rpc_port = actual
        # the background uploader needs the BOUND port for its node
        # name (ephemeral-port starts resolve it only now)
        if self.store is not None and self._store_interval > 0:
            from jubatus_tpu.framework.model_store import StoreUploader

            self.store_uploader = StoreUploader(
                self.store, self._store_node_name(),
                model_id="auto", config=self.config_json,
                compress=getattr(self.args, "store_compress", "off"),
                compact_every=getattr(self.args, "store_compact_every", 8))
        # event plane (ISSUE 14): journals attribute events by node name,
        # which an ephemeral-port bind only resolves now; the process
        # default journal keeps the FIRST server's name (one server per
        # process in production)
        from jubatus_tpu.utils import events as _events

        self.rpc.trace.events.node = NodeInfo(self.args.eth, actual).name
        if not _events.default_journal().node:
            _events.default_journal().node = self.rpc.trace.events.node
        self.telemetry.start()
        self.profiler.start()
        if getattr(self.args, "metrics_port", -1) >= 0:
            from jubatus_tpu.utils.metrics_http import MetricsServer

            node = NodeInfo(self.args.eth, actual)
            self.metrics = MetricsServer(
                self.rpc.trace,
                labels={"engine": self.engine, "cluster": self.args.name,
                        "node": node.name},
                health_fn=self._health,
                host=self.args.bind_host, port=self.args.metrics_port)
            self.args.metrics_port = self.metrics.start()
            log.info("metrics endpoint on %s:%d", self.args.bind_host,
                     self.args.metrics_port)
        if self.coord is not None and self.mixer is not None:
            node = NodeInfo(self.args.eth, actual)
            # ephemeral-port binds (start(0)) resolve only now
            self.mixer.self_node = node
            if getattr(self.mixer, "flight", None) is not None:
                self.mixer.flight.node = node.name
            path = membership.register_actor(
                self.coord, self.engine, self.args.name, node.host, node.port
            )
            membership.register_active(
                self.coord, self.engine, self.args.name, node.host, node.port
            )
            # put_diff outcome drives my own actives entry (through MY
            # coordinator session, so it dies with me, not with the master)
            def on_active(ok: bool, _n=node) -> None:
                if ok:
                    membership.register_active(
                        self.coord, self.engine, self.args.name, _n.host, _n.port
                    )
                else:
                    membership.unregister_active(
                        self.coord, self.engine, self.args.name, _n.host, _n.port
                    )

            self.mixer.on_active = on_active
            # suicide watcher (server_helper.cpp:91-94,105-109)
            self.coord.watch_delete(path, lambda _p: self.stop())
            # keyword/key partitioning: drivers exposing set_assignment
            # (burst) process only their CHT(2)-assigned keys, re-hashed
            # on membership change (burst_serv.cpp:225-239, 264-290)
            if hasattr(self.driver, "set_assignment"):
                self._install_assignment(node)
            self.mixer.start()
            # elastic membership (ISSUE 10): a joining replica streams
            # its owned key ranges from the current owners in the
            # background (CHT-routed engines only — drivers exposing the
            # row hooks). The proxy's double-dispatch window covers the
            # in-between.
            if getattr(self.args, "auto_rebalance", True) and \
                    hasattr(self.driver, "put_rows"):
                threading.Thread(target=self._join_migration,
                                 daemon=True, name="join-migrate").start()
        log.info("%s server listening on %s:%d", self.engine,
                 self.args.bind_host, actual)
        return actual

    def _install_assignment(self, me: NodeInfo) -> None:
        """Wire CHT keyword assignment into the driver and keep it fresh
        across membership changes (≙ the reference's child watcher
        re-hash, burst_serv.cpp:264-290). The predicate snapshots the
        ring at (re)build time; each change swaps in a new snapshot."""
        from jubatus_tpu.coord.cht import CHT

        def rebuild(_path: str = "") -> None:
            try:
                cht = CHT.from_coordinator(
                    self.coord, self.engine, self.args.name,
                    actives_only=False)
            except Exception:  # broad-ok — transient coord trouble
                log.warning("assignment rebuild failed; keeping previous",
                            exc_info=True)
                return
            if not cht.members:
                return

            def assigned(kw: str, _cht=cht, _me=me.name) -> bool:
                return any(n.name == _me for n in _cht.find(kw, 2))

            self.driver.set_assignment(assigned)

        rebuild()
        nodes_dir = membership.actor_path(self.engine, self.args.name) + "/nodes"
        try:
            self.coord.watch_children(nodes_dir, rebuild)
        except NotImplementedError:
            pass  # backends without watches: assignment stays static

    def join(self) -> None:
        self._stop_event.wait()

    def stop(self) -> None:
        # reentry-safe: the suicide watcher and a lost coordinator session
        # can both call stop() concurrently from different threads
        if not self._stop_once.acquire(blocking=False):
            return
        if self.usage is not None:
            # drop out of the process-wide retry fan-in: a stopped
            # server's ledger must not keep collecting another server's
            # client retries (multi-server tests/benches)
            from jubatus_tpu.utils import usage as usage_mod

            usage_mod.detach(self.usage)
        try:
            # each step independently: stop() is unretryable (_stop_once),
            # so one failing step must not skip the others
            for step in (
                (self.mixer.stop if self.mixer is not None else None),
                (self.coord.close if self.coord is not None else None),
                self.rpc.stop,
                (self.metrics.stop if self.metrics is not None else None),
                self.telemetry.stop,
                self.profiler.stop,
                self._close_peers,
            ):
                if step is None:
                    continue
                try:
                    step()
                except Exception:  # broad-ok — teardown must finish
                    log.exception("shutdown step %r failed", step)
        finally:
            # set LAST (join() must not return mid-teardown) but ALWAYS
            self._stop_event.set()
