"""Engine → driver factory (≙ per-engine factories, e.g.
classifier_factory::create_classifier at classifier_serv.cpp:108-109).

Config is the reference's JSON config verbatim (config/<engine>/*.json):
{"method": ..., "converter": {...}, "parameter": {...}} for model engines,
engine-specific top-level keys for the rest.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Type

from jubatus_tpu.models import (
    AnomalyDriver,
    BanditDriver,
    BurstDriver,
    ClassifierDriver,
    ClusteringDriver,
    GraphDriver,
    NearestNeighborDriver,
    RecommenderDriver,
    RegressionDriver,
    StatDriver,
    WeightDriver,
)

DRIVER_CLASSES: Dict[str, Type] = {
    "anomaly": AnomalyDriver,
    "bandit": BanditDriver,
    "burst": BurstDriver,
    "classifier": ClassifierDriver,
    "clustering": ClusteringDriver,
    "graph": GraphDriver,
    "nearest_neighbor": NearestNeighborDriver,
    "recommender": RecommenderDriver,
    "regression": RegressionDriver,
    "stat": StatDriver,
    "weight": WeightDriver,
}


def create_driver(engine: str, config: Any, mesh=None):
    """Instantiate the engine's driver from a JSON config (str or dict).

    ``mesh`` (``--shard-devices``): span the model over a local device
    mesh — FEATURE-sharded [.., D] tables for the linear engines
    (classifier/regression), ROW-sharded signature tables for the
    instance engines with hash methods (nearest_neighbor, recommender,
    anomaly, instance classifier — ``NNBackend.attach_mesh``; anomaly's
    LOF rides the full-distance sharded scan)."""
    if isinstance(config, str):
        config = json.loads(config)
    try:
        cls = DRIVER_CLASSES[engine]
    except KeyError:
        raise KeyError(
            f"unknown engine {engine!r}; known: {', '.join(sorted(DRIVER_CLASSES))}"
        )
    # classifier splits by method family: linear (PA/.../NHERD) vs
    # instance-based (NN/cosine/euclidean), like classifier_factory
    if engine == "classifier":
        from jubatus_tpu.models.classifier_nn import NN_METHODS, ClassifierNNDriver

        if isinstance(config, dict) and config.get("method") in NN_METHODS:
            return _maybe_attach(ClassifierNNDriver(config), mesh)
        return cls(config, mesh=mesh)
    if engine == "regression":
        return cls(config, mesh=mesh)
    if engine in ("nearest_neighbor", "recommender", "anomaly"):
        # anomaly rides sharded_distances (LOF needs full distance
        # vectors); NN/recommender ride the sharded top-k
        return _maybe_attach(cls(config), mesh)
    if mesh is not None:
        raise ValueError(
            f"--shard-devices is not supported for engine {engine!r}")
    return cls(config)


def _maybe_attach(driver, mesh):
    """Row-shard an instance driver's NN backend over the mesh (hash
    methods only — NNBackend.attach_mesh validates)."""
    if mesh is not None:
        backend = getattr(driver, "backend", None)
        if backend is None:
            raise ValueError(
                "--shard-devices: this method has no shardable backend")
        backend.attach_mesh(mesh)
    return driver
