"""Engine → driver factory (≙ per-engine factories, e.g.
classifier_factory::create_classifier at classifier_serv.cpp:108-109).

Config is the reference's JSON config verbatim (config/<engine>/*.json):
{"method": ..., "converter": {...}, "parameter": {...}} for model engines,
engine-specific top-level keys for the rest.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Type

from jubatus_tpu.models import (
    AnomalyDriver,
    BanditDriver,
    BurstDriver,
    ClassifierDriver,
    ClusteringDriver,
    GraphDriver,
    NearestNeighborDriver,
    RecommenderDriver,
    RegressionDriver,
    StatDriver,
    WeightDriver,
)

DRIVER_CLASSES: Dict[str, Type] = {
    "anomaly": AnomalyDriver,
    "bandit": BanditDriver,
    "burst": BurstDriver,
    "classifier": ClassifierDriver,
    "clustering": ClusteringDriver,
    "graph": GraphDriver,
    "nearest_neighbor": NearestNeighborDriver,
    "recommender": RecommenderDriver,
    "regression": RegressionDriver,
    "stat": StatDriver,
    "weight": WeightDriver,
}


def create_driver(engine: str, config: Any):
    """Instantiate the engine's driver from a JSON config (str or dict)."""
    if isinstance(config, str):
        config = json.loads(config)
    try:
        cls = DRIVER_CLASSES[engine]
    except KeyError:
        raise KeyError(
            f"unknown engine {engine!r}; known: {', '.join(sorted(DRIVER_CLASSES))}"
        )
    # classifier splits by method family: linear (PA/.../NHERD) vs
    # instance-based (NN/cosine/euclidean), like classifier_factory
    if engine == "classifier":
        from jubatus_tpu.models.classifier_nn import NN_METHODS, ClassifierNNDriver

        if isinstance(config, dict) and config.get("method") in NN_METHODS:
            return ClassifierNNDriver(config)
    return cls(config)
