"""Engine → driver factory (≙ per-engine factories, e.g.
classifier_factory::create_classifier at classifier_serv.cpp:108-109).

Config is the reference's JSON config verbatim (config/<engine>/*.json):
{"method": ..., "converter": {...}, "parameter": {...}} for model engines,
engine-specific top-level keys for the rest.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Type

from jubatus_tpu.models import (
    AnomalyDriver,
    BanditDriver,
    BurstDriver,
    ClassifierDriver,
    ClusteringDriver,
    GraphDriver,
    NearestNeighborDriver,
    RecommenderDriver,
    RegressionDriver,
    StatDriver,
    WeightDriver,
)

DRIVER_CLASSES: Dict[str, Type] = {
    "anomaly": AnomalyDriver,
    "bandit": BanditDriver,
    "burst": BurstDriver,
    "classifier": ClassifierDriver,
    "clustering": ClusteringDriver,
    "graph": GraphDriver,
    "nearest_neighbor": NearestNeighborDriver,
    "recommender": RecommenderDriver,
    "regression": RegressionDriver,
    "stat": StatDriver,
    "weight": WeightDriver,
}


#: engines with a sharded layout, by mechanism — the error message below
#: and docs/SHARDING.md must both name these
FEATURE_SHARDED_ENGINES = ("classifier", "regression")
ROW_SHARDED_ENGINES = ("nearest_neighbor", "recommender", "anomaly")


def create_driver(engine: str, config: Any, mesh=None,
                  shard_features: int = 0, ann: str = "off",
                  ann_cells: int = 0, ann_nprobe: int = 8):
    """Instantiate the engine's driver from a JSON config (str or dict).

    ``mesh`` (``--shard-devices``): span the model over a local device
    mesh — FEATURE-sharded [.., D] tables for the linear engines
    (classifier/regression, shard_map'd train/classify in
    parallel/sharded_model.py), ROW-sharded arenas + signature tables
    for the instance engines with hash methods (nearest_neighbor,
    recommender, anomaly, instance classifier —
    ``NNBackend.attach_mesh`` over parallel/row_store.py; anomaly's LOF
    rides the full-distance sharded scan).

    ``shard_features`` (``--shard-features D_PER_SHARD``): linear
    engines only — derive the shard count from the per-device feature
    budget instead of naming a device count.

    ``ann`` (``--ann {off,ivf}``): arm the IVF approximate-NN tier on
    the instance engines' NN backend (ISSUE 16) — default "off" keeps
    every query on the exact scan. ``ann_cells``/``ann_nprobe`` map to
    ``--ann-cells``/``--ann-nprobe``."""
    if isinstance(config, str):
        config = json.loads(config)
    try:
        cls = DRIVER_CLASSES[engine]
    except KeyError:
        raise KeyError(
            f"unknown engine {engine!r}; known: {', '.join(sorted(DRIVER_CLASSES))}"
        )
    if shard_features and engine not in FEATURE_SHARDED_ENGINES:
        raise ValueError(
            f"--shard-features applies to the feature-sharded linear "
            f"engines ({', '.join(FEATURE_SHARDED_ENGINES)}), not "
            f"{engine!r}; row-store engines "
            f"({', '.join(ROW_SHARDED_ENGINES)}) shard rows via "
            "--shard-devices N")
    # classifier splits by method family: linear (PA/.../NHERD) vs
    # instance-based (NN/cosine/euclidean), like classifier_factory
    if engine == "classifier":
        from jubatus_tpu.models.classifier_nn import NN_METHODS, ClassifierNNDriver

        if isinstance(config, dict) and config.get("method") in NN_METHODS:
            return _maybe_attach(ClassifierNNDriver(config), mesh)
        return cls(config, mesh=mesh, shard_features=shard_features)
    if engine == "regression":
        return cls(config, mesh=mesh, shard_features=shard_features)
    if ann != "off" and engine not in ROW_SHARDED_ENGINES:
        raise ValueError(
            f"--ann applies to the instance engines "
            f"({', '.join(ROW_SHARDED_ENGINES)}), not {engine!r}")
    if engine in ROW_SHARDED_ENGINES:
        # anomaly rides sharded_distances (LOF needs full distance
        # vectors); NN/recommender ride the sharded top-k over the
        # sharded row store
        driver = _maybe_attach(cls(config), mesh)
        if ann != "off":
            backend = getattr(driver, "backend", None)
            if backend is None:
                raise ValueError(
                    "--ann: this method has no NN backend to index")
            backend.configure_ann(ann, cells=ann_cells, nprobe=ann_nprobe)
        return driver
    if mesh is not None:
        raise ValueError(
            f"--shard-devices is not supported for engine {engine!r}; "
            f"sharding-capable engines: "
            f"{', '.join(FEATURE_SHARDED_ENGINES)} (feature-sharded "
            "weight state; also --shard-features D_PER_SHARD) and "
            f"{', '.join(ROW_SHARDED_ENGINES)} (row-sharded stores). "
            "Spell the flag --shard-devices N (local device count) or "
            "--shard-features D_PER_SHARD (per-device feature budget)")
    return cls(config)


def _maybe_attach(driver, mesh):
    """Row-shard an instance driver's NN backend over the mesh (hash
    methods only — NNBackend.attach_mesh validates)."""
    if mesh is not None:
        backend = getattr(driver, "backend", None)
        if backend is None:
            raise ValueError(
                "--shard-devices: this method has no shardable backend")
        backend.attach_mesh(mesh)
    return driver
