"""Engine → driver factory (≙ per-engine factories, e.g.
classifier_factory::create_classifier at classifier_serv.cpp:108-109).

Config is the reference's JSON config verbatim (config/<engine>/*.json):
{"method": ..., "converter": {...}, "parameter": {...}} for model engines,
engine-specific top-level keys for the rest.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Type

from jubatus_tpu.models import (
    AnomalyDriver,
    BanditDriver,
    BurstDriver,
    ClassifierDriver,
    ClusteringDriver,
    GraphDriver,
    NearestNeighborDriver,
    RecommenderDriver,
    RegressionDriver,
    StatDriver,
    WeightDriver,
)

DRIVER_CLASSES: Dict[str, Type] = {
    "anomaly": AnomalyDriver,
    "bandit": BanditDriver,
    "burst": BurstDriver,
    "classifier": ClassifierDriver,
    "clustering": ClusteringDriver,
    "graph": GraphDriver,
    "nearest_neighbor": NearestNeighborDriver,
    "recommender": RecommenderDriver,
    "regression": RegressionDriver,
    "stat": StatDriver,
    "weight": WeightDriver,
}


def create_driver(engine: str, config: Any, mesh=None):
    """Instantiate the engine's driver from a JSON config (str or dict).

    ``mesh``: feature-shard the model tables over a local device mesh
    (linear classifier and regression — ``--shard-devices``); other
    engines scale via ``NNBackend.attach_mesh`` / the mix plane."""
    if isinstance(config, str):
        config = json.loads(config)
    try:
        cls = DRIVER_CLASSES[engine]
    except KeyError:
        raise KeyError(
            f"unknown engine {engine!r}; known: {', '.join(sorted(DRIVER_CLASSES))}"
        )
    # classifier splits by method family: linear (PA/.../NHERD) vs
    # instance-based (NN/cosine/euclidean), like classifier_factory
    if engine == "classifier":
        from jubatus_tpu.models.classifier_nn import NN_METHODS, ClassifierNNDriver

        if isinstance(config, dict) and config.get("method") in NN_METHODS:
            if mesh is not None:
                raise ValueError(
                    "--shard-devices applies to linear classifier methods; "
                    "instance-based methods use NNBackend.attach_mesh")
            return ClassifierNNDriver(config)
        return cls(config, mesh=mesh)
    if engine == "regression":
        return cls(config, mesh=mesh)
    if mesh is not None:
        raise ValueError(
            f"--shard-devices is not supported for engine {engine!r}")
    return cls(config)
