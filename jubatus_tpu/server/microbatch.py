"""Adaptive microbatch coalescing for the ingest hot path.

The reference applies each datum under a write lock as it arrives
(classifier_serv.cpp:127-146) — fine when an update is a few hundred ns
of pointer math, wrong on TPU where every kernel dispatch costs ~ms
regardless of batch size. This queue is SURVEY.md §7 step 4's
"microbatching queue into the JAX update loop": concurrent update RPCs
coalesce into one device batch.

Design — batching from backpressure, zero idle waiting: a submitter that
finds no flush in progress becomes the flusher and processes its items
IMMEDIATELY (a lone client never waits); while its flush occupies the
device, later submitters enqueue and block on tickets; when the flusher
finishes it drains everything that accumulated as ONE batch, and keeps
draining until the queue is empty before handing off. Load creates
batches; idleness creates latency-free pass-through.

Exceptions from a flush propagate to exactly the tickets whose items
were in that batch.

Coalescing depth is bounded by RPC worker concurrency: with the
reference-parity default of 2 worker threads (``-c``), at most one call
can queue behind a flush, so flushes ≈ RPCs. TPU ingest deployments
should raise ``-c`` toward their client concurrency — measured over
loopback: 10 clients × ``-c 8`` turned 100 train RPCs into 37 flushes.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Sequence

from jubatus_tpu.rpc import principal as principals

__all__ = ["Coalescer", "PipelinedCoalescer"]

#: trailing flush-duration EWMA weight (ISSUE 20): one estimate shared
#: by the coalescer tuner's Little's-law target and the capacity model
#: in utils/usage.py — ~10 flushes of memory, newest weighted heaviest
FLUSH_EWMA_ALPHA = 0.2  # knob-ok — the smoothing weight, not a depth


class _Ticket:
    __slots__ = ("event", "result", "error", "count", "weight",
                 "principal", "enq", "claimed")

    def __init__(self, count: int, weight: int,
                 principal: str | None = None, enq: float = 0.0) -> None:
        self.event = threading.Event()
        self.result: Any = None
        self.error: BaseException | None = None
        self.count = count    # item-list slots (queue bookkeeping)
        self.weight = weight  # examples represented (max_batch accounting)
        #: usage attribution (ISSUE 19): the submitting RPC thread's
        #: principal rides the ticket into the flush — the flusher runs
        #: on ANOTHER ticket's thread, so the thread-local is useless by
        #: flush time — plus the enqueue/claim stamps queue residency
        #: derives from
        self.principal = principal
        self.enq = enq
        self.claimed = 0.0


class Coalescer:
    """Coalesce concurrent ``submit(items)`` calls into batched
    ``flush_fn(all_items)`` invocations.

    ``flush_fn`` receives the concatenated item list and returns a value;
    every contributing submitter gets that same return value (engines
    here return accepted-count, which callers recompute from their own
    len(items) — see ``submit``'s return). ``max_batch`` bounds one
    flush; the rest stays queued for the next round.
    """

    def __init__(self, flush_fn: Callable[[List[Any]], Any],
                 max_batch: int = 8192,
                 weigher: Callable[[Any], int] | None = None,
                 split_results: bool = False) -> None:
        """``weigher(item) -> examples`` lets one item represent a whole
        request's batch (the native fast path queues per-REQUEST array
        triples — far less Python object churn than per-example rows);
        max_batch then bounds examples, not items. Default: 1 per item.

        ``split_results``: QUERY-plane mode — ``flush_fn`` must return a
        sequence with one entry per submitted item, and each submitter
        receives exactly its own slice (train flushes return one shared
        scalar instead, the default)."""
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self._flush = flush_fn
        self._max_batch = max_batch
        self._weigher = weigher
        self._split = split_results
        self._lock = threading.Lock()
        self._pending_items: List[Any] = []
        self._pending_tickets: List[_Ticket] = []
        self._active = False
        #: flush invocations / items flushed (observability; get_status)
        self.flush_count = 0
        self.item_count = 0
        #: queued-but-unflushed examples (the autoscaler's primary load
        #: signal: arrival outrunning the device drains HERE first) and
        #: the cumulative arrival counter its rate derives from
        self._pending_weight = 0
        self._arrived = 0
        self._arrival_ref = (time.monotonic(), 0)
        #: trailing flush-duration EWMA (ms); 0 until a flush has run.
        #: The single-stage coalescer folds the whole flush in, the
        #: pipelined one folds only the device stage — either way this
        #: is the drain-rate estimate the coalescer tuner and the
        #: capacity model share (ISSUE 20)
        self._flush_ms_ewma = 0.0
        #: usage attribution (ISSUE 19): when set, called once per
        #: completed ticket as hook(principal, rows, queue_seconds,
        #: device_share_seconds) — the flush's device time amortized by
        #: rows contributed. The service layer binds it to the usage
        #: ledger with the method name closed over.
        self.usage_hook: Callable[[str | None, int, float, float],
                                  None] | None = None

    def submit(self, items: Sequence[Any],
               timeout: float | None = 60.0) -> Any:
        """Block until a flush containing ``items`` completes; returns
        that flush's result. Raises whatever the flush raised.

        ``timeout`` None or <= 0 waits forever. On timeout, items still
        QUEUED are withdrawn first — a TimeoutError then guarantees the
        model was not updated (same contract as a failed direct call); if
        the items were already claimed by an in-flight flush they cannot
        be recalled, so one more ``timeout`` is granted before giving up
        with a message saying the update may still land."""
        items = list(items)
        if not items:
            # split mode's contract is one result per item — for zero
            # items that is an empty sequence, not a flush of nothing
            return [] if self._split else self._flush([])
        if timeout is not None and timeout <= 0:
            timeout = None
        weight = (sum(self._weigher(i) for i in items)
                  if self._weigher is not None else len(items))
        # stamp the principal HERE, on the submitting RPC thread, where
        # the dispatch swap still holds it (only when billing is on —
        # the disarmed path stays a None check)
        ticket = _Ticket(len(items), weight,
                         principal=(principals.current()
                                    if self.usage_hook is not None
                                    else None),
                         enq=(time.perf_counter()
                              if self.usage_hook is not None else 0.0))
        with self._lock:
            self._pending_items.extend(items)
            self._pending_tickets.append(ticket)
            self._pending_weight += weight
            self._arrived += weight
            i_flush = not self._active
            if i_flush:
                self._active = True
        if i_flush:
            self._drain()
        if not ticket.event.wait(timeout):
            with self._lock:
                if ticket in self._pending_tickets:
                    i = self._pending_tickets.index(ticket)
                    off = sum(t.count for t in self._pending_tickets[:i])
                    del self._pending_items[off:off + ticket.count]
                    self._pending_tickets.pop(i)
                    self._pending_weight -= ticket.weight
                    raise TimeoutError(
                        "microbatch flush did not start in time "
                        + ("(query withdrawn)" if self._split else
                           "(items withdrawn; model NOT updated)"))
            if not ticket.event.wait(timeout):
                raise TimeoutError(
                    "microbatch flush still running after grace period"
                    + ("" if self._split else
                       " — the update may still be applied; "
                       "do not blind-retry"))
        if ticket.error is not None:
            raise ticket.error
        return ticket.result

    def _claim(self):
        """Pop the next batch (items + tickets + weight) under the lock;
        None when the queue is empty (caller releases flusher duty).
        Shared by the single-stage and pipelined drain loops."""
        if not self._pending_tickets:
            self._active = False
            return None
        batch: List[Any] = []
        tickets: List[_Ticket] = []
        batch_weight = 0
        while self._pending_tickets and \
                batch_weight + self._pending_tickets[0].weight \
                <= self._max_batch:
            t = self._pending_tickets.pop(0)
            tickets.append(t)
            batch_weight += t.weight
            batch.extend(self._pending_items[:t.count])
            del self._pending_items[:t.count]
        if not tickets:  # one oversized submit: flush it alone
            t = self._pending_tickets.pop(0)
            tickets.append(t)
            batch_weight += t.weight
            batch.extend(self._pending_items[:t.count])
            del self._pending_items[:t.count]
        self._pending_weight -= batch_weight
        if self.usage_hook is not None:
            now = time.perf_counter()
            for t in tickets:
                t.claimed = now
        return batch, tickets, batch_weight

    def _bill(self, tickets: List[_Ticket], batch_weight: int,
              device_dt: float) -> None:
        """Per-ticket usage attribution at flush completion: queue
        residency (claim - enqueue) plus the flush's device time
        amortized by rows contributed. Never raises — billing must not
        fail a flush that already succeeded."""
        hook = self.usage_hook
        if hook is None:
            return
        for t in tickets:
            share = (device_dt * t.weight / batch_weight
                     if batch_weight else 0.0)
            queued = max(0.0, t.claimed - t.enq) if t.enq else 0.0
            try:
                hook(t.principal, t.weight, queued, share)
            except Exception:  # broad-ok — billing is best-effort
                pass

    def _note_flush_ms(self, dt_s: float) -> None:
        """Fold one flush's duration into the trailing EWMA (under the
        queue lock — stats() reads it there)."""
        ms = dt_s * 1e3
        with self._lock:
            self._flush_ms_ewma = ms if self._flush_ms_ewma == 0.0 else \
                FLUSH_EWMA_ALPHA * ms \
                + (1.0 - FLUSH_EWMA_ALPHA) * self._flush_ms_ewma

    def set_max_batch(self, depth: int) -> int:
        """Retarget the per-flush example bound (the coalescer tuner's
        actuation point, ISSUE 20). Clamped to >= 1 — a zero depth
        would wedge every submit. Returns the applied value."""
        depth = max(1, int(depth))
        with self._lock:
            self._max_batch = depth
        return depth

    @property
    def max_batch(self) -> int:
        return self._max_batch

    def _drain(self) -> None:
        while True:
            with self._lock:
                claimed = self._claim()
                if claimed is None:
                    return
                batch, tickets, batch_weight = claimed
            t0 = time.perf_counter()
            try:
                result = self._flush(batch)
                if self._split:
                    if len(result) != len(batch):
                        raise RuntimeError(
                            f"split flush returned {len(result)} results "
                            f"for {len(batch)} items")
                    off = 0
                    for t in tickets:
                        t.result = result[off:off + t.count]
                        off += t.count
                else:
                    for t in tickets:
                        t.result = result
            except BaseException as e:  # noqa: BLE001 — deliver to callers
                for t in tickets:
                    t.error = e
            finally:
                dt = time.perf_counter() - t0
                with self._lock:
                    self.flush_count += 1
                    self.item_count += batch_weight  # examples, not items
                self._note_flush_ms(dt)
                # single-stage flush: the whole flush IS the device step
                self._bill(tickets, batch_weight, dt)
                for t in tickets:
                    t.event.set()

    def queue_depth(self) -> int:
        """Examples queued behind the current flush (0 when idle) —
        the backpressure signal the autoscaler scales out on."""
        with self._lock:
            return self._pending_weight

    def arrival_per_sec(self) -> float:
        """Trailing arrival rate (examples/s) since the last reference
        point; the reference re-anchors every ~10 s, so callers polling
        on the telemetry tick read a short-window rate, not a lifetime
        mean."""
        now = time.monotonic()
        with self._lock:
            ref_t, ref_c = self._arrival_ref
            dt = now - ref_t
            rate = (self._arrived - ref_c) / dt if dt > 0 else 0.0
            if dt >= 10.0:
                self._arrival_ref = (now, self._arrived)
        return rate

    def stats(self) -> dict:
        rate = self.arrival_per_sec()
        with self._lock:
            flushes, items = self.flush_count, self.item_count
            depth = self._pending_weight
            flush_ms = self._flush_ms_ewma
            max_batch = self._max_batch
        return {
            "flush_count": flushes,
            "item_count": items,
            "avg_batch": (items / flushes if flushes else 0.0),
            "queue_depth": depth,
            "arrival_per_sec": round(rate, 1),
            "flush_ms_ewma": round(flush_ms, 3),
            "max_batch": max_batch,
        }


class PipelinedCoalescer(Coalescer):
    """Two-stage coalescer: host featurization overlapped with the device
    step (the feature pipeline's host/device overlap).

    ``prep_fn(items) -> prepared`` is stage 1 (host: decode + batch
    featurize); ``flush_fn(prepared)`` is stage 2 (device: upload +
    kernel). The flusher thread preps batch N+1 while a dedicated device
    worker consumes batch N — double-buffered (at most ONE prepared
    batch waits, so prep can never run unboundedly ahead of the model
    it trains against), with Coalescer's ticket/error semantics: a
    stage-1 error fails exactly that batch's tickets immediately, a
    stage-2 error fails them when the device stage completes.

    Span stamping: when ``trace`` (a tracing Registry) is given, stage 1
    records ``fv.convert`` and stage 2 ``fv.upload`` — the featurize vs
    device split in ``jubactl -c trace``/get_status.

    Overlap accounting: ``stats()`` adds prep/device seconds and
    ``overlap_fraction`` — the share of host featurize time that ran
    while the device stage was busy (time the pipeline hid)."""

    def __init__(self, prep_fn: Callable[[List[Any]], Any],
                 flush_fn: Callable[[Any], Any],
                 max_batch: int = 8192,
                 weigher: Callable[[Any], int] | None = None,
                 trace: Any = None) -> None:
        super().__init__(flush_fn, max_batch=max_batch, weigher=weigher)
        self._prep = prep_fn
        self._trace = trace
        self._dev_lock = threading.Lock()
        self._dev_ready = threading.Condition(self._dev_lock)
        self._dev_queue: List[tuple] = []      # at most 1 prepared batch
        self._dev_slot = threading.Semaphore(1)
        self._dev_thread: threading.Thread | None = None
        self._busy_lock = threading.Lock()
        self._dev_busy_total = 0.0
        self._dev_busy_since: float | None = None
        self.prep_seconds = 0.0
        self.device_seconds = 0.0
        self.overlap_seconds = 0.0

    # -- overlap accounting --------------------------------------------------
    def _device_busy_seconds(self) -> float:
        with self._busy_lock:
            t = self._dev_busy_total
            if self._dev_busy_since is not None:
                t += time.perf_counter() - self._dev_busy_since
            return t

    def _finish(self, tickets: List[_Ticket], batch_weight: int,
                device_dt: float = 0.0) -> None:
        with self._lock:
            self.flush_count += 1
            self.item_count += batch_weight
        self._bill(tickets, batch_weight, device_dt)
        for t in tickets:
            t.event.set()

    def _ensure_worker(self) -> None:
        if self._dev_thread is None or not self._dev_thread.is_alive():
            self._dev_thread = threading.Thread(
                target=self._device_loop, daemon=True,
                name="microbatch-device")
            self._dev_thread.start()

    def _device_loop(self) -> None:
        while True:
            with self._dev_lock:
                while not self._dev_queue:
                    self._dev_ready.wait()
                prepared, tickets, batch_weight = self._dev_queue.pop(0)
            with self._busy_lock:
                self._dev_busy_since = time.perf_counter()
            try:
                if self._trace is not None:
                    with self._trace.span("fv.upload"):
                        result = self._flush(prepared)
                else:
                    result = self._flush(prepared)
                for t in tickets:
                    t.result = result
            except BaseException as e:  # noqa: BLE001 — deliver to callers
                for t in tickets:
                    t.error = e
            finally:
                with self._busy_lock:
                    now = time.perf_counter()
                    dt = now - self._dev_busy_since
                    self._dev_busy_total += dt
                    self.device_seconds += dt
                    self._dev_busy_since = None
                # the device stage IS the drain rate here — the prep
                # stage overlaps it, so only stage 2 bounds throughput
                self._note_flush_ms(dt)
                self._finish(tickets, batch_weight, device_dt=dt)
                self._dev_slot.release()

    def _drain(self) -> None:
        while True:
            with self._lock:
                claimed = self._claim()
                if claimed is None:
                    return
                batch, tickets, batch_weight = claimed
            # stage 1 in THIS thread: overlaps whatever batch the device
            # worker is currently consuming
            t0 = time.perf_counter()
            d0 = self._device_busy_seconds()
            err: BaseException | None = None
            prepared = None
            try:
                if self._trace is not None:
                    with self._trace.span("fv.convert"):
                        prepared = self._prep(batch)
                else:
                    prepared = self._prep(batch)
            except BaseException as e:  # noqa: BLE001 — deliver to callers
                err = e
            d1 = self._device_busy_seconds()
            dt = time.perf_counter() - t0
            with self._busy_lock:
                self.prep_seconds += dt
                self.overlap_seconds += min(dt, max(d1 - d0, 0.0))
            if err is not None:
                for t in tickets:
                    t.error = err
                self._finish(tickets, batch_weight)
                continue
            # stage 2 handoff: block only when BOTH buffers are full
            # (one in flight on the device + one prepared)
            self._dev_slot.acquire()
            self._ensure_worker()
            with self._dev_lock:
                self._dev_queue.append((prepared, tickets, batch_weight))
                self._dev_ready.notify()

    def stats(self) -> dict:
        out = super().stats()
        with self._busy_lock:
            prep = self.prep_seconds
            dev = self.device_seconds
            ov = self.overlap_seconds
        out.update(
            prep_seconds=round(prep, 6),
            device_seconds=round(dev, 6),
            overlap_seconds=round(ov, 6),
            overlap_fraction=round(ov / prep, 4) if prep > 0 else 0.0,
        )
        return out
