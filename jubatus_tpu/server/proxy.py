"""Query-routing proxy tier (≙ framework/proxy.{hpp,cpp} + proxy_common.{hpp,cpp}).

The reference's ``juba<engine>_proxy`` binaries are async RPC servers whose
methods are registered by routing class — random (1 active node), broadcast
(all actives + reducer fold), cht (N ring successors of the key + reducer)
(proxy.hpp:64-186,229-286) — with built-ins save/load/get_status/
get_proxy_status (proxy.cpp:43-66). Member lookup reads the coordination
store's ``actives`` list through a watch-invalidated cache (proxy_common.cpp:
73-114, cached_zk). Sessions to backend servers live in a pool with expiry
(proxy.hpp:502-593).

Here one ``Proxy`` class serves any engine: the routing/aggregator table
comes from ``framework.idl.SERVICES`` (what the reference bakes into the
generated ``*_proxy.cpp``). Wire behavior matches: same method names, same
leading cluster-name param, same reducer semantics, per-host failures
tolerated as long as one backend answers (proxy.hpp:325-392).

Beyond the reference — the self-healing request plane:

- **per-backend circuit breakers** (rpc/breaker.py): transport failures
  land in a rolling window per member; an OPEN backend is skipped by
  random/cht routing and re-admitted via half-open probes, replacing the
  old blunt ``members.invalidate(cluster)`` (which nuked the whole
  cluster's cache because ONE node failed);
- **idempotent failover**: random-routed reads that hit a transport
  failure fail over to the next active replica (retry-budget-gated, so a
  degraded cluster sees bounded amplification); effectful calls keep
  propagate-don't-double-apply semantics;
- **deadline-aware fan-out**: the broadcast collects against ONE shared
  deadline (``concurrent.futures.wait``) derived from the caller's
  remaining budget, abandoning stragglers (counted as
  ``proxy.fanout_timeouts``) instead of serially paying ``timeout+1`` per
  hung backend; per-attempt backend timeouts derive from the remaining
  budget because the forwarded call re-ships it on the envelope.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor, wait as futures_wait
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from jubatus_tpu.coord import create_coordinator, membership
from jubatus_tpu.coord.base import Coordinator, NodeInfo
from jubatus_tpu.coord.cht import CHT, ring_key
from jubatus_tpu.framework.idl import INTERNAL, get_service, idempotent_methods
from jubatus_tpu.rpc import aggregators
from jubatus_tpu.rpc import deadline as deadlines
from jubatus_tpu.rpc import principal as principals
from jubatus_tpu.rpc.breaker import BreakerBoard
from jubatus_tpu.rpc.client import RpcClient
from jubatus_tpu.rpc.errors import (
    DeadlineExceeded,
    EpochMismatch,
    HostError,
    MultiRpcError,
    NodeDraining,
    RpcIoError,
    RpcNoClient,
    RpcNoResult,
    RpcTimeoutError,
)
from jubatus_tpu.rpc.retry import RetryBudget
from jubatus_tpu.utils import faults, tracing
from jubatus_tpu.version import __version__

log = logging.getLogger(__name__)

#: transport-level failures (a breaker's evidence; failover triggers)
_TRANSPORT_ERRORS = (RpcIoError, RpcTimeoutError, faults.FaultInjected)

#: membership-protocol rejections (elastic membership, ISSUE 10): the
#: backend refused BEFORE applying anything (draining, or a ring-epoch
#: disagreement). Safe to re-route even for EFFECTFUL calls — the fix is
#: a membership refresh, not a backoff
_MEMBERSHIP_ERRORS = (NodeDraining, EpochMismatch)


def _membership_rejection(exc: BaseException) -> bool:
    """True when ``exc`` is (or a fan-out whose every failure is) a
    membership-protocol rejection — the caller should refresh its ring
    and re-route."""
    if isinstance(exc, _MEMBERSHIP_ERRORS):
        return True
    if isinstance(exc, MultiRpcError) and exc.errors:
        return all(isinstance(e.cause, _MEMBERSHIP_ERRORS)
                   for e in exc.errors)
    return False


class _RingCache:
    """CHT snapshots per cluster, rebuilt ONLY when the member list
    changes (the satellite fix for the per-request ``CHT(actives)``
    rebuild: 8 MD5 hashes per member per call, pure hot-path tax).

    Each entry remembers the PREVIOUS ring and when the swap happened:
    for ``handoff_window`` seconds after a membership change the proxy
    double-dispatches CHT-routed effectful calls to the union of old and
    new owners, so no key ever has zero owners while rows migrate."""

    def __init__(self, handoff_window: float = 15.0) -> None:
        self.handoff_window = float(handoff_window)
        self._lock = threading.Lock()
        #: name -> (ring_key, ring, prev_ring_or_None, swap_monotonic)
        self._entries: Dict[str, Tuple[Tuple[str, ...], CHT,
                                       Optional[CHT], float]] = {}
        self.builds = 0
        self.hits = 0

    def get(self, name: str, actives: Sequence[NodeInfo]
            ) -> Tuple[CHT, Optional[CHT]]:
        """(current ring, previous ring while inside the handoff
        window — else None)."""
        key = ring_key(actives)
        now = time.monotonic()
        with self._lock:
            e = self._entries.get(name)
            if e is not None and e[0] == key:
                self.hits += 1
                ring, prev, swapped = e[1], e[2], e[3]
                if prev is not None and now - swapped > self.handoff_window:
                    # window over: forget the old ring
                    self._entries[name] = (key, ring, None, swapped)
                    prev = None
                return ring, prev
        ring = CHT(actives)
        with self._lock:
            e = self._entries.get(name)
            prev = e[1] if (e is not None and e[0] != key
                            and e[1].members) else None
            self._entries[name] = (key, ring, prev, now)
            self.builds += 1
        return ring, prev

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"builds": self.builds, "hits": self.hits,
                    "clusters": len(self._entries),
                    "in_handoff": sum(1 for e in self._entries.values()
                                      if e[2] is not None)}


@dataclasses.dataclass
class ProxyArgs:
    """≙ proxy_argv (server_util.cpp:440-557). Same defaults: 4 worker
    threads vs the server's 2, 10 s timeouts, session-pool knobs."""

    engine: str = ""
    rpc_port: int = 9199
    listen_addr: str = ""
    thread: int = 4
    timeout: float = 10.0
    coordinator: str = ""
    coordinator_timeout: float = 10.0
    interconnect_timeout: float = 10.0
    session_pool_expire: float = 60.0   # --pool_expire
    session_pool_size: int = 0          # --pool_size, 0 = unbounded
    daemon: bool = False
    legacy_wire: bool = False           # --legacy-wire (see rpc/legacy.py)
    modern_wire: bool = False           # --modern-wire: no autodetection
    #: Prometheus /metrics + /healthz HTTP port: -1 = off, 0 = ephemeral
    metrics_port: int = -1
    #: circuit breaker tuning (rpc/breaker.py): this many transport
    #: failures to one backend inside the window open its breaker for
    #: the cooldown; half-open probes re-admit it
    breaker_failures: int = 5
    breaker_window: float = 30.0
    breaker_cooldown: float = 5.0
    #: retry budget: failover retries per first-attempt forward (10% =
    #: the gRPC/Finagle convention; see rpc/retry.py)
    retry_budget_ratio: float = 0.1
    #: --slowlog-*: tail-based slow-request capture at the PROXY hop
    #: (utils/slowlog.py) — same semantics as the engine servers
    slowlog_capacity: int = 256
    slowlog_quantile: float = 0.99
    slowlog_min_count: int = 64
    #: runtime telemetry sampler period (0 disables the thread)
    telemetry_interval: float = 10.0
    #: --slo et al.: the model-health plane at the PROXY hop (ISSUE 7) —
    #: same grammar/semantics as the engine servers (utils/slo.py);
    #: proxy-side SLOs watch the forwarded-request spans
    slo: List[str] = dataclasses.field(default_factory=list)
    slo_fast_window: float = 300.0
    slo_slow_window: float = 3600.0
    slo_burn_threshold: float = 2.0
    #: metric time-series ring depth (0 disables ring + SLO evaluation)
    timeseries_capacity: int = 360
    #: --profile-hz: always-on stack sampler at the PROXY hop
    #: (utils/profiler.py) — the proxy's own routing/fan-out stacks fold
    #: into the cluster profile next to the backends'; 0 = off
    profile_hz: float = 67.0
    #: --profile-trigger-*: slow-log breach trigger for the proxy's own
    #: spans (same semantics as the engine servers)
    profile_trigger_breaches: int = 3
    profile_trigger_window: float = 10.0
    #: --handoff-window: seconds after a membership change during which
    #: CHT-routed EFFECTFUL calls double-dispatch to the union of old
    #: and new ring owners (elastic membership: no key has zero owners
    #: while rows migrate); idempotent reads fail over old->new instead
    handoff_window: float = 15.0
    #: --event-capacity: cluster event journal depth at the PROXY hop
    #: (utils/events.py, ISSUE 14) — breaker transitions and proxy SLO
    #: edges land here; 0 disables emission
    event_capacity: int = 2048
    #: --incident-window: debounce window (seconds) for automatic
    #: incident bundles at the proxy hop (0 disables auto-capture)
    incident_window: float = 300.0
    #: --incident-dir: capped bundle artifacts dir; empty = under /tmp
    #: keyed by the bound port
    incident_dir: str = ""
    #: --usage-top: exact per-principal ledger rows at the PROXY hop
    #: (utils/usage.py, ISSUE 19) — the proxy is in the request path,
    #: so it attributes its own dispatch cost per tenant; 0 disables
    usage_top: int = 64
    #: --usage-gauge-principals: top-N principals published as
    #: usage.<principal>.* gauges each telemetry tick
    usage_gauge_principals: int = 8

    @property
    def bind_host(self) -> str:
        return self.listen_addr or "0.0.0.0"

    def flags_status(self) -> Dict[str, Any]:
        return {f"argv.{f.name}": getattr(self, f.name)
                for f in dataclasses.fields(self)}


class MemberCache:
    """Watch-invalidated actives cache (≙ cached_zk, common/cached_zk.hpp:
    31-59): one entry per cluster name, cleared when the coordinator signals
    a child change, with a TTL backstop for coordinators whose watches are
    best-effort."""

    def __init__(self, coord: Coordinator, engine: str, ttl: float = 2.0) -> None:
        self._coord = coord
        self._engine = engine
        self._ttl = ttl
        self._lock = threading.Lock()
        self._cache: Dict[str, Tuple[float, List[NodeInfo]]] = {}
        self._watched: set = set()

    def actives(self, name: str) -> List[NodeInfo]:
        now = time.monotonic()
        with self._lock:
            hit = self._cache.get(name)
            if hit is not None and now - hit[0] < self._ttl:
                return hit[1]
        nodes = membership.get_all_actives(self._coord, self._engine, name)
        with self._lock:
            self._cache[name] = (now, nodes)
            need_watch = name not in self._watched
            if need_watch:
                self._watched.add(name)
        if need_watch:  # outside the lock: watchers may fire synchronously
            path = f"{membership.actor_path(self._engine, name)}/actives"
            try:
                self._coord.watch_children(path, lambda _p, n=name: self.invalidate(n))
            except NotImplementedError:
                pass
        return nodes

    def invalidate(self, name: str) -> None:
        with self._lock:
            self._cache.pop(name, None)


def _peek_cluster_name(raw_params: bytes) -> Optional[str]:
    """First element of the params array when it is a string — WITHOUT
    feeding the (possibly multi-megabyte) span through an unpacker copy.
    None for any other wire shape."""
    try:
        t = raw_params[0]
        if 0x90 <= t <= 0x9F:
            if t == 0x90:
                return None
            i = 1
        elif t == 0xDC:
            if int.from_bytes(raw_params[1:3], "big") < 1:
                return None
            i = 3
        elif t == 0xDD:
            if int.from_bytes(raw_params[1:5], "big") < 1:
                return None
            i = 5
        else:
            return None
        t = raw_params[i]
        if 0xA0 <= t <= 0xBF:
            n, i = t & 0x1F, i + 1
        elif t == 0xD9:
            n, i = raw_params[i + 1], i + 2
        elif t == 0xDA:
            n, i = int.from_bytes(raw_params[i + 1:i + 3], "big"), i + 3
        else:
            return None
        return raw_params[i:i + n].decode("utf-8", "surrogateescape")
    except (IndexError, ValueError):
        return None


class _Session:
    __slots__ = ("client", "last_used")

    def __init__(self, client: RpcClient) -> None:
        self.client = client
        self.last_used = time.monotonic()


class Proxy:
    """One engine's routing proxy. listen → start → join, like the servers."""

    def __init__(self, args: ProxyArgs, coord: Optional[Coordinator] = None) -> None:
        if not args.engine:
            raise ValueError("ProxyArgs.engine required")
        self.args = args
        self.engine = args.engine
        self.coord = coord or create_coordinator(args.coordinator)
        self.members = MemberCache(self.coord, self.engine)
        # same transport selection as the engine servers: the C++
        # front-end when JUBATUS_TPU_NATIVE_RPC=1 (rpc/native_server.py)
        from jubatus_tpu.rpc.native_server import create_rpc_server

        self.rpc = create_rpc_server(
            timeout=args.timeout,
            legacy_wire=getattr(args, "legacy_wire", False),
            wire_detect=not getattr(args, "modern_wire", False))
        self.start_time = time.time()  # wall-clock
        self._pool: Dict[Tuple[str, int], List[_Session]] = {}
        self._pool_lock = threading.Lock()
        self._last_expiry = 0.0
        self._executor = ThreadPoolExecutor(
            max_workers=max(8, args.thread * 4), thread_name_prefix="proxy-fanout"
        )
        self._stop_event = threading.Event()
        # counters (proxy_common.cpp:126-182)
        self._counters_lock = threading.Lock()
        self.request_counts: Dict[str, int] = {}
        self.forward_count = 0
        self.forward_errors = 0
        #: self-healing plane: per-backend breakers + the failover retry
        #: budget; transitions count into the proxy's own registry
        #: (proxy.breaker_open / proxy.breaker_close on /metrics)
        self.breakers = BreakerBoard(
            window_sec=args.breaker_window,
            failure_threshold=args.breaker_failures,
            cooldown_sec=args.breaker_cooldown,
            registry=self.rpc.trace, counter_prefix="proxy.breaker")
        self.retry_budget = RetryBudget(ratio=args.retry_budget_ratio)
        self._idempotent = idempotent_methods(self.engine)
        #: elastic membership (ISSUE 10): member-list-keyed ring cache
        #: (no per-request CHT rebuild) + the double-dispatch window
        self.rings = _RingCache(
            handoff_window=getattr(args, "handoff_window", 15.0))
        #: C++ relay plane (native transport only): random-routed raw
        #: methods forward in rpc_frontend.cpp without entering Python;
        #: this side only keeps the routing table fresh (clusters seen ->
        #: current actives) and serves whatever the C++ declines
        self._relay_methods: List[str] = []
        self._relay_seen: Dict[str, float] = {}  # cluster -> last-live ts
        self._relay_lock = threading.Lock()
        #: Prometheus /metrics + /healthz endpoint (--metrics-port >= 0)
        self.metrics = None
        # forensics plane (ISSUE 4): slow-request ring at the proxy hop +
        # the runtime telemetry sampler (started with the listener)
        self.rpc.trace.slowlog.configure(
            capacity=getattr(args, "slowlog_capacity", 256),
            quantile=getattr(args, "slowlog_quantile", 0.99),
            min_count=getattr(args, "slowlog_min_count", 64))
        from jubatus_tpu.utils.runtime_telemetry import RuntimeTelemetry

        self.telemetry = RuntimeTelemetry(
            self.rpc.trace,
            interval_sec=getattr(args, "telemetry_interval", 10.0))
        # continuous profiling plane (ISSUE 8) at the proxy hop: the
        # same always-on sampler + slowlog tail trigger as the servers
        # (no device capture — proxies have no accelerator work)
        from jubatus_tpu.utils.profiler import SamplingProfiler

        self.profiler = SamplingProfiler(
            self.rpc.trace, hz=getattr(args, "profile_hz", 67.0))
        trig = getattr(args, "profile_trigger_breaches", 3)
        if trig > 0 and self.profiler.enabled:
            self.rpc.trace.slowlog.set_trigger(
                self.profiler.tail_snapshot, breaches=trig,
                window_s=getattr(args, "profile_trigger_window", 10.0))
        # model-health plane (ISSUE 7) at the proxy hop: time-series
        # ring + SLO burn-rate engine, ticked by the telemetry sampler
        from jubatus_tpu.utils.slo import SloEngine, parse_slo
        from jubatus_tpu.utils.timeseries import TimeSeriesRing

        ts_cap = getattr(args, "timeseries_capacity", 360)
        interval = self.telemetry.interval_sec
        self.timeseries: Optional[TimeSeriesRing] = None
        self.slo: Optional[SloEngine] = None
        if ts_cap > 0:
            self.timeseries = TimeSeriesRing(
                capacity=ts_cap,
                min_spacing_s=min(1.0, interval / 2) if interval > 0
                else 0.0)
            self.slo = SloEngine(
                [parse_slo(s) for s in getattr(args, "slo", []) or []],
                self.timeseries, self.rpc.trace,
                fast_window_s=getattr(args, "slo_fast_window", 300.0),
                slow_window_s=getattr(args, "slo_slow_window", 3600.0),
                burn_threshold=getattr(args, "slo_burn_threshold", 2.0))
            self.telemetry.hooks.append(self._model_health_tick)
        # cluster event plane + incident bundles (ISSUE 14) at the
        # proxy hop: breaker transitions and proxy-side SLO edges land
        # in this journal; the same two triggers capture bundles
        from jubatus_tpu.utils.incidents import IncidentManager

        self.rpc.trace.events.set_capacity(
            getattr(args, "event_capacity", 2048))
        self.incidents = IncidentManager(
            self.rpc.trace, self._incident_state, self._incident_dir,
            window_s=getattr(args, "incident_window", 300.0),
            journal=self.rpc.trace.events)
        if self.slo is not None:
            self.slo.on_fire = self._on_slo_fire
        # usage-attribution plane (ISSUE 19) at the proxy hop: the proxy
        # is in the request path, so it keeps its OWN per-tenant ledger
        # (dispatch spans + request/response bytes); jubactl -c usage
        # folds it with the backends' via usage.merge_usage
        from jubatus_tpu.utils import usage as usage_mod

        self.usage: Optional[usage_mod.UsageLedger] = None
        ut = getattr(args, "usage_top", 64)
        if ut > 0:
            self.usage = usage_mod.UsageLedger(
                top=ut,
                gauge_principals=getattr(args, "usage_gauge_principals", 8),
                registry=self.rpc.trace)
            self.rpc.usage_recorder = self.usage
            self.rpc.trace.usage_sink = self.usage.span_sink
            usage_mod.attach(self.usage)
        self._was_degraded = False
        #: re-entrancy guard (see EngineServer): the incident
        #: collector's _health() re-runs the telemetry hooks
        self._in_health_tick = False
        self._register_methods()
        if hasattr(self.rpc, "relay_config"):
            t = threading.Thread(target=self._relay_refresher, daemon=True,
                                 name="proxy-relay-refresh")
            t.start()

    # -- session pool (proxy.hpp:502-593) ------------------------------------
    # Borrow/return, like the reference's get/return session pool: each
    # in-flight forward owns a connection, so N concurrent client calls
    # ride N parallel backend connections instead of serializing their
    # round trips through one socket. ``self._pool`` holds IDLE sessions.
    def _checkout(self, node: NodeInfo) -> _Session:
        key = (node.host, node.port)
        with self._pool_lock:
            lst = self._pool.get(key)
            if lst:
                return lst.pop()
        # the proxy's backend clients do NOT retry at the client layer:
        # the proxy owns failover ACROSS replicas (same budget, better
        # spread) — stacked same-host retries under the fan-out would
        # multiply tail latency
        return _Session(RpcClient(node.host, node.port,
                                  timeout=self.args.interconnect_timeout,
                                  retry_methods=frozenset(),
                                  registry=self.rpc.trace))

    def _checkin(self, node: NodeInfo, sess: _Session) -> None:
        sess.last_used = time.monotonic()
        with self._pool_lock:
            self._pool.setdefault((node.host, node.port), []).append(sess)

    def _expire_sessions(self) -> None:
        # throttled: expiry precision is seconds (pool_expire defaults to
        # 60 s); walking the pool under its lock on EVERY forward is pure
        # hot-path tax
        now = time.monotonic()
        if now - self._last_expiry < 1.0:
            return
        self._last_expiry = now
        horizon = time.monotonic() - self.args.session_pool_expire
        dead: List[_Session] = []
        with self._pool_lock:
            for key, lst in list(self._pool.items()):
                keep = [s for s in lst if s.last_used >= horizon]
                dead.extend(s for s in lst if s.last_used < horizon)
                if keep:
                    self._pool[key] = keep
                else:
                    del self._pool[key]
            if self.args.session_pool_size > 0:
                flat = sorted(
                    ((s.last_used, key, s)
                     for key, lst in self._pool.items() for s in lst),
                    key=lambda e: e[0])
                excess = len(flat) - self.args.session_pool_size
                for _, key, s in flat[:max(0, excess)]:
                    self._pool[key].remove(s)
                    dead.append(s)
                for key in [k for k, v in self._pool.items() if not v]:
                    del self._pool[key]
        for s in dead:
            s.client.close()

    def _drop_sessions(self, node: NodeInfo) -> None:
        """A backend failed: close its idle sessions (in-flight ones die
        with their own errors)."""
        with self._pool_lock:
            lst = self._pool.pop((node.host, node.port), [])
        for s in lst:
            s.client.close()

    # -- fan-out core (async_task, proxy.hpp:296-495) ------------------------
    def _fan(
        self,
        nodes: Sequence[NodeInfo],
        method: str,
        args: Sequence[Any],
        reducer: Callable[[Any, Any], Any],
    ) -> Any:
        """Call all nodes in parallel; fold successes left-to-right through
        the reducer; per-host errors are tolerated unless every host fails
        (proxy.hpp:325-392). The whole collection runs against ONE shared
        deadline — a single hung backend costs the broadcast one budget,
        not N serial budgets; stragglers are abandoned and counted."""
        if not nodes:
            raise RpcNoClient(f"no active {self.engine} servers")
        with self._counters_lock:
            self.forward_count += len(nodes)
        if len(nodes) == 1:
            return self._one(nodes[0], method, args)
        # the fan-out hops threads: carry this request's trace context,
        # deadline AND principal into the executor so each backend call
        # ships the same trace_id, derives its timeout from the remaining
        # budget, and bills the same tenant (ISSUE 19)
        ctx = tracing.current_trace()
        dl = deadlines.current()
        pr = principals.current()

        def call(n: NodeInfo) -> Any:
            with tracing.use_trace(ctx), deadlines.use(dl), \
                    principals.use(pr):
                return self._one(n, method, args)

        futs: Dict[Any, NodeInfo] = {
            self._executor.submit(call, n): n for n in nodes}
        budget = self.args.timeout + 1.0
        rem = deadlines.remaining()
        if rem is not None:
            budget = min(budget, max(rem, 0.0))
        done, pending = futures_wait(futs, timeout=budget)
        results: List[Any] = []
        errors: List[HostError] = []
        # iterate in submission order (dict preserves it): the reducer
        # fold stays deterministic even though completion order isn't
        for fut, n in futs.items():
            if fut in pending:
                fut.cancel()  # abandon: result (if any) is discarded
                errors.append(HostError(
                    n.host, n.port,
                    RpcTimeoutError(f"{method} @ {n.host}:{n.port}: "
                                    "fanout deadline")))
                continue
            try:
                results.append(fut.result())
            except Exception as e:  # broad-ok — per-host failure is data
                errors.append(HostError(n.host, n.port, e))
        if pending:
            self.rpc.trace.count("proxy.fanout_timeouts", len(pending))
        if errors:
            with self._counters_lock:
                self.forward_errors += len(errors)
        if not results:
            raise MultiRpcError(errors) if errors else RpcNoResult(method)
        acc = results[0]
        for r in results[1:]:
            acc = reducer(acc, r)
        return acc

    def _one(self, node: NodeInfo, method: str, args: Sequence[Any]) -> Any:
        """One forwarded call, feeding the backend's breaker: transport
        failures tear the node's sessions down and count against it;
        application errors prove the backend alive. The old
        ``members.invalidate(cluster)`` on any failure is gone — one sick
        node no longer blinds the cache for the whole cluster."""
        key = (node.host, node.port)
        sess = self._checkout(node)
        try:
            result = sess.client.call(method, *args)
        except _TRANSPORT_ERRORS:
            # dead/unreachable backend: close this session, drop its idle
            # siblings, feed the breaker, and let the caller decide
            sess.client.close()
            self._drop_sessions(node)
            self.breakers.record(key, False)
            raise
        except DeadlineExceeded:
            # the CALLER's budget ran out — no evidence about the backend
            sess.client.close()
            raise
        except Exception:  # broad-ok — app error from a healthy backend
            self._checkin(node, sess)
            self.breakers.record(key, True)
            raise
        self._checkin(node, sess)
        self.breakers.record(key, True)
        return result

    # -- routing handlers (register_async_{random,broadcast,cht}) -------------
    def _count(self, method: str) -> None:
        with self._counters_lock:
            self.request_counts[method] = self.request_counts.get(method, 0) + 1

    def _route_candidates(self, nodes: Sequence[NodeInfo]) -> List[NodeInfo]:
        """Breaker-aware filter (peek only — the probe slot is claimed by
        ``allow`` on the node actually called): open backends drop out of
        routing; if EVERY candidate is open, fail static (route anyway —
        refusing all traffic would turn a breaker bug into an outage)."""
        allowed = [n for n in nodes
                   if self.breakers.available((n.host, n.port))]
        if allowed:
            return allowed
        if nodes:
            self.rpc.trace.count("proxy.breaker_fail_static")
        return list(nodes)

    def _call_random(self, name: str, actives: Sequence[NodeInfo],
                     params: Sequence[Any]) -> Any:
        """Random routing with breaker-aware selection and idempotent
        failover: a read that hits a transport failure moves to the next
        active replica (budget-gated); an effectful call propagates its
        first failure — re-forwarding could double-apply."""
        if not actives:
            raise RpcNoClient(f"no active {self.engine} servers")
        candidates = self._route_candidates(actives)
        random.shuffle(candidates)
        idem = name in self._idempotent
        last: Optional[BaseException] = None
        tried = 0
        for node in candidates:
            if not self.breakers.allow((node.host, node.port)):
                continue  # half-open probe slot already taken
            tried += 1
            with self._counters_lock:
                self.forward_count += 1
            try:
                return self._one(node, name, params)
            except _MEMBERSHIP_ERRORS as e:
                # pre-apply rejection (draining backend): move to the
                # next replica regardless of idempotency — nothing was
                # applied. Refresh so the NEXT request routes clean.
                self._refresh_members(str(params[0]))
                last = e
                continue
            except _TRANSPORT_ERRORS as e:
                with self._counters_lock:
                    self.forward_errors += 1
                last = e
                if not idem:
                    raise
                rem = deadlines.remaining()
                if rem is not None and rem <= 0:
                    raise
                if not self.retry_budget.try_withdraw():
                    self.rpc.trace.count("rpc.retry_budget_exhausted")
                    raise
                self.rpc.trace.count("rpc.retries")
                continue
        if last is not None:
            raise last
        if not tried:
            # every candidate refused (all half-open with a probe in
            # flight): force one attempt rather than failing closed
            node = random.choice(list(candidates))
            with self._counters_lock:
                self.forward_count += 1
            try:
                return self._one(node, name, params)
            except _TRANSPORT_ERRORS:
                with self._counters_lock:
                    self.forward_errors += 1
                raise
        raise RpcNoClient(f"no active {self.engine} servers")

    #: clusters with no actives for this long fall out of the relay
    #: table and the seen-set (client-supplied names must not grow state
    #: unboundedly — a typo'd cluster should cost one window, not forever)
    _RELAY_SEEN_TTL = 60.0
    _RELAY_SEEN_CAP = 1024

    def _note_cluster(self, cluster: str) -> None:
        """A cluster first seen on the Python path enters the relay table
        at the next refresher tick — after that, its random-routed raw
        traffic never comes back here (C++ relay plane)."""
        with self._relay_lock:
            if cluster not in self._relay_seen and \
                    len(self._relay_seen) >= self._RELAY_SEEN_CAP:
                return  # cap: a flood of bogus names relays nothing anyway
            self._relay_seen.setdefault(cluster, time.monotonic())

    def _relay_refresher(self) -> None:
        """Keep the C++ relay's routing table fresh: every tick, push the
        current actives of every cluster this proxy has served. Replaced
        wholesale — a de-registered backend retires its pipes via the
        config generation (rpc_frontend.cpp relay_try). A cluster whose
        actives lookup FAILS transiently keeps its previous routing (a
        coordinator hiccup must not bounce traffic to the Python path);
        one that stays EMPTY past the TTL is dropped entirely. Backends
        with an OPEN breaker are withheld from the relay table — the C++
        plane routes around them exactly like the Python plane."""
        last_table: Dict[str, list] = {}
        while not self._stop_event.wait(1.0):
            with self._relay_lock:
                seen = dict(self._relay_seen)
            if not seen:
                continue
            now = time.monotonic()
            table = {}
            expired = []
            for cluster, last_live in seen.items():
                try:
                    nodes = [(n.host, n.port)
                             for n in self.members.actives(cluster)]
                except Exception:  # broad-ok — carry last known
                    log.debug("relay refresh failed for %s", cluster,
                              exc_info=True)
                    nodes = last_table.get(cluster, [])
                if nodes:
                    open_set = {k for k in nodes
                                if not self.breakers.available(k)}
                    healthy = [k for k in nodes if k not in open_set]
                    table[cluster] = healthy or nodes  # fail static
                    with self._relay_lock:
                        if cluster in self._relay_seen:
                            self._relay_seen[cluster] = now
                elif now - last_live > self._RELAY_SEEN_TTL:
                    expired.append(cluster)
            if expired:
                with self._relay_lock:
                    for cluster in expired:
                        self._relay_seen.pop(cluster, None)
            last_table = table
            try:
                self.rpc.relay_config(
                    self._relay_methods, table,
                    timeout=self.args.interconnect_timeout,
                    idle_expire=self.args.session_pool_expire)
            except Exception:  # broad-ok — next tick retries
                log.debug("relay config push failed", exc_info=True)

    def _route_cht(self, name: str, cht_n: int,
                   reducer: Callable[[Any, Any], Any],
                   cluster: str, actives: Sequence[NodeInfo],
                   params: Sequence[Any]) -> Any:
        """CHT routing over the CACHED ring (rebuilt only on membership
        change). Inside the handoff window after a change:

        - EFFECTFUL calls double-dispatch to the UNION of old and new
          owners — no key has zero owners while rows migrate (the
          per-host-failure tolerance of ``_fan`` means one dead old
          owner cannot fail the call);
        - IDEMPOTENT reads try new owners first and fail over to the
          old ones — whichever actually holds the row answers (a row
          not yet migrated raises an app error on the new owner)."""
        key = str(params[1])
        ring, prev = self.rings.get(cluster, actives)
        nodes = ring.find(key, cht_n)
        if prev is None:
            return self._fan(self._route_candidates(nodes), name, params,
                             reducer)
        old_nodes = prev.find(key, cht_n)
        seen = {n.name for n in nodes}
        extra = [n for n in old_nodes if n.name not in seen]
        if name in self._idempotent:
            # reads: first owner (new ring first, then old) that answers
            last: Optional[BaseException] = None
            for node in list(nodes) + extra:
                with self._counters_lock:
                    self.forward_count += 1
                try:
                    return self._one(node, name, params)
                except Exception as e:  # broad-ok — try the next owner
                    last = e
            if last is not None:
                raise last
            raise RpcNoClient(f"no active {self.engine} servers")
        if extra:
            self.rpc.trace.count("proxy.double_dispatch")
        return self._fan(self._route_candidates(list(nodes) + extra),
                         name, params, reducer)

    def _handler(self, name: str, routing: str, cht_n: int,
                 reducer: Callable[[Any, Any], Any]) -> Callable[..., Any]:
        def handle_once(*params: Any) -> Any:
            actives = self.members.actives(str(params[0]))
            if routing == "broadcast":
                # writes must reach every member: breakers observe but
                # never skip a broadcast (a success even self-heals an
                # open breaker — proof of life)
                return self._fan(actives, name, params, reducer)
            if routing == "cht":
                if len(params) < 2:
                    raise TypeError(f"{name}: cht routing needs a key param")
                return self._route_cht(name, cht_n, reducer,
                                       str(params[0]), actives, params)
            # random (proxy.hpp:229-247) + breaker skip + idempotent
            # failover
            return self._call_random(name, actives, params)

        def handle(*params: Any) -> Any:
            if params and isinstance(params[0], (str, bytes)):
                c = params[0]
                self._note_cluster(c.decode("utf-8", "surrogateescape")
                                   if isinstance(c, bytes) else c)
            self._count(name)
            self._expire_sessions()
            try:
                return handle_once(*params)
            except Exception as e:  # broad-ok — refined below, re-raised
                if not _membership_rejection(e):
                    raise
                # the backend(s) rejected BEFORE applying (draining /
                # stale ring): refresh the membership view and re-route
                # once — safe for effectful calls too
                self._refresh_members(str(params[0]))
                return handle_once(*params)

        return handle

    def _refresh_members(self, cluster: str) -> None:
        """A membership-protocol rejection means this proxy's ring view
        is stale: drop the actives cache (the ring cache revalidates by
        member-list key on the next lookup) and count the event."""
        self.members.invalidate(cluster)
        self.rpc.trace.count("proxy.ring_refresh")

    def _raw_handler(self, name: str) -> Callable[[bytes], Any]:
        """Zero-decode relay for RANDOM-routed methods: forward the raw
        params span to one backend and splice its raw result span into the
        response (rpc/server.py RawResult) — the multi-megabyte train/
        classify payloads never materialize as Python objects at the
        proxy, matching the reference proxy's C++ forwarding cost shape
        (proxy.hpp:64-186). Anything irregular (no actives, backend
        error/IO, undecodable name) declines to the generic path, which
        owns retry and error taxonomy. Breaker-aware like the generic
        path: open backends are skipped, and IDEMPOTENT methods fail over
        to the next replica on a transport failure."""
        from jubatus_tpu.rpc.server import RAW_FALLBACK, RawResult

        idem = name in self._idempotent

        def handle(raw_params: bytes) -> Any:
            cluster = _peek_cluster_name(raw_params)
            if cluster is None:
                return RAW_FALLBACK  # odd wire: generic path decides
            self._note_cluster(cluster)
            self._expire_sessions()
            actives = self.members.actives(cluster)
            if not actives:
                return RAW_FALLBACK  # generic path raises RpcNoClient
            # counted only once we own the request: every RAW_FALLBACK
            # re-enters the generic handler, which counts it there
            self._count(name)
            candidates = self._route_candidates(actives)
            random.shuffle(candidates)
            last: Optional[BaseException] = None
            tried = 0
            for node in candidates:
                key = (node.host, node.port)
                if not self.breakers.allow(key):
                    continue
                tried += 1
                with self._counters_lock:
                    self.forward_count += 1
                sess = self._checkout(node)
                try:
                    span = sess.client.call_raw(name, raw_params)
                except _TRANSPORT_ERRORS as e:
                    # transport failure AFTER the request may have reached
                    # the backend: for an EFFECTFUL method a silent
                    # re-forward would double-apply a train batch, so
                    # propagate — reads fail over to the next replica.
                    # Tear the node's sessions down either way.
                    sess.client.close()
                    self._drop_sessions(node)
                    self.breakers.record(key, False)
                    with self._counters_lock:
                        self.forward_errors += 1
                    if not idem:
                        raise
                    rem = deadlines.remaining()
                    if rem is not None and rem <= 0:
                        raise
                    if not self.retry_budget.try_withdraw():
                        self.rpc.trace.count("rpc.retry_budget_exhausted")
                        raise
                    self.rpc.trace.count("rpc.retries")
                    last = e
                    continue
                except DeadlineExceeded:
                    sess.client.close()
                    raise
                except _MEMBERSHIP_ERRORS as e:
                    # the backend refused BEFORE applying (draining):
                    # healthy connection, so pool it — then move to the
                    # next replica regardless of idempotency
                    self._checkin(node, sess)
                    self.breakers.record(key, True)
                    self._refresh_members(cluster)
                    last = e
                    continue
                except Exception:  # broad-ok — app error: backend alive
                    # application error from a HEALTHY backend (non-nil
                    # error span): the connection read the full response —
                    # return it to the pool and relay the error as-is
                    self._checkin(node, sess)
                    self.breakers.record(key, True)
                    raise
                self._checkin(node, sess)
                self.breakers.record(key, True)
                return RawResult(span)
            if last is not None:
                raise last
            if not tried:
                return RAW_FALLBACK  # all probes busy: generic path decides
            raise RpcNoClient(f"no active {self.engine} servers")

        # era-safe for every client: call_raw pins pooled backend
        # connections MODERN via its str8 method encoding, so a legacy
        # client's relayed span can never latch a shared connection
        # legacy; legacy clients get their response re-encoded old-raw by
        # build_response's RawResult materialization
        return handle

    def _register(self, name: str, arity: int, routing: str,
                  reducer: Callable[[Any, Any], Any], cht_n: int = 2) -> None:
        self.rpc.register(name, self._handler(name, routing, cht_n, reducer),
                          arity=arity)
        if routing == "random" and hasattr(self.rpc, "register_raw"):
            self.rpc.register_raw(name, self._raw_handler(name))
            self._relay_methods.append(name)

    def _register_methods(self) -> None:
        for m in get_service(self.engine):
            if m.routing == INTERNAL:
                continue  # create_node_here etc. are server↔server only
            self._register(m.name, len(m.args) + 1, m.routing,
                           aggregators.BY_NAME.get(m.aggregator, aggregators.pass_),
                           m.cht_n)
        # built-ins (proxy.cpp:43-66; get_config routes like any analysis call)
        self._register("get_config", 1, "random", aggregators.pass_)
        self._register("save", 2, "broadcast", aggregators.merge)
        self._register("load", 2, "broadcast", aggregators.all_and)
        self._register("get_status", 1, "broadcast", aggregators.merge)
        self._register("get_metrics", 1, "broadcast", aggregators.merge)
        self._register("get_mix_history", 1, "broadcast", aggregators.concat)
        # trace forensics (ISSUE 4): broadcast + fold the proxy's OWN
        # records into the reply, so one call against the proxy returns
        # the full cross-node view (the proxy hop is part of the trace)
        self.rpc.register("get_spans",
                          self._forensics_handler(
                              "get_spans", self.get_proxy_spans),
                          arity=2)
        self.rpc.register("get_slow_log",
                          self._forensics_handler(
                              "get_slow_log", self.get_proxy_slow_log),
                          arity=1)
        # model-health plane (ISSUE 7): one call against the proxy
        # returns the whole cluster's time-series/alert state (backends
        # broadcast + the proxy's own hop folded in)
        self.rpc.register("get_timeseries",
                          self._forensics_handler(
                              "get_timeseries", self.get_proxy_timeseries),
                          arity=1)
        self.rpc.register("get_alerts",
                          self._forensics_handler(
                              "get_alerts", self.get_proxy_alerts),
                          arity=1)
        # data-quality plane (ISSUE 17): one call against the proxy
        # returns every backend's mergeable sketch doc keyed by node —
        # jubactl folds them with quality.merge_quality, so fleet drift
        # is recomputed exactly from the merged sketches
        self.rpc.register("get_quality",
                          self._forensics_handler(
                              "get_quality", self.get_proxy_quality),
                          arity=1)
        # usage-attribution plane (ISSUE 19): one call against the
        # proxy returns every node's mergeable ledger doc keyed by node
        # (proxy hop included) — jubactl folds them with
        # usage.merge_usage (sketch merge, never gauge averaging)
        self.rpc.register("get_usage",
                          self._forensics_handler(
                              "get_usage", self.get_proxy_usage),
                          arity=1)
        # continuous profiling plane (ISSUE 8): one get_profile against
        # the proxy returns the whole cluster's folded stacks (backends
        # broadcast + the proxy's own samples); device captures
        # broadcast so `jubactl -c profile --device` hits every backend
        self.rpc.register("get_profile",
                          self._forensics_handler(
                              "get_profile", self.get_proxy_profile),
                          arity=2)
        self._register("profile_device", 2, "broadcast", aggregators.merge)
        # event plane + incident bundles (ISSUE 14): one call against
        # the proxy returns the whole cluster's causally merged events /
        # bundle index (backends broadcast + the proxy's own folded in)
        self.rpc.register("get_events",
                          self._forensics_handler(
                              "get_events", self.get_proxy_events),
                          arity=3)
        self.rpc.register("get_incidents",
                          self._forensics_handler(
                              "get_incidents", self.get_proxy_incidents),
                          arity=2)
        self._register("do_mix", 1, "random", aggregators.pass_)
        # elastic membership (ISSUE 10): ring-version probe routes like
        # any read (all backends agree modulo watch latency)
        self._register("get_epoch", 1, "random", aggregators.pass_)
        self.rpc.register("get_proxy_status", self.get_proxy_status, arity=1)
        self.rpc.register("get_proxy_metrics", self.get_metrics, arity=1)
        self.rpc.register("get_proxy_spans", self.get_proxy_spans, arity=2)
        self.rpc.register("get_proxy_slow_log", self.get_proxy_slow_log,
                          arity=1)
        self.rpc.register("get_proxy_timeseries", self.get_proxy_timeseries,
                          arity=1)
        self.rpc.register("get_proxy_alerts", self.get_proxy_alerts,
                          arity=1)
        self.rpc.register("get_proxy_quality", self.get_proxy_quality,
                          arity=1)
        self.rpc.register("get_proxy_usage", self.get_proxy_usage,
                          arity=1)
        self.rpc.register("get_proxy_profile", self.get_proxy_profile,
                          arity=2)
        self.rpc.register("get_proxy_events", self.get_proxy_events,
                          arity=3)
        self.rpc.register("get_proxy_incidents", self.get_proxy_incidents,
                          arity=2)
        self.rpc.register("get_breakers", self.get_breakers, arity=1)

    def _forensics_handler(self, name: str,
                           own_fn: Callable[..., Dict[str, Any]]
                           ) -> Callable[..., Dict[str, Any]]:
        """Broadcast ``name`` to the backends and fold the proxy's OWN
        records in — a proxied trace/slow-log query returns every hop of
        the story in one call. Backend failures (no actives, partial
        cluster) degrade to whatever answered plus the proxy's view: a
        forensics query against a sick cluster is exactly when partial
        data matters most."""
        fan = self._handler(name, "broadcast", 2, aggregators.merge)

        def handle(*params: Any) -> Dict[str, Any]:
            out: Dict[str, Any] = {}
            try:
                folded = fan(*params)
                if isinstance(folded, dict):
                    out.update(folded)
            except Exception:  # broad-ok — partial forensics beat none
                log.debug("%s backend broadcast failed", name,
                          exc_info=True)
            out.update(own_fn(*params))
            return out

        return handle

    # -- own status (proxy_common::get_status) --------------------------------
    def get_proxy_spans(self, _name: str = "",
                        trace_id: str = "") -> Dict[str, Any]:
        """This proxy's OWN span records for one trace (its dispatch and
        per-backend client-call spans), keyed by proxy node name."""
        node = NodeInfo(self.args.bind_host, self.rpc.port or self.args.rpc_port)
        return {node.name: self.rpc.trace.get_spans(str(trace_id))}

    def get_proxy_slow_log(self, _name: str = "") -> Dict[str, Any]:
        """This proxy's slow-request ring (tail-based capture of the
        proxy hop itself)."""
        node = NodeInfo(self.args.bind_host, self.rpc.port or self.args.rpc_port)
        return {node.name: self.rpc.trace.slowlog.snapshot()}

    def _model_health_tick(self) -> None:
        """Telemetry tick: ring sample + SLO evaluation (ISSUE 7) +
        the degraded-healthz incident trigger (ISSUE 14)."""
        if self.timeseries is None or self._in_health_tick:
            return
        self._in_health_tick = True
        try:
            # proxies have no device plane: capacity 0 keeps the
            # capacity.* gauges quiet while per-tenant demand publishes
            if self.usage is not None:
                self.usage.tick(0.0)
            self.timeseries.sample(self.rpc.trace.snapshot())
            if self.slo is not None:
                self.slo.evaluate()
            degraded = bool(self._health().get("degraded_reasons"))
            if degraded and not self._was_degraded:
                self.incidents.trigger("healthz_degraded")
            self._was_degraded = degraded
        finally:
            self._in_health_tick = False

    # -- event plane + incident bundles (ISSUE 14) ----------------------------
    def get_proxy_events(self, _name: str = "", since: int = 0,
                         grep: str = "") -> Dict[str, Any]:
        """This proxy's OWN event journal (breaker transitions, SLO
        edges at the proxy hop) merged with the process default journal;
        the RPC-routed ``get_events`` additionally broadcasts."""
        from jubatus_tpu.utils import events as ev

        node = NodeInfo(self.args.bind_host,
                        self.rpc.port or self.args.rpc_port)
        grep = grep.decode() if isinstance(grep, bytes) else str(grep or "")
        recs = ev.merge_events([
            self.rpc.trace.events.snapshot(since=int(since or 0), grep=grep),
            ev.default_journal().snapshot(since=int(since or 0), grep=grep),
        ])
        return {node.name: {"events": recs, "hlc_now": ev.hlc_now(),
                            "stats": self.rpc.trace.events.stats()}}

    def get_proxy_incidents(self, _name: str = "",
                            incident_id: str = "") -> Dict[str, Any]:
        """This proxy's incident bundles: empty id lists, a concrete id
        returns the full forensic doc."""
        node = NodeInfo(self.args.bind_host,
                        self.rpc.port or self.args.rpc_port)
        incident_id = incident_id.decode() \
            if isinstance(incident_id, bytes) else str(incident_id or "")
        if incident_id:
            return {node.name: self.incidents.get(incident_id)}
        return {node.name: self.incidents.list()}

    def _incident_dir(self) -> str:
        return getattr(self.args, "incident_dir", "") or os.path.join(
            "/tmp", f"jubatus_incidents_{self.engine}_proxy_"
            f"{self.rpc.port or self.args.rpc_port}")

    def _on_slo_fire(self, name: str, _state: Dict[str, Any]) -> None:
        ids = [r.get("trace_id", "")
               for r in self.rpc.trace.slowlog.snapshot(last=16)]
        self.incidents.trigger(f"slo_firing:{name}",
                               trace_ids=[t for t in ids if t][-8:])

    def _incident_state(self) -> Dict[str, Any]:
        """Proxy-flavored forensic snapshot: events, timeseries, slow
        log, per-backend breaker state, profiler tail, health."""
        from jubatus_tpu.utils import events as ev

        doc: Dict[str, Any] = {
            "node": NodeInfo(self.args.bind_host,
                             self.rpc.port or self.args.rpc_port).name,
            "events": ev.merge_events([
                self.rpc.trace.events.snapshot(limit=256),
                ev.default_journal().snapshot(limit=64)]),
            "slow_log": self.rpc.trace.slowlog.snapshot(last=64),
            "breakers": self.breakers.snapshot(),
            "health": self._health(),
        }
        if self.usage is not None:
            doc["usage"] = self.usage.incident_doc()
        if self.timeseries is not None:
            doc["timeseries"] = self.timeseries.points(last=60)
        try:
            prof = self.profiler.profile(30.0)
            folded = prof.get("folded") or {}
            top = dict(sorted(folded.items(), key=lambda kv: -kv[1])[:50])
            doc["profile"] = {"folded_top": top,
                              "snapshots": prof.get("snapshots") or [],
                              "stats": prof.get("stats") or {}}
        except Exception:  # broad-ok — forensics must not block capture
            log.debug("incident profile fold failed", exc_info=True)
        return doc

    def get_proxy_timeseries(self, _name: str = "") -> Dict[str, Any]:
        """This proxy's OWN metric time-series ring (the RPC-routed
        ``get_timeseries`` additionally broadcasts to the backends)."""
        node = NodeInfo(self.args.bind_host, self.rpc.port or self.args.rpc_port)
        if self.timeseries is None:
            return {node.name: {"stats": {}, "points": []}}
        return {node.name: {"stats": self.timeseries.stats(),
                            "points": self.timeseries.points()}}

    def get_proxy_alerts(self, _name: str = "") -> Dict[str, Any]:
        """This proxy's OWN SLO state (firing alerts + burn rates)."""
        node = NodeInfo(self.args.bind_host, self.rpc.port or self.args.rpc_port)
        if self.slo is None:
            return {node.name: {"alerts": [], "slos": []}}
        return {node.name: {"alerts": self.slo.alerts(),
                            "slos": self.slo.status()}}

    def get_proxy_quality(self, _name: str = "") -> Dict[str, Any]:
        """The proxy hop has no train path, so it contributes no
        quality doc of its own — the RPC-routed ``get_quality`` is the
        backend broadcast folded over this empty dict."""
        return {}

    def get_proxy_usage(self, _name: str = "") -> Dict[str, Any]:
        """This proxy's OWN per-tenant ledger doc, keyed by proxy node
        name — unlike quality, the proxy hop has real cost to report
        (every forward dispatches here). The RPC-routed ``get_usage``
        is the backend broadcast folded over this."""
        node = NodeInfo(self.args.bind_host,
                        self.rpc.port or self.args.rpc_port)
        if self.usage is None:
            return {node.name: {}}
        return {node.name: self.usage.snapshot()}

    def get_proxy_profile(self, _name: str = "",
                          seconds: float = 0.0) -> Dict[str, Any]:
        """This proxy's OWN folded stack profile (the RPC-routed
        ``get_profile`` additionally broadcasts to the backends)."""
        node = NodeInfo(self.args.bind_host, self.rpc.port or self.args.rpc_port)
        return {node.name: self.profiler.profile(float(seconds or 0.0))}

    def get_breakers(self, _name: str = "") -> Dict[str, Dict[str, Any]]:
        """Breaker + retry-budget state, keyed by proxy node name — the
        ``jubactl -c breakers`` view and the ops answer to 'why is this
        backend getting no traffic?'."""
        node = NodeInfo(self.args.bind_host, self.rpc.port or self.args.rpc_port)
        return {node.name: {
            "breakers": self.breakers.snapshot(),
            "retry_budget": self.retry_budget.status(),
        }}

    def get_proxy_status(self, _name: str = "") -> Dict[str, Dict[str, Any]]:
        node = NodeInfo(self.args.bind_host, self.rpc.port or self.args.rpc_port)
        # requests the C++ relay served never reach Python — fold its
        # per-method counts into the same counters the reference reports
        relayed: Dict[str, int] = {}
        if hasattr(self.rpc, "relay_stats"):
            try:
                relayed = self.rpc.relay_stats()
            except Exception:  # broad-ok — status must never fail
                log.debug("relay stats fetch failed", exc_info=True)
        relay_errors = relayed.pop("__errors__", 0)
        breakers = self.breakers.snapshot()
        with self._counters_lock:
            st: Dict[str, Any] = {
                "timestamp": int(time.time()),  # wall-clock
                "uptime": int(time.time() - self.start_time),  # wall-clock
                "type": f"{self.engine}_proxy",
                "version": __version__,
                "forward_count": self.forward_count + sum(relayed.values()),
                "forward_errors": self.forward_errors + relay_errors,
                "session_pool_size": sum(
                    len(v) for v in self._pool.values()),
                "relay_count": sum(relayed.values()),
            }
            counts = dict(self.request_counts)
            for m, c in relayed.items():
                counts[m] = counts.get(m, 0) + c
            st.update({f"request.{k}": v for k, v in counts.items()})
        st["breaker_backends"] = len(breakers)
        st["breaker_open"] = sum(
            1 for b in breakers.values() if b["state"] == "open")
        st["breaker_opened_total"] = sum(
            b["opened_total"] for b in breakers.values())
        # elastic membership (ISSUE 10): ring-cache engagement + how
        # many clusters are inside a double-dispatch handoff window
        for k, v in self.rings.stats().items():
            st[f"ring.{k}"] = v
        for k, v in self.retry_budget.status().items():
            st[f"retry_budget.{k}"] = v
        st.update(self.args.flags_status())
        # span histograms + counters (same registry /metrics exposes) —
        # the proxy hop's rpc.* quantiles and trace ids sit next to the
        # backends' in a merged get_status view
        st.update(self.rpc.trace.trace_status())
        st.update({f"runtime.{k}": v
                   for k, v in self.telemetry.status().items()})
        st.update({f"slowlog.{k}": v
                   for k, v in self.rpc.trace.slowlog.stats().items()})
        st.update({f"profiler.{k}": v
                   for k, v in self.profiler.stats().items()})
        st.update({f"events.{k}": v
                   for k, v in self.rpc.trace.events.stats().items()})
        st.update({f"incident.{k}": v
                   for k, v in self.incidents.stats().items()})
        # usage-attribution plane (ISSUE 19): the per-tenant summary
        if self.usage is not None:
            st.update({f"usage.{k}": v
                       for k, v in self.usage.stats().items()})
        return {node.name: st}

    def get_metrics(self, _name: str = "") -> Dict[str, Dict[str, Any]]:
        """This proxy's own mergeable metrics snapshot (the RPC-routed
        ``get_metrics`` fans out to the backends instead)."""
        node = NodeInfo(self.args.bind_host, self.rpc.port or self.args.rpc_port)
        return {node.name: self.rpc.trace.snapshot()}

    def _health(self) -> Dict[str, Any]:
        with self._counters_lock:
            fwd, errs = self.forward_count, self.forward_errors
        breakers = self.breakers.snapshot()
        # structured degraded reasons (ISSUE 7): open backend breakers
        # + firing proxy-side SLOs, same shape as the servers' /healthz
        reasons: List[Dict[str, Any]] = []
        open_backends = sorted(
            str(k) for k, b in breakers.items() if b["state"] == "open")
        if open_backends:
            reasons.append({"kind": "breaker_open",
                            "count": len(open_backends),
                            "backends": open_backends})
        if self.slo is not None:
            for a in self.slo.alerts():
                reasons.append({"kind": "slo_firing", "name": a["name"],
                                "burn_fast": a.get("burn_fast"),
                                "burn_slow": a.get("burn_slow")})
        doc = {"engine": f"{self.engine}_proxy",
               "status": "degraded" if reasons else "ok",
               "degraded_reasons": reasons,
               "uptime_s": int(time.time() - self.start_time),  # wall-clock
               "rpc_port": self.rpc.port or self.args.rpc_port,
               "forward_count": fwd, "forward_errors": errs,
               "breaker_open": len(open_backends)}
        pstats = self.profiler.stats()
        doc["profiler_hz"] = pstats["hz"]
        doc["profiler_samples"] = pstats["samples"]
        rt = self.telemetry.status()
        for k in ("rss_bytes", "open_fds", "threads", "slowlog_depth"):
            if k in rt:
                doc[k] = rt[k]
        return doc

    # -- lifecycle ------------------------------------------------------------
    def start(self, port: Optional[int] = None) -> int:
        actual = self.rpc.serve_background(
            port if port is not None else self.args.rpc_port,
            nthreads=self.args.thread,
            host=self.args.bind_host,
        )
        self.args.rpc_port = actual
        # event plane (ISSUE 14): attribute this proxy's events by its
        # bound node name
        self.rpc.trace.events.node = NodeInfo(self.args.bind_host,
                                              actual).name
        self.telemetry.start()
        self.profiler.start()
        if getattr(self.args, "metrics_port", -1) >= 0:
            from jubatus_tpu.utils.metrics_http import MetricsServer

            self.metrics = MetricsServer(
                self.rpc.trace,
                labels={"engine": f"{self.engine}_proxy",
                        "node": f"{self.args.bind_host}_{actual}"},
                health_fn=self._health,
                host=self.args.bind_host, port=self.args.metrics_port)
            self.args.metrics_port = self.metrics.start()
            log.info("proxy metrics endpoint on %s:%d", self.args.bind_host,
                     self.args.metrics_port)
        try:
            membership.register_proxy(self.coord, self.args.bind_host, actual)
        except Exception:  # broad-ok — registry is informational for proxies
            log.debug("proxy registration failed", exc_info=True)
        log.info("%s proxy listening on %s:%d", self.engine, self.args.bind_host, actual)
        return actual

    def join(self) -> None:
        self._stop_event.wait()

    def stop(self) -> None:
        if self.usage is not None:
            from jubatus_tpu.utils import usage as usage_mod

            usage_mod.detach(self.usage)
        self.rpc.stop()
        self.telemetry.stop()
        self.profiler.stop()
        if self.metrics is not None:
            try:
                self.metrics.stop()
            except Exception:  # broad-ok — teardown must finish
                log.debug("metrics endpoint stop failed", exc_info=True)
        with self._pool_lock:
            for lst in self._pool.values():
                for sess in lst:
                    sess.client.close()
            self._pool.clear()
        self._executor.shutdown(wait=False)
        self.coord.close()
        self._stop_event.set()


def main(argv: Optional[List[str]] = None) -> int:
    """``python -m jubatus_tpu.server.proxy <engine> -z <coord> [-p PORT]``
    (≙ juba<engine>_proxy binaries)."""
    import argparse
    import signal
    import sys

    p = argparse.ArgumentParser(prog="jubatus_tpu.server.proxy")
    p.add_argument("engine")
    p.add_argument("-p", "--rpc-port", type=int, default=9199)
    p.add_argument("-b", "--listen-addr", default="")
    p.add_argument("-c", "--thread", type=int, default=4)
    p.add_argument("-t", "--timeout", type=float, default=10.0)
    p.add_argument("-z", "--coordinator", required=True)
    p.add_argument("--interconnect-timeout", type=float, default=10.0)
    p.add_argument("--pool-expire", dest="session_pool_expire", type=float, default=60.0)
    p.add_argument("--pool-size", dest="session_pool_size", type=int, default=0)
    p.add_argument("--legacy-wire", action="store_true",
                   help="FORCE responses into the pre-str8/bin msgpack "
                        "format for unmodified legacy jubatus clients "
                        "(otherwise autodetected per connection)")
    p.add_argument("--modern-wire", action="store_true",
                   help="disable per-connection legacy-wire autodetection")
    p.add_argument("--metrics-port", type=int, default=-1,
                   help="serve Prometheus /metrics + /healthz on this "
                        "HTTP port (0 = ephemeral; default off)")
    p.add_argument("--breaker-failures", type=int, default=5,
                   help="transport failures within --breaker-window that "
                        "open a backend's circuit breaker")
    p.add_argument("--breaker-window", type=float, default=30.0)
    p.add_argument("--breaker-cooldown", type=float, default=5.0,
                   help="seconds an open breaker refuses traffic before "
                        "admitting a half-open probe")
    p.add_argument("--retry-budget-ratio", type=float, default=0.1,
                   help="failover retries allowed per first-attempt "
                        "forward (token bucket; 0 disables failover)")
    p.add_argument("--slowlog-capacity", type=int, default=256,
                   help="slow-request ring size at the proxy hop "
                        "(0 disables tail-based capture)")
    p.add_argument("--slowlog-quantile", type=float, default=0.99,
                   help="per-span histogram quantile at/above which a "
                        "forwarded request is captured in the slow log")
    p.add_argument("--slowlog-min-count", type=int, default=64,
                   help="samples a span needs before slow-log "
                        "thresholding starts")
    p.add_argument("--telemetry-interval", type=float, default=10.0,
                   help="runtime telemetry sampling period in seconds "
                        "(0 disables the sampler thread)")
    p.add_argument("--slo", action="append", default=None, metavar="SPEC",
                   help="declarative SLO at the proxy hop, evaluated as "
                        "a multi-window burn rate (repeatable; same "
                        "grammar as the servers: latency:<span>:p<QQ>:"
                        "<threshold_ms>[:<objective>], error_rate:"
                        "<span|*>:<objective>, gauge:<key>:<ceiling>)")
    p.add_argument("--slo-fast-window", type=float, default=300.0,
                   help="fast burn-rate window in seconds")
    p.add_argument("--slo-slow-window", type=float, default=3600.0,
                   help="slow burn-rate window in seconds")
    p.add_argument("--slo-burn-threshold", type=float, default=2.0,
                   help="fire when BOTH windows burn at/above this "
                        "multiple of the sustainable budget spend")
    p.add_argument("--timeseries-capacity", type=int, default=360,
                   help="metric time-series ring depth (points; 0 "
                        "disables the ring and SLO evaluation)")
    p.add_argument("--profile-hz", type=float, default=67.0,
                   help="always-on stack sampling rate at the proxy hop "
                        "(Hz); the proxy's samples fold into jubactl -c "
                        "profile next to the backends'; 0 disables")
    p.add_argument("--profile-trigger-breaches", type=int, default=3,
                   help="slow-log captures of the SAME span inside "
                        "--profile-trigger-window that auto-capture a "
                        "profile snapshot (once per window; 0 disables)")
    p.add_argument("--profile-trigger-window", type=float, default=10.0,
                   help="breach-counting window (seconds) for the "
                        "tail-triggered profile snapshot")
    p.add_argument("--handoff-window", type=float, default=15.0,
                   help="seconds after a membership change during which "
                        "CHT-routed effectful calls double-dispatch to "
                        "the union of old and new ring owners (no key "
                        "has zero owners while rows migrate); idempotent "
                        "reads fail over new->old instead")
    p.add_argument("--event-capacity", type=int, default=2048,
                   help="cluster event journal depth at the proxy hop "
                        "(breaker transitions, proxy SLO edges; served "
                        "by get_events / jubactl -c timeline); 0 "
                        "disables emission")
    p.add_argument("--incident-window", type=float, default=300.0,
                   help="debounce window (seconds) for automatic "
                        "incident bundles at the proxy hop: a firing "
                        "proxy SLO or degraded /healthz captures ONE "
                        "correlated snapshot per window; 0 disables")
    p.add_argument("--incident-dir", default="",
                   help="capped incident-bundle artifacts dir (oldest "
                        "pruned); empty = under /tmp keyed by the "
                        "bound port")
    p.add_argument("--usage-top", type=int, default=64,
                   help="exact per-principal usage-ledger rows at the "
                        "proxy hop (overflow folds into the '(other)' "
                        "row backed by a heavy-hitter sketch; 0 "
                        "disables the ledger)")
    p.add_argument("--usage-gauge-principals", type=int, default=8,
                   help="top-N principals published as "
                        "usage.<principal>.* gauges each telemetry tick")
    ns = p.parse_args(argv)
    ns.slo = ns.slo or []
    args = ProxyArgs(**{f.name: getattr(ns, f.name)
                        for f in dataclasses.fields(ProxyArgs)
                        if hasattr(ns, f.name)})
    for spec in args.slo:
        from jubatus_tpu.utils.slo import parse_slo

        try:  # reject bad grammar at argv time
            parse_slo(spec)
        except ValueError as e:
            raise SystemExit(str(e))
    logging.basicConfig(
        level=logging.INFO,
        format=f"%(asctime)s %(levelname)s [{args.engine}_proxy:{args.rpc_port}] %(message)s",
    )
    proxy = Proxy(args)
    signal.signal(signal.SIGTERM, lambda *_: proxy.stop())
    signal.signal(signal.SIGINT, lambda *_: proxy.stop())
    proxy.start()
    proxy.join()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
