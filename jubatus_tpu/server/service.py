"""Wire adapters: bind drivers onto RpcServer (≙ generated *_impl.cpp).

One binder per engine converts between msgpack wire types (datum 3-tuples,
[k,v] pair lists) and driver types (Datum, tuples), registers each IDL method
under its wire name with the leading cluster-name param every jubatus call
carries, calls driver.event_model_updated() after update methods (the
reference's generated impls do this via lock decorators + serv methods,
classifier_impl.cpp:56-59 → classifier_serv.cpp:127-146), and registers the
built-ins (get_config/save/load/get_status/do_mix, client.hpp:30-87).

Update methods run under the driver lock (JWLOCK_); the built-ins take it
where the reference does.
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List

import numpy as np

from jubatus_tpu.core.datum import Datum
from jubatus_tpu.rpc.server import RpcServer

log = logging.getLogger(__name__)

# -- wire ↔ driver conversions ----------------------------------------------


def _datum(obj: Any) -> Datum:
    return Datum.from_msgpack(obj)


def _datums(objs: Any) -> List[Datum]:
    return [Datum.from_msgpack(o) for o in objs]


def _wire_datum(d: Datum):
    return d.to_msgpack()


def _scored(results: List) -> List:
    """[(id, score)] → [[id, score]] (id_with_score wire shape)."""
    return [[i, float(s)] for i, s in results]


# -- binder registry ---------------------------------------------------------

_BINDERS: Dict[str, Callable[[RpcServer, Any], None]] = {}


def _binder(engine: str):
    def deco(fn):
        _BINDERS[engine] = fn
        return fn

    return deco


def bind_engine(rpc: RpcServer, server: Any) -> None:
    """Register built-ins + the engine's IDL surface on the RPC server."""
    rpc.register("get_config", server.get_config, arity=1)
    rpc.register("save", server.save, arity=2)
    rpc.register("load", server.load, arity=2)
    rpc.register("get_status", server.get_status, arity=1)
    rpc.register("get_metrics", server.get_metrics, arity=1)
    # trace forensics (ISSUE 4): per-trace span store + slow-request ring
    rpc.register("get_spans", server.get_spans, arity=2)
    rpc.register("get_slow_log", server.get_slow_log, arity=1)
    # model-health plane (ISSUE 7): metric time-series + SLO alerts
    rpc.register("get_timeseries", server.get_timeseries, arity=1)
    rpc.register("get_alerts", server.get_alerts, arity=1)
    # data-quality plane (ISSUE 17): mergeable drift/prequential doc
    rpc.register("get_quality", server.get_quality, arity=1)
    # usage-attribution plane (ISSUE 19): per-principal cost ledger doc
    rpc.register("get_usage", server.get_usage, arity=1)
    # self-tuning performance plane (ISSUE 20): tuner state + journal
    rpc.register("get_tune", server.get_tune, arity=1)
    # continuous profiling plane (ISSUE 8): folded stack profile +
    # on-demand XLA device capture
    rpc.register("get_profile", server.get_profile, arity=2)
    rpc.register("profile_device", server.profile_device, arity=2)
    # cluster event plane + incident bundles (ISSUE 14): HLC-ordered
    # event journal (cursor-resumable) + the capped forensic bundles
    rpc.register("get_events", server.get_events, arity=3)
    rpc.register("get_incidents", server.get_incidents, arity=2)
    rpc.register("do_mix", server.do_mix, arity=1)
    # elastic membership (ISSUE 10): ring-version + drain control +
    # the state-migration data plane (framework/migration.py). The
    # migration payloads ship packed row vectors between our own
    # servers — binary=True keeps them modern even under --legacy-wire.
    rpc.register("get_epoch", server.get_epoch, arity=1)
    rpc.register("drain", server.drain, arity=2)
    rpc.register("drain_status", server.drain_status, arity=1)
    rpc.register("rebalance", server.rebalance, arity=1)
    rpc.register("migrate_range", server.migrate_range, arity=5,
                 binary=True)
    rpc.register("put_rows", server.put_rows, arity=2, binary=True)
    rpc.register("get_row_count", server.get_row_count, arity=1)
    # model-integrity plane (ISSUE 15): restore the last-good snapshot
    rpc.register("rollback", server.rollback, arity=2)
    # durable model plane (ISSUE 18): point-in-time restore from the
    # shared snapshot store + the store's status read
    rpc.register("store_restore", server.store_restore, arity=2)
    rpc.register("get_store_status", server.get_store_status, arity=1)
    _BINDERS[server.engine](rpc, server)


def _updating(server: Any, fn: Callable, count: Callable[[Any], int] = lambda r: 1):
    """Wrap an update method: driver lock + event_model_updated (the
    reference's JWLOCK_ + serv-side bookkeeping). Most driver methods bump
    the counter themselves; the wrapper only adds the event when the driver
    didn't, so updates are never double-counted."""

    def wrapped(*args):
        with server.driver.lock:
            before = server.driver.update_count
            result = fn(*args)
            if server.driver.update_count == before:
                n = count(result)
                if n:
                    server.driver.event_model_updated(n)
        return result

    return wrapped


# -- per-engine binders -------------------------------------------------------


class _ComboPlanCache:
    """Device-expansion plans for combination-rule configs, keyed by the
    base index row (the feature schema). The C++ base parser ships only
    the [B, K0] base columns; the plan carries the full base+slot index
    vector and the (a, b, op) bilinear terms the device expands
    (ops._expand_combo). Slot hashes and pair structure come from the
    Python converter's own combo plan (core/fv/converter.py) — the
    single owner of combination semantics — validated against the C++
    row by hashing a sample datum's base names. Schemas the plan cannot
    serve exactly (hash collisions, idf/user weights, multi-term slots)
    are declined and the request falls back to the generic
    batch-converter path with identical semantics."""

    _MISS = object()

    class Plan:
        __slots__ = ("uidx", "a_idx", "b_idx", "mul_mask")

        def __init__(self, uidx, a_idx, b_idx, mul_mask):
            self.uidx = uidx
            self.a_idx = a_idx
            self.b_idx = b_idx
            self.mul_mask = mul_mask

    def __init__(self, conv: dict, converter) -> None:
        self._conv = conv or {}
        self._converter = converter  # the driver's full converter
        self._plans: Dict[bytes, Any] = {}

    def make_base_parser(self, dim_bits: int):
        """The C++ parser for the config SANS combination rules (base
        features only); None when that subset is not native-expressible."""
        from jubatus_tpu.native.ingest import IngestParser

        base_conv = {k: v for k, v in self._conv.items()
                     if k != "combination_rules"}
        try:
            return IngestParser.from_converter_config(base_conv, dim_bits)
        except Exception:  # broad-ok — plan mode is strictly optional
            return None

    def _plan_for(self, row0, raw_params: bytes, with_labels: bool):
        key = row0.tobytes()
        plan = self._plans.get(key, self._MISS)
        if plan is not self._MISS:
            return plan
        plan = self._build(row0, raw_params, with_labels)
        if len(self._plans) >= 64:
            self._plans.clear()
        self._plans[key] = plan
        return plan

    def _build(self, row0, raw_params: bytes, with_labels: bool):
        import msgpack

        from jubatus_tpu.core.datum import Datum

        try:
            req = msgpack.unpackb(raw_params, raw=False,
                                  strict_map_key=False, use_list=True,
                                  unicode_errors="surrogateescape")
            wire = req[1][0][1] if with_labels else req[1][0]
            datum = Datum.from_msgpack(wire)
        except Exception:  # broad-ok — undecodable sample: decline plan
            return None
        conv = self._converter
        named = conv._base_named_features(datum)
        names = list(named)
        live = row0[row0 != 0]
        if len(names) != live.shape[0]:
            return None  # hash collision merged base columns
        idxs, kinds = conv._resolve_names(names)
        order = np.argsort(idxs, kind="stable")
        if not np.array_equal(idxs[order], live.astype(np.int32)):
            return None  # sample's schema does not explain the row
        if kinds.any():
            return None  # base features must be bin-weighted
        sorted_names = tuple(names[i] for i in order)
        cplan = conv._combo_plan_for(sorted_names)
        if cplan.slot_kind.any():
            return None  # combo slots must be bin-weighted
        if cplan.t_starts.shape[0] != cplan.a_idx.shape[0]:
            return None  # multi-term slots: host semantics required
        nz = np.concatenate([live.astype(np.int32), cplan.slot_idx])
        if np.unique(nz).shape[0] != nz.shape[0]:
            return None  # index collision: expansion would double-count
        uidx = np.concatenate([row0.astype(np.int32), cplan.slot_idx])
        return self.Plan(uidx, cplan.a_idx, cplan.b_idx,
                         cplan.mul_mask.astype(bool))

    def parse_train(self, base_parser, raw_params: bytes):
        """Raw train params -> a coalescer item riding the device-
        expansion plan, or RAW_FALLBACK (generic path, same semantics)."""
        from jubatus_tpu.rpc.server import RAW_FALLBACK

        parsed = base_parser.parse_indexed(raw_params)
        if parsed is None:
            return RAW_FALLBACK
        labels, idx, val = parsed
        if isinstance(labels, np.ndarray):
            return RAW_FALLBACK  # numeric labels on a classifier wire
        b = idx.shape[0]
        if b == 0:
            return RAW_FALLBACK
        row0 = idx[0]
        if b > 1 and not (idx == row0).all():
            return RAW_FALLBACK  # mixed schemas in one request
        plan = self._plan_for(row0, raw_params, with_labels=True)
        if plan is None:
            return RAW_FALLBACK
        return (("combo", plan), labels, idx, val)

    def parse_query(self, base_parser, raw_params: bytes):
        """Raw datum-list params -> (plan, base_val) or RAW_FALLBACK."""
        from jubatus_tpu.rpc.server import RAW_FALLBACK

        parsed = base_parser.parse_datums(raw_params)
        if parsed is None:
            return RAW_FALLBACK
        idx, val = parsed
        if idx.shape[0] == 0:
            return (None, val)
        row0 = idx[0]
        if idx.shape[0] > 1 and not (idx == row0).all():
            return RAW_FALLBACK
        plan = self._plan_for(row0, raw_params, with_labels=False)
        if plan is None:
            return RAW_FALLBACK
        return (plan, val)


def _quality_observe_pairs(server: Any, pairs) -> None:
    """Prequential (test-then-train) hook for the generic train path
    (ISSUE 17): on sampled batches, score a bounded prefix with the
    CURRENT model before the update is submitted, and record the label
    distribution. Reads are snapshot reads (no driver lock), failures
    never reach the ingest path."""
    q = getattr(server, "quality", None)
    if q is None or not pairs or not q.admit("train"):
        return
    d = server.driver
    sub = pairs[:q.max_score_rows]
    try:
        q.record_labels(p[0] for p in pairs)
        data = [p[1] for p in sub]
        if isinstance(sub[0][0], str) and hasattr(d, "classify"):
            for (truth, _dat), ranked in zip(sub, d.classify(data)):
                q.record_classified(truth, ranked)
        elif hasattr(d, "estimate"):
            for (truth, _dat), est in zip(sub, d.estimate(data)):
                q.record_estimated(float(truth), float(est))
    except Exception:  # broad-ok — quality scoring must not break ingest
        log.debug("prequential hook failed", exc_info=True)


def _quality_observe_raw(server: Any, item, numeric: bool) -> None:
    """Prequential + feature-stat hook for the native raw-ingest path:
    names never materialize here, so values record under the ``hashed``
    group; scoring rides classify_hashed/estimate_hashed on a bounded
    row prefix. Combo-plan items skip scoring (the base arrays are not
    the model's input rows)."""
    q = getattr(server, "quality", None)
    if q is None or not q.admit("train"):
        return
    d = server.driver
    tag, labels, idx, val = item
    try:
        q.record_hashed(val)
        if tag[0] != "plain":
            return
        k = min(q.max_score_rows, idx.shape[0])
        if numeric:
            if hasattr(d, "estimate_hashed"):
                for t, e in zip(labels[:k], d.estimate_hashed(idx[:k],
                                                              val[:k])):
                    q.record_estimated(float(t), float(e))
        else:
            uniq, lidx = labels
            q.record_labels(uniq[int(j)] for j in lidx)
            if hasattr(d, "classify_hashed"):
                ranked = d.classify_hashed(idx[:k], val[:k])
                for j, r in enumerate(ranked):
                    q.record_classified(uniq[int(lidx[j])], r)
    except Exception:  # broad-ok — quality scoring must not break ingest
        log.debug("raw prequential hook failed", exc_info=True)


def _usage_batch_hook(server: Any, method: str):
    """Microbatch billing hook (ISSUE 19): the coalescer calls this once
    per ticket per flush with the submitting tenant, its row weight, its
    queue residency and its amortized share of the flush's device time —
    the ledger rolls them into the ``usage.<principal>.*`` gauges. None
    (no hook, zero overhead) when the ledger is disabled."""
    u = getattr(server, "usage", None)
    if u is None:
        return None

    def hook(principal, rows, queue_s, device_s):
        u.record_batch(principal, method, rows, queue_s, device_s)

    return hook


def _register_train(rpc: RpcServer, server: Any, decode_pair,
                    train_fn) -> None:
    """Register "train" with microbatch coalescing (server/microbatch.py):
    concurrent train RPCs merge into one driver/device batch — SURVEY.md
    §7 step 4's ingest queue. ``--microbatch-max 0`` restores the direct
    per-RPC path. Either way each caller's reply is its own item count
    (the reference's per-call return, classifier_impl.cpp:56-59).

    Drivers exposing the featurize/apply split (``featurize_train`` +
    ``train_hashed``) ride the two-stage PipelinedCoalescer: batch N+1
    featurizes on the flusher's host thread (span ``fv.convert``) while
    the device consumes batch N (span ``fv.upload``) — the feature
    pipeline's host/device overlap."""
    max_batch = getattr(server.args, "microbatch_max", 8192)
    flush = _updating(server, train_fn, count=lambda r: r)
    if not max_batch:
        def train_direct(name, data):
            pairs = [decode_pair(p) for p in data]
            _quality_observe_pairs(server, pairs)
            return flush(pairs)

        rpc.register("train", train_direct, arity=2)
        return
    driver = server.driver
    featurize = getattr(driver, "featurize_train", None)
    apply_fn = getattr(driver, "train_hashed", None)
    if featurize is not None and apply_fn is not None:
        from jubatus_tpu.server.microbatch import PipelinedCoalescer

        device_step = _updating(
            server, lambda prepared: apply_fn(*prepared),
            count=lambda r: r)
        co = PipelinedCoalescer(featurize, device_step,
                                max_batch=max_batch, trace=rpc.trace)
    else:
        from jubatus_tpu.server.microbatch import Coalescer

        co = Coalescer(flush, max_batch=max_batch)
    server.coalescers["train"] = co
    co.usage_hook = _usage_batch_hook(server, "train")

    # -t 0 conventionally means "no timeout" — map to an unbounded wait
    wait_s = server.args.timeout * 6 if server.args.timeout > 0 else None

    def train(name, data):
        pairs = [decode_pair(p) for p in data]
        if not pairs:
            return 0
        # test-then-train: prequential scoring sees the pre-update model
        _quality_observe_pairs(server, pairs)
        co.submit(pairs, timeout=wait_s)
        return len(pairs)

    rpc.register("train", train, arity=2)


def _register_train_raw(rpc: RpcServer, server: Any, numeric: bool) -> None:
    """Native ingest fast path for ``train`` (native/fast_ingest.cpp): the
    request's raw msgpack params parse in C++ straight to pre-hashed [B, K]
    arrays — no Datum objects, no Python convert loop. Registered only
    when the transport exposes raw spans, the driver has ``train_hashed``,
    and the converter config is expressible in the native parser
    (jubatus_tpu/native/ingest.py gates); any request the parser declines
    (unexpected wire shape, unrepresentable values) falls back to the
    generic decode + converter path, so behavior is identical either way."""
    import json as _json

    driver = server.driver
    if not hasattr(rpc, "register_raw") or not hasattr(driver, "train_hashed"):
        return
    try:
        from jubatus_tpu.native.ingest import IngestParser

        conv = _json.loads(server.config_json).get("converter")
        parser = IngestParser.from_converter_config(
            conv, driver.converter.hasher.dim_bits)
    except Exception:  # broad-ok — fast path is strictly optional
        return
    if parser is None:
        return
    from jubatus_tpu.rpc.server import RAW_FALLBACK

    def _pad_concat(pairs):
        """Merge per-request (idx, val) pairs into one batch: pad widths
        to the max (already pow2-bucketed by the parser, so pads are rare
        and small) and concatenate at numpy speed. ONE owner for both the
        train and query flush paths."""
        kmax = max(i.shape[1] for i, _ in pairs)
        parts_i, parts_v = [], []
        for ir, vr in pairs:
            if ir.shape[1] != kmax:
                pad = kmax - ir.shape[1]
                ir = np.pad(ir, ((0, 0), (0, pad)))
                vr = np.pad(vr, ((0, 0), (0, pad)))
            parts_i.append(ir)
            parts_v.append(vr)
        return (np.concatenate(parts_i) if len(parts_i) > 1 else parts_i[0],
                np.concatenate(parts_v) if len(parts_v) > 1 else parts_v[0])

    def _uniform_row(pairs):
        """The shared index row if EVERY row of every (idx, val) pair
        equals the first one (same width), else None. A fixed key schema
        — the common production feed shape — hashes every datum to the
        same index vector; detecting it per flush costs ~B*K int
        compares (~0.02 µs/sample) and unlocks the dense submatrix train
        plan (ops.train_batch_schema: no B*K-element gathers/scatters)."""
        first = pairs[0][0]
        row0 = first[0]
        k = first.shape[1]
        for ir, _vr in pairs:
            if ir.shape[1] != k or not (ir == row0).all():
                return None
        return row0

    schema_train = getattr(driver, "train_indexed_schema", None)
    combo_train = getattr(driver, "train_indexed_combo", None)
    # schema-plan accounting, surfaced by get_status ("ingest.*" keys,
    # server/base.py) and the e2e bench: how often flushes actually ride
    # the dense submatrix plan
    stats = server.ingest_stats = {"schema_flushes": 0, "sparse_flushes": 0,
                                   "combo_flushes": 0,
                                   "schema_query_flushes": 0,
                                   "sparse_query_flushes": 0}

    # deferred-idf (pure-idf specs): parses run lock-free against zero df
    # tables; ONE observe+scale per coalesced flush (the idf
    # batch-collapse fix — see native/ingest.py deferred_idf_scale)
    deferred = parser.deferred_idf
    weights = driver.converter.weights \
        if (parser.needs_weights or deferred) else None

    # combo device plan (classifier combo configs): parse only the BASE
    # features in C++, expand the cross product ON DEVICE
    # (ops.train_batch_schema_combo) — the (K0+S)-wide row never crosses
    # the host/device wire. None when ineligible; requests the plan
    # cannot serve fall back to the generic batch-converter path.
    combo_ctx = None
    if combo_train is not None and not numeric \
            and (conv or {}).get("combination_rules"):
        combo_ctx = _ComboPlanCache(conv, driver.converter)
        base_parser = combo_ctx.make_base_parser(
            driver.converter.hasher.dim_bits)
        if base_parser is None:
            combo_ctx = None

    def _merge_labels(label_pairs):
        """Union per-request (uniq_labels, label_idx) pairs into one
        distinct-label list + remapped int32 row index — no per-example
        Python loop (the C++ dedup did the heavy lifting)."""
        label_map: dict = {}
        parts_l = []
        for uniq, lidx in label_pairs:
            lut = np.empty(len(uniq), np.int32)
            for j, u in enumerate(uniq):
                lut[j] = label_map.setdefault(u, len(label_map))
            parts_l.append(lut[lidx])
        lidx = np.concatenate(parts_l) if len(parts_l) > 1 else parts_l[0]
        return list(label_map), lidx

    def prep_requests(reqs):
        """Stage 1 (host) of the pipelined flush: merge per-request
        arrays into ONE device-ready batch — label-map union, width
        pad+concat, deferred-idf observe+scale, execution-plan selection.
        Runs on the flusher thread while the device consumes the
        previous batch."""
        if not reqs:
            return None
        if reqs[0][0][0] == "combo":
            # (("combo", plan), labels, base_idx, base_val): group by
            # plan (one group for a fixed-schema feed) for the
            # device-expansion path
            groups: dict = {}
            for tag, lb, _ir, vr in reqs:
                entry = groups.setdefault(id(tag[1]), (tag[1], [], []))
                entry[1].append(lb)
                entry[2].append(vr)
            out = []
            for plan, lbs, vals in groups.values():
                uniq, lidx = _merge_labels(lbs)
                val = np.concatenate(vals) if len(vals) > 1 else vals[0]
                out.append((uniq, lidx, plan, val))
            stats["combo_flushes"] += 1
            return ("combo", out)
        if numeric:
            idx, val = _pad_concat([(ir, vr) for _t, _lb, ir, vr in reqs])
            labels = np.concatenate([r[1] for r in reqs]) \
                if len(reqs) > 1 else reqs[0][1]
            return ("numeric", labels, idx, val)
        uniq, lidx = _merge_labels([lb for _t, lb, _i, _v in reqs])
        if schema_train is not None and not deferred:
            row0 = _uniform_row([(ir, vr) for _t, _lb, ir, vr in reqs])
            if row0 is not None:
                stats["schema_flushes"] += 1
                val = np.concatenate([vr for _t, _lb, _ir, vr in reqs]) \
                    if len(reqs) > 1 else reqs[0][3]
                return ("schema", uniq, lidx, row0, val)
        stats["sparse_flushes"] += 1
        idx, val = _pad_concat([(ir, vr) for _t, _lb, ir, vr in reqs])
        if deferred:
            from jubatus_tpu.native.ingest import deferred_idf_scale

            val = deferred_idf_scale(idx, val, weights, observe=True)
        return ("sparse", uniq, lidx, idx, val)

    def apply_prepared(prepared):
        """Stage 2 (device): dispatch the prepared batch onto the
        matching driver plan."""
        if prepared is None:
            return 0
        kind = prepared[0]
        if kind == "combo":
            n = 0
            for uniq, lidx, plan, val in prepared[1]:
                n += combo_train(uniq, lidx, plan.uidx, val,
                                 plan.a_idx, plan.b_idx, plan.mul_mask)
            return n
        if kind == "numeric":
            return driver.train_hashed(prepared[1], prepared[2], prepared[3])
        if kind == "schema":
            return schema_train(prepared[1], prepared[2], prepared[3],
                                prepared[4])
        return driver.train_indexed(prepared[1], prepared[2], prepared[3],
                                    prepared[4])

    max_batch = getattr(server.args, "microbatch_max", 8192)
    wait_s = server.args.timeout * 6 if server.args.timeout > 0 else None
    device_step = _updating(server, apply_prepared, count=lambda r: r)
    if max_batch:
        from jubatus_tpu.server.microbatch import (Coalescer,
                                                   PipelinedCoalescer)

        co = PipelinedCoalescer(
            prep_requests, device_step, max_batch=max_batch,
            weigher=lambda item: item[2].shape[0], trace=rpc.trace)
        server.coalescers["train_raw"] = co
        co.usage_hook = _usage_batch_hook(server, "train")
    trace = rpc.trace

    def train_raw(raw_params: bytes):
        with trace.span("fv.convert"):
            if combo_ctx is not None:
                item = combo_ctx.parse_train(base_parser, raw_params)
                if item is RAW_FALLBACK:
                    return RAW_FALLBACK  # generic batch-converter path
            elif weights is not None and not deferred:
                with weights.lock:
                    parsed = parser.parse_indexed(raw_params,
                                                  weights=weights)
            else:
                # deferred idf / unweighted: lock-free parallel parse
                parsed = parser.parse_indexed(raw_params)
        if combo_ctx is None:
            if parsed is None:
                return RAW_FALLBACK
            labels, idx, val = parsed
            if numeric != isinstance(labels, np.ndarray):
                return RAW_FALLBACK  # label kind mismatch: let the
                # generic path produce the proper type error
            item = (("plain",), labels, idx, val)
        n = item[2].shape[0]
        if n == 0:
            return 0
        # test-then-train: prequential scoring sees the pre-update model
        _quality_observe_raw(server, item, numeric)
        if max_batch:
            co.submit([item], timeout=wait_s)
        else:
            device_step(prep_requests([item]))
        return n

    rpc.register_raw("train", train_raw)

    # the query path rides the same parser: [name, [datum, ...]] -> hashed
    # batch -> snapshot-read scores, no Datum objects
    def _parse_datums(raw_params: bytes):
        if deferred:
            # lock-free parse, then one vectorized idf gather (queries
            # read idf, never observe)
            parsed = parser.parse_datums(raw_params)
            if parsed is None:
                return None
            from jubatus_tpu.native.ingest import deferred_idf_scale

            idx, val = parsed
            return idx, deferred_idf_scale(idx, val, weights,
                                           observe=False)
        if weights is not None:
            with weights.lock:  # queries read idf, never observe
                return parser.parse_datums(raw_params, weights=weights)
        return parser.parse_datums(raw_params)

    def _query_coalescer(name: str, score_batch, schema_score=None):
        """Query-plane microbatching (the mirror of the train coalescer):
        concurrent read requests join ONE device dispatch against the
        same model snapshot — every kernel launch costs ~ms on an
        accelerator regardless of batch size, so per-request dispatch
        caps the query plane at launches/s, not samples/s.
        ``score_batch(idx, val) -> per-row results``; each request gets
        exactly its rows back (Coalescer split_results).
        ``schema_score(uidx, val)`` is the uniform-schema dense variant,
        taken whenever the flush's rows all share one index vector."""
        def query_flush(items):
            if schema_score is not None:
                row0 = _uniform_row(items)
                if row0 is not None:
                    stats["schema_query_flushes"] += 1
                    if len(items) == 1:
                        return [schema_score(row0, items[0][1])]
                    vals = np.concatenate([v for _i, v in items])
                    rows = schema_score(row0, vals)
                    out, off = [], 0
                    for i, _ in items:
                        out.append(rows[off:off + i.shape[0]])
                        off += i.shape[0]
                    return out
            stats["sparse_query_flushes"] += 1
            if len(items) == 1:
                i, v = items[0]
                return [score_batch(i, v)]
            rows = score_batch(*_pad_concat(items))
            out, off = [], 0
            for i, _ in items:
                out.append(rows[off:off + i.shape[0]])
                off += i.shape[0]
            return out

        qco = Coalescer(query_flush, max_batch=max_batch,
                        weigher=lambda it: it[0].shape[0],
                        split_results=True)
        server.coalescers[name] = qco
        # bill under the wire method ("classify"), not the coalescer key
        qco.usage_hook = _usage_batch_hook(
            server, name[:-4] if name.endswith("_raw") else name)

        def raw_handler(raw_params: bytes):
            with trace.span("fv.convert"):
                parsed = _parse_datums(raw_params)
            if parsed is None:
                return RAW_FALLBACK
            idx, val = parsed
            if idx.shape[0] == 0:
                return []
            (mine,) = qco.submit([(idx, val)], timeout=wait_s)
            return mine

        return raw_handler

    if numeric and hasattr(driver, "estimate_hashed"):
        if max_batch:
            rpc.register_raw("estimate", _query_coalescer(
                "estimate_raw", driver.estimate_hashed))
        else:
            def estimate_raw(raw_params: bytes):
                parsed = _parse_datums(raw_params)
                if parsed is None:
                    return RAW_FALLBACK
                return driver.estimate_hashed(*parsed)

            rpc.register_raw("estimate", estimate_raw)
    elif combo_ctx is not None and hasattr(driver, "classify_hashed_combo"):
        def classify_combo_raw(raw_params: bytes):
            with trace.span("fv.convert"):
                out = combo_ctx.parse_query(base_parser, raw_params)
            if out is RAW_FALLBACK:
                return RAW_FALLBACK
            plan, val = out
            if plan is None:
                return []
            rows = driver.classify_hashed_combo(
                plan.uidx, val, plan.a_idx, plan.b_idx, plan.mul_mask)
            return [_scored(r) for r in rows]

        rpc.register_raw("classify", classify_combo_raw)
    elif not numeric and hasattr(driver, "classify_hashed"):
        if max_batch:
            schema_cls = getattr(driver, "classify_hashed_schema", None)
            rpc.register_raw("classify", _query_coalescer(
                "classify_raw",
                lambda i, v: [_scored(r)
                              for r in driver.classify_hashed(i, v)],
                schema_score=None if schema_cls is None else
                (lambda u, v: [_scored(r) for r in schema_cls(u, v)])))
        else:
            def classify_raw(raw_params: bytes):
                parsed = _parse_datums(raw_params)
                if parsed is None:
                    return RAW_FALLBACK
                return [_scored(r) for r in driver.classify_hashed(*parsed)]

            rpc.register_raw("classify", classify_raw)


@_binder("classifier")
def _bind_classifier(rpc: RpcServer, server: Any) -> None:
    d = server.driver
    _register_train(rpc, server,
                    lambda p: (p[0], _datum(p[1])), d.train)
    _register_train_raw(rpc, server, numeric=False)
    rpc.register("classify",  # no-usage — uncoalesced path: dispatch-span billing covers it
                 lambda name, data: [_scored(r)
                                     for r in d.classify(_datums(data))],
                 arity=2)
    rpc.register("get_labels", lambda name: {k: int(v) for k, v in d.get_labels().items()}, arity=1)
    rpc.register("set_label", _updating(server, lambda name, lbl: d.set_label(lbl)), arity=2)
    rpc.register("delete_label", _updating(server, lambda name, lbl: d.delete_label(lbl)), arity=2)
    rpc.register("clear", _updating(server, lambda name: (d.clear(), True)[1]), arity=1)


@_binder("regression")
def _bind_regression(rpc: RpcServer, server: Any) -> None:
    d = server.driver
    _register_train(rpc, server,
                    lambda p: (float(p[0]), _datum(p[1])), d.train)
    _register_train_raw(rpc, server, numeric=True)
    rpc.register(
        "estimate",
        lambda name, data: [float(x) for x in d.estimate(_datums(data))],
        arity=2,
    )
    rpc.register("clear", _updating(server, lambda name: (d.clear(), True)[1]), arity=1)


@_binder("recommender")
def _bind_recommender(rpc: RpcServer, server: Any) -> None:
    d = server.driver
    rpc.register("clear_row", _updating(server, lambda name, rid: d.clear_row(rid)), arity=2)
    rpc.register(
        "update_row",
        _updating(server, lambda name, rid, row: d.update_row(rid, _datum(row))),
        arity=3,
    )
    rpc.register("clear", _updating(server, lambda name: (d.clear(), True)[1]), arity=1)
    rpc.register("complete_row_from_id", lambda name, rid: _wire_datum(d.complete_row_from_id(rid)), arity=2)
    rpc.register("complete_row_from_datum",
                 lambda name, row: _wire_datum(d.complete_row_from_datum(_datum(row))), arity=2)
    rpc.register("similar_row_from_id",
                 lambda name, rid, size: _scored(d.similar_row_from_id(rid, int(size))), arity=3)
    rpc.register("similar_row_from_datum",
                 lambda name, row, size: _scored(d.similar_row_from_datum(_datum(row), int(size))), arity=3)
    rpc.register("decode_row", lambda name, rid: _wire_datum(d.decode_row(rid)), arity=2)
    rpc.register("get_all_rows", lambda name: d.get_all_rows(), arity=1)
    rpc.register("calc_similarity", lambda name, lhs, rhs: float(d.calc_similarity(_datum(lhs), _datum(rhs))),
                 arity=3)
    rpc.register("calc_l2norm", lambda name, row: float(d.calc_l2norm(_datum(row))), arity=2)


@_binder("nearest_neighbor")
def _bind_nearest_neighbor(rpc: RpcServer, server: Any) -> None:
    d = server.driver
    rpc.register("clear", _updating(server, lambda name: (d.clear(), True)[1]), arity=1)
    rpc.register("set_row", _updating(server, lambda name, rid, dat: d.set_row(rid, _datum(dat))), arity=3)
    rpc.register("neighbor_row_from_id",
                 lambda name, rid, size: _scored(d.neighbor_row_from_id(rid, int(size))), arity=3)
    rpc.register("neighbor_row_from_datum",
                 lambda name, q, size: _scored(d.neighbor_row_from_datum(_datum(q), int(size))), arity=3)
    rpc.register("similar_row_from_id", lambda name, rid, n: _scored(d.similar_row_from_id(rid, int(n))),
                 arity=3)
    rpc.register("similar_row_from_datum",
                 lambda name, q, n: _scored(d.similar_row_from_datum(_datum(q), int(n))), arity=3)
    rpc.register("get_all_rows", lambda name: d.get_all_rows(), arity=1)


def _replicated_write(server: Any, key: str, apply_local, apply_remote,
                      replication: int = 2):
    """Server-side CHT-replicated write (≙ anomaly_serv.cpp:178-211,
    graph_serv.cpp:181-228): place ``key`` on its ``replication`` ring
    successors — apply locally when a successor is me, RPC the peer
    otherwise. The primary write must succeed (exceptions propagate);
    replicas are best-effort (warn + continue). Returns the primary's
    result. Falls back to a local-only apply when the ring is empty."""
    cht = server.cluster_cht()
    nodes = cht.find(key, replication) if cht is not None else []
    if not nodes:
        return apply_local()
    me = server.self_nodeinfo()
    result = None
    for i, node in enumerate(nodes):
        try:
            if node.name == me.name:
                out = apply_local()
            else:
                out = apply_remote(server.peer_client(node))
            if i == 0:
                result = out
        except Exception:  # broad-ok — replica writes are best-effort
            if i == 0:
                raise  # primary failure is the caller's failure
            server.drop_peer_client(node)
            log.warning("replica write to %s failed (best-effort)",
                        node.name, exc_info=True)
    return result


@_binder("anomaly")
def _bind_anomaly(rpc: RpcServer, server: Any) -> None:
    d = server.driver
    rpc.register("clear_row", _updating(server, lambda name, rid: d.clear_row(rid)), arity=2)

    def add(name, row):
        """Distributed add = mint id + CHT(2) placement + primary write +
        best-effort replica, INSIDE the server — a direct-to-server add is
        replicated immediately, not at the next mix (anomaly_serv.cpp:
        155-211). Standalone keeps the driver's local add."""
        if server.coord is None:
            return list(_updating(server, lambda: d.add(_datum(row)))())
        row_id = str(d.idgen.generate()) if getattr(d, "idgen", None) \
            else None
        if row_id is None:
            return list(_updating(server, lambda: d.add(_datum(row)))())
        score = _replicated_write(
            server, row_id,
            apply_local=_updating(
                server, lambda: float(d.overwrite(row_id, _datum(row)))),
            apply_remote=lambda cli: float(
                cli.call("overwrite", name, row_id, row)),
        )
        return [row_id, float(score)]

    rpc.register("add", add, arity=2)
    rpc.register("update", _updating(server, lambda name, rid, row: float(d.update(rid, _datum(row)))),
                 arity=3)
    rpc.register("overwrite", _updating(server, lambda name, rid, row: float(d.overwrite(rid, _datum(row)))),
                 arity=3)
    rpc.register("clear", _updating(server, lambda name: (d.clear(), True)[1]), arity=1)
    rpc.register("calc_score", lambda name, row: float(d.calc_score(_datum(row))), arity=2)
    rpc.register("get_all_rows", lambda name: d.get_all_rows(), arity=1)


@_binder("graph")
def _bind_graph(rpc: RpcServer, server: Any) -> None:
    d = server.driver

    def edge_parts(e):
        """wire edge [property_map, source, target] → driver arg order
        (source, target, properties)."""
        return e[1], e[2], dict(e[0])

    def create_node(name):
        """Distributed create_node = mint global id + create_node_here on
        the CHT(2) successors via direct peer RPC (graph_serv.cpp:181-228)
        — a direct-to-server create is visible on its replica before any
        mix. Standalone keeps the local driver path."""
        if server.coord is None:
            return _updating(server, lambda: d.create_node())()
        node_id = str(d.idgen.generate()) if getattr(d, "idgen", None) \
            else None
        if node_id is None:
            return _updating(server, lambda: d.create_node())()
        _replicated_write(
            server, node_id,
            apply_local=_updating(
                server, lambda: d.create_node_here(node_id)),
            apply_remote=lambda cli: cli.call(
                "create_node_here", name, node_id),
        )
        return node_id

    rpc.register("create_node", create_node, arity=1)
    rpc.register("remove_node", _updating(server, lambda name, nid: d.remove_node(nid)), arity=2)
    rpc.register("update_node", _updating(server, lambda name, nid, prop: d.update_node(nid, dict(prop))),
                 arity=3)
    rpc.register(
        "create_edge",
        _updating(server, lambda name, nid, e: d.create_edge(nid, *edge_parts(e))),
        arity=3,
    )
    rpc.register(
        "update_edge",
        _updating(server, lambda name, nid, eid, e: d.update_edge(nid, int(eid), *edge_parts(e))),
        arity=4,
    )
    rpc.register("remove_edge", _updating(server, lambda name, nid, eid: d.remove_edge(nid, int(eid))),
                 arity=3)
    rpc.register("get_centrality", lambda name, nid, ct, q: float(d.get_centrality(nid, int(ct), q)), arity=4)
    rpc.register("add_centrality_query", _updating(server, lambda name, q: d.add_centrality_query(q)),
                 arity=2)
    rpc.register("add_shortest_path_query", _updating(server, lambda name, q: d.add_shortest_path_query(q)),
                 arity=2)
    rpc.register("remove_centrality_query", _updating(server, lambda name, q: d.remove_centrality_query(q)),
                 arity=2)
    rpc.register("remove_shortest_path_query", _updating(server,
                 lambda name, q: d.remove_shortest_path_query(q)), arity=2)
    rpc.register(
        "get_shortest_path",
        lambda name, q: d.get_shortest_path(q[0], q[1], int(q[2]), q[3] if len(q) > 3 else None),
        arity=2,
    )
    rpc.register("update_index", _updating(server, lambda name: d.update_index()), arity=1)
    rpc.register("clear", _updating(server, lambda name: (d.clear(), True)[1]), arity=1)
    rpc.register(
        "get_node",
        lambda name, nid: (lambda n: [n["property"], n["in_edges"], n["out_edges"]])(d.get_node(nid)),
        arity=2,
    )
    rpc.register(
        "get_edge",
        lambda name, nid, eid: (lambda e: [e["property"], e["source"],
                                           e["target"]])(d.get_edge(nid, int(eid))),
        arity=3,
    )
    rpc.register("create_node_here", _updating(server, lambda name, nid: d.create_node_here(nid)), arity=2)
    rpc.register("remove_global_node", _updating(server, lambda name, nid: d.remove_global_node(nid)),
                 arity=2)
    rpc.register(
        "create_edge_here",
        _updating(server, lambda name, eid, e: d.create_edge_here(int(eid), *edge_parts(e))),
        arity=3,
    )


@_binder("burst")
def _bind_burst(rpc: RpcServer, server: Any) -> None:
    d = server.driver

    def wire_window(w):
        """driver window dict → wire [start_pos, [[all, rel, weight]...]]."""
        return [w["start_pos"],
                [[b["all_data_count"], b["relevant_data_count"],
                  b["burst_weight"]] for b in w["batches"]]]

    rpc.register(
        "add_documents",
        lambda name, docs: _updating(
            server,
            lambda: d.add_documents([(float(p), t) for p, t in docs]),
            count=lambda r: r,
        )(),
        arity=2,
    )
    rpc.register("get_result", lambda name, kw: wire_window(d.get_result(kw)), arity=2)
    rpc.register("get_result_at", lambda name, kw, pos: wire_window(d.get_result_at(kw, float(pos))), arity=3)
    rpc.register(
        "get_all_bursted_results",
        lambda name: {k: wire_window(w) for k, w in d.get_all_bursted_results().items()},
        arity=1,
    )
    rpc.register(
        "get_all_bursted_results_at",
        lambda name, pos: {k: wire_window(w) for k, w in d.get_all_bursted_results_at(float(pos)).items()},
        arity=2,
    )
    rpc.register(
        "get_all_keywords",
        lambda name: [[k["keyword"], k["scaling_param"], k["gamma"]] for k in d.get_all_keywords()],
        arity=1,
    )
    rpc.register(
        "add_keyword",
        _updating(server, lambda name, kw: d.add_keyword(kw[0], float(kw[1]), float(kw[2]))),
        arity=2,
    )
    rpc.register("remove_keyword", _updating(server, lambda name, kw: d.remove_keyword(kw)), arity=2)
    rpc.register("remove_all_keywords", _updating(server, lambda name: d.remove_all_keywords()), arity=1)
    rpc.register("clear", _updating(server, lambda name: (d.clear(), True)[1]), arity=1)


@_binder("clustering")
def _bind_clustering(rpc: RpcServer, server: Any) -> None:
    d = server.driver

    def wd(pair):  # (weight, Datum) → wire weighted_datum
        return [float(pair[0]), _wire_datum(pair[1])]

    def wi(pair):  # (weight, id) → wire weighted_index
        return [float(pair[0]), pair[1]]

    rpc.register(
        "push",
        _updating(server, lambda name, points: d.push([(p[0], _datum(p[1])) for p in points])),
        arity=2,
    )
    rpc.register("get_revision", lambda name: int(d.get_revision()), arity=1)
    rpc.register("get_core_members", lambda name: [[wd(p) for p in c] for c in d.get_core_members()], arity=1)
    rpc.register("get_core_members_light",
                 lambda name: [[wi(p) for p in c] for c in d.get_core_members_light()], arity=1)
    rpc.register("get_k_center", lambda name: [_wire_datum(c) for c in d.get_k_center()], arity=1)
    rpc.register("get_nearest_center", lambda name, p: _wire_datum(d.get_nearest_center(_datum(p))), arity=2)
    rpc.register("get_nearest_members", lambda name, p: [wd(x) for x in d.get_nearest_members(_datum(p))],
                 arity=2)
    rpc.register("get_nearest_members_light",
                 lambda name, p: [wi(x) for x in d.get_nearest_members_light(_datum(p))], arity=2)
    rpc.register("clear", _updating(server, lambda name: (d.clear(), True)[1]), arity=1)


@_binder("stat")
def _bind_stat(rpc: RpcServer, server: Any) -> None:
    d = server.driver
    rpc.register("push", _updating(server, lambda name, key, val: d.push(key, float(val))), arity=3)
    rpc.register("sum", lambda name, key: float(d.sum(key)), arity=2)
    rpc.register("stddev", lambda name, key: float(d.stddev(key)), arity=2)
    rpc.register("max", lambda name, key: float(d.max(key)), arity=2)
    rpc.register("min", lambda name, key: float(d.min(key)), arity=2)
    rpc.register("entropy", lambda name, key: float(d.entropy(key)), arity=2)
    rpc.register("moment", lambda name, key, deg, center: float(d.moment(key, int(deg), float(center))),
                 arity=4)
    rpc.register("clear", _updating(server, lambda name: (d.clear(), True)[1]), arity=1)


@_binder("bandit")
def _bind_bandit(rpc: RpcServer, server: Any) -> None:
    d = server.driver
    rpc.register("register_arm", _updating(server, lambda name, a: d.register_arm(a)), arity=2)
    rpc.register("delete_arm", _updating(server, lambda name, a: d.delete_arm(a)), arity=2)
    rpc.register("select_arm", _updating(server, lambda name, p: d.select_arm(p)), arity=2)
    rpc.register("register_reward", _updating(server,
                 lambda name, p, a, r: d.register_reward(p, a, float(r))), arity=4)
    rpc.register(
        "get_arm_info",
        lambda name, p: {
            arm: [int(info["trial_count"]), float(info["weight"])]
            for arm, info in d.get_arm_info(p).items()
        },
        arity=2,
    )
    rpc.register("reset", _updating(server, lambda name, p: d.reset(p)), arity=2)
    rpc.register("clear", _updating(server, lambda name: (d.clear(), True)[1]), arity=1)


@_binder("weight")
def _bind_weight(rpc: RpcServer, server: Any) -> None:
    d = server.driver
    rpc.register(
        "update",
        lambda name, dat: [[k, float(v)] for k, v in _updating(server, lambda: d.update(_datum(dat)))()],
        arity=2,
    )
    rpc.register(
        "calc_weight",
        lambda name, dat: [[k, float(v)] for k, v in d.calc_weight(_datum(dat))],
        arity=2,
    )
    rpc.register("clear", _updating(server, lambda name: (d.clear(), True)[1]), arity=1)
