"""Shared utilities: msgpack serialization of array pytrees, timers."""

from jubatus_tpu.utils.serialization import pack_obj, unpack_obj  # noqa: F401
