"""Cluster event plane (ISSUE 14): one causally ordered, queryable
record of *what happened, in what order, across the fleet*.

Every state-transition-owning subsystem — membership epoch bumps, drain
phases, mix rounds/degrades/fallbacks, async-mix master elections,
breaker open/half-open/close, SLO fire/clear, autoscaler decisions,
checkpoint save/restore/reshard, fault arms/fires — emits one typed
event into a bounded per-process **EventJournal**. Events are stamped
with a **hybrid logical clock** timestamp, so merging journals from
nodes with skewed wall clocks still yields a causally consistent
interleaving wherever the clocks were connected by a message (the mix
plane's put_diff payload carries the master's HLC; receivers
``observe()`` it).

HLC encoding: one sortable int, ``wall_ms << 20 | counter``. The
physical component is the local wall clock in milliseconds; the logical
counter breaks same-millisecond ties and absorbs observed remote
timestamps that run ahead of the local clock. ``now()`` is strictly
monotonic per process — which makes the HLC double as the **cursor**
for ``get_events(since=...)`` / ``jubactl -c timeline --follow``: a
caller re-polls with the max HLC it has seen and receives exactly the
events emitted after it.

Two journals exist per process: each tracing ``Registry`` owns one
(``registry.events`` — per-server attribution, like the slow log), and
a module-level **default journal** catches emissions from code with no
registry in reach (membership epoch bumps, fault arms/fires, checkpoint
paths). ``get_events`` serves the merge of both; in the rare
multi-server test process, default-journal events appear under every
embedded server — by design (they are process-scoped facts).

Severities: ``debug`` < ``info`` < ``warning`` < ``error``. Each event
also captures the active trace_id when one exists, which is what lets
an incident bundle (utils/incidents.py) correlate the event window with
slow-log records and flight records of the same request.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

#: default journal depth — ~hours of cluster life at normal event rates,
#: minutes under a breaker flap storm (the ring bounds the damage)
DEFAULT_CAPACITY = 2048

#: logical-counter bits in the packed HLC int
_CTR_BITS = 20
_CTR_MASK = (1 << _CTR_BITS) - 1

SEVERITIES = ("debug", "info", "warning", "error")


class HLCClock:
    """Hybrid logical clock: strictly monotonic per process, merges
    remote timestamps so message receipt establishes happens-before
    even when the wall clocks are skewed."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._last = 0

    def now(self) -> int:
        phys = int(time.time() * 1000) << _CTR_BITS  # wall-clock
        with self._lock:
            self._last = phys if phys > self._last else self._last + 1
            return self._last

    def observe(self, remote: int) -> int:
        """Merge a remote HLC: every subsequent local ``now()`` sorts
        after it (and after everything local so far). Returns the
        clock's current value."""
        try:
            remote = int(remote)
        except (TypeError, ValueError):
            return self.peek()
        with self._lock:
            if remote > self._last:
                self._last = remote
            return self._last

    def peek(self) -> int:
        with self._lock:
            return self._last


def hlc_wall_s(hlc: int) -> float:
    """Wall-clock seconds encoded in an HLC timestamp (the physical
    component; logical ties collapse to the same instant)."""
    return (int(hlc) >> _CTR_BITS) / 1000.0


def wall_to_hlc(ts_s: float) -> int:
    """Lower bound of every HLC stamped at/after wall time ``ts_s`` —
    the ``since`` filter for 'events in the last N seconds'."""
    return max(0, int(ts_s * 1000)) << _CTR_BITS


_clock = HLCClock()


def hlc_now() -> int:
    """Next process-wide HLC tick (strictly monotonic)."""
    return _clock.now()


def observe(remote: int) -> int:
    """Merge a remote node's HLC into the process clock (call when a
    message carrying a remote timestamp is received)."""
    return _clock.observe(remote)


def _rec_matches(rec: Dict[str, Any], grep: str) -> bool:
    """Case-insensitive substring match over the rendered identity of
    one event (subsystem, type, severity, node, trace, field values)."""
    hay = " ".join(
        str(v) for v in rec.values() if isinstance(v, (str, int, float))
    ).lower()
    return grep.lower() in hay


class EventJournal:
    """Bounded per-process ring of typed, HLC-stamped events."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 counter: Optional[Any] = None) -> None:
        self._lock = threading.Lock()
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=max(1, self.capacity))
        self._emitted = 0
        #: owner's node name (set by the server once the port is known,
        #: like the mix flight recorder)
        self.node = ""
        #: optional ``count(name)`` callback (the owning Registry's) so
        #: `event.emitted` / `event.dropped` ride /metrics
        self._counter = counter

    def set_capacity(self, capacity: int) -> None:
        """Re-bound at server start (``--event-capacity``); 0 disables
        emission entirely (``emit`` becomes a no-op)."""
        with self._lock:
            self.capacity = int(capacity)
            self._ring = deque(self._ring, maxlen=max(1, self.capacity))

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def emit(self, subsystem: str, etype: str, severity: str = "info",
             **fields: Any) -> Optional[Dict[str, Any]]:
        """Record one event; returns the record (its ``hlc`` is the
        event id other planes cross-link) or None when disabled. The
        active trace context's id is captured automatically."""
        if self.capacity <= 0:
            return None
        h = _clock.now()
        rec: Dict[str, Any] = {
            "hlc": h,
            "ts": round(hlc_wall_s(h), 3),
            "node": self.node,
            "subsystem": str(subsystem),
            "type": str(etype),
            "severity": severity if severity in SEVERITIES else "info",
        }
        tid = _current_trace_id()
        if tid:
            rec["trace_id"] = tid
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        dropped = False
        with self._lock:
            self._emitted += 1
            if len(self._ring) == self._ring.maxlen:
                dropped = True
            self._ring.append(rec)
        if self._counter is not None:
            self._counter("event.emitted")
            if dropped:
                self._counter("event.dropped")
        return rec

    def snapshot(self, since: int = 0, grep: str = "",
                 limit: int = 0) -> List[Dict[str, Any]]:
        """Oldest-first copy of events with ``hlc > since`` (the
        cursor contract: re-poll with the max hlc you saw), optionally
        grep-filtered; ``limit > 0`` keeps the newest that many."""
        since = int(since or 0)
        with self._lock:
            out = [dict(r) for r in self._ring if r["hlc"] > since]
        if grep:
            out = [r for r in out if _rec_matches(r, str(grep))]
        return out[-limit:] if limit > 0 else out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"emitted": self._emitted,
                    "retained": len(self._ring),
                    "capacity": self.capacity}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._emitted = 0


def merge_events(lists: Iterable[List[Dict[str, Any]]]
                 ) -> List[Dict[str, Any]]:
    """Fold event lists from N journals/nodes into one causally ordered
    timeline: sort by (hlc, node) — HLC order IS causal order wherever
    the clocks were connected by an observed message, and a stable
    node tiebreak keeps concurrent events deterministic. Deduplicates
    by (hlc, node): an HLC is unique per process, so the same record
    reaching the merge twice (a default-journal event served by every
    embedded server of a test process, or an overlapping re-poll) is
    the same event, not two."""
    out: List[Dict[str, Any]] = []
    seen: set = set()
    for lst in lists:
        for r in lst or []:
            key = (int(r.get("hlc", 0)), str(r.get("node", "")))
            if key in seen:
                continue
            seen.add(key)
            out.append(r)
    out.sort(key=lambda r: (int(r.get("hlc", 0)), str(r.get("node", ""))))
    return out


_tracing_mod = None


def _current_trace_id() -> str:
    """Active trace id, if any. Lazy module cache: tracing imports this
    module (Registry owns an EventJournal), so the reverse import must
    happen at first use, not at import time."""
    global _tracing_mod
    if _tracing_mod is None:
        from jubatus_tpu.utils import tracing as _t

        _tracing_mod = _t
    ctx = _tracing_mod.current_trace()
    return ctx.trace_id if ctx is not None else ""


_default = EventJournal()


def default_journal() -> EventJournal:
    """The process-scoped journal for emitters with no Registry in
    reach (membership, faults, checkpoint plumbing). ``get_events``
    merges it with the serving registry's journal."""
    return _default


def emit(subsystem: str, etype: str, severity: str = "info",
         **fields: Any) -> Optional[Dict[str, Any]]:
    """Emit into the process default journal."""
    return _default.emit(subsystem, etype, severity=severity, **fields)
