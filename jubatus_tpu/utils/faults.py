"""Fault injection registry — the framework the reference never had.

SURVEY.md §5: the reference's failure handling (mix skipping dead hosts,
actives demotion, suicide watchers, obsolete recovery) is real but has
"no fault injection framework" to exercise it; its failure paths were
only ever tested by killing whole processes. This registry makes failure
deterministic and surgical: named sites in the RPC and mix planes call
``fire(site, ...)``, and a test (or the ``JUBATUS_TPU_FAULTS`` env var,
for subprocess servers) arms rules against them.

Rule syntax (one per rule, comma-separated in the env var, and one per
repeated ``--fault`` server flag):

    <site-glob>:error            raise FaultInjected at matching sites
    <site-glob>:error:<p>        ... with probability p (seeded RNG)
    <site-glob>:delay:<seconds>  sleep before proceeding
    <site-glob>:drop             silently lose the operation (fire()
                                 returns True; drop-aware sites — the
                                 mixer comm fan-outs, put_diff, the
                                 async submit path — discard the
                                 message instead of erroring; sites
                                 that don't check the return value
                                 ignore drops by construction)
    <site-glob>:nan              poison: patch one element of a random
                                 float diff leaf to NaN (mutation-aware
                                 sites only — fire_mutate callers)
    <site-glob>:scale:<F>        poison: multiply every float leaf of a
                                 contribution by F (a runaway learner's
                                 norm-exploded diff)
    <site-glob>:bitflip          corruption: flip one byte of a staged
                                 wire chunk (mutation-aware sites only)
    <site-glob>:error@<n>        ... only for the first n firings

Sites are dotted names matched with fnmatch, e.g. ``rpc.call.get_diff``,
``rpc.connect``, ``mix.put_diff``, ``mix.comm.get_diff``,
``mix.async.submit.<node>``, ``migration.pull``, and the autoscaler's
actuation sites ``autoscale.spawn`` / ``autoscale.drain`` (a fired
error there must surface as a ``blocked`` journal record with
exponential backoff, never a hot-loop — coord/autoscaler.py). The
self-tuning plane (ISSUE 20) actuates through
``tune.mix.apply`` / ``tune.coalescer.apply`` / ``tune.cadence.apply``
(coord/perf_tuner.py) with the same blocked/backoff contract — and
because the sites fire BEFORE the knob mutates, a failed apply leaves
the fleet on its previous coherent plan, never a mixed one. The
model-integrity plane (ISSUE 15) adds two MUTATION-aware sites:
``mix.diff.poison`` (the member's diff snapshot, as it leaves the
model lock — ``nan``/``scale:F`` model a sick replica) and
``mix.wire.corrupt`` (each staged collective wire chunk — ``bitflip``
models transport corruption the chunk CRC must catch). The durable
model plane (ISSUE 18) adds ``store.put`` / ``store.get`` (the blob
backend choke points — ``error``/``delay``/``drop``, plus ``bitflip``
through ``fire_mutate`` to corrupt the bytes so the envelope CRC
refusal is what gets exercised) and ``store.compact`` (compaction is
advisory: a fired error must leave the chain replayable). Mutation
rules fire only through ``fire_mutate``; plain ``fire`` sites ignore
them by construction. ``fire`` is a no-op
(one dict lookup on a module flag) when nothing is armed — safe on hot
paths.

    with faults.armed("rpc.call.get_diff:error@1"):
        ...  # the next get_diff anywhere in this process fails once
"""

from __future__ import annotations

import fnmatch
import os
import random
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional

__all__ = ["FaultInjected", "arm", "disarm", "disarm_all", "armed", "fire",
           "fire_mutate", "poison_tree", "flip_byte", "is_armed", "stats"]

#: actions that MUTATE data instead of dropping/raising: returned by
#: fire_mutate for the caller to apply (poison_tree / flip_byte), and
#: invisible to plain fire() sites by construction
MUTATE_ACTIONS = ("nan", "scale", "bitflip")


class FaultInjected(RuntimeError):
    """The error fault injection raises (subclasses RuntimeError so site
    error taxonomies treat it like any runtime failure)."""


class _Rule:
    __slots__ = ("pattern", "action", "arg", "remaining", "prob", "hits")

    def __init__(self, pattern: str, action: str, arg: float,
                 remaining: Optional[int], prob: float) -> None:
        self.pattern = pattern
        self.action = action
        self.arg = arg
        self.remaining = remaining
        self.prob = prob
        self.hits = 0


_lock = threading.Lock()
_rules: List[_Rule] = []
_armed = False  # fast-path flag: fire() returns immediately when False
_rng = random.Random(0xFA017)
_fired: Dict[str, int] = {}


def parse_rule(text: str) -> _Rule:
    # site patterns may themselves contain colons (host:port), so locate
    # the action token from the RIGHT
    parts = text.strip().split(":")
    action_idx = None
    for i in range(len(parts) - 1, -1, -1):
        if parts[i].split("@", 1)[0] in ("error", "delay", "drop",
                                         "nan", "scale", "bitflip"):
            action_idx = i
            break
    if action_idx is None or action_idx == 0:
        raise ValueError(
            f"bad fault rule {text!r} (want site:action[:arg], action in "
            "{error, delay, drop, nan, scale, bitflip})")
    pattern = ":".join(parts[:action_idx])
    action = parts[action_idx]
    extra = parts[action_idx + 1:]
    remaining = None
    if "@" in action:
        action, n = action.split("@", 1)
        remaining = int(n)
    arg = 0.0
    prob = 1.0
    if action == "delay":
        if not extra:
            raise ValueError(f"delay rule needs seconds: {text!r}")
        arg = float(extra[0])
    elif action == "scale":
        if not extra:
            raise ValueError(f"scale rule needs a factor: {text!r}")
        arg = float(extra[0])
    elif extra:  # error with probability
        prob = float(extra[0])
    return _Rule(pattern, action, arg, remaining, prob)


def arm(*rule_texts: str) -> List[_Rule]:
    """Add rules (see module docstring for syntax). Returns the rule
    objects so a scope can later remove exactly what it added."""
    global _armed
    parsed = [parse_rule(t) for t in rule_texts]
    if not parsed:
        return []
    with _lock:
        _rules.extend(parsed)
        _armed = True
    # event plane (ISSUE 14): an armed fault site is cluster state an
    # incident timeline must show — chaos drills self-document
    from jubatus_tpu.utils import events

    events.emit("faults", "armed", severity="warning",
                rules=list(rule_texts))
    return parsed


def disarm(rules: List[_Rule]) -> None:
    """Remove specific rules (leaves others — env-armed, outer scopes —
    in place)."""
    global _armed
    with _lock:
        for r in rules:
            if r in _rules:
                _rules.remove(r)
        _armed = bool(_rules)


def disarm_all() -> None:
    global _armed
    with _lock:
        _rules.clear()
        _fired.clear()
        _armed = False


def is_armed() -> bool:
    """Cheap hot-path guard: callers may skip building site names when
    nothing is armed."""
    return _armed


@contextmanager
def armed(*rule_texts: str):
    """Scope rules to a with-block; removes ONLY the rules it added, so
    nesting and env-armed rules compose."""
    mine = arm(*rule_texts)
    try:
        yield
    finally:
        disarm(mine)


def _fire(site: str, mutate: bool):
    """Shared firing core: sleeps delays, raises errors, and returns
    (dropped, mutation) where mutation is the strongest matched
    ``(action, arg)`` mutation pair (only when ``mutate`` — plain
    fire() sites never consume or observe mutation rules)."""
    delay = 0.0
    boom = False
    dropped = False
    mutation: Optional[tuple] = None
    with _lock:
        for r in _rules:
            if r.remaining is not None and r.remaining <= 0:
                continue
            if r.action in MUTATE_ACTIONS and not mutate:
                continue
            if not fnmatch.fnmatch(site, r.pattern):
                continue
            if r.prob < 1.0 and _rng.random() >= r.prob:
                continue
            if r.remaining is not None:
                r.remaining -= 1
            r.hits += 1
            _fired[site] = _fired.get(site, 0) + 1
            if r.action == "delay":
                delay = max(delay, r.arg)
            elif r.action == "drop":
                dropped = True
            elif r.action in MUTATE_ACTIONS:
                if mutation is None:
                    mutation = (r.action, r.arg)
            else:
                boom = True
    if delay or boom or dropped or mutation:
        # a fault actually FIRING is a timeline event (emitted outside
        # the rule lock; the no-rule fast path above never reaches here)
        from jubatus_tpu.utils import events

        events.emit("faults", "fired", severity="warning", site=site,
                    action=("error" if boom else
                            "drop" if dropped else
                            mutation[0] if mutation else "delay"))
    if delay:
        time.sleep(delay)
    if boom:
        raise FaultInjected(f"injected fault at {site}")
    return dropped, mutation


def fire(site: str) -> bool:
    """Injection point. No-op unless rules are armed. Returns True when
    a ``drop`` rule matched — drop-aware sites silently discard the
    operation; everyone else ignores the return value (a drop then has
    no effect, by design). Mutation rules (nan/scale/bitflip) never
    match here — only ``fire_mutate`` sites apply them."""
    if not _armed:
        return False
    dropped, _ = _fire(site, mutate=False)
    return dropped


def fire_mutate(site: str) -> Optional[tuple]:
    """Mutation-aware injection point (the model-integrity chaos sites):
    error/delay rules behave as at any site, and the strongest matched
    mutation rule is returned as ``(action, arg)`` for the caller to
    apply with ``poison_tree`` / ``flip_byte``. None = leave the data
    alone."""
    if not _armed:
        return None
    _, mutation = _fire(site, mutate=True)
    return mutation


def poison_tree(diffs, mutation: tuple):
    """Apply a ``nan``/``scale:F`` mutation to a materialized (host
    numpy) diff payload — the ``mix.diff.poison`` drill. ``nan``
    patches ONE element of the first float leaf encountered (a single
    bad datum's footprint); ``scale`` multiplies every float leaf by F
    (a runaway learner). Leaves are copied — the caller's model state
    is never touched, only the outgoing snapshot."""
    import jax
    import numpy as np

    action, arg = mutation
    state = {"done": False}

    def mutate(x):
        if not isinstance(x, np.ndarray) or \
                not np.issubdtype(x.dtype, np.floating) or x.size == 0:
            return x
        if action == "scale":
            return x * np.asarray(arg, dtype=x.dtype)
        if state["done"]:
            return x
        state["done"] = True
        y = x.copy()
        y.reshape(-1)[_rng.randrange(x.size)] = np.nan
        return y

    return jax.tree_util.tree_map(mutate, diffs)


def flip_byte(buf: bytes) -> bytes:
    """One-byte corruption of a staged wire chunk (the ``bitflip``
    drill): returns a copy with a single bit flipped at a seeded-random
    offset."""
    if not buf:
        return buf
    out = bytearray(buf)
    out[_rng.randrange(len(out))] ^= 0x40
    return bytes(out)


def stats() -> Dict[str, int]:
    with _lock:
        return dict(_fired)


def _arm_from_env() -> None:
    spec = os.environ.get("JUBATUS_TPU_FAULTS", "")
    if spec:
        arm(*[s for s in spec.split(",") if s.strip()])


_arm_from_env()
