"""Automatic incident forensics bundles (ISSUE 14).

When an SLO transitions to firing or /healthz goes degraded, the first
question is always the same: what did the cluster look like *right
then*? By the time an operator runs the forensics RPCs by hand, the
rings have rotated and the moment is gone. The IncidentManager snapshots
the correlated state AT the transition — the event-journal window, the
metric time-series window, slow-log entries, mix flight records, the
profiler's tail snapshots, breaker/health state — into one JSON bundle
in a capped artifacts dir.

- **Debounced**: one capture per ``--incident-window`` (default 300 s);
  a storm of SLO flaps produces one bundle per window, with the
  suppressed triggers counted (``incident.suppressed``).
- **Capped**: at most ``capacity`` bundles on disk; the oldest is
  pruned (same stance as the device-capture dir).
- **Owner-assembled**: the owning server/proxy supplies a ``collector``
  callable that builds the forensic doc from its own rings — the
  manager owns only the trigger discipline, artifact naming, disk cap,
  and the ``list``/``get`` surface behind the ``get_incidents`` RPC and
  ``jubactl -c incident [--list | --pull ID]``.

Bundle identity: ``inc-<hlc-hex>`` — the capturing process's HLC tick,
which also orders bundles against the event timeline they snapshot.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from jubatus_tpu.utils import events

log = logging.getLogger(__name__)

#: bundles kept on disk; the oldest is pruned past this
DEFAULT_CAPACITY = 16
#: default debounce window (seconds); 0 disables auto-capture entirely
DEFAULT_WINDOW_S = 300.0


class IncidentManager:
    """Trigger discipline + artifact store for one server/proxy."""

    def __init__(self, registry: Any,
                 collector: Callable[[], Dict[str, Any]],
                 dir_fn: Callable[[], str],
                 window_s: float = DEFAULT_WINDOW_S,
                 capacity: int = DEFAULT_CAPACITY,
                 journal: Optional[events.EventJournal] = None) -> None:
        self.registry = registry
        self.collector = collector
        #: resolved lazily — the default dir carries the BOUND rpc port,
        #: which an ephemeral-port start only knows at serve time
        self.dir_fn = dir_fn
        self.window_s = float(window_s)
        self.capacity = max(1, int(capacity))
        self.journal = journal
        self._lock = threading.Lock()
        self._last_capture = 0.0  # monotonic
        self.captured = 0
        self.suppressed = 0
        self.last_id = ""
        self.last_error = ""

    @property
    def enabled(self) -> bool:
        return self.window_s > 0

    # -- trigger --------------------------------------------------------------
    def trigger(self, reason: str,
                trace_ids: Optional[List[str]] = None,
                force: bool = False) -> Optional[Dict[str, Any]]:
        """Maybe capture one bundle. Debounced to once per window
        (``force=True`` bypasses — the operator's manual capture path).
        Never raises: a broken collector must not take down the
        telemetry tick that fired the trigger."""
        if not self.enabled and not force:
            return None
        now = time.monotonic()
        with self._lock:
            if not force and self._last_capture and \
                    now - self._last_capture < self.window_s:
                self.suppressed += 1
                if self.registry is not None:
                    self.registry.count("incident.suppressed")
                return None
            self._last_capture = now
        try:
            return self._capture(reason, trace_ids or [])
        except Exception as e:  # broad-ok — forensics must never harm serving
            self.last_error = repr(e)[:200]
            log.warning("incident capture failed (%s)", reason,
                        exc_info=True)
            return None

    def _capture(self, reason: str,
                 trace_ids: List[str]) -> Dict[str, Any]:
        h = events.hlc_now()
        incident_id = f"inc-{h:x}"
        doc: Dict[str, Any] = {
            "id": incident_id,
            "reason": reason,
            "hlc": h,
            "ts": round(events.hlc_wall_s(h), 3),
            "trace_ids": [t for t in trace_ids if t],
        }
        doc.update(self.collector() or {})
        path = self._write(incident_id, doc)
        doc["path"] = path
        self.captured += 1
        self.last_id = incident_id
        if self.registry is not None:
            self.registry.count("incident.captured")
        if self.journal is not None:
            self.journal.emit("incident", "captured", severity="warning",
                              id=incident_id, reason=reason,
                              bundle_trace_ids=len(doc["trace_ids"]))
        log.warning("incident bundle captured: %s (%s) -> %s",
                    incident_id, reason, path)
        return doc

    # -- disk -----------------------------------------------------------------
    def _dir(self) -> str:
        d = self.dir_fn()
        os.makedirs(d, exist_ok=True)
        return d

    def _write(self, incident_id: str, doc: Dict[str, Any]) -> str:
        d = self._dir()
        path = os.path.join(d, f"{incident_id}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, default=str)
        os.replace(tmp, path)
        self._prune(d)
        return path

    def _prune(self, d: str) -> None:
        bundles = sorted(
            f for f in os.listdir(d)
            if f.startswith("inc-") and f.endswith(".json"))
        # inc-<hlc-hex> names sort chronologically only at equal width;
        # sort by mtime to stay honest across clock jumps
        bundles.sort(key=lambda f: os.path.getmtime(os.path.join(d, f)))
        for f in bundles[:max(0, len(bundles) - self.capacity)]:
            try:
                os.remove(os.path.join(d, f))
            except OSError:
                pass

    # -- query surface (get_incidents RPC) ------------------------------------
    def list(self) -> Dict[str, Any]:
        """Bundle index from the artifacts dir (survives restarts)."""
        try:
            d = self._dir()
        except OSError as e:
            return {"dir": "", "error": str(e), "incidents": [],
                    "stats": self.stats()}
        out: List[Dict[str, Any]] = []
        for f in sorted(os.listdir(d)):
            if not (f.startswith("inc-") and f.endswith(".json")):
                continue
            path = os.path.join(d, f)
            meta = {"id": f[:-len(".json")],
                    "bytes": os.path.getsize(path)}
            try:
                with open(path) as fh:
                    doc = json.load(fh)
                meta["reason"] = doc.get("reason", "")
                meta["ts"] = doc.get("ts", 0.0)
                meta["hlc"] = doc.get("hlc", 0)
                meta["trace_ids"] = doc.get("trace_ids") or []
            except (OSError, json.JSONDecodeError) as e:
                meta["error"] = str(e)
            out.append(meta)
        out.sort(key=lambda m: m.get("hlc", 0))
        return {"dir": d, "incidents": out, "stats": self.stats()}

    def get(self, incident_id: str) -> Dict[str, Any]:
        incident_id = str(incident_id)
        if os.sep in incident_id or not incident_id.startswith("inc-"):
            return {"error": f"bad incident id {incident_id!r}"}
        try:
            path = os.path.join(self._dir(), f"{incident_id}.json")
            with open(path) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            return {"error": f"{incident_id}: {e}"}

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"captured": self.captured,
                    "suppressed": self.suppressed,
                    "window_s": self.window_s,
                    "capacity": self.capacity,
                    "last_id": self.last_id,
                    "last_error": self.last_error}
