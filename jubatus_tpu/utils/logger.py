"""Process logging setup (≙ common/logger + log4cxx wiring, SURVEY.md §5).

The reference logs through log4cxx behind glog-style macros, with a
per-process pattern carrying (progname, host, port), an optional XML
config file (``--log_config``) hot-reloaded on SIGHUP
(server_util.cpp:70-127), and ``--logdir`` redirecting to files. Here:

- ``setup(progname, host, port, logdir, log_config)`` configures the root
  logger: stderr by default, ``<logdir>/<progname>.log`` when logdir is
  set, or a Python ``logging.config`` dictConfig JSON file when
  log_config is set.
- ``install_sighup_reload(...)`` re-applies the config file on SIGHUP —
  same operational contract (rotate/adjust levels without restart).
"""

from __future__ import annotations

import json
import logging
import logging.config
import os
import signal
from typing import Optional

DEFAULT_FORMAT = "%(asctime)s %(levelname)s [{prog}:{host}:{port}] %(message)s"


def setup(progname: str, host: str = "", port: int = 0,
          logdir: str = "", log_config: str = "") -> None:
    if log_config:
        apply_config_file(log_config)
        return
    fmt = DEFAULT_FORMAT.format(prog=progname, host=host or "-", port=port)
    root = logging.getLogger()
    for h in list(root.handlers):
        root.removeHandler(h)
    if logdir:
        os.makedirs(logdir, exist_ok=True)
        handler: logging.Handler = logging.FileHandler(
            os.path.join(logdir, f"{progname}.log"))
    else:
        handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter(fmt))
    root.addHandler(handler)
    root.setLevel(logging.INFO)


def apply_config_file(path: str) -> None:
    """JSON dictConfig (the Python-native stand-in for log4cxx XML)."""
    with open(path) as f:
        cfg = json.load(f)
    # module-level loggers created before setup() must stay enabled unless
    # the config explicitly says otherwise (dictConfig defaults to True,
    # which would silently mute every jubatus module logger)
    cfg.setdefault("disable_existing_loggers", False)
    logging.config.dictConfig(cfg)


def install_sighup_reload(log_config: str) -> None:
    """Re-apply the logging config file on SIGHUP (server_util.cpp:70-127).
    No-op when no config file is in use."""
    if not log_config:
        return

    def _reload(_sig, _frame) -> None:
        try:
            apply_config_file(log_config)
            logging.getLogger(__name__).info("log config reloaded from %s",
                                             log_config)
        except Exception:  # noqa: BLE001 — keep the old config on error
            logging.getLogger(__name__).exception(
                "failed to reload log config %s", log_config)

    signal.signal(signal.SIGHUP, _reload)
