"""Prometheus /metrics + /healthz HTTP endpoint (per server process).

A tiny stdlib ``http.server`` thread each engine server / proxy starts
when ``--metrics-port`` is given (off by default; ``0`` binds an
ephemeral port — the actual port lands in get_status). Serves:

- ``GET /metrics``  — Prometheus text exposition (0.0.4) of the node's
  tracing Registry (span latency histograms + event counters + runtime
  gauges), with static identity labels (engine, cluster, node). Buckets
  holding a slow-request capture carry an OpenMetrics-style exemplar
  (``# {trace_id="..."} value ts``) linking the spike to a trace.
- ``GET /healthz``  — JSON liveness document from a caller-supplied
  callable (uptime, rpc port, mixer counters, runtime telemetry
  summary, ...). Always 200 while the process serves; orchestration
  probes hit this, scrapers hit /metrics.
- ``GET /slowlog``  — JSON dump of the registry's slow-request ring
  (tail-based capture, utils/slowlog.py): the curl-able twin of the
  ``get_slow_log`` RPC / ``jubadump --slow-log``.

Deliberately read-only and unauthenticated, like every Prometheus
exporter: bind it to an internal interface. The RPC plane stays the
source of truth for control operations.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional

from jubatus_tpu.utils.tracing import Registry

log = logging.getLogger(__name__)

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsServer:
    """Background exposition endpoint over one tracing Registry."""

    def __init__(self, registry: Registry, *,
                 labels: Optional[Dict[str, str]] = None,
                 health_fn: Optional[Callable[[], Dict[str, Any]]] = None,
                 host: str = "0.0.0.0", port: int = 0) -> None:
        self.registry = registry
        self.labels = dict(labels or {})
        self.health_fn = health_fn
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        """Bind + serve in a daemon thread; returns the bound port."""
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self) -> None:  # noqa: N802 — stdlib contract
                try:
                    if self.path.split("?", 1)[0] == "/metrics":
                        body = outer.registry.prometheus_text(
                            outer.labels).encode()
                        ctype = PROM_CONTENT_TYPE
                    elif self.path.split("?", 1)[0] == "/healthz":
                        doc: Dict[str, Any] = {"status": "ok"}
                        if outer.health_fn is not None:
                            doc.update(outer.health_fn())
                        body = (json.dumps(doc) + "\n").encode()
                        ctype = "application/json"
                    elif self.path.split("?", 1)[0] == "/slowlog":
                        body = (json.dumps({
                            "stats": outer.registry.slowlog.stats(),
                            "records": outer.registry.slowlog.snapshot(),
                        }) + "\n").encode()
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception:  # noqa: BLE001 — a scrape must not 500-loop
                    log.exception("metrics request failed")
                    self.send_error(500)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *_a: Any) -> None:
                pass  # scrapes every few seconds must not spam the log

        # 0.0.0.0 rpc default maps cleanly; the handler threads are daemons
        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.5},
            daemon=True, name="metrics-http")
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
