"""Continuous profiling plane (ISSUE 8).

The observability stack so far answers *which* RPC, trace, or mix round
was slow (PRs 2/4/7); this module answers *where the time went inside
the process*. Three capture modes:

- **Always-on sampling profiler** (:class:`SamplingProfiler`): one
  daemon thread per process samples every thread's stack via
  ``sys._current_frames()`` at ``--profile-hz`` (default ~67 Hz, a
  deliberately non-round rate so the sampler never phase-locks with
  periodic work; 0 = fully off, no thread). Samples fold into
  collapsed-stack keys (``root;caller;...;leaf``, one
  ``file.py:function`` token per frame — no line numbers, so hot
  functions aggregate instead of exploding key cardinality) in a
  BOUNDED store: at most ``max_stacks`` distinct keys per bucket,
  overflow folding into ``(other)`` so counts stay honest under churn.
  The store is windowed like utils/timeseries.py — the live bucket
  rotates into a bounded ring every ``bucket_s`` seconds, so
  ``profile(seconds=N)`` is an exact fold of the last N seconds, not a
  process-lifetime smear. Served by the ``get_profile`` RPC (proxies
  broadcast + fold backends with their own samples), rendered by
  ``jubactl -c profile`` (top-N self/cumulative table, or ``--folded``
  collapsed-stack output consumable by flamegraph.pl / speedscope) and
  dumped by ``jubadump --profile``.
- **On-demand device capture** (:class:`DeviceCapture`): the
  ``profile_device`` RPC wraps ``jax.profiler.trace()`` for a bounded
  duration into a capped artifacts directory (``--profile-dir``), so
  XLA compile/execute/HBM time on a real TPU is one
  ``jubactl -c profile --device`` away. Old captures are pruned —
  the artifacts dir can never grow without bound.
- **Tail-triggered snapshots**: when utils/slowlog.py sees K breaches
  of the same span inside a window (``--profile-trigger-*``), it calls
  :meth:`SamplingProfiler.tail_snapshot`, which folds the last few
  seconds of samples into a bounded snapshot ring stamped with the
  offending trace_ids — closing the loop from PR 4's "this request was
  slow" to "this stack made it slow".

Overhead is a first-class number: the sampler accounts its own wall
time (``profiler.overhead_ms_per_s`` gauge) and bench_serving.py's
``run_profiling_overhead`` A/B holds the e2e cost under the
observability plane's <2% budget.
"""

from __future__ import annotations

import logging
import os
import shutil
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from jubatus_tpu.utils.tracing import Registry

log = logging.getLogger(__name__)

#: default sampling rate; ~67 Hz ≈ 15 ms period — coarse enough to stay
#: invisible next to a multi-ms RPC, fine enough that a 1-second stall
#: lands ~67 samples
DEFAULT_HZ = 67.0
#: distinct collapsed-stack keys retained per bucket before overflow
#: folds into ``(other)``
DEFAULT_MAX_STACKS = 512
#: live-bucket rotation period (the window resolution of ``profile()``)
DEFAULT_BUCKET_S = 5.0
#: ring depth: 10 minutes of history at the 5 s bucket
DEFAULT_RING = 120
#: tail-triggered snapshot ring depth
DEFAULT_SNAPSHOTS = 16
#: seconds of samples a tail-triggered snapshot folds
SNAPSHOT_WINDOW_S = 5.0
#: frames deeper than this truncate (a runaway recursion must not mint
#: unbounded keys)
MAX_DEPTH = 64

#: overflow key for stacks beyond the per-bucket bound
OTHER_KEY = "(other)"


#: code object -> "file.py:func" token. Memoized because the token is
#: rebuilt for EVERY frame of EVERY thread at the sampling rate — the
#: basename+format work dominated the raw sample cost. Keyed by the
#: code object itself (keeps it alive; the population is bounded by the
#: program's code, and the overflow clear below backstops pathological
#: dynamic-code generators). Plain dict: GIL-atomic get/set.
_CODE_TOKENS: Dict[Any, str] = {}
_CODE_TOKENS_CAP = 8192


def _code_token(co: Any) -> str:
    tok = _CODE_TOKENS.get(co)
    if tok is None:
        if len(_CODE_TOKENS) >= _CODE_TOKENS_CAP:
            _CODE_TOKENS.clear()
        tok = _CODE_TOKENS[co] = \
            f"{os.path.basename(co.co_filename)}:{co.co_name}"
    return tok


def collapse_frame(frame: Any, thread_name: str = "") -> str:
    """One thread's stack as a collapsed key: ``root;...;leaf`` with
    ``file.py:function`` tokens (basename only, NO line numbers — hot
    functions aggregate; the key space stays bounded by the code, not
    the data). The thread name roots the stack so worker pools and the
    accept loop separate in a flamegraph."""
    parts: List[str] = []
    f = frame
    while f is not None and len(parts) < MAX_DEPTH:
        parts.append(_code_token(f.f_code))
        f = f.f_back
    parts.reverse()
    if thread_name:
        parts.insert(0, f"thread:{thread_name}")
    return ";".join(parts) if parts else "(empty)"


class SamplingProfiler:
    """Per-process always-on stack sampler with a bounded, windowed
    aggregate store. One instance per server/proxy, bound to its tracing
    Registry (gauges/counters land there)."""

    def __init__(self, registry: Optional[Registry] = None,
                 hz: float = DEFAULT_HZ,
                 max_stacks: int = DEFAULT_MAX_STACKS,
                 bucket_s: float = DEFAULT_BUCKET_S,
                 ring_capacity: int = DEFAULT_RING,
                 snapshot_capacity: int = DEFAULT_SNAPSHOTS) -> None:
        self.registry = registry
        self.hz = max(0.0, float(hz))
        self.max_stacks = max(8, int(max_stacks))
        self.bucket_s = max(0.5, float(bucket_s))
        self._lock = threading.Lock()
        #: live bucket: collapsed key -> sample count
        self._current: Dict[str, int] = {}
        self._current_start = time.time()  # wall-clock
        #: rotated buckets, oldest-first: (t_start, t_end, {key: count})
        self._ring: deque = deque(maxlen=max(2, int(ring_capacity)))
        #: tail-triggered snapshots (see tail_snapshot)
        self._snapshots: deque = deque(maxlen=max(1, int(snapshot_capacity)))
        self._samples = 0
        self._truncated = 0
        self._snapshots_taken = 0
        self._sample_s = 0.0          # cumulative wall time spent sampling
        self._bucket_samples = 0      # since last rotation (for gauges)
        self._bucket_sample_s = 0.0
        self._thread_names: Dict[int, str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def enabled(self) -> bool:
        return self.hz > 0

    # -- sampling ------------------------------------------------------------
    def sample_once(self) -> int:
        """Take one sample of every live thread (except the sampler
        itself); returns the number of stacks folded."""
        t0 = time.perf_counter()
        me = threading.get_ident()
        frames = sys._current_frames()
        keys: List[str] = []
        fresh_names = None
        for ident, frame in frames.items():
            if ident == me:
                continue
            name = self._thread_names.get(ident)
            if name is None:
                if fresh_names is None:
                    fresh_names = {t.ident: t.name
                                   for t in threading.enumerate()}
                    self._thread_names = fresh_names
                name = fresh_names.get(ident, "?")
            keys.append(collapse_frame(frame, name))
        del frames
        now = time.time()  # wall-clock: windows compare across nodes
        cost = time.perf_counter() - t0
        with self._lock:
            self._samples += 1
            self._bucket_samples += 1
            self._sample_s += cost
            self._bucket_sample_s += cost
            for k in keys:
                self._ingest_locked(k)
            rotated = None
            if now - self._current_start >= self.bucket_s:
                rotated = self._rotate_locked(now)
        if rotated is not None:
            self._publish(rotated)
        return len(keys)

    def _ingest_locked(self, key: str) -> None:
        cur = self._current
        n = cur.get(key)
        if n is not None:
            cur[key] = n + 1
        elif len(cur) < self.max_stacks:
            cur[key] = 1
        else:
            cur[OTHER_KEY] = cur.get(OTHER_KEY, 0) + 1
            self._truncated += 1

    def _rotate_locked(self, now: float) -> Dict[str, Any]:
        """Push the live bucket into the ring; returns the gauge doc the
        caller publishes OUTSIDE the lock."""
        self._ring.append((self._current_start, now, self._current))
        doc = {
            "stacks": len(self._current),
            "samples": self._bucket_samples,
            "wall_s": max(now - self._current_start, 1e-9),
            "sample_s": self._bucket_sample_s,
        }
        self._current = {}
        self._current_start = now
        self._bucket_samples = 0
        self._bucket_sample_s = 0.0
        return doc

    def _publish(self, doc: Dict[str, Any]) -> None:
        reg = self.registry
        if reg is None:
            return
        reg.count("profiler.samples", int(doc["samples"]))
        reg.gauge("profiler.hz", self.hz)
        reg.gauge("profiler.stacks", doc["stacks"])
        reg.gauge("profiler.overhead_ms_per_s",
                  round(doc["sample_s"] / doc["wall_s"] * 1e3, 3))

    # -- views ---------------------------------------------------------------
    def profile(self, seconds: float = 0.0) -> Dict[str, Any]:
        """Wire-safe folded view over the last ``seconds`` (0 = every
        retained bucket): collapsed stacks, sampler stats, and the
        tail-triggered snapshot ring."""
        now = time.time()  # wall-clock
        with self._lock:
            entries: List[Tuple[float, float, Dict[str, int]]] = \
                list(self._ring)
            entries.append((self._current_start, now, dict(self._current)))
            snapshots = [dict(s) for s in self._snapshots]
            stats = self._stats_locked()
        start = now - float(seconds) if seconds and seconds > 0 else 0.0
        folded: Dict[str, int] = {}
        t_oldest = now
        for t0, t1, bucket in entries:
            if t1 < start:
                continue
            t_oldest = min(t_oldest, t0)
            for k, v in bucket.items():
                folded[k] = folded.get(k, 0) + v
        return {"folded": folded,
                "ts_start": round(max(start, t_oldest), 3),
                "ts_end": round(now, 3),
                "stats": stats,
                "snapshots": snapshots}

    def tail_snapshot(self, span: str,
                      trace_ids: Optional[List[str]] = None
                      ) -> Optional[Dict[str, Any]]:
        """Fold the last ``SNAPSHOT_WINDOW_S`` seconds of samples into a
        snapshot stamped with the breaching span + trace_ids and ring
        it (utils/slowlog.py's breach trigger calls this). No-op when
        the sampler is off — there is nothing to snapshot."""
        if not self.enabled:
            return None
        doc = self.profile(SNAPSHOT_WINDOW_S)
        rec = {"span": str(span),
               "trace_ids": [str(t) for t in (trace_ids or []) if t][:8],
               "ts": round(time.time(), 3),  # wall-clock
               "window_s": SNAPSHOT_WINDOW_S,
               "samples": sum(doc["folded"].values()),
               "folded": doc["folded"]}
        with self._lock:
            self._snapshots.append(rec)
            self._snapshots_taken += 1
        if self.registry is not None:
            self.registry.count("profiler.snapshots")
        return rec

    def snapshots(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(s) for s in self._snapshots]

    def _stats_locked(self) -> Dict[str, Any]:
        return {"enabled": self.enabled,
                "hz": self.hz,
                "samples": self._samples,
                "truncated": self._truncated,
                "ring_buckets": len(self._ring),
                "bucket_s": self.bucket_s,
                "current_stacks": len(self._current),
                "max_stacks": self.max_stacks,
                "snapshots_taken": self._snapshots_taken,
                "sample_ms_total": round(self._sample_s * 1e3, 3)}

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return self._stats_locked()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="stack-profiler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _loop(self) -> None:
        period = 1.0 / self.hz
        while not self._stop.wait(period):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 — the sampler must survive
                log.debug("stack sample failed", exc_info=True)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._snapshots.clear()
            self._current = {}
            self._current_start = time.time()  # wall-clock
            self._samples = self._truncated = self._snapshots_taken = 0
            self._sample_s = 0.0
            self._bucket_samples = 0
            self._bucket_sample_s = 0.0


# -- cross-node folding + rendering -------------------------------------------


def fold_profiles(docs) -> Dict[str, int]:
    """Fold N ``profile()`` docs (or bare folded dicts) into one
    collapsed-stack map — bucket-wise sums, deterministic regardless of
    input order (same property as tracing.merge_snapshots)."""
    out: Dict[str, int] = {}
    for doc in docs:
        folded = doc.get("folded", doc) if isinstance(doc, dict) else {}
        for k, v in (folded or {}).items():
            out[str(k)] = out.get(str(k), 0) + int(v)
    return out


def folded_lines(folded: Dict[str, int]) -> List[str]:
    """flamegraph.pl / speedscope input: one ``stack count`` line per
    collapsed key, sorted for determinism."""
    return [f"{k} {v}" for k, v in sorted(folded.items())]


def top_table(folded: Dict[str, int]) -> List[Dict[str, Any]]:
    """Per-frame self/cumulative sample counts from a folded map,
    hottest-self first. ``cum`` counts each stack once per frame even
    under recursion (set-dedup within the stack)."""
    self_c: Dict[str, int] = {}
    cum: Dict[str, int] = {}
    total = 0
    for stack, n in folded.items():
        n = int(n)
        total += n
        frames = stack.split(";")
        leaf = frames[-1]
        self_c[leaf] = self_c.get(leaf, 0) + n
        for f in set(frames):
            cum[f] = cum.get(f, 0) + n
    rows = []
    for frame in cum:
        s = self_c.get(frame, 0)
        rows.append({
            "frame": frame,
            "self": s,
            "cum": cum[frame],
            "self_pct": round(s / total * 100, 2) if total else 0.0,
            "cum_pct": round(cum[frame] / total * 100, 2) if total else 0.0,
        })
    rows.sort(key=lambda r: (-r["self"], -r["cum"], r["frame"]))
    return rows


def render_top(folded: Dict[str, int], top: int = 30) -> str:
    """The ``jubactl -c profile`` table: top-N frames by self time."""
    total = sum(int(v) for v in folded.values())
    lines = [f"{'self%':>7} {'cum%':>7} {'self':>8} {'cum':>8}  frame"]
    for row in top_table(folded)[:max(1, int(top))]:
        lines.append(f"{row['self_pct']:>6.2f}% {row['cum_pct']:>6.2f}% "
                     f"{row['self']:>8} {row['cum']:>8}  {row['frame']}")
    lines.append(f"total: {total} sample(s), "
                 f"{len(folded)} distinct stack(s)")
    return "\n".join(lines)


# -- on-demand device capture -------------------------------------------------


class DeviceCapture:
    """Bounded jax.profiler capture directory: ``capture(seconds)``
    traces XLA compile/execute (TensorBoard-viewable; on TPU: HBM +
    per-op device time) into a fresh subdirectory, pruning the oldest
    captures past ``max_captures`` so the artifacts dir is capped."""

    def __init__(self, base_dir: str, max_captures: int = 8) -> None:
        self.base_dir = str(base_dir)
        self.max_captures = max(1, int(max_captures))
        self._lock = threading.Lock()
        self._captures = 0

    #: a single capture may not run longer than this (the RPC blocks
    #: one worker for the duration)
    MAX_SECONDS = 60.0

    def capture(self, seconds: float) -> Dict[str, Any]:
        """Trace the device for ``seconds`` (clamped to
        [0.05, MAX_SECONDS]); returns {"artifact": path, ...} or
        {"error": ...} — a missing/broken profiler backend degrades to
        a structured error, never an exception on the RPC plane."""
        seconds = min(max(float(seconds), 0.05), self.MAX_SECONDS)
        if not self._lock.acquire(blocking=False):
            return {"error": "capture already in progress",
                    "dir": self.base_dir}
        try:
            self._captures += 1
            stamp = time.strftime("%Y%m%d-%H%M%S")  # wall-clock
            path = os.path.join(self.base_dir,
                                f"device-{stamp}-{self._captures:03d}")
            try:
                os.makedirs(path, exist_ok=True)
                import jax

                with jax.profiler.trace(path):
                    time.sleep(seconds)
            except Exception as e:  # noqa: BLE001 — backend quirks degrade
                log.warning("device capture failed", exc_info=True)
                shutil.rmtree(path, ignore_errors=True)
                return {"error": f"{type(e).__name__}: {e}",
                        "dir": self.base_dir}
            self._prune()
            return {"artifact": path, "seconds": seconds,
                    "bytes": _tree_bytes(path)}
        finally:
            self._lock.release()

    def list(self) -> Dict[str, Any]:
        """Existing capture artifacts, oldest-first."""
        arts = []
        try:
            names = sorted(os.listdir(self.base_dir))
        except OSError:
            names = []
        for name in names:
            p = os.path.join(self.base_dir, name)
            if os.path.isdir(p):
                arts.append({"name": name, "path": p,
                             "bytes": _tree_bytes(p)})
        return {"dir": self.base_dir, "artifacts": arts,
                "max_captures": self.max_captures}

    def _prune(self) -> None:
        try:
            names = sorted(n for n in os.listdir(self.base_dir)
                           if os.path.isdir(os.path.join(self.base_dir, n)))
        except OSError:
            return
        for name in names[:-self.max_captures]:
            shutil.rmtree(os.path.join(self.base_dir, name),
                          ignore_errors=True)


def _tree_bytes(path: str) -> int:
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(root, f))
            except OSError:
                pass
    return total
