"""Data-quality & prediction-quality plane (ISSUE 17).

Five observability PRs made the *system* legible; this module observes
the *data and the model*. Three signal families, all bounded and all
mergeable across the fleet (utils/sketches.py):

- **Drift** — per-feature-group and per-prediction PSI between a PINNED
  reference window and the live window. PSI (population stability
  index, the credit-scoring classic) is ``sum((p_i - q_i) *
  ln(p_i / q_i))`` over a fixed binning — symmetric, additive over
  bins, and 0 iff the distributions agree; >~0.2 conventionally means
  "significant shift". The binning here is the sketch's own log-bucket
  grid (coarsened to octaves) for values and top-k + tail for labels,
  so drift falls straight out of two sketch states with no raw data.
  Scores publish as ``quality.drift.<group>`` gauges plus the
  ``quality.drift.max`` roll-up — SLO-able via the existing ``gauge:``
  grammar (utils/slo.py) with zero engine changes. The roll-up covers
  INCOMING data only (feature groups + the training-label mix);
  model-output drift keys (``OUTPUT_DRIFT_KEYS``) publish under their
  own gauges but never page the input-drift SLO.
- **Prequential accuracy** — test-then-train (Dawid 1984; Gama et al.
  2013): every sampled train datum is FIRST scored with the current
  model, then trained on. The running accuracy/MAE is an unbiased
  streaming estimate of held-out performance with zero extra labels —
  the signal that catches concept shift (the label boundary moved)
  which covariate drift alone cannot see.
- **Calibration** — classifier confidence (softmax over the ranked
  scores) vs empirical accuracy in 10 fixed bins; the expected
  calibration error (ECE) is the weighted mean |confidence - accuracy|
  gap.

:class:`QualityPlane` owns the live window, the completed-window ring,
the pinned reference, and the sampling gates; ``server/base.py`` ticks
it from the telemetry thread and ships ``snapshot()`` through the
idempotent ``get_quality`` RPC; ``merge_quality`` is the proxy/CLI fold.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence

import numpy as np

from jubatus_tpu.utils import sketches

#: PSI above this is "significant shift" by the usual operator rule of
#: thumb; docs + the bench drill use it as the SLO ceiling example
DEFAULT_DRIFT_THRESHOLD = 0.2
#: live window needs this many recorded values before its PSI is
#: trusted (a 3-sample window against a 10^6-sample reference is noise)
DEFAULT_DRIFT_MIN_COUNT = 50
#: collapse quarter-octave bins to octave bins for PSI (full resolution
#: splits the mass too thin for small windows)
_PSI_COARSEN = 4
#: smoothing mass per bin: keeps ln(p/q) finite when one side is empty
_PSI_EPS = 1e-3

_CAL_BINS = 10


# -- drift scores ------------------------------------------------------------

def psi_from_freqs(p: Dict[Any, float], q: Dict[Any, float],
                   eps: float = _PSI_EPS) -> float:
    """PSI between two relative-frequency dicts over the union support,
    with additive smoothing ``eps`` per bin."""
    keys = set(p) | set(q)
    if not keys:
        return 0.0
    denom_p = 1.0 + eps * len(keys)
    denom_q = 1.0 + eps * len(keys)
    out = 0.0
    for k in keys:
        pi = (float(p.get(k, 0.0)) + eps) / denom_p
        qi = (float(q.get(k, 0.0)) + eps) / denom_q
        out += (pi - qi) * math.log(pi / qi)
    return out


def kl_from_freqs(p: Dict[Any, float], q: Dict[Any, float],
                  eps: float = _PSI_EPS) -> float:
    """Smoothed KL(p || q) over the union support — the asymmetric
    companion score (PSI is its symmetrized form)."""
    keys = set(p) | set(q)
    if not keys:
        return 0.0
    denom_p = 1.0 + eps * len(keys)
    denom_q = 1.0 + eps * len(keys)
    out = 0.0
    for k in keys:
        pi = (float(p.get(k, 0.0)) + eps) / denom_p
        qi = (float(q.get(k, 0.0)) + eps) / denom_q
        out += pi * math.log(pi / qi)
    return out


def value_freqs(state: Dict[str, Any],
                coarsen: int = _PSI_COARSEN) -> Dict[int, float]:
    """A value sketch state as coarse-bin relative frequencies (the
    fixed binning PSI compares)."""
    count = int(state.get("count", 0))
    if count <= 0:
        return {}
    out: Dict[int, float] = {}
    for k, v in (state.get("bins") or {}).items():
        b = int(k) // max(1, int(coarsen))
        out[b] = out.get(b, 0.0) + int(v) / count
    return out


def psi_value_states(ref: Dict[str, Any], live: Dict[str, Any]) -> float:
    return psi_from_freqs(value_freqs(ref), value_freqs(live))


def psi_categorical_states(ref: Dict[str, Any],
                           live: Dict[str, Any]) -> float:
    return psi_from_freqs(sketches.categorical_freqs(ref),
                          sketches.categorical_freqs(live))


# -- prequential accumulator -------------------------------------------------

def _empty_prequential() -> Dict[str, Any]:
    return {"n": 0, "correct": 0, "abs_err": 0.0, "sq_err": 0.0,
            "conf": [[0, 0, 0.0] for _ in range(_CAL_BINS)]}


def merge_prequential(states: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Sum prequential accumulators (commutative integer/float sums)."""
    out = _empty_prequential()
    for st in states:
        if not st:
            continue
        out["n"] += int(st.get("n", 0))
        out["correct"] += int(st.get("correct", 0))
        out["abs_err"] += float(st.get("abs_err", 0.0))
        out["sq_err"] += float(st.get("sq_err", 0.0))
        for i, row in enumerate((st.get("conf") or [])[:_CAL_BINS]):
            out["conf"][i][0] += int(row[0])
            out["conf"][i][1] += int(row[1])
            out["conf"][i][2] += float(row[2])
    return out


def prequential_accuracy(state: Dict[str, Any]) -> Optional[float]:
    n = int(state.get("n", 0))
    return int(state.get("correct", 0)) / n if n else None


def prequential_mae(state: Dict[str, Any]) -> Optional[float]:
    n = int(state.get("n", 0))
    return float(state.get("abs_err", 0.0)) / n if n else None


def calibration_ece(state: Dict[str, Any]) -> Optional[float]:
    """Expected calibration error: confidence-bin-weighted mean of
    |empirical accuracy - mean confidence|."""
    rows = state.get("conf") or []
    total = sum(int(r[0]) for r in rows)
    if not total:
        return None
    ece = 0.0
    for n, correct, conf_sum in rows:
        if not n:
            continue
        ece += (n / total) * abs(correct / n - conf_sum / n)
    return ece


def _softmax_confidence(ranked: Sequence) -> float:
    """Winner's softmax probability over the ranked (label, score) list
    — classifier margins are unnormalized, so calibration bins need a
    common [0, 1] scale."""
    scores = np.array([float(s) for _l, s in ranked], dtype=np.float64)
    scores -= scores.max()
    e = np.exp(scores)
    return float(e.max() / e.sum())


# -- the plane ---------------------------------------------------------------

#: distinct feature groups tracked before the long tail folds into one
#: overflow group (bounded memory under feature-name churn)
MAX_GROUPS = 32
OVERFLOW_GROUP = "__overflow__"
#: group name for the prediction-output sketch (per-prediction drift)
PREDICTIONS_GROUP = "predictions"
#: drift keys that track model OUTPUT distributions, not incoming
#: data: they publish under their own quality.drift.<key> gauges but
#: stay out of the quality.drift.max roll-up — a cold or still-
#: converging model swings its prediction mix between windows with
#: nothing wrong in the data, and an input-drift SLO must not page on
#: that (alarm these keys separately if prediction drift is an SLO in
#: its own right). Incoming feature groups AND the training-label
#: distribution stay in the roll-up: both are the data's business.
OUTPUT_DRIFT_KEYS = ("label_predictions", PREDICTIONS_GROUP)


def _input_drift_max(drift: Dict[str, float]) -> float:
    vals = [v for g, v in drift.items() if g not in OUTPUT_DRIFT_KEYS]
    return max(vals) if vals else 0.0


def group_of(name: str) -> str:
    """Feature-key → drift group: the leading run of the key before the
    first digit or separator (``ch0`` → ``ch``, ``user@str$tokyo`` →
    ``user``, ``age`` → ``age``) — per-feature-family granularity that
    stays bounded when keys carry per-row suffixes."""
    for j, ch in enumerate(name):
        if ch.isdigit() or ch in "@$/#:":
            return name[:j] or "other"
    return name or "other"


class QualityPlane:
    """Per-process data-quality recorder: bounded live-window sketches,
    a completed-window ring with a pinned reference, prequential and
    calibration accumulators, and the drift gauges the telemetry tick
    publishes. All entry points are thread-safe (one lock; record paths
    do O(sampled rows) work)."""

    def __init__(self, *, sample: float = 0.05, window_s: float = 60.0,
                 ref_windows: int = 2,
                 ring_capacity: int = sketches.DEFAULT_RING_CAPACITY,
                 registry: Any = None, max_score_rows: int = 8,
                 drift_min_count: int = DEFAULT_DRIFT_MIN_COUNT) -> None:
        self.sample = max(0.0, min(1.0, float(sample)))
        self.window_s = max(1.0, float(window_s))
        self.ref_windows = max(1, int(ref_windows))
        self.registry = registry
        self.max_score_rows = max(1, int(max_score_rows))
        self.drift_min_count = max(1, int(drift_min_count))
        self._lock = threading.Lock()
        self._gates: Dict[str, float] = {}
        self._groups: Dict[str, sketches.ValueSketch] = {}
        self._group_memo: Dict[str, str] = {}
        self._labels = sketches.CategoricalSketch()
        self._predictions = sketches.CategoricalSketch()
        self._preq_live = _empty_prequential()
        self._preq_total = _empty_prequential()
        self.ring = sketches.SnapshotRing(capacity=ring_capacity)
        self._ref_pending: List[Dict[str, Any]] = []
        #: stamped on the first tick (not at construction) so injected
        #: clocks in tests and replays behave
        self._live_started: Optional[float] = None
        self._drift: Dict[str, float] = {}
        self._trend: List[Dict[str, Any]] = []
        self._recorded_rows = 0
        self._scored_rows = 0

    # -- sampling gates ------------------------------------------------------
    def admit(self, gate: str) -> bool:
        """Deterministic stride sampler: admits ``sample`` of the calls
        on each gate (no RNG — replays stay replays)."""
        if self.sample <= 0.0:
            return False
        with self._lock:
            acc = self._gates.get(gate, 0.0) + self.sample
            if acc >= 1.0:
                self._gates[gate] = acc - 1.0
                return True
            self._gates[gate] = acc
            return False

    def arm(self, sample: Optional[float] = None,
            now: Optional[float] = None) -> None:
        """(Re)arm the recorder mid-flight: optionally change the
        sample rate, restart the live-window clock at ``now`` and drop
        whatever the old rate recorded mid-window — so the NEXT roll
        covers exactly one window of post-arm traffic. Operators (and
        the bench drills) toggling the plane on a warm server want the
        first window to start when real traffic does; a stale window
        start would pin a seconds-short, unrepresentative reference.
        A reference already pinned survives re-arming (it is still the
        agreed baseline); prequential totals survive too."""
        with self._lock:
            if sample is not None:
                self.sample = max(0.0, min(1.0, float(sample)))
            self._live_started = time.time() if now is None \
                else float(now)
            self._groups = {}
            self._labels = sketches.CategoricalSketch()
            self._predictions = sketches.CategoricalSketch()
            self._preq_live = _empty_prequential()

    # -- recording -----------------------------------------------------------
    def _group_sketch(self, group: str) -> sketches.ValueSketch:
        sk = self._groups.get(group)
        if sk is None:
            if len(self._groups) >= MAX_GROUPS:
                group = OVERFLOW_GROUP
                sk = self._groups.get(group)
                if sk is None:
                    sk = self._groups[group] = sketches.ValueSketch()
                return sk
            sk = self._groups[group] = sketches.ValueSketch()
        return sk

    def record_named(self, names: Sequence[str], values: Any) -> None:
        """The batched-FV hook (core/fv/converter.convert_batch): one
        flat (feature name, value) batch, self-sampled. Group codes come
        from a memo dict (hot key sets repeat), bucketing is one
        vectorized pass per touched group."""
        if not self.admit("fv") or not len(names):
            return
        vals = np.asarray(values, dtype=np.float64).ravel()
        memo = self._group_memo
        codes = []
        for nm in names:
            g = memo.get(nm)
            if g is None:
                if len(memo) >= 4096:
                    memo.clear()
                g = memo[nm] = group_of(nm)
            codes.append(g)
        with self._lock:
            arr = np.asarray(codes)
            for g in dict.fromkeys(codes):
                self._group_sketch(g).observe_array(vals[arr == g])
            self._recorded_rows += int(vals.size)
        reg = self.registry
        if reg is not None:
            reg.count("quality.recorded_values", int(vals.size))

    def record_hashed(self, values: Any) -> None:
        """Raw-ingest hook (native fast path): feature names never
        materialize there, so the post-hash value distribution records
        under one ``hashed`` group."""
        vals = np.asarray(values, dtype=np.float64).ravel()
        if vals.size == 0:
            return
        with self._lock:
            self._group_sketch("hashed").observe_array(vals)
            self._recorded_rows += int(vals.size)
        reg = self.registry
        if reg is not None:
            reg.count("quality.recorded_values", int(vals.size))

    def record_labels(self, labels: Iterable[Any]) -> None:
        with self._lock:
            self._labels.observe_many(str(x) for x in labels)

    def record_classified(self, truth: str, ranked: Sequence) -> None:
        """One prequential classifier observation: ``ranked`` is the
        CURRENT model's (label, score) list for a datum about to be
        trained on."""
        if not ranked:
            return
        pred, _score = max(ranked, key=lambda kv: float(kv[1]))
        conf = _softmax_confidence(ranked)
        correct = 1 if str(pred) == str(truth) else 0
        b = min(_CAL_BINS - 1, int(conf * _CAL_BINS))
        with self._lock:
            for st in (self._preq_live, self._preq_total):
                st["n"] += 1
                st["correct"] += correct
                st["conf"][b][0] += 1
                st["conf"][b][1] += correct
                st["conf"][b][2] += conf
            self._predictions.observe(str(pred))
            self._scored_rows += 1
        reg = self.registry
        if reg is not None:
            reg.count("quality.scored_rows")

    def record_estimated(self, truth: float, predicted: float) -> None:
        """One prequential regression observation (current-model
        estimate vs the incoming target)."""
        err = abs(float(predicted) - float(truth))
        with self._lock:
            for st in (self._preq_live, self._preq_total):
                st["n"] += 1
                st["abs_err"] += err
                st["sq_err"] += err * err
            self._group_sketch(PREDICTIONS_GROUP).observe(float(predicted))
            self._scored_rows += 1
        reg = self.registry
        if reg is not None:
            reg.count("quality.scored_rows")

    # -- windowing + drift ---------------------------------------------------
    def _live_doc_locked(self) -> Dict[str, Any]:
        return {
            "features": {g: sk.state() for g, sk in self._groups.items()},
            "labels": self._labels.state(),
            "predictions": self._predictions.state(),
            "prequential": dict(self._preq_live,
                                conf=[list(r)
                                      for r in self._preq_live["conf"]]),
            "started": self._live_started or 0.0,
        }

    def _roll_locked(self, now: float) -> None:
        doc = self._live_doc_locked()
        doc["ts"] = now
        self.ring.push(doc, now)
        if self.ring.reference is None:
            self._ref_pending.append(doc)
            if len(self._ref_pending) >= self.ref_windows:
                self.ring.pin_reference(
                    merge_window_docs(self._ref_pending), now)
                self._ref_pending = []
        self._groups = {}
        self._labels = sketches.CategoricalSketch()
        self._predictions = sketches.CategoricalSketch()
        self._preq_live = _empty_prequential()
        self._live_started = now

    def _live_count_locked(self) -> int:
        return sum(sk.count for sk in self._groups.values())

    def tick(self, now: Optional[float] = None) -> Dict[str, float]:
        """One telemetry tick: roll the live window when due, recompute
        drift against the pinned reference, publish the quality gauges
        into the registry, and append the trend point. Returns the
        gauge dict (tests read it directly)."""
        now = time.time() if now is None else float(now)
        with self._lock:
            if self._live_started is None:
                self._live_started = now
            if now - self._live_started >= self.window_s and \
                    (self._live_count_locked() or self._preq_live["n"] or
                     self._labels.total):
                self._roll_locked(now)
            ref = self.ring.reference
            # score COMPLETED windows only: a partial live window reads
            # spiky against a full reference (few distinct rows early
            # in the window — zipf traffic makes this brutal), and a
            # gauge that pages must not ride window-phase noise.
            # Detection cost: at most one window plus one tick.
            live = self.ring.newest()
            drift: Dict[str, float] = {}
            if ref is not None and live is not None:
                for g, st in (live.get("features") or {}).items():
                    rst = (ref.get("features") or {}).get(g)
                    if rst is None or int(st.get("count", 0)) < \
                            self.drift_min_count:
                        continue
                    drift[g] = round(psi_value_states(rst, st), 4)
                if int((live.get("labels") or {}).get("total", 0)) >= \
                        self.drift_min_count and \
                        int((ref.get("labels") or {}).get("total", 0)):
                    drift["labels"] = round(psi_categorical_states(
                        ref["labels"], live["labels"]), 4)
                rp = ref.get("predictions") or {}
                lp = live.get("predictions") or {}
                if int(lp.get("total", 0)) >= self.drift_min_count and \
                        int(rp.get("total", 0)):
                    drift["label_predictions"] = round(
                        psi_categorical_states(rp, lp), 4)
            self._drift = drift
            total = self._preq_total
            acc = prequential_accuracy(total)
            mae = prequential_mae(total)
            ece = calibration_ece(total)
            point = {"ts": round(now, 3),
                     "drift_max": _input_drift_max(drift),
                     "accuracy": acc, "mae": mae}
            self._trend.append(point)
            del self._trend[:-120]
        gauges: Dict[str, float] = {}
        for g, v in drift.items():
            gauges[f"quality.drift.{g}"] = v
        gauges["quality.drift.max"] = _input_drift_max(drift)
        if acc is not None:
            gauges["quality.prequential.accuracy"] = round(acc, 4)
            gauges["quality.prequential.error_rate"] = round(1.0 - acc, 4)
        if mae is not None:
            gauges["quality.prequential.mae"] = round(mae, 6)
        if ece is not None:
            gauges["quality.calibration.ece"] = round(ece, 4)
        reg = self.registry
        if reg is not None:
            for g, v in drift.items():
                reg.gauge(f"quality.drift.{g}", v)
            reg.gauge("quality.drift.max", gauges["quality.drift.max"])
            if acc is not None:
                reg.gauge("quality.prequential.accuracy", round(acc, 4))
                reg.gauge("quality.prequential.error_rate",
                          round(1.0 - acc, 4))
            if mae is not None:
                reg.gauge("quality.prequential.mae", round(mae, 6))
            if ece is not None:
                reg.gauge("quality.calibration.ece", round(ece, 4))
        return gauges

    def drift_scores(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._drift)

    # -- views ---------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """This node's mergeable quality doc — the ``get_quality`` RPC
        payload (reference + live sketch states, prequential totals,
        drift scores, trend)."""
        with self._lock:
            live = self._live_doc_locked()
            live["ts"] = time.time()
            ref = self.ring.reference
            return {
                "sample": self.sample,
                "window_s": self.window_s,
                "reference": ref,
                "reference_ts": self.ring.reference_ts,
                "live": live,
                "drift": dict(self._drift),
                "prequential": dict(self._preq_total,
                                    conf=[list(r) for r in
                                          self._preq_total["conf"]]),
                "trend": list(self._trend),
                "stats": dict(self.ring.stats(),
                              recorded_rows=self._recorded_rows,
                              scored_rows=self._scored_rows,
                              groups=len(self._groups)),
            }

    def incident_doc(self) -> Dict[str, Any]:
        """The forensic slice an incident bundle captures: the top
        drifting group NAMED, with its reference/live sketch pair."""
        with self._lock:
            drift = dict(self._drift)
            # name the worst INPUT group when any input drifted — the
            # bundle's headline is "which data went bad", model-output
            # keys only lead when they are the only thing moving
            pool = {g: v for g, v in drift.items()
                    if g not in OUTPUT_DRIFT_KEYS} or drift
            top = max(pool.items(), key=lambda kv: kv[1])[0] if pool \
                else ""
            ref = self.ring.reference or {}
            live = self._live_doc_locked() \
                if self._live_count_locked() else (self.ring.newest() or {})
            doc: Dict[str, Any] = {"drift": drift, "top_drift_group": top}
            if top:
                if top in ("labels", "label_predictions"):
                    key = "labels" if top == "labels" else "predictions"
                    doc["reference_sketch"] = ref.get(key)
                    doc["live_sketch"] = live.get(key)
                else:
                    doc["reference_sketch"] = \
                        (ref.get("features") or {}).get(top)
                    doc["live_sketch"] = \
                        (live.get("features") or {}).get(top)
            acc = prequential_accuracy(self._preq_total)
            if acc is not None:
                doc["prequential_accuracy"] = round(acc, 4)
            return doc

    def stats(self) -> Dict[str, Any]:
        """Flat stat rows for get_status (``quality.*`` keys)."""
        with self._lock:
            drift = dict(self._drift)
            out = {
                "sample": self.sample,
                "window_s": self.window_s,
                "groups": len(self._groups),
                "recorded_rows": self._recorded_rows,
                "scored_rows": self._scored_rows,
                "drift_max": _input_drift_max(drift),
                "reference_pinned": self.ring.reference is not None,
                "windows": self.ring.stats()["pushed"],
            }
            acc = prequential_accuracy(self._preq_total)
            if acc is not None:
                out["prequential_accuracy"] = round(acc, 4)
            mae = prequential_mae(self._preq_total)
            if mae is not None:
                out["prequential_mae"] = round(mae, 6)
            return out


# -- fleet folds -------------------------------------------------------------

def merge_window_docs(docs: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge window docs ({features, labels, predictions, prequential})
    sketch-wise — the reference pin and the cross-node fold share it."""
    feats: Dict[str, List[Dict[str, Any]]] = {}
    labels: List[Dict[str, Any]] = []
    preds: List[Dict[str, Any]] = []
    preqs: List[Dict[str, Any]] = []
    ts = 0.0
    for d in docs:
        if not d:
            continue
        for g, st in (d.get("features") or {}).items():
            feats.setdefault(g, []).append(st)
        if d.get("labels"):
            labels.append(d["labels"])
        if d.get("predictions"):
            preds.append(d["predictions"])
        if d.get("prequential"):
            preqs.append(d["prequential"])
        ts = max(ts, float(d.get("ts", 0.0)))
    return {
        "features": {g: sketches.merge_value_states(sts)
                     for g, sts in feats.items()},
        "labels": sketches.merge_categorical_states(labels),
        "predictions": sketches.merge_categorical_states(preds),
        "prequential": merge_prequential(preqs),
        "ts": ts,
    }


def merge_quality(docs: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold per-node ``get_quality`` docs into one fleet view: merge
    reference and live sketches group-wise, RECOMPUTE drift from the
    merged pair (that is what mergeable sketches buy — fleet drift is
    exact, not an average of node scores), sum prequential totals."""
    live_docs = [d.get("live") for d in docs if d and d.get("live")]
    ref_docs = [d.get("reference") for d in docs if d and d.get("reference")]
    live = merge_window_docs(live_docs) if live_docs else {}
    ref = merge_window_docs(ref_docs) if ref_docs else {}
    drift: Dict[str, float] = {}
    for g, st in (live.get("features") or {}).items():
        rst = (ref.get("features") or {}).get(g)
        if rst is not None and int(st.get("count", 0)):
            drift[g] = round(psi_value_states(rst, st), 4)
    if int((live.get("labels") or {}).get("total", 0)) and \
            int((ref.get("labels") or {}).get("total", 0)):
        drift["labels"] = round(
            psi_categorical_states(ref["labels"], live["labels"]), 4)
    # nodes mid-window ship empty live sketches; their last COMPUTED
    # drift scores still describe the fleet, so fold them in (per-key
    # max) wherever the merged-sketch recompute had no data
    recomputed = set(drift)
    for d in docs:
        for g, v in ((d or {}).get("drift") or {}).items():
            if g not in recomputed:
                drift[g] = max(float(v), drift.get(g, 0.0))
    preq = merge_prequential(
        [d.get("prequential") for d in docs if d])
    trend: List[Dict[str, Any]] = []
    for d in docs:
        if d:
            trend.extend(d.get("trend") or [])
    trend.sort(key=lambda p: p.get("ts", 0.0))
    return {
        "nodes": len([d for d in docs if d]),
        "reference": ref,
        "live": live,
        "drift": drift,
        "prequential": preq,
        "trend": trend[-240:],
        "sample": max([float(d.get("sample", 0.0)) for d in docs if d],
                      default=0.0),
    }
