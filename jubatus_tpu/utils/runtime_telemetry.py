"""Per-process runtime/XLA telemetry sampler (ISSUE 4).

TensorFlow's production experience (Abadi et al., arxiv 1605.08695)
taught that runtime telemetry — memory, compilation — must be
first-class or regressions hide until they page someone. This module is
one daemon thread per process that periodically samples:

- **process**: RSS/VIRT (``/proc/self/statm``), open FDs, thread count,
  GC generation counts + total collections;
- **JAX/XLA signals**: cumulative jit compile count and wall-ms (via
  ``jax.monitoring`` duration listeners — the runtime's own
  instrumentation, zero polling cost), jit cache size (pjit C++ caches),
  live ``jax.Array`` count, and live device memory when the backend
  reports it (``Device.memory_stats`` — TPU/GPU; CPU returns nothing);
- **forensics depth**: the owning registry's slow-log ring depth.

Every sample lands as gauges in the owning tracing ``Registry``
(``jubatus_runtime_gauge{key=...}`` on ``/metrics``) and in
``status()`` (merged as ``runtime.*`` keys into ``get_status`` and
summarized in ``/healthz``). Sampling never raises: a missing /proc or
an import-less jax just drops keys.

jax.monitoring listeners are registered once per process (they cannot be
unregistered individually) and accumulate into module-level counters, so
any number of samplers/servers in one process read one consistent view.
"""

from __future__ import annotations

import gc
import logging
import os
import sys
import threading
import time
from typing import Any, Dict, Optional

from jubatus_tpu.utils.tracing import Registry

log = logging.getLogger(__name__)

DEFAULT_INTERVAL_SEC = 10.0

# -- jax.monitoring hooks (process-wide, install-once) ------------------------

_jax_lock = threading.Lock()
_jax_hooked = False
_jax_stats: Dict[str, float] = {
    "compile_count": 0.0,   # backend_compile events (actual XLA compiles)
    "compile_ms": 0.0,      # cumulative backend compile wall-ms
    "trace_ms": 0.0,        # cumulative jaxpr trace wall-ms
    "lower_ms": 0.0,        # cumulative jaxpr->MLIR lowering wall-ms
}

#: jax.monitoring event suffixes -> stat keys (duration events)
_DURATION_EVENTS = {
    "/jax/core/compile/backend_compile_duration": ("compile_ms",
                                                   "compile_count"),
    "/jax/core/compile/jaxpr_trace_duration": ("trace_ms", None),
    "/jax/core/compile/jaxpr_to_mlir_module_duration": ("lower_ms", None),
}


def _on_duration(event: str, duration_secs: float, **_kw: Any) -> None:
    keys = _DURATION_EVENTS.get(event)
    if keys is None:
        return
    ms_key, count_key = keys
    with _jax_lock:
        _jax_stats[ms_key] += duration_secs * 1e3
        if count_key is not None:
            _jax_stats[count_key] += 1


def install_jax_hooks() -> bool:
    """Register the jax.monitoring listeners (idempotent). Returns True
    when hooks are active, False when jax/monitoring is unavailable."""
    global _jax_hooked
    with _jax_lock:
        if _jax_hooked:
            return True
    try:
        import jax.monitoring as monitoring
    except Exception:  # noqa: BLE001 — no jax: sampler still serves /proc
        return False
    with _jax_lock:
        if _jax_hooked:
            return True
        monitoring.register_event_duration_secs_listener(_on_duration)
        _jax_hooked = True
    return True


def jax_compile_stats() -> Dict[str, float]:
    with _jax_lock:
        return dict(_jax_stats)


# -- sample collection --------------------------------------------------------


def _proc_sample() -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    try:
        with open("/proc/self/statm") as f:
            pages = f.read().split()
        page = os.sysconf("SC_PAGE_SIZE")
        out["vms_bytes"] = int(pages[0]) * page
        out["rss_bytes"] = int(pages[1]) * page
    except (OSError, IndexError, ValueError):
        pass
    try:
        out["open_fds"] = len(os.listdir("/proc/self/fd"))
    except OSError:
        pass
    out["threads"] = threading.active_count()
    gen = gc.get_count()
    for i, n in enumerate(gen):
        out[f"gc_gen{i}"] = n
    try:
        out["gc_collections"] = sum(
            s.get("collections", 0) for s in gc.get_stats())
    except Exception:  # noqa: BLE001 — telemetry must never raise
        pass
    return out


def _jax_sample() -> Dict[str, Any]:
    """JAX signals — only when jax is ALREADY imported (a telemetry
    thread must never pay, or trigger, the jax import in a process that
    doesn't use it: jubactl, jubadump, coordd)."""
    if "jax" not in sys.modules:
        return {}
    out: Dict[str, Any] = {}
    for k, v in jax_compile_stats().items():
        out[f"jax_{k}"] = round(v, 3) if k.endswith("_ms") else int(v)
    try:
        import jax

        out["jax_live_arrays"] = len(jax.live_arrays())
        in_use = 0
        have = False
        for d in jax.local_devices():
            ms = d.memory_stats() if hasattr(d, "memory_stats") else None
            if ms and "bytes_in_use" in ms:
                in_use += int(ms["bytes_in_use"])
                have = True
        if have:
            out["jax_device_bytes_in_use"] = in_use
    except Exception:  # noqa: BLE001 — backend quirks must not kill sampling
        pass
    try:  # pjit C++ jit caches (internal API — best-effort by design)
        from jax._src import pjit as _pjit

        out["jax_jit_cache_size"] = (
            _pjit._cpp_pjit_cache_fun_only.size()
            + _pjit._cpp_pjit_cache_explicit_attributes.size())
    except Exception:  # noqa: BLE001
        pass
    return out


class RuntimeTelemetry:
    """One process's sampler thread bound to one tracing Registry."""

    def __init__(self, registry: Registry,
                 interval_sec: float = DEFAULT_INTERVAL_SEC) -> None:
        self.registry = registry
        self.interval_sec = float(interval_sec)
        self._last: Dict[str, Any] = {}
        self._last_at = 0.0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._samples = 0
        #: per-tick callbacks (ISSUE 7): the owning server appends its
        #: model-health tick here — time-series ring sampling + SLO
        #: evaluation ride the existing sampler thread instead of
        #: spawning their own. Hooks run AFTER the runtime gauges are
        #: published (so the tick's ring point sees them) and must never
        #: raise (guarded anyway).
        self.hooks: list = []
        install_jax_hooks()

    def sample(self) -> Dict[str, Any]:
        """Collect one sample now; publishes gauges into the registry and
        returns the sample dict (unprefixed keys)."""
        s = _proc_sample()
        s.update(_jax_sample())
        try:
            s["slowlog_depth"] = self.registry.slowlog.stats()["retained"]
        except Exception:  # noqa: BLE001
            pass
        with self._lock:
            self._samples += 1
            s["samples"] = self._samples
            self._last = s
            self._last_at = time.monotonic()
        for k, v in s.items():
            if isinstance(v, (int, float)):
                self.registry.gauge(k, v)
        for hook in list(self.hooks):
            try:
                hook()
            except Exception:  # noqa: BLE001 — a tick must never raise
                log.debug("telemetry tick hook failed", exc_info=True)
        return s

    def status(self) -> Dict[str, Any]:
        """Most recent sample, refreshed on demand when stale (> 1 s):
        get_status and /healthz readers see live numbers without paying a
        sample per call under scrape load."""
        with self._lock:
            fresh = (time.monotonic() - self._last_at) <= 1.0
            last = dict(self._last)
        if last and fresh:
            return last
        return self.sample()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None or self.interval_sec <= 0:
            return
        self.sample()  # get_status must have runtime keys immediately
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="runtime-telemetry")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_sec):
            try:
                self.sample()
            except Exception:  # noqa: BLE001 — the sampler must survive
                log.debug("runtime telemetry sample failed", exc_info=True)
