"""msgpack packing for model state pytrees (numpy / JAX arrays included).

The reference serializes models through core::framework::packer into msgpack
streams (linear_mixer.cpp:513-517, save_load.cpp:113-158). We keep msgpack as
the envelope for wire/file parity and add one ext type for ndarrays.
"""

from __future__ import annotations

from typing import Any

import msgpack
import numpy as np

_EXT_NDARRAY = 42


def _default(obj: Any):
    # jax.Array and np.ndarray both expose __array__
    if hasattr(obj, "__array__"):
        arr = np.ascontiguousarray(np.asarray(obj))
        payload = msgpack.packb(
            (arr.dtype.str, list(arr.shape), arr.tobytes()), use_bin_type=True
        )
        return msgpack.ExtType(_EXT_NDARRAY, payload)
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    raise TypeError(f"cannot msgpack {type(obj)!r}")


def _ext_hook(code: int, data: bytes):
    if code == _EXT_NDARRAY:
        dtype, shape, raw = msgpack.unpackb(data, raw=False)
        return np.frombuffer(raw, dtype=np.dtype(dtype)).reshape(shape).copy()
    return msgpack.ExtType(code, data)


def pack_obj(obj: Any) -> bytes:
    return msgpack.packb(obj, default=_default, use_bin_type=True)


def unpack_obj(data: bytes) -> Any:
    return msgpack.unpackb(
        data, ext_hook=_ext_hook, raw=False, strict_map_key=False
    )
