"""Bounded mergeable streaming sketches (ISSUE 17).

The data-quality plane needs distribution summaries that are (a) bounded
— a fixed few KB per tracked group no matter how many values stream
through, (b) *mergeable* — fold across microbatches, windows, and fleet
nodes with ``merge(a, b) == merge(b, a)`` so the proxy's ``get_quality``
broadcast+fold is exact, and (c) cheap to record — vectorized numpy on
the batched FV path. Three primitives:

- :class:`ValueSketch` — log-bucket quantile sketch over SIGNED reals,
  reusing the PR 2 histogram geometry (utils/tracing.py: quarter-octave
  log2 buckets spanning 2^-20..2^7 plus overflow). The signed domain is
  three ranges laid end to end: negative magnitudes descending, an
  exact-zero bin, positive magnitudes ascending — 219 bins total, so a
  bucket-frequency comparison (PSI/KL in utils/quality.py) and a
  quantile walk both read one dense int array.
- :class:`CategoricalSketch` — count-min (fixed ``depth x width``
  counter matrix, seeded crc32 row hashes: deterministic across
  processes, so matrices merge element-wise) + a top-k heavy-hitter
  dict re-estimated from the matrix on merge. Bounded under arbitrary
  label/category cardinality churn.
- :class:`SnapshotRing` — windowed reference-vs-live snapshots ringed
  like utils/timeseries.py: completed window docs in a bounded deque,
  plus a PINNED reference doc (the drift baseline) that survives ring
  eviction.

States are plain dicts of ints/floats/strings (sparse where it pays) so
they ride msgpack verbatim, exactly like tracing histogram states.
"""

from __future__ import annotations

import zlib
from collections import deque
from typing import Any, Dict, Iterable, List, Optional, Tuple

import numpy as np

# -- signed log-bucket geometry (mirrors tracing's quarter-octave grid) ------

_LOG2_MIN = -20          # |v| at/below 2^-20 lands in magnitude bucket 0
_SUB = 4                 # quarter-octave: 4 buckets per power of two
_OCTAVES = 27            # top finite bound 2^7
_OVERFLOW = _SUB * _OCTAVES   # = 108: |v| >= 2^7 magnitude bucket
_NMAG = _OVERFLOW + 1    # 109 magnitude buckets per sign

#: zero bin index; negatives occupy [0, 108] (most negative first),
#: positives [110, 218] — the bins are ordered along the real line
ZERO_BIN = _NMAG
NBINS = 2 * _NMAG + 1    # 219

#: magnitude-bucket upper bounds (inclusive, like tracing._BOUNDS)
_BOUNDS = np.array([2.0 ** (_LOG2_MIN + (i + 1) / _SUB)
                    for i in range(_OVERFLOW)])
#: geometric midpoint multiplier below a bucket's upper bound
_MID = 2.0 ** (-0.5 / _SUB)
#: representative value per magnitude bucket (overflow pegged above top)
_REPS = np.concatenate([_BOUNDS * _MID, [2.0 ** (_LOG2_MIN + _OCTAVES + 1)]])


def _mag_buckets(a: np.ndarray) -> np.ndarray:
    """Vectorized magnitude bucket (a > 0): smallest i with bound >= a —
    the same inclusive-upper-bound rule as tracing.bucket_index."""
    with np.errstate(divide="ignore"):
        i = np.ceil((np.log2(a) - _LOG2_MIN) * _SUB) - 1
    return np.clip(i, 0, _OVERFLOW).astype(np.int64)


def value_bins(values: np.ndarray) -> np.ndarray:
    """Signed bin per value: one vectorized pass, NaNs dropped by the
    caller (``ValueSketch.observe_array`` masks them)."""
    v = np.asarray(values, dtype=np.float64)
    out = np.full(v.shape, ZERO_BIN, dtype=np.int64)
    pos = v > 0.0
    neg = v < 0.0
    if pos.any():
        out[pos] = ZERO_BIN + 1 + _mag_buckets(v[pos])
    if neg.any():
        out[neg] = _OVERFLOW - _mag_buckets(-v[neg])
    return out


def value_bin(v: float) -> int:
    """Scalar signed bin (tests + single observations)."""
    return int(value_bins(np.array([v]))[0])


def bin_rep(i: int) -> float:
    """Representative real value of bin ``i`` (quantile reporting)."""
    if i == ZERO_BIN:
        return 0.0
    if i > ZERO_BIN:
        return float(_REPS[i - ZERO_BIN - 1])
    return -float(_REPS[_OVERFLOW - i])


class ValueSketch:
    """Bounded signed-value quantile sketch: one dense int64 bin array
    (219 entries, ~2 KB) + count/sum/min/max moments."""

    __slots__ = ("bins", "count", "sum", "min", "max")

    def __init__(self) -> None:
        self.bins = np.zeros(NBINS, dtype=np.int64)
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")

    def observe_array(self, values: np.ndarray) -> int:
        """Record every finite value of ``values`` (vectorized); returns
        the number recorded."""
        v = np.asarray(values, dtype=np.float64).ravel()
        if v.size == 0:
            return 0
        finite = np.isfinite(v)
        if not finite.all():
            v = v[finite]
            if v.size == 0:
                return 0
        np.add.at(self.bins, value_bins(v), 1)
        self.count += int(v.size)
        self.sum += float(v.sum())
        self.min = min(self.min, float(v.min()))
        self.max = max(self.max, float(v.max()))
        return int(v.size)

    def observe(self, value: float) -> None:
        self.observe_array(np.array([value]))

    def state(self) -> Dict[str, Any]:
        """Sparse mergeable state (msgpack-ready, like tracing hist
        states): only occupied bins ship."""
        nz = np.flatnonzero(self.bins)
        return {
            "bins": {int(i): int(self.bins[i]) for i in nz},
            "count": int(self.count),
            "sum": float(self.sum),
            "min": float(self.min) if self.count else 0.0,
            "max": float(self.max) if self.count else 0.0,
        }


def merge_value_states(states: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold sketch states bin-wise; commutative and associative (integer
    sums + min/max), so fleet folds are order-independent."""
    bins: Dict[int, int] = {}
    count = 0
    total = 0.0
    vmin, vmax = float("inf"), float("-inf")
    for st in states:
        if not st:
            continue
        for k, v in (st.get("bins") or {}).items():
            i = int(k)  # msgpack map keys may arrive as strings
            bins[i] = bins.get(i, 0) + int(v)
        c = int(st.get("count", 0))
        count += c
        total += float(st.get("sum", 0.0))
        if c:
            vmin = min(vmin, float(st.get("min", 0.0)))
            vmax = max(vmax, float(st.get("max", 0.0)))
    return {"bins": bins, "count": count, "sum": total,
            "min": vmin if count else 0.0, "max": vmax if count else 0.0}


def value_quantile(state: Dict[str, Any], q: float) -> Optional[float]:
    """Quantile at bin resolution: walk the signed bins in real-line
    order, return the target bin's representative value clamped into
    the observed [min, max]."""
    count = int(state.get("count", 0))
    if count <= 0:
        return None
    target = max(0.0, min(1.0, q)) * count
    seen = 0
    items = sorted((int(k), int(v))
                   for k, v in (state.get("bins") or {}).items())
    for i, n in items:
        seen += n
        if seen >= target:
            rep = bin_rep(i)
            return float(min(max(rep, state.get("min", rep)),
                             state.get("max", rep)))
    return float(state.get("max", 0.0))


# -- categorical frequencies: count-min + top-k ------------------------------

DEFAULT_CMS_WIDTH = 512
DEFAULT_CMS_DEPTH = 4
DEFAULT_TOPK = 16


def _row_hash(item: str, seed: int, width: int) -> int:
    """Deterministic per-row hash: crc32 with a seed prefix — identical
    across processes, so fleet-wide matrices index the same cells."""
    return zlib.crc32(b"%d\x00%s" % (seed, item.encode("utf-8"))) % width


class CategoricalSketch:
    """Bounded label/category frequency sketch: count-min matrix (exact
    element-wise merge) + a top-k heavy-hitter dict whose estimates come
    from the matrix — the dict is a cache, the matrix is the truth, so
    merges re-derive the dict and stay commutative."""

    __slots__ = ("width", "depth", "k", "rows", "total", "topk")

    def __init__(self, width: int = DEFAULT_CMS_WIDTH,
                 depth: int = DEFAULT_CMS_DEPTH,
                 k: int = DEFAULT_TOPK) -> None:
        self.width = int(width)
        self.depth = int(depth)
        self.k = int(k)
        self.rows = np.zeros((self.depth, self.width), dtype=np.int64)
        self.total = 0
        self.topk: Dict[str, int] = {}

    def _estimate(self, item: str) -> int:
        return int(min(self.rows[d][_row_hash(item, d, self.width)]
                       for d in range(self.depth)))

    def observe(self, item: str, n: int = 1) -> None:
        for d in range(self.depth):
            self.rows[d][_row_hash(item, d, self.width)] += n
        self.total += n
        est = self._estimate(item)
        if item in self.topk or len(self.topk) < self.k:
            self.topk[item] = est
            return
        worst = min(self.topk.items(), key=lambda kv: (kv[1], kv[0]))
        if est > worst[1]:
            del self.topk[worst[0]]
            self.topk[item] = est

    def observe_many(self, items: Iterable[str]) -> None:
        for it in items:
            self.observe(str(it))

    def state(self) -> Dict[str, Any]:
        return {
            "width": self.width, "depth": self.depth, "k": self.k,
            "rows": [row.tolist() for row in self.rows],
            "total": int(self.total),
            "topk": {k: int(v) for k, v in self.topk.items()},
        }


def merge_categorical_states(
        states: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Element-wise matrix sum + top-k re-derivation from the MERGED
    matrix over the union of candidate items — commutative by
    construction (deterministic tie-break on the item string)."""
    acc: Optional[np.ndarray] = None
    width = DEFAULT_CMS_WIDTH
    depth = DEFAULT_CMS_DEPTH
    k = DEFAULT_TOPK
    total = 0
    candidates: set = set()
    for st in states:
        if not st or not st.get("rows"):
            continue
        rows = np.asarray(st["rows"], dtype=np.int64)
        if acc is None:
            acc = rows.copy()
            width = int(st.get("width", rows.shape[1]))
            depth = int(st.get("depth", rows.shape[0]))
            k = int(st.get("k", DEFAULT_TOPK))
        elif rows.shape == acc.shape:
            acc += rows
        else:
            continue  # geometry mismatch: skip rather than corrupt
        total += int(st.get("total", 0))
        candidates.update((st.get("topk") or {}).keys())
    if acc is None:
        return {"width": width, "depth": depth, "k": k,
                "rows": [], "total": 0, "topk": {}}
    est = {item: int(min(acc[d][_row_hash(item, d, width)]
                         for d in range(depth)))
           for item in candidates}
    top = sorted(est.items(), key=lambda kv: (-kv[1], kv[0]))[:k]
    return {"width": width, "depth": depth, "k": k,
            "rows": [row.tolist() for row in acc],
            "total": total, "topk": dict(top)}


def categorical_freqs(state: Dict[str, Any]) -> Dict[str, float]:
    """Top-k relative frequencies + the residual tail mass under
    ``__other__`` — the fixed-support distribution PSI compares."""
    total = int(state.get("total", 0))
    if total <= 0:
        return {}
    out = {str(k): int(v) / total
           for k, v in (state.get("topk") or {}).items()}
    other = 1.0 - sum(out.values())
    if other > 1e-9:
        out["__other__"] = other
    return out


# -- windowed reference-vs-live ring -----------------------------------------

DEFAULT_RING_CAPACITY = 48


class SnapshotRing:
    """Bounded ring of completed-window snapshot docs plus one PINNED
    reference doc (the drift baseline): the live window compares against
    the reference long after the reference's windows left the ring —
    the same shape as utils/timeseries.TimeSeriesRing, minus deltas
    (sketch windows are already per-window, not cumulative)."""

    def __init__(self, capacity: int = DEFAULT_RING_CAPACITY) -> None:
        self.capacity = max(2, int(capacity))
        self._ring: deque = deque(maxlen=self.capacity)
        self._pushed = 0
        self.reference: Optional[Dict[str, Any]] = None
        self.reference_ts = 0.0

    def push(self, doc: Dict[str, Any], ts: float) -> None:
        self._ring.append({"ts": float(ts), "doc": doc})
        self._pushed += 1

    def pin_reference(self, doc: Dict[str, Any], ts: float) -> None:
        self.reference = doc
        self.reference_ts = float(ts)

    def newest(self) -> Optional[Dict[str, Any]]:
        return self._ring[-1]["doc"] if self._ring else None

    def points(self, last: int = 0) -> List[Dict[str, Any]]:
        out = list(self._ring)
        return out[-last:] if last > 0 else out

    def stats(self) -> Dict[str, Any]:
        return {"pushed": self._pushed, "retained": len(self._ring),
                "capacity": self.capacity,
                "reference_pinned": self.reference is not None,
                "reference_ts": self.reference_ts}


# -- shared helpers ----------------------------------------------------------

def top_bins(state: Dict[str, Any], n: int = 8) -> List[Tuple[float, int]]:
    """The ``n`` heaviest (representative_value, count) pairs of a value
    state — the compact sketch rendering jubactl tables use."""
    items = sorted(((int(k), int(v))
                    for k, v in (state.get("bins") or {}).items()),
                   key=lambda kv: -kv[1])[:n]
    return [(bin_rep(i), c) for i, c in items]
