"""Declarative SLOs evaluated as multi-window burn rates (ISSUE 7).

An SLO here is "at most ``objective`` of events may be bad"; the **burn
rate** over a window is ``bad_fraction / objective`` — burn 1.0 means
the error budget is being spent exactly as fast as it accrues, burn N
means N× too fast (Google SRE workbook, ch. 5). An alert **fires** only
when BOTH a fast and a slow window burn above the threshold: the slow
window keeps one latency blip from paging, the fast window makes the
alert clear quickly once the burst ends (the classic multi-window
multi-burn construction, with one burn threshold instead of the
four-pair ladder — operators tune windows/threshold via flags).

Spec grammar (``--slo``, repeatable; parsed by :func:`parse_slo`):

- ``latency:<span>:p<QQ>:<threshold_ms>[:<objective>]`` — bad event =
  a ``<span>`` request at/above ``threshold_ms``; the p<QQ> names the
  intent (p99 → objective 0.01, p90 → 0.10, ...) and doubles as the
  default objective. Example: ``latency:rpc.classify:p99:50``.
- ``error_rate:<span|*>:<objective>`` — bad event = a dispatch of
  ``<span>`` that raised (the ``rpc.<m>.errors`` counters); ``*`` sums
  every ``rpc.*`` span. Example: ``error_rate:*:0.01``.
- ``gauge:<key>:<ceiling>`` — burn = windowed mean of gauge ``<key>``
  divided by ``ceiling`` (for signals that are levels, not event
  streams: ``mix.ef_residual_drift_rate``, quantization drift, queue
  depths). Example: ``gauge:mix.ef_residual_drift_rate:0.05``.

Any spec may carry a ``name=`` prefix (``hot=latency:rpc.train:p99:20``)
— otherwise the name derives from the fields. Evaluation runs on the
runtime-telemetry sampler tick against the process's TimeSeriesRing
(utils/timeseries.py); results surface as ``slo.<name>.burn_fast`` /
``burn_slow`` / ``firing`` gauges on ``/metrics``, degrade ``/healthz``,
and list under ``jubactl -c alerts`` via the ``get_alerts`` RPC.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Dict, List, Optional

from jubatus_tpu.utils.timeseries import TimeSeriesRing, Window
from jubatus_tpu.utils.tracing import Registry

log = logging.getLogger(__name__)

#: default multi-window pair (seconds): 5 min confirms the burst is
#: current, 1 h proves it is significant
DEFAULT_FAST_WINDOW = 300.0
DEFAULT_SLOW_WINDOW = 3600.0
#: default burn-rate threshold: fire at 2x budget spend
DEFAULT_BURN_THRESHOLD = 2.0

KINDS = ("latency", "error_rate", "gauge")


@dataclasses.dataclass(frozen=True)
class SloSpec:
    name: str
    kind: str                 # latency | error_rate | gauge
    span: str                 # span name, '*' (error_rate), or gauge key
    threshold_s: float = 0.0  # latency: bad at/above this duration
    objective: float = 0.01   # allowed bad fraction (error budget)
    ceiling: float = 0.0      # gauge: burn = mean / ceiling

    def describe(self) -> str:
        if self.kind == "latency":
            return (f"latency {self.span} >= {self.threshold_s * 1e3:g} ms "
                    f"for > {self.objective:g} of requests")
        if self.kind == "error_rate":
            return f"error rate of {self.span} > {self.objective:g}"
        return f"gauge {self.span} > {self.ceiling:g}"


def parse_slo(spec: str) -> SloSpec:
    """Parse one ``--slo`` spec string; raises ValueError on bad
    grammar so servers reject misconfiguration at argv time."""
    s = spec.strip()
    name = ""
    if "=" in s.split(":", 1)[0]:
        name, s = s.split("=", 1)
        name = name.strip()
    parts = [p.strip() for p in s.split(":")]
    if not parts or parts[0] not in KINDS:
        raise ValueError(
            f"--slo {spec!r}: kind must be one of {', '.join(KINDS)}")
    kind = parts[0]
    try:
        if kind == "latency":
            if len(parts) not in (4, 5):
                raise ValueError("want latency:<span>:p<QQ>:<threshold_ms>"
                                 "[:<objective>]")
            span, pq, thr_ms = parts[1], parts[2], float(parts[3])
            if not pq.startswith("p") or not pq[1:].isdigit():
                raise ValueError(f"bad quantile {pq!r} (want pNN)")
            q = int(pq[1:])
            if not 0 < q < 100:
                raise ValueError(f"quantile p{q} out of range")
            objective = float(parts[4]) if len(parts) == 5 \
                else (100 - q) / 100.0
            if thr_ms <= 0:
                raise ValueError("threshold_ms must be > 0")
            return SloSpec(name or f"{span}.{pq}", "latency", span,
                           threshold_s=thr_ms / 1e3, objective=objective)
        if kind == "error_rate":
            if len(parts) != 3:
                raise ValueError("want error_rate:<span|*>:<objective>")
            span, objective = parts[1], float(parts[2])
            if not 0 < objective < 1:
                raise ValueError("objective must be in (0, 1)")
            return SloSpec(name or f"errors.{span}", "error_rate", span,
                           objective=objective)
        # gauge
        if len(parts) != 3:
            raise ValueError("want gauge:<key>:<ceiling>")
        span, ceiling = parts[1], float(parts[2])
        if ceiling <= 0:
            raise ValueError("ceiling must be > 0")
        return SloSpec(name or f"gauge.{span}", "gauge", span,
                       ceiling=ceiling)
    except ValueError as e:
        raise ValueError(f"--slo {spec!r}: {e}") from None


def _slug(name: str) -> str:
    """Gauge-key-safe SLO name (no '*' or whitespace on /metrics)."""
    return name.replace("*", "all").replace(" ", "_")


class SloEngine:
    """Evaluates a set of SLO specs against one TimeSeriesRing and
    publishes the verdicts into one tracing Registry."""

    def __init__(self, specs: List[SloSpec], ring: TimeSeriesRing,
                 registry: Registry, *,
                 fast_window_s: float = DEFAULT_FAST_WINDOW,
                 slow_window_s: float = DEFAULT_SLOW_WINDOW,
                 burn_threshold: float = DEFAULT_BURN_THRESHOLD) -> None:
        self.specs = list(specs)
        self.ring = ring
        self.registry = registry
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self.burn_threshold = float(burn_threshold)
        #: per-SLO evaluated state (name -> dict); see evaluate()
        self._state: Dict[str, Dict[str, Any]] = {}
        #: incident hook (ISSUE 14): called as ``on_fire(name, state)``
        #: when an SLO TRANSITIONS to firing (never on re-evaluation of
        #: an already-firing one); exceptions are swallowed — forensics
        #: must not break the telemetry tick
        self.on_fire: Optional[Any] = None

    # -- burn math ------------------------------------------------------------
    def _bad_fraction(self, spec: SloSpec,
                      win: Window) -> Optional[float]:
        if spec.kind == "latency":
            return win.bad_fraction(spec.span, spec.threshold_s)
        if spec.kind == "error_rate":
            if spec.span == "*":
                spans = win.spans("rpc.")
            else:
                spans = [spec.span]
            total = sum(win.span_count(s) for s in spans)
            if total == 0:
                return None
            bad = sum(win.counter_delta(f"{s}.errors") for s in spans)
            return min(1.0, bad / total)
        return None  # gauge kind does not use fractions

    def _burn(self, spec: SloSpec, win: Optional[Window]) -> float:
        if win is None:
            return 0.0
        if spec.kind == "gauge":
            mean = win.gauge_mean(spec.span)
            return 0.0 if mean is None else mean / spec.ceiling
        frac = self._bad_fraction(spec, win)
        if frac is None:
            return 0.0
        return frac / max(spec.objective, 1e-9)

    # -- evaluation -----------------------------------------------------------
    def evaluate(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """One evaluation pass (the sampler tick): recompute every
        SLO's fast/slow burn, update firing state + gauges, and return
        the full per-SLO state list."""
        now = time.time() if now is None else float(now)
        fast = self.ring.window(self.fast_window_s, now=now)
        slow = self.ring.window(self.slow_window_s, now=now)
        out: List[Dict[str, Any]] = []
        for spec in self.specs:
            burn_fast = self._burn(spec, fast)
            burn_slow = self._burn(spec, slow)
            firing = burn_fast >= self.burn_threshold and \
                burn_slow >= self.burn_threshold
            st = self._state.get(spec.name)
            if st is None:
                st = {"name": spec.name, "kind": spec.kind,
                      "span": spec.span, "describe": spec.describe(),
                      "firing": False, "since_ts": 0.0,
                      "transitions": 0}
                self._state[spec.name] = st
            transitioned = firing != st["firing"]
            # commit the new state BEFORE any edge hook runs: the
            # incident trigger's forensic collector may (transitively)
            # re-enter evaluate, and a not-yet-committed transition
            # would read as a SECOND edge
            st["firing"] = firing
            st["burn_fast"] = round(burn_fast, 4)
            st["burn_slow"] = round(burn_slow, 4)
            st["burn_threshold"] = self.burn_threshold
            if transitioned:
                st["transitions"] += 1
                st["since_ts"] = round(now, 3)
                self.registry.count("slo.transitions")
                # event plane (ISSUE 14): every fire/clear edge is a
                # timeline event; fires additionally run the incident
                # trigger hook
                self.registry.events.emit(
                    "slo", "firing" if firing else "resolved",
                    severity="warning" if firing else "info",
                    name=spec.name, burn_fast=round(burn_fast, 4),
                    burn_slow=round(burn_slow, 4))
                if firing and self.on_fire is not None:
                    try:
                        self.on_fire(spec.name, dict(st))
                    except Exception:  # noqa: BLE001 — hook must not break
                        log.debug("slo on_fire hook failed", exc_info=True)
                (log.warning if firing else log.info)(
                    "SLO %s %s (burn fast=%.2f slow=%.2f, threshold %.2f): "
                    "%s", spec.name, "FIRING" if firing else "resolved",
                    burn_fast, burn_slow, self.burn_threshold,
                    spec.describe())
            slug = _slug(spec.name)
            self.registry.gauge(f"slo.{slug}.burn_fast", round(burn_fast, 4))
            self.registry.gauge(f"slo.{slug}.burn_slow", round(burn_slow, 4))
            self.registry.gauge(f"slo.{slug}.firing", 1.0 if firing else 0.0)
            out.append(dict(st))
        return out

    def alerts(self) -> List[Dict[str, Any]]:
        """Currently-firing SLOs (last evaluation's view)."""
        return [dict(st) for st in self._state.values() if st["firing"]]

    def status(self) -> List[Dict[str, Any]]:
        """Every SLO's last-evaluated state (firing or not)."""
        return [dict(st) for st in self._state.values()]
