"""Tail-based slow-request capture (ISSUE 4: slow-request forensics).

A bounded ring of structured records for requests that landed at or
above a configurable quantile of their OWN span histogram (default
p99): method, duration, trace_id, peer, deadline-remaining, and the
threshold that tripped. Tail-based means the log captures exactly the
requests an operator would go hunting for after a latency page — the
ones past the knee of the distribution — with no sampling decision made
before the duration is known (head-based sampling throws the tail away
by construction).

The quantile threshold is computed against the span's log-bucketed
histogram (utils/tracing.py) and CACHED on the histogram, refreshed
every 64 records, so the record hot path pays one float compare, not a
109-bucket walk. No capture happens until a span has ``min_count``
samples — early in a process's life every request is "p99".

Owned by each tracing ``Registry`` (one per server process); configured
by ``--slowlog-capacity`` / ``--slowlog-quantile`` / ``--slowlog-min-count``;
queried by the ``get_slow_log`` RPC, ``jubadump --slow-log``, and the
``/slowlog`` endpoint of utils/metrics_http.py. Each captured record also
stamps a Prometheus exemplar (trace_id) onto the histogram bucket it
landed in, so a scrape dashboard links a p99 spike straight to a trace.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Dict, List, Optional

#: ring size; 0 disables capture entirely (record() never consults us)
DEFAULT_CAPACITY = 256
#: a request at/above this quantile of its own span histogram is slow
DEFAULT_QUANTILE = 0.99
#: no thresholding until a span has this many samples
DEFAULT_MIN_COUNT = 64


class SlowLog:
    """Bounded ring of slow-request records for one Registry."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 quantile: float = DEFAULT_QUANTILE,
                 min_count: int = DEFAULT_MIN_COUNT) -> None:
        self._lock = threading.Lock()
        self.capacity = int(capacity)
        self.quantile = float(quantile)
        self.min_count = int(min_count)
        self._ring: deque = deque(maxlen=max(self.capacity, 1))
        self._captured = 0

    def configure(self, capacity: Optional[int] = None,
                  quantile: Optional[float] = None,
                  min_count: Optional[int] = None) -> None:
        """Re-tune at server start (flags); keeps already-captured
        records up to the new capacity."""
        with self._lock:
            if capacity is not None:
                self.capacity = int(capacity)
                self._ring = deque(self._ring,
                                   maxlen=max(self.capacity, 1))
            if quantile is not None:
                if not 0.0 < quantile <= 1.0:
                    raise ValueError(f"slowlog quantile {quantile} not in "
                                     "(0, 1]")
                self.quantile = float(quantile)
            if min_count is not None:
                self.min_count = max(1, int(min_count))

    def add(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            self._captured += 1
            self._ring.append(rec)

    def snapshot(self, last: int = 0) -> List[Dict[str, Any]]:
        """Oldest-first copy (the newest ``last`` when > 0)."""
        with self._lock:
            out = list(self._ring)
        return out[-last:] if last > 0 else out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"captured": self._captured,
                    "retained": len(self._ring),
                    "capacity": self.capacity,
                    "quantile": self.quantile,
                    "min_count": self.min_count}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._captured = 0
