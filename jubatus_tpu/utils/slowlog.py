"""Tail-based slow-request capture (ISSUE 4: slow-request forensics).

A bounded ring of structured records for requests that landed at or
above a configurable quantile of their OWN span histogram (default
p99): method, duration, trace_id, peer, deadline-remaining, and the
threshold that tripped. Tail-based means the log captures exactly the
requests an operator would go hunting for after a latency page — the
ones past the knee of the distribution — with no sampling decision made
before the duration is known (head-based sampling throws the tail away
by construction).

The quantile threshold is computed against the span's log-bucketed
histogram (utils/tracing.py) and CACHED on the histogram, refreshed
every 64 records, so the record hot path pays one float compare, not a
109-bucket walk. No capture happens until a span has ``min_count``
samples — early in a process's life every request is "p99".

Owned by each tracing ``Registry`` (one per server process); configured
by ``--slowlog-capacity`` / ``--slowlog-quantile`` / ``--slowlog-min-count``;
queried by the ``get_slow_log`` RPC, ``jubadump --slow-log``, and the
``/slowlog`` endpoint of utils/metrics_http.py. Each captured record also
stamps a Prometheus exemplar (trace_id) onto the histogram bucket it
landed in, so a scrape dashboard links a p99 spike straight to a trace.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

log = logging.getLogger(__name__)

#: ring size; 0 disables capture entirely (record() never consults us)
DEFAULT_CAPACITY = 256
#: a request at/above this quantile of its own span histogram is slow
DEFAULT_QUANTILE = 0.99
#: no thresholding until a span has this many samples
DEFAULT_MIN_COUNT = 64
#: tail-trigger defaults (ISSUE 8): K breaches of the SAME span inside
#: the window fire the breach callback (a profiler snapshot) ONCE
DEFAULT_TRIGGER_BREACHES = 3
DEFAULT_TRIGGER_WINDOW_S = 10.0
#: trace ids carried per breach window (enough to pivot into jubactl
#: -c trace; unbounded capture would let a storm grow the window rec)
_TRIGGER_MAX_IDS = 8


class SlowLog:
    """Bounded ring of slow-request records for one Registry."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 quantile: float = DEFAULT_QUANTILE,
                 min_count: int = DEFAULT_MIN_COUNT) -> None:
        self._lock = threading.Lock()
        self.capacity = int(capacity)
        self.quantile = float(quantile)
        self.min_count = int(min_count)
        self._ring: deque = deque(maxlen=max(self.capacity, 1))
        self._captured = 0
        #: tail-triggered profiling (ISSUE 8): ``on_breach(span,
        #: trace_ids)`` fires when ``trigger_breaches`` captures of the
        #: SAME span land inside ``trigger_window_s`` — exactly once per
        #: window (the flag clears when the window expires)
        self.on_breach: Optional[Callable[[str, List[str]], Any]] = None
        self.trigger_breaches = 0          # 0 = trigger disabled
        self.trigger_window_s = DEFAULT_TRIGGER_WINDOW_S
        self._windows: Dict[str, Dict[str, Any]] = {}
        self._trigger_fired = 0

    def configure(self, capacity: Optional[int] = None,
                  quantile: Optional[float] = None,
                  min_count: Optional[int] = None) -> None:
        """Re-tune at server start (flags); keeps already-captured
        records up to the new capacity."""
        with self._lock:
            if capacity is not None:
                self.capacity = int(capacity)
                self._ring = deque(self._ring,
                                   maxlen=max(self.capacity, 1))
            if quantile is not None:
                if not 0.0 < quantile <= 1.0:
                    raise ValueError(f"slowlog quantile {quantile} not in "
                                     "(0, 1]")
                self.quantile = float(quantile)
            if min_count is not None:
                self.min_count = max(1, int(min_count))

    def set_trigger(self, fn: Optional[Callable[[str, List[str]], Any]],
                    breaches: int = DEFAULT_TRIGGER_BREACHES,
                    window_s: float = DEFAULT_TRIGGER_WINDOW_S) -> None:
        """Arm (or disarm with fn=None / breaches=0) the tail trigger:
        K same-span captures inside the window call ``fn(span,
        trace_ids)`` once. The callback runs on the capturing request's
        thread OUTSIDE the ring lock and must be cheap (the profiler's
        snapshot fold is)."""
        with self._lock:
            self.on_breach = fn
            self.trigger_breaches = max(0, int(breaches))
            self.trigger_window_s = float(window_s)
            self._windows.clear()

    def add(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            self._captured += 1
            self._ring.append(rec)
        self._note_breach(str(rec.get("method", "")),
                          str(rec.get("trace_id", "")))

    def _note_breach(self, span: str, trace_id: str,
                     now: Optional[float] = None) -> bool:
        """Advance one span's breach window; fires the trigger exactly
        once per window when it reaches ``trigger_breaches`` captures.
        ``now`` is injectable for tests (monotonic domain). Returns
        True when the callback fired."""
        fire: Optional[Callable[[str, List[str]], Any]] = None
        ids: List[str] = []
        with self._lock:
            if self.trigger_breaches <= 0 or self.on_breach is None \
                    or not span:
                return False
            t = time.monotonic() if now is None else float(now)
            w = self._windows.get(span)
            if w is None or t - w["start"] > self.trigger_window_s:
                w = self._windows[span] = {"start": t, "count": 0,
                                           "ids": [], "fired": False}
            w["count"] += 1
            if trace_id and len(w["ids"]) < _TRIGGER_MAX_IDS:
                w["ids"].append(trace_id)
            if not w["fired"] and w["count"] >= self.trigger_breaches:
                w["fired"] = True
                self._trigger_fired += 1
                fire, ids = self.on_breach, list(w["ids"])
        if fire is None:
            return False
        try:
            fire(span, ids)
        except Exception:  # noqa: BLE001 — a trigger must never break capture
            log.debug("slowlog breach trigger failed", exc_info=True)
        return True

    def snapshot(self, last: int = 0) -> List[Dict[str, Any]]:
        """Oldest-first copy (the newest ``last`` when > 0)."""
        with self._lock:
            out = list(self._ring)
        return out[-last:] if last > 0 else out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"captured": self._captured,
                    "retained": len(self._ring),
                    "capacity": self.capacity,
                    "quantile": self.quantile,
                    "min_count": self.min_count,
                    "trigger_breaches": self.trigger_breaches,
                    "trigger_window_s": self.trigger_window_s,
                    "trigger_fired": self._trigger_fired}

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._captured = 0
            self._windows.clear()
            self._trigger_fired = 0
