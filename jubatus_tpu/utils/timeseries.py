"""Metric time-series: a bounded ring of periodic registry snapshots.

ISSUE 7: every metric the cluster exports today is an instantaneous
snapshot — counters only ever go up, quantiles cover process lifetime,
and "is the error rate rising?" has no answer without scraping twice
and doing the subtraction by hand. This module does that subtraction as
a first-class facility:

- **TimeSeriesRing** — one per process, holding up to ``capacity``
  points sampled from the owning tracing ``Registry`` (the existing
  runtime-telemetry thread is the sampler; see server/base.py). Each
  point is the registry's mergeable ``snapshot()`` (sparse histogram
  buckets + counters + gauges) plus a wall-clock timestamp, so the ring
  costs a few KB per point and survives msgpack verbatim — the
  ``get_timeseries`` RPC ships points as-is and proxies broadcast+fold.
- **Window** — the delta between the newest point and the newest point
  at/older than the window start. Because histogram buckets are
  monotonic per span, a bucket-wise subtraction IS the histogram of the
  requests that arrived inside the window — windowed p50/p99 are exact
  at bucket resolution, windowed counter rates are exact, and the SLO
  engine's "fraction of requests above X ms over the last N seconds"
  (utils/slo.py) falls straight out of the cumulative-bucket diff.

A registry ``reset()`` (bench warmup hygiene) makes counters go
backwards; deltas clamp at 0 so a reset costs one window of data, not a
crash or a negative rate.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from jubatus_tpu.utils import tracing

#: default ring capacity: one hour of history at the default 10 s
#: telemetry interval
DEFAULT_CAPACITY = 360


def _counters_of(point: Dict[str, Any]) -> Dict[str, int]:
    return point.get("counters") or {}


def _hists_of(point: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    return point.get("hists") or {}


def hist_state_delta(new: Dict[str, Any],
                     old: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """The histogram of events recorded between two snapshots of one
    span: bucket-wise (clamped) subtraction of the cumulative states.
    ``max_s`` keeps the newer side's value — an upper bound for the
    window (the true window max is not recoverable from cumulative
    buckets), which quantile clamping tolerates."""
    if old is None:
        old = {}
    old_buckets = {int(k): int(v)
                   for k, v in (old.get("buckets") or {}).items()}
    buckets: Dict[int, int] = {}
    for k, v in (new.get("buckets") or {}).items():
        i = int(k)
        d = int(v) - old_buckets.get(i, 0)
        if d > 0:
            buckets[i] = d
    return {
        "buckets": buckets,
        "count": max(0, int(new.get("count", 0)) - int(old.get("count", 0))),
        "total_s": max(0.0, float(new.get("total_s", 0.0))
                       - float(old.get("total_s", 0.0))),
        "max_s": float(new.get("max_s", 0.0)),
        "last_s": float(new.get("last_s", 0.0)),
        "last_trace_id": new.get("last_trace_id", ""),
    }


class Window:
    """One evaluated window over a ring: newest point vs the baseline
    point at/just-past ``seconds`` ago. All rates are per second over
    the ACTUAL covered span (``covered_s``), not the nominal window —
    a freshly-booted process reports honest rates immediately."""

    def __init__(self, newest: Dict[str, Any],
                 baseline: Optional[Dict[str, Any]]) -> None:
        self.newest = newest
        self.baseline = baseline or {"ts": newest.get("ts", 0.0)}
        self.covered_s = max(
            0.0, float(newest.get("ts", 0.0))
            - float(self.baseline.get("ts", 0.0)))

    def counter_delta(self, name: str) -> int:
        new = _counters_of(self.newest).get(name, 0)
        old = _counters_of(self.baseline).get(name, 0)
        return max(0, int(new) - int(old))

    def counter_rate(self, name: str) -> float:
        if self.covered_s <= 0:
            return 0.0
        return self.counter_delta(name) / self.covered_s

    def hist_delta(self, span: str) -> Optional[Dict[str, Any]]:
        """Histogram state of the requests inside the window; None when
        the span never appeared."""
        new = _hists_of(self.newest).get(span)
        if new is None:
            return None
        return hist_state_delta(new, _hists_of(self.baseline).get(span))

    def span_count(self, span: str) -> int:
        d = self.hist_delta(span)
        return int(d["count"]) if d else 0

    def span_rate(self, span: str) -> float:
        if self.covered_s <= 0:
            return 0.0
        return self.span_count(span) / self.covered_s

    def quantile_ms(self, span: str, q: float) -> Optional[float]:
        """Windowed quantile (ms) of one span, exact at bucket
        resolution — the p99-over-the-last-minute the lifetime
        histograms cannot answer."""
        d = self.hist_delta(span)
        if not d or not d["count"]:
            return None
        v = tracing.state_quantile(d, q)
        return None if v is None else v * 1e3

    def bad_fraction(self, span: str, threshold_s: float) -> Optional[float]:
        """Fraction of the window's requests that took >= threshold
        (bucket-resolution: a request counts as bad when its whole
        bucket lies at/above the threshold's bucket). None when the
        span saw no traffic in the window."""
        d = self.hist_delta(span)
        if not d or not d["count"]:
            return None
        thr_idx = tracing.bucket_index(threshold_s)
        bad = sum(c for i, c in d["buckets"].items() if int(i) >= thr_idx)
        return bad / d["count"]

    def spans(self, prefix: str = "") -> List[str]:
        return sorted(n for n in _hists_of(self.newest)
                      if n.startswith(prefix))

    def counter_names(self, prefix: str = "") -> List[str]:
        return sorted(n for n in _counters_of(self.newest)
                      if n.startswith(prefix))

    def gauge_mean(self, name: str) -> Optional[float]:
        """Mean of a gauge across the window's two endpoints (gauges are
        point-in-time; the ring doesn't integrate between samples)."""
        vals = [p.get("gauges", {}).get(name)
                for p in (self.baseline, self.newest)]
        vals = [float(v) for v in vals if v is not None]
        if not vals:
            return None
        return sum(vals) / len(vals)


def window_from_points(points: List[Dict[str, Any]], seconds: float,
                       now: Optional[float] = None) -> Optional[Window]:
    """A :class:`Window` over a raw oldest-first point list — what
    ``jubactl -c watch`` does with each node's ``get_timeseries`` reply
    (the ring itself stays on the server). None below two points."""
    if len(points) < 2:
        return None
    newest = points[-1]
    start = (float(newest["ts"]) if now is None else float(now)) \
        - float(seconds)
    baseline = points[0]
    for p in points[:-1]:
        if float(p["ts"]) <= start:
            baseline = p
        else:
            break
    return Window(newest, baseline)


class TimeSeriesRing:
    """Bounded per-process ring of timestamped registry snapshots."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 min_spacing_s: float = 0.0) -> None:
        self._lock = threading.Lock()
        self.capacity = max(2, int(capacity))
        #: samples closer than this to the previous one are dropped
        #: (the on-demand telemetry refresh under scrape load must not
        #: flood the ring with near-duplicate points)
        self.min_spacing_s = float(min_spacing_s)
        self._ring: deque = deque(maxlen=self.capacity)
        self._sampled = 0

    def sample(self, snapshot: Dict[str, Any], ts: Optional[float] = None,
               force: bool = False) -> bool:
        """Append one registry snapshot; returns False when dropped by
        the spacing guard. ``ts`` defaults to now (wall-clock: points
        must be comparable across nodes in jubactl views)."""
        ts = time.time() if ts is None else float(ts)
        point = {"ts": ts,
                 "hists": snapshot.get("hists") or {},
                 "counters": snapshot.get("counters") or {},
                 "gauges": snapshot.get("gauges") or {}}
        with self._lock:
            if not force and self._ring and self.min_spacing_s > 0 and \
                    ts - float(self._ring[-1]["ts"]) < self.min_spacing_s:
                return False
            self._ring.append(point)
            self._sampled += 1
        return True

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def points(self, last: int = 0) -> List[Dict[str, Any]]:
        """Oldest-first copy of the ring (the newest ``last`` when > 0)."""
        with self._lock:
            out = list(self._ring)
        return out[-last:] if last > 0 else out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {"sampled": self._sampled, "retained": len(self._ring),
                   "capacity": self.capacity}
            if self._ring:
                out["oldest_ts"] = self._ring[0]["ts"]
                out["newest_ts"] = self._ring[-1]["ts"]
        return out

    def window(self, seconds: float,
               now: Optional[float] = None) -> Optional[Window]:
        """The window ending at the newest point and starting
        ``seconds`` earlier. Baseline = the newest point at/older than
        the start (so the window COVERS at least ``seconds`` when the
        ring is deep enough, the whole ring otherwise). None when the
        ring holds fewer than two points."""
        with self._lock:
            pts = list(self._ring)
        return window_from_points(pts, seconds, now=now)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._sampled = 0
